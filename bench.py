"""Headline benchmark: docs/sec on TPU vs the 8-rank CPU oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "docs/sec", "vs_baseline": N}

Method (BASELINE.json north star, scaled to fit a CI budget): generate a
synthetic Zipf-distributed corpus on disk, run the native bit-reference
with 8 worker ranks (the "8-rank MPI CPU baseline" — measured, since the
reference publishes no numbers, BASELINE.md), then run the TPU path
end-to-end (read + native tokenize/hash + pack + device histogram/DF/
score/top-k) and report TPU docs/sec with vs_baseline = tpu/cpu ratio.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_DOCS = int(os.environ.get("BENCH_DOCS", 32768))
DOC_LEN = int(os.environ.get("BENCH_DOC_LEN", 256))
N_WORDS = 8192
VOCAB = 1 << 16
TOPK = 16


def make_corpus(root: str) -> str:
    rng = np.random.default_rng(42)
    words = np.array([f"w{i}".encode() for i in range(N_WORDS)], dtype=object)
    input_dir = os.path.join(root, "input")
    os.makedirs(input_dir)
    zipf = np.clip(rng.zipf(1.3, size=N_DOCS * DOC_LEN), 1, N_WORDS) - 1
    lens = rng.integers(DOC_LEN // 2, DOC_LEN + 1, N_DOCS)
    off = 0
    for i in range(1, N_DOCS + 1):
        n = int(lens[i - 1])
        doc = b" ".join(words[zipf[off:off + n]])
        off += n
        with open(os.path.join(input_dir, f"doc{i}"), "wb") as f:
            f.write(doc)
    return input_dir


def bench_native(input_dir: str, out: str) -> float:
    binary = os.path.join(REPO, "native", "tfidf_ref")
    if not os.path.exists(binary):
        subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                       check=True, capture_output=True)
    best = float("inf")
    for _ in range(2):  # best-of-2: host-side timing noise (see bench_tpu)
        t0 = time.perf_counter()
        subprocess.run([binary, input_dir, out, "9"], check=True,
                       stdout=subprocess.DEVNULL)
        best = min(best, time.perf_counter() - t0)
    return best


def bench_tpu(input_dir: str) -> float:
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import run_overlapped

    # Overlapped chunked ingest on the row-sparse engine: the native
    # parallel loader packs chunk i+1 while the device runs chunk i
    # (async dispatch), DF accumulates across chunks, and resident
    # triples are rescored against the final corpus-wide IDF. O(D x L)
    # device memory — no [D, V] materialization at any point.
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                         max_doc_len=DOC_LEN, doc_chunk=DOC_LEN, topk=TOPK,
                         engine="sparse")
    chunk = min(N_DOCS, 8192)

    # Untimed warmup compiles both phases at the chunk shape; the timed
    # runs re-ingest from raw bytes and hit the jit cache. Best-of-3:
    # single-core host contention with the device tunnel makes
    # individual runs noisy; the minimum is the honest steady state.
    run_overlapped(input_dir, cfg, chunk_docs=chunk, doc_len=DOC_LEN)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        result = run_overlapped(input_dir, cfg, chunk_docs=chunk,
                                doc_len=DOC_LEN)
        best = min(best, time.perf_counter() - t0)
        assert result.topk_vals.shape == (N_DOCS, TOPK)
    return best


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="tfidf_bench_")
    try:
        input_dir = make_corpus(tmp)
        cpu_s = bench_native(input_dir, os.path.join(tmp, "ref_out.txt"))
        tpu_s = bench_tpu(input_dir)
        cpu_dps = N_DOCS / cpu_s
        tpu_dps = N_DOCS / tpu_s
        print(json.dumps({
            "metric": f"docs/sec, {N_DOCS}-doc Zipf corpus, hashed 2^16 "
                      f"vocab, top-{TOPK} (vs 8-worker native CPU oracle)",
            "value": round(tpu_dps, 1),
            "unit": "docs/sec",
            "vs_baseline": round(tpu_dps / cpu_dps, 2),
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
