"""Headline benchmark: docs/sec on TPU vs the 8-rank CPU oracle.

Prints ONE JSON line on stdout, ALWAYS — success or failure:
  {"metric": ..., "value": N, "unit": "docs/sec", "vs_baseline": N, ...}
plus diagnostic fields: "backend", "recall_at_k", "cpu_docs_per_sec",
"pack_s", "tpu_s", and "error" when something went wrong. All other
chatter goes to stderr, so the driver's JSON parse cannot be broken by
progress output.

Method (BASELINE.json north star, scaled to fit a CI budget): generate a
synthetic Zipf-distributed corpus on disk, run the native bit-reference
with 8 worker ranks (the "8-rank MPI CPU baseline" — measured, since the
reference publishes no numbers, BASELINE.md), then run the TPU path
end-to-end (read + native tokenize/hash + pack + device histogram/DF/
score/top-k) and report TPU docs/sec with vs_baseline = tpu/cpu ratio.
The same oracle run's output feeds the top-k recall metric
(tfidf_tpu/recall.py) on a sampled doc subset — both halves of the
north star in one line.

Hardening (VERDICT round 1 item 1): the TPU backend (axon tunnel) can
hang at init, so the backend is pre-flighted in a SUBPROCESS with a hard
timeout and bounded retries before jax is ever imported in-process; on
exhaustion the bench still runs (CPU backend) and the JSON carries
"backend" + "error" so a degraded environment produces a parseable,
honestly-labeled line instead of rc=1.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import traceback

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_DOCS = int(os.environ.get("BENCH_DOCS", 32768))
DOC_LEN = int(os.environ.get("BENCH_DOC_LEN", 256))
REPEATS = int(os.environ.get("BENCH_REPEATS", 5))  # SAME for both sides
# 5 interleaved pairs: the tunneled link and the single-core host both
# jitter +-20-40% run to run (docs/SCALING.md "link variance"); the
# artifact ratio is the paired MEDIAN, and five samples make that
# median meaningfully sturdier than three for ~25 s of extra oracle
# time. Best-of fields keep min as the honest steady state.
RECALL_DOCS = int(os.environ.get("BENCH_RECALL_DOCS", 512))
PREFLIGHT_S = float(os.environ.get("BENCH_PREFLIGHT_S", 120))
N_WORDS = 8192
VOCAB = 1 << 16
TOPK = 16
# Device margin for the exact-terms mode: the chip keeps 4k candidate
# buckets so the exact-string re-rank can recover words whose bucket a
# collision partner pushed below rank k. 4x is the measured knee of the
# margin->recall curve (docs/EXACT.md: recall 1.0000 at 4x on this
# corpus; 0.9994 at the round-2 default of 2x).
MARGIN = 4 * TOPK


def log(msg: str) -> None:
    # Structured progress event: the stderr echo keeps the old
    # "print to stderr" behavior, and the ring keeps the last window
    # of progress for the flight recorder if the run dies (obs/log.py).
    from tfidf_tpu.obs import log as obs_log
    obs_log.log_event("info", "bench_progress", msg=msg)


def preflight_backend(retries: int = 2) -> str:
    """Probe jax's default backend in a subprocess with a hard timeout.

    The axon TPU tunnel has been observed to hang jax.devices() past
    90 s (VERDICT r1); a subprocess probe is killable, an in-process
    import is not. Returns the backend name the in-process import can
    expect ("tpu"/"cpu"/...), or "none" if every probe failed.
    """
    probe = "import jax; print(jax.default_backend())"

    def attempt_probe(tag: str, env) -> str:
        try:
            t0 = time.perf_counter()
            out = subprocess.run(
                [sys.executable, "-c", probe], capture_output=True,
                timeout=PREFLIGHT_S, text=True, env=env)
            lines = out.stdout.strip().splitlines() if out.stdout else []
            backend = lines[-1].strip() if lines else ""
            if out.returncode == 0 and backend:
                log(f"preflight[{tag}]: backend={backend} "
                    f"({time.perf_counter() - t0:.1f}s)")
                return backend
            log(f"preflight[{tag}] rc={out.returncode}: "
                f"{out.stderr.strip()[-300:]}")
        except subprocess.TimeoutExpired:
            log(f"preflight[{tag}]: timed out after {PREFLIGHT_S:.0f}s")
        return ""

    for attempt in range(retries + 1):
        backend = attempt_probe(str(attempt), None)
        if backend:
            return backend
    # Accelerator init hangs/fails: a CPU-only jax still measures the
    # pipeline (labeled degraded via the JSON "backend"/"error" fields).
    cpu_env = dict(os.environ, JAX_PLATFORMS="cpu")
    if attempt_probe("cpu-fallback", cpu_env) == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"  # in-process import follows suit
        return "cpu"
    return "none"


def make_corpus(root: str) -> str:
    rng = np.random.default_rng(42)
    words = np.array([f"w{i}".encode() for i in range(N_WORDS)], dtype=object)
    input_dir = os.path.join(root, "input")
    os.makedirs(input_dir)
    zipf = np.clip(rng.zipf(1.3, size=N_DOCS * DOC_LEN), 1, N_WORDS) - 1
    # Doc LENGTHS are Zipf-shaped too (round 6): the corpus always
    # called itself "Zipf" but drew lengths uniform in [L/2, L] — a
    # nearly-dense batch no real corpus resembles, which silently
    # understated the padded wire's padding tax (docs/SCALING.md
    # round-6 costing). length = L/z with z ~ Zipf(1.3): a quarter of
    # docs are full-length, the median is far below L, mean ~0.3 L —
    # the heavy-tailed shape 20-Newsgroups-style corpora actually have.
    # BENCH_LEN_DIST=uniform reproduces the round-5 protocol verbatim
    # for apples-to-apples reruns against BENCH_r05.json.
    if os.environ.get("BENCH_LEN_DIST", "zipf") == "uniform":
        lens = rng.integers(DOC_LEN // 2, DOC_LEN + 1, N_DOCS)
    else:
        lens = np.maximum(
            DOC_LEN // np.clip(rng.zipf(1.3, N_DOCS), 1, DOC_LEN), 1)
    off = 0
    for i in range(1, N_DOCS + 1):
        n = int(lens[i - 1])
        doc = b" ".join(words[zipf[off:off + n]])
        off += n
        with open(os.path.join(input_dir, f"doc{i}"), "wb") as f:
            f.write(doc)
    return input_dir


def native_once(input_dir: str, out: str) -> float:
    binary = os.path.join(REPO, "native", "tfidf_ref")
    if not os.path.exists(binary):
        built = subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                               capture_output=True, text=True)
        if built.returncode != 0:
            raise RuntimeError(f"native build failed:\n{built.stderr[-2000:]}")
    t0 = time.perf_counter()
    subprocess.run([binary, input_dir, out, "9"], check=True,
                   stdout=subprocess.DEVNULL)
    return time.perf_counter() - t0


def bench_tpu(input_dir: str):
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import (make_bytes_packer, make_flat_packer,
                                  run_overlapped, use_bytes_wire)
    from tfidf_tpu.io.corpus import discover_names

    # Overlapped chunked ingest on the row-sparse engine: the native
    # parallel loader packs chunk i+1 while the device runs chunk i
    # (async dispatch), DF folds into one device accumulator, and pass B
    # rescoreds each chunk against the corpus-wide IDF. Device memory is
    # O(chunk x L) — flat in corpus size. BENCH_WIRE selects the chunk
    # wire (ragged default; "bytes" ships raw UTF-8 and tokenizes on
    # device — round 14).
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                         max_doc_len=DOC_LEN, doc_chunk=DOC_LEN, topk=TOPK,
                         engine="sparse",
                         wire=os.environ.get("BENCH_WIRE", "ragged"))
    # ~4 chunks won the round-3 structure sweep (tools/ab probes): each
    # chunk pays ~8 ms of tunnel dispatch, and 4 chunks still pipeline
    # transfer+sort behind host packing.
    chunk = max(2048, N_DOCS // 4)

    # SERIALIZED host pack cost alone — one fenced pass over the corpus
    # with the exact packer run_overlapped uses (native loader or
    # Python fallback), nothing overlapped. This is the artifact's
    # `pack_serial_s` (and the perf_gate's `pack_s` metric); the
    # overlapped run's `phases.pack` is a DIFFERENT span — the stall
    # waiting on the double-buffered packer thread (round 14 named
    # them apart; docs/SCALING.md round 14).
    names = discover_names(input_dir, strict=True)
    pack_split = {}
    if use_bytes_wire(cfg, chunk, DOC_LEN):
        packer = make_bytes_packer(input_dir, cfg, chunk, DOC_LEN,
                                   stats=pack_split)
    else:
        packer = make_flat_packer(input_dir, cfg, chunk, DOC_LEN)
    t0 = time.perf_counter()
    for s in range(0, len(names), chunk):
        packer(names[s:s + chunk])
    pack_s = time.perf_counter() - t0

    # Untimed warmup compiles both phases at the chunk shape; the timed
    # runs re-ingest from raw bytes and hit the jit cache.
    result = run_overlapped(input_dir, cfg, chunk_docs=chunk,
                            doc_len=DOC_LEN)

    def tpu_once():
        t0 = time.perf_counter()
        r = run_overlapped(input_dir, cfg, chunk_docs=chunk,
                           doc_len=DOC_LEN)
        dt = time.perf_counter() - t0
        assert r.topk_vals.shape == (N_DOCS, TOPK)
        return dt, r

    return tpu_once, pack_s, pack_split, result, cfg, chunk


def _resolved_pack_threads(cfg) -> int:
    from tfidf_tpu.io.fast_tokenizer import resolve_pack_threads
    return resolve_pack_threads(getattr(cfg, "pack_threads", None))


def profile_phases(input_dir: str, cfg, chunk: int, result):
    """Serialized (fenced) per-phase costs: pack / upload / compute /
    fetch with no overlap — the honest answer to "where does the
    wall-clock go" (VERDICT r2 item 1). jit cache must be warm. Only
    valid in the resident regime: the profiler stages every chunk on
    device at once, which the streaming regime exists to avoid.

    Round 8: the profile runs TWICE — once with the run's resolved
    finish (scan by default) and once forced to the chunked per-chunk
    finish — so the artifact's ``dispatch`` object can quote both
    sides' fixed overhead (compute_warm − compute_marginal) from the
    same session. The chunked twin's first compute includes its
    per-chunk programs' compile; only its warm/marginal fields feed
    the dispatch comparison.

    Round 10: the overlapped run's phase seconds fold through ONE
    accumulator — ``PhaseTimer.add``, the same definition the CLI's
    ``--timing`` report uses and the same intervals the span tracer
    records (``utils.timing._TimedSpan``) — instead of a hand-copied
    dict, so the bench phases and a ``TFIDF_TPU_TRACE`` timeline of
    the same run cannot drift apart."""
    from tfidf_tpu.utils.timing import PhaseTimer

    timer = PhaseTimer()
    for name, secs in (result.phases or {}).items():
        timer.add(name, secs)
    phases = {n: s for n, s in timer.items()}
    if result.path == "resident":
        from tfidf_tpu.ingest import profile_resident

        def tpu_sample():
            return {k: round(v, 3)
                    for k, v in profile_resident(
                        input_dir, cfg, chunk_docs=chunk,
                        doc_len=DOC_LEN).items()}

        # Link weather (VERDICT weak-8): the tunneled link's transfer
        # cost varies with contention on the shared path — a single
        # gusty sample would file a storm as the steady state. When
        # the first sample's link tax (upload + fetch) exceeds the
        # threshold (env TFIDF_TPU_LINK_WEATHER_S, default 30 s —
        # roughly 3x the calm-window tax observed across committed
        # BENCH artifacts), the TPU side re-samples ONCE and the
        # calmer sample wins; the artifact records the window health
        # and retry count either way, so a bad-weather number is
        # labeled, not laundered.
        threshold_s = float(os.environ.get(
            "TFIDF_TPU_LINK_WEATHER_S", "30.0") or "30.0")
        ser = tpu_sample()
        taxes = [round(ser.get("upload", 0.0) + ser.get("fetch", 0.0),
                       3)]
        retries = 0
        if threshold_s > 0 and taxes[0] > threshold_s:
            retries = 1
            resampled = tpu_sample()
            taxes.append(round(resampled.get("upload", 0.0)
                               + resampled.get("fetch", 0.0), 3))
            if taxes[1] < taxes[0]:
                ser = resampled
        phases["serialized"] = ser
        phases["link_weather"] = {
            "threshold_s": threshold_s,
            "link_tax_s": min(taxes),
            "samples": taxes,
            "retries": retries,
            "healthy": int(min(taxes) <= threshold_s
                           or threshold_s <= 0),
        }
        prior = os.environ.get("TFIDF_TPU_FINISH")
        os.environ["TFIDF_TPU_FINISH"] = "chunked"
        try:
            phases["serialized_chunked"] = {
                k: round(v, 3)
                for k, v in profile_resident(
                    input_dir, cfg, chunk_docs=chunk,
                    doc_len=DOC_LEN).items()}
        finally:
            if prior is None:
                os.environ.pop("TFIDF_TPU_FINISH", None)
            else:
                os.environ["TFIDF_TPU_FINISH"] = prior
    return phases


# The compile-cache probe program: sort + searchsorted + top_k at a
# modest shape — the op mix of a phase-B program, big enough that its
# compile wall is measurable, small enough to stay a footnote in the
# bench budget. Runs in a SUBPROCESS pinned to JAX_PLATFORMS=cpu: a
# fresh process is the only honest cold-start, and the axon tunnel
# admits one client, so the probe must never touch the TPU backend.
_CACHE_PROBE = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, %(repo)r)
from tfidf_tpu.config import apply_compile_cache
if sys.argv[1] != "-":
    apply_compile_cache(sys.argv[1])
import jax, jax.numpy as jnp
def fn(x, lens):
    s = jnp.sort(x, axis=1)
    e = jnp.searchsorted(s.reshape(-1),
                         jnp.arange(4096, dtype=jnp.int32))
    v, i = jax.lax.top_k(jnp.where(x < lens[:, None], 1.0, 0.0), 16)
    return e.sum() + v.sum().astype(jnp.int32) + i.sum()
x = np.zeros((2048, 256), np.int32)
lens = np.zeros((2048,), np.int32)
t0 = time.perf_counter()
jax.jit(fn).lower(x, lens).compile()
print(json.dumps({"compile_s": round(time.perf_counter() - t0, 3)}))
"""


def measure_compile_cache(tmp: str):
    """Cold-vs-warm compile wall of the persistent XLA compilation
    cache (config.apply_compile_cache): three subprocess runs of the
    same probe program — no cache, cache cold (first fill), cache warm
    (hit on a fresh process). The warm/cold delta is what a CLI
    cold-start stops paying per program with --compile-cache set."""
    cache_dir = os.path.join(tmp, "compile_cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = {}
    for key, arg in (("no_cache_s", "-"), ("cache_cold_s", cache_dir),
                     ("cache_warm_s", cache_dir)):
        proc = subprocess.run(
            [sys.executable, "-c", _CACHE_PROBE % {"repo": REPO}, arg],
            capture_output=True, text=True, timeout=PREFLIGHT_S, env=env)
        if proc.returncode != 0:
            out["error"] = proc.stderr.strip()[-300:]
            return out
        out[key] = json.loads(proc.stdout.strip().splitlines()[-1])[
            "compile_s"]
    out["backend"] = "cpu"  # compile wall is host-side; tunnel untouched
    return out


def bench_exact(input_dir: str):
    """One timed end-to-end run of the exact-terms mode (what
    `cli run --exact-terms` does): device-exact intern ids when the
    corpus fits the vocab — collision-free selection, host float64
    rescore from wire integers, no corpus re-pass — else hashed margin
    + native re-rank. This is the apples-to-apples comparison against
    the CPU oracle, whose output is exact strings too.
    """
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.rerank import exact_terms_lines

    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                         max_doc_len=DOC_LEN, doc_chunk=DOC_LEN,
                         topk=MARGIN, engine="sparse")
    chunk = max(2048, N_DOCS // 4)
    exact_terms_lines(input_dir, cfg, k=TOPK, doc_len=DOC_LEN,
                      chunk_docs=chunk)  # warm (compiles the exact wire)
    best, engine, sample_fn = float("inf"), "?", None
    for _ in range(max(REPEATS, 1)):  # best-of-N, same N as other sides
        t0 = time.perf_counter()
        # The timed job is COMPLETE: ingest, float64 rescore, per-doc
        # and global sorts, reference-format output bytes — the same
        # work the CPU oracle's wall includes.
        lines, engine, sample_fn = exact_terms_lines(
            input_dir, cfg, k=TOPK, doc_len=DOC_LEN, chunk_docs=chunk)
        best = min(best, time.perf_counter() - t0)
    sample = [f"doc{i}" for i in range(1, min(RECALL_DOCS, N_DOCS) + 1)]
    return best, sample_fn(sample), engine


def measure_recall(result, reranked, oracle_out: str):
    """(bucket_recall, exact_recall) on the sampled docs.

    bucket_recall: collision-aware recall of the raw hashed top-k
    (the headline artifact). exact_recall: string-level recall of the
    exact-terms mode's output — the north star's "identical top-k
    terms", measured with no collision forgiveness.
    """
    import numpy as np

    from tfidf_tpu.recall import (corpus_recall, exact_doc_recall,
                                  parse_oracle_output)

    sample = [f"doc{i}" for i in range(1, min(RECALL_DOCS, N_DOCS) + 1)]
    per_doc = parse_oracle_output(oracle_out, docs=sample)
    bucket = corpus_recall(per_doc, result.names, result.topk_ids,
                           result.topk_vals, TOPK, VOCAB)
    scores = []
    for name, ref in per_doc.items():
        r = exact_doc_recall(ref, [w for w, _ in reranked[name]], TOPK)
        if r is not None:
            scores.append(r)
    return bucket, float(np.mean(scores))


def main() -> None:
    len_dist = os.environ.get("BENCH_LEN_DIST", "zipf")
    record = {
        "metric": f"docs/sec, {N_DOCS}-doc Zipf-word/{len_dist}-length "
                  f"corpus, hashed 2^16 "
                  f"vocab, top-{TOPK} (paired-run median vs 8-worker "
                  f"native CPU oracle)",
        "value": 0.0,
        "unit": "docs/sec",
        "vs_baseline": 0.0,
    }
    tmp = tempfile.mkdtemp(prefix="tfidf_bench_")
    try:
        backend = preflight_backend()
        record["backend"] = backend
        if backend == "none":
            record["error"] = ("jax backend init failed/hung in all "
                               "preflight attempts; no compute backend")
            return
        if backend != "tpu":
            record["error"] = f"TPU unavailable; measured on {backend}"

        # Host span timeline (TFIDF_TPU_TRACE): when armed, the timed
        # runs record onto one trace, the artifact carries its path,
        # and tools/trace_check.py can assert the overlap this JSON
        # line claims. Guarded on the env var so the degraded no-
        # backend paths never import tfidf_tpu just for a no-op.
        if os.environ.get("TFIDF_TPU_TRACE"):
            from tfidf_tpu import obs
            if obs.configure() is not None:
                record["trace_path"] = obs.trace_path()

        log(f"generating {N_DOCS}-doc corpus...")
        input_dir = make_corpus(tmp)
        oracle_out = os.path.join(tmp, "ref_out.txt")
        # Paired-run protocol (VERDICT r4 item 7): oracle and TPU runs
        # INTERLEAVED, one ratio per pair, so link/host jitter hits both
        # sides of each ratio sample alike. The artifact ratio is the
        # paired median with its IQR — prose can no longer quote a
        # better run than the artifact records.
        # Device-truth receipts (round 12): a CompileWatch counts every
        # XLA backend compile across the whole bench (warm + steady),
        # and a DeviceMonitor samples HBM peaks after the timed runs —
        # both land in the artifact so tools/perf_gate.py can hold
        # memory/compile regressions against the ledger the way it
        # already holds latency ones. On CPU memory_stats() is None
        # and the HBM keys are simply absent.
        from tfidf_tpu.obs import devmon as obs_devmon
        compile_watch = obs_devmon.CompileWatch()
        obs_devmon.set_watch(compile_watch)
        hbm_mon = obs_devmon.DeviceMonitor()
        log("warming TPU path (compile)...")
        tpu_once, pack_s, pack_split, result, cfg_tpu, chunk = \
            bench_tpu(input_dir)
        cpu_times, tpu_times, ratios = [], [], []
        for i in range(REPEATS):
            c = native_once(input_dir, oracle_out)
            t, r = tpu_once()
            if not tpu_times or t <= min(tpu_times):
                result = r
            cpu_times.append(c)
            tpu_times.append(t)
            ratios.append(c / t)
            log(f"  pair {i + 1}/{REPEATS}: cpu {c:.2f}s tpu {t:.2f}s "
                f"ratio {c / t:.2f}")
        cpu_s, tpu_s = min(cpu_times), min(tpu_times)
        hbm_mon.sample()   # peak covers warm-up + every timed run
        record["xla_compiles"] = compile_watch.compiles
        record["xla_compile_s"] = round(compile_watch.compile_seconds, 3)
        if hbm_mon.peak_bytes:
            record["peak_hbm_bytes"] = hbm_mon.peak_bytes
            record["memory_pressure"] = hbm_mon.memory_pressure
        phases = profile_phases(input_dir, cfg_tpu, chunk, result)
        log(f"paired median ratio {float(np.median(ratios)):.2f} "
            f"(pack-only {pack_s:.2f}s); exact mode...")
        exact_s, reranked, exact_engine = bench_exact(input_dir)
        log(f"exact-terms: {exact_s:.2f}s; recall...")
        recall, recall_exact = measure_recall(result, reranked, oracle_out)

        cpu_dps = N_DOCS / cpu_s
        tpu_dps = N_DOCS / tpu_s
        # The chip-ceiling numbers, first-class in the artifact
        # (VERDICT r3 item 2): the fenced serialized phases separate
        # what the DEVICE does (compute) from what the tunneled link
        # and 1-core host cost (pack/upload/fetch). device_docs_per_sec
        # is the measured per-chip rate behind docs/SCALING.md's
        # "50x story"; link_tax_s is the transfer cost the tunnel
        # imposes that PCIe/DMA hardware would not.
        ser = phases.get("serialized", {})
        weather = phases.pop("link_weather", None)
        if weather is not None:
            # Top-level so the ledger/doctor read window health and
            # the retry count without digging through phases.
            record["link_weather"] = weather
        if ser.get("compute"):
            dev_dps = N_DOCS / ser["compute"]
            record["device_docs_per_sec"] = round(dev_dps, 1)
            if ser.get("compute_marginal"):
                # Steady-state per-batch device rate (pipelined chain,
                # tunnel round trip amortized — ingest.profile_resident).
                record["device_docs_per_sec_marginal"] = round(
                    N_DOCS / ser["compute_marginal"], 1)
            record["link_tax_s"] = round(ser.get("upload", 0.0)
                                         + ser.get("fetch", 0.0), 3)
            # Attributed link columns (round 19): the aggregate
            # link_tax_s splits into the H2D staging wall (upload_s)
            # and the synchronizing D2H result round trip (sync_s), so
            # the ledger tracks the column the multi-process sharded
            # ingest attacks — not just the sum. link_utilization is
            # per-worker: the fraction of each link-owning process's
            # end-to-end wall spent driving its link (one entry here;
            # tools/ingest_mh_bench.py reports N under --workers N).
            up_s, sync_s = ser.get("upload", 0.0), ser.get("fetch", 0.0)
            record["upload_s"] = round(up_s, 3)
            record["sync_s"] = round(sync_s, 3)
            record["link"] = {
                "upload_s": round(up_s, 3),
                "sync_s": round(sync_s, 3),
                "n_workers": 1,
                "link_utilization": [
                    round(min(1.0, (up_s + sync_s) / tpu_s), 3)
                    if tpu_s > 0 else 0.0],
            }
            record["north_star_projection"] = {
                # measured: one chip's fenced compute vs the measured
                # 8-worker CPU oracle on this host
                "per_chip_device_ratio": round(dev_dps / cpu_dps, 1),
                # docs-axis mesh overhead measured ~1.0 on the 8-way
                # virtual mesh (tools/mesh_overhead.py): 8 chips of a
                # v4-8 project linearly; the oracle is generously
                # scaled 8x too (1 core here -> 8 real cores), so the
                # projected ratio equals the per-chip device ratio.
                "v4_8_device_docs_per_sec": round(8 * dev_dps, 1),
                "v4_8_ratio_vs_8core_oracle": round(dev_dps / cpu_dps, 1),
                "basis": "serialized.compute (fenced, warm); "
                         "docs/SCALING.md '50x story'",
            }
        # Wire accounting (round 6): actual host->device payload of the
        # overlapped run vs what the padded [D, L] format would have
        # shipped — the byte-level receipt for the ragged wire's upload
        # cut. wire_ratio < 1 means ragged beat padded on this corpus.
        if result.bytes_on_wire:
            record["wire"] = result.wire
            record["bytes_on_wire"] = int(result.bytes_on_wire)
            record["bytes_on_wire_padded"] = int(result.bytes_on_wire_padded)
            record["wire_ratio"] = round(
                result.bytes_on_wire / result.bytes_on_wire_padded, 3)
        # Bytes-wire pack split (round 14): the serialized pack measure
        # above decomposes into file reads (load_s) and slab assembly
        # (slab_s) — there is no tokenize/hash on the host at all.
        if pack_split:
            record["pack_split"] = {
                f"{k}_s": round(v, 3) for k, v in pack_split.items()}
        # Downlink accounting (round 7): actual device->host result
        # payload vs what the same selection costs as (int32 id,
        # float32 score) pairs. result_wire_ratio <= 0.55 means the
        # packed word wire carried the run.
        if result.bytes_off_wire:
            record["result_wire"] = result.result_wire
            record["bytes_off_wire"] = int(result.bytes_off_wire)
            record["bytes_off_wire_pair"] = int(result.bytes_off_wire_pair)
            record["result_wire_ratio"] = round(
                result.bytes_off_wire / result.bytes_off_wire_pair, 3)
        # Per-phase overlap efficiency: how much of the fenced
        # (serialized) phase wall the double-buffered pipeline hides.
        # pack_stall_s is the dispatch loop's only synchronous pack
        # cost (waiting on the packer thread); pack_hidden_frac is the
        # fraction of the packer thread's own wall that overlapped
        # staging/dispatch. overlap_efficiency compares the overlapped
        # end-to-end wall against the serialized phase sum.
        rph = result.phases or {}
        pack_host = float(rph.get("pack_host", 0.0))
        pack_stall = float(rph.get("pack", rph.get("pack_a", 0.0)))
        overlap = {
            "pack_stall_s": round(pack_stall, 3),
            "pack_host_s": round(pack_host, 3),
        }
        if pack_host > 0:
            overlap["pack_hidden_frac"] = round(
                max(0.0, 1.0 - pack_stall / pack_host), 3)
        ser_sum = sum(ser.get(k, 0.0)
                      for k in ("pack", "upload", "compute", "fetch"))
        if ser_sum > 0:
            overlap["serialized_sum_s"] = round(ser_sum, 3)
            overlap["overlap_efficiency"] = round(
                max(0.0, 1.0 - tpu_s / ser_sum), 3)
        record["overlap"] = overlap
        # Downlink overlap efficiency (round 7): fetch_stall_s is the
        # dispatch loop's only synchronous drain cost (waiting on the
        # _DrainAhead worker after the last chunk's scoring was
        # dispatched); fetch_host_s is the worker's own materialize+
        # unpack wall, which overlapped scoring; fetch_hidden_frac is
        # the fraction of the fenced serialized fetch the chunked
        # async drain hid behind phase-B compute.
        fetch_stall = float(rph.get("fetch", 0.0))
        downlink = {
            "fetch_stall_s": round(fetch_stall, 3),
            "fetch_host_s": round(float(rph.get("fetch_host", 0.0)), 3),
        }
        if "fetch" in ser:
            downlink["fetch_serialized_s"] = round(ser["fetch"], 3)
            if ser["fetch"] > 0:
                downlink["fetch_hidden_frac"] = round(
                    max(0.0, 1.0 - fetch_stall / ser["fetch"]), 3)
        if "fetch_warm" in ser:
            downlink["fetch_warm_s"] = round(ser["fetch_warm"], 3)
        record["downlink"] = downlink
        # Dispatch accounting (round 8): how much of warm phase-B
        # device time is FIXED per-dispatch launch/re-entry cost, per
        # finish structure. compute_fixed_s = compute_warm − n_chunks ·
        # (compute_marginal / n_chunks) = compute_warm −
        # compute_marginal: the chain-differenced marginal amortizes
        # the fixed cost away, so the difference IS the fixed overhead
        # the scanned one-dispatch finish exists to kill. The
        # compile_cache object is the cold-start receipt for
        # --compile-cache (subprocess probe, CPU backend).
        n_chunks = -(-N_DOCS // chunk)
        dispatch = {
            "finish": result.finish,
            "n_phase_b_dispatches": result.n_finish_dispatches,
            "n_chunks": n_chunks,
        }
        # the first profile carries the run's RESOLVED finish (scan
        # unless overridden); the second is the forced chunked twin
        for tag, key in ((result.finish or "scan", "serialized"),
                         ("chunked", "serialized_chunked")):
            s = phases.get(key, {})
            if s.get("compute_warm") and s.get("compute_marginal"):
                dispatch[tag] = {
                    "n_phase_b_dispatches": s.get("n_phase_b_dispatches"),
                    "compute_warm_s": s["compute_warm"],
                    "compute_marginal_s": s["compute_marginal"],
                    "compute_marginal_per_chunk_s": round(
                        s["compute_marginal"] / n_chunks, 4),
                    "compute_fixed_s": round(
                        max(0.0, s["compute_warm"] - s["compute_marginal"]),
                        3),
                }
        if "scan" in dispatch:
            dispatch["compute_fixed_s"] = dispatch["scan"][
                "compute_fixed_s"]
        try:
            dispatch["compile_cache"] = measure_compile_cache(tmp)
        except Exception as e:  # the probe is a footnote, never fatal
            dispatch["compile_cache"] = {"error": repr(e)[-300:]}
        record["dispatch"] = dispatch
        # THE artifact numbers: paired medians. Best-of fields keep the
        # old best-run semantics for continuity, explicitly labeled.
        med_ratio = float(np.median(ratios))
        q25, q75 = (float(np.percentile(ratios, 25)),
                    float(np.percentile(ratios, 75)))
        record.update(
            value=round(N_DOCS / float(np.median(tpu_times)), 1),
            vs_baseline=round(med_ratio, 2),
            vs_baseline_iqr=[round(q25, 2), round(q75, 2)],
            paired_ratios=[round(x, 2) for x in ratios],
            tpu_docs_per_sec_best=round(tpu_dps, 1),
            vs_baseline_best=round(tpu_dps / cpu_dps, 2),
            cpu_docs_per_sec=round(cpu_dps, 1),
            tpu_s=round(tpu_s, 3),
            cpu_s=round(cpu_s, 3),
            # pack_serial_s: the fenced one-pass host pack measure the
            # perf_gate tracks as `pack_s` (renamed round 14 — the old
            # top-level `pack_s` collided with `phases.pack`, which is
            # the overlapped run's packer-thread STALL, a different
            # span; BENCH_r05 showed 0.248 vs 0.369 for that reason,
            # not drift). perf_ledger reads pack_serial_s with a
            # pack_s fallback for pre-round-14 artifacts.
            pack_serial_s=round(pack_s, 3),
            # Resolved host packer thread count (the reference's
            # OpenMP knob, --pack-threads / TFIDF_TPU_PACK_THREADS).
            pack_threads=_resolved_pack_threads(cfg_tpu),
            recall_at_k=round(recall, 4),
            recall_exact_rerank=round(recall_exact, 4),
            exact_docs_per_sec=round(N_DOCS / exact_s, 1),
            exact_vs_baseline=round((N_DOCS / exact_s) / cpu_dps, 2),
            exact_engine=exact_engine,
            phases={k: (v if isinstance(v, dict) else round(v, 3))
                    for k, v in phases.items()},
            n_docs=N_DOCS,
            engine="sparse",
            ingest_path=result.path,  # reported by run_overlapped itself
            repeats=REPEATS,
        )
    except Exception:
        record["error"] = traceback.format_exc(limit=20)[-2000:]
    finally:
        if os.environ.get("TFIDF_TPU_TRACE"):
            try:  # write whatever spans the run recorded, even on error
                from tfidf_tpu import obs
                path = obs.export()
                if path:
                    log(f"trace written to {path}")
            except Exception:
                pass  # tracing must never break the artifact line
        shutil.rmtree(tmp, ignore_errors=True)
        print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
