"""End-to-end pipeline tests: golden byte-parity and properties."""

import math

import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline, discover_corpus
from tfidf_tpu.config import VocabMode
from tfidf_tpu.golden import golden_lines, golden_output
from tfidf_tpu.io.corpus import Corpus


def make_corpus(docs):
    return Corpus(names=[f"doc{i+1}" for i in range(len(docs))], docs=docs)


class TestGoldenOracle:
    def test_known_small_case(self):
        # 2 docs; "b" appears in both -> idf 0; "a" only in doc1.
        corpus = make_corpus([b"a b", b"b b"])
        lines = golden_lines(corpus)
        score_a = (1 / 2) * math.log(2 / 1)
        assert lines == sorted([
            b"doc1@a\t" + (b"%.16f" % score_a),
            b"doc1@b\t" + (b"%.16f" % 0.0),
            b"doc2@b\t" + (b"%.16f" % 0.0),
        ])

    def test_lexicographic_doc10_before_doc2(self):
        # strcmp ordering quirk (SURVEY §2.5-9).
        docs = [b"w"] * 10
        corpus = make_corpus(docs)
        lines = golden_lines(corpus)
        names = [l.split(b"@")[0] for l in lines]
        assert names.index(b"doc10") < names.index(b"doc2")


class TestPipelineGoldenParity:
    @pytest.mark.parametrize("cfg", [
        PipelineConfig.golden(),
        PipelineConfig(vocab_mode=VocabMode.EXACT, doc_chunk=8,
                       max_doc_len=8),  # force chunked path
    ])
    def test_exact_vocab_matches_golden_bytes(self, toy_corpus_dir, cfg):
        corpus = discover_corpus(toy_corpus_dir)
        result = TfidfPipeline(cfg).run(corpus)
        assert result.output_bytes() == golden_output(corpus)

    def test_mesh_padding_docs_do_not_change_output(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        pipe = TfidfPipeline(PipelineConfig.golden())
        batch = pipe.pack(corpus, pad_docs_to=8)
        assert batch.token_ids.shape[0] == 8
        result = pipe.run_packed(batch)
        assert result.output_bytes() == golden_output(corpus)

    def test_hashed_vocab_no_collisions_matches_golden(self, toy_corpus_dir):
        # With a huge hashed vocab and a tiny word set, collisions are
        # (with this seed) absent, so hashed output == golden output.
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=1 << 20)
        result = TfidfPipeline(cfg).run(corpus)
        assert result.output_bytes() == golden_output(corpus)


class TestPipelineProperties:
    def test_tf_row_sums_and_df_bounds(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        result = TfidfPipeline(PipelineConfig.golden()).run(corpus)
        d = result.num_docs
        assert (result.counts.sum(axis=1) == result.lengths[: d]).all()
        assert (result.df >= 0).all() and (result.df <= d).all()
        # every word with counts has df >= 1
        seen = (result.counts > 0).any(axis=0)
        assert (result.df[seen] >= 1).all()

    def test_topk_config(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.EXACT, topk=3)
        result = TfidfPipeline(cfg).run(corpus)
        assert result.topk_vals.shape[1] == 3
        # topk mode honors its contract: dense scores stay on device
        assert result.scores is None
        # top-1 per doc matches argmax of a dense run
        dense = TfidfPipeline(PipelineConfig(vocab_mode=VocabMode.EXACT)).run(corpus)
        assert (result.topk_ids[:, 0] == dense.scores.argmax(axis=1)).all()


class TestDiscovery:
    def test_strict_contract_missing_doc_raises(self, tmp_path):
        d = tmp_path / "input"
        d.mkdir()
        (d / "doc1").write_bytes(b"x")
        (d / "other").write_bytes(b"y")  # breaks doc<i> naming
        with pytest.raises(FileNotFoundError):
            discover_corpus(str(d))  # doc2 missing -> hard error (TFIDF.c:137)

    def test_strict_counts_subdirs_like_readdir(self, tmp_path):
        # The reference counts *every* readdir entry except '.'/'..' —
        # a stray subdir inflates numDocs (TFIDF.c:104-109) and the
        # derived name list then demands a doc<count> that may not exist.
        from tfidf_tpu.io.corpus import discover_names
        d = tmp_path / "input"
        d.mkdir()
        (d / "doc1").write_bytes(b"x")
        (d / "doc2").write_bytes(b"y")
        (d / "stray").mkdir()  # directory, not a file
        assert discover_names(str(d)) == ["doc1", "doc2", "doc3"]
        with pytest.raises((FileNotFoundError, IsADirectoryError)):
            discover_corpus(str(d))  # doc3 missing -> hard error

    def test_nonstrict_loads_any_files(self, tmp_path):
        d = tmp_path / "input"
        d.mkdir()
        (d / "b.txt").write_bytes(b"x")
        (d / "a.txt").write_bytes(b"y")
        c = discover_corpus(str(d), strict=False)
        assert c.names == ["a.txt", "b.txt"]


class TestEngineDefault:
    """Measured engine default (docs/ENGINES.md): sparse for hashed,
    dense for exact — and never silently dropping an explicit --pallas."""

    def test_hashed_defaults_sparse(self):
        from tfidf_tpu.config import PipelineConfig, VocabMode
        assert PipelineConfig(vocab_mode=VocabMode.HASHED).engine == "sparse"
        assert PipelineConfig(vocab_mode=VocabMode.EXACT).engine == "dense"

    def test_use_pallas_defaults_dense(self):
        from tfidf_tpu.config import PipelineConfig, VocabMode
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, use_pallas=True)
        assert cfg.engine == "dense"  # pallas is a dense-engine feature

    def test_explicit_engine_wins(self):
        from tfidf_tpu.config import PipelineConfig, VocabMode
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, engine="dense")
        assert cfg.engine == "dense" and not cfg._engine_defaulted
