"""Replicated serving tier: routing, the launcher, merged metrics,
the ledger/gate wiring, and (slow) a real 2-replica front with the
chaos rehearsal.

The reference serves one rank-partitioned corpus per MPI process
(``TFIDF.c:130``); the tier here is N full replica processes behind
one front — same process model (``launch_rank``), but every replica
holds the WHOLE index and visibility moves by two-phase epoch bumps.
The pinned invariants (docs/SERVING.md "Replicated tier"):

* no client observes a mixed epoch — in-flight queries drain onto the
  admitted epoch before any replica flips;
* a replica SIGKILLed between its prepare-ack and the commit leaves
  the tier on the OLD epoch everywhere (the swap aborts).
"""

import json
import os
import signal
import sys
import time

import numpy as np
import pytest

from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.parallel.multihost import MpiLiteComm, launch_rank
from tfidf_tpu.serve.front import (FrontError, ReplicatedFront,
                                   SwapAborted)


def _write_corpus(path, n_docs, seed, n_words=200, doc_len=30):
    """Strict-discovery corpus: doc1..docN, space-joined words."""
    rng = np.random.default_rng(seed)
    path.mkdir(parents=True, exist_ok=True)
    for i in range(1, n_docs + 1):
        words = [f"w{rng.integers(0, n_words)}"
                 for _ in range(doc_len)]
        (path / f"doc{i}").write_text(" ".join(words))
    return str(path)


def _cfg():
    return PipelineConfig(vocab_mode=VocabMode.HASHED,
                          vocab_size=4096, max_doc_len=64)


# ---------------------------------------------------------------------
# fast: config validation


def test_replicas_requires_snapshot_dir():
    with pytest.raises(ValueError, match="snapshot"):
        ServeConfig(replicas=2)


def test_replicas_env_roundtrip(monkeypatch):
    monkeypatch.setenv("TFIDF_TPU_REPLICAS", "3")
    monkeypatch.setenv("TFIDF_TPU_SNAPSHOT_DIR", "/tmp/x")
    cfg = ServeConfig.from_env()
    assert cfg.replicas == 3
    # The flag wins over the env, the ServeConfig pick contract.
    cfg = ServeConfig.from_env(replicas=2)
    assert cfg.replicas == 2


def test_front_rejects_no_replicas(tmp_path):
    with pytest.raises(ValueError, match="replicas"):
        ReplicatedFront(str(tmp_path), _cfg(),
                        ServeConfig(snapshot_dir=str(tmp_path / "s")))


# ---------------------------------------------------------------------
# fast: routing policy (no processes — the front's handle table is
# populated by hand)


def _unstarted_front(tmp_path, n=4):
    serve_cfg = ServeConfig(snapshot_dir=str(tmp_path / "snap"),
                            replicas=n)
    return ReplicatedFront(str(tmp_path), _cfg(), serve_cfg)


def test_pick_is_deterministic_and_cache_affine(tmp_path):
    front = _unstarted_front(tmp_path)
    try:
        for rep in front._replicas.values():
            rep.state = "live"
        picks = {q: front._pick(front._norm_for({"queries": [q]}))
                 for q in ("alpha beta", "gamma", "delta epsilon")}
        # Same query -> same replica, every time (cache affinity).
        for q, first in picks.items():
            for _ in range(5):
                assert front._pick(
                    front._norm_for({"queries": [q]})) == first
        # Normalization IS the routing key: whitespace variants of
        # one query land on one replica (one cache, one entry).
        assert front._pick(front._norm_for(
            {"queries": ["  alpha   beta "]})) == picks["alpha beta"]
    finally:
        front.close()


def test_pick_falls_back_off_dead_replica(tmp_path):
    front = _unstarted_front(tmp_path)
    try:
        for rep in front._replicas.values():
            rep.state = "live"
        q = {"queries": ["alpha beta"]}
        preferred = front._pick(front._norm_for(q))
        front._replicas[preferred].state = "dead"
        # Load the survivors unevenly; the fallback is least-loaded.
        live = [r for r, rp in front._replicas.items()
                if rp.state == "live"]
        for r in live:
            front._replicas[r].inflight = 5
        front._replicas[live[-1]].inflight = 0
        assert front._pick(front._norm_for(q)) == live[-1]
        # Degraded (failing healthz) is routed around the same way.
        front._replicas[preferred].state = "live"
        front._replicas[preferred].health = "failing"
        assert front._pick(front._norm_for(q)) != preferred
    finally:
        front.close()


def test_pick_no_live_replicas_raises(tmp_path):
    front = _unstarted_front(tmp_path)
    try:
        with pytest.raises(FrontError, match="no live"):
            front._pick(b"anything")
    finally:
        front.close()


# ---------------------------------------------------------------------
# fast: launch_rank — the process model the tier rides


def test_launch_rank_wires_mpi_lite_child():
    child_src = (
        "import json\n"
        "from tfidf_tpu.parallel.multihost import MpiLiteComm\n"
        "comm = MpiLiteComm.from_env()\n"
        "obj = json.loads(comm.recv(0, 7))\n"
        "comm.send(0, 8, json.dumps(\n"
        "    {'echo': obj, 'rank': comm.rank}).encode())\n"
        "comm.close()\n")
    fd, proc = launch_rank(1, 2, [sys.executable, "-c", child_src])
    comm = MpiLiteComm(0, 2, [-1, fd])
    try:
        comm.send(1, 7, json.dumps({"ping": 42}).encode())
        ack = json.loads(comm.recv(1, 8))
        assert ack == {"echo": {"ping": 42}, "rank": 1}
        assert proc.wait(timeout=30) == 0
    finally:
        comm.close()
        if proc.poll() is None:
            proc.kill()


# ---------------------------------------------------------------------
# fast: ledger + gate wiring for the replica artifact


def _replica_artifact(tmp_path, mixed=0):
    art = {
        "metric": "replica_bench", "backend": "cpu", "docs": 256,
        "k": 10, "requests": 16, "concurrency": 4, "host_cores": 1,
        "cpu_bound": 1, "n_replicas": 2,
        "replica": {"sweep": []},
        "throughput_qps": 400.0, "qps_1": 410.0,
        "qps_scaling_x": 0.97, "scaling_efficiency": 0.49,
        "latency_ms": {"p50": 20.0, "p99": 50.0, "max": 50.0},
        "parity_checked": 48, "parity_mismatches": 0, "parity_ok": 1,
        "mixed_epoch_responses": mixed,
        "recompiles_after_warmup": 0,
        "chaos": {"plan": "replica_prepare:fatal:n=1",
                  "swap_aborted": 1,
                  "old_epoch_everywhere_after_abort": 1,
                  "restarts": 1, "second_swap_epoch": 1,
                  "mixed_epoch_responses": mixed,
                  "parity_mismatches": 0},
    }
    p = tmp_path / "REPLICA_rX.json"
    p.write_text(json.dumps(art))
    return str(p)


def _tools():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "tools"))
    import perf_gate
    import perf_ledger
    return perf_ledger, perf_gate


def test_ledger_classifies_replica_artifact(tmp_path):
    perf_ledger, _ = _tools()
    rec, reason = perf_ledger.normalize(_replica_artifact(tmp_path))
    assert reason is None
    # The chaos block must NOT misfile it as a single-process chaos
    # run — replica_serve has its own comparability context.
    assert rec["kind"] == "replica_serve"
    assert rec["context"]["n_replicas"] == 2
    assert rec["context"]["host_cores"] == 1
    assert rec["metrics"]["mixed_epoch_responses"] == 0
    assert rec["metrics"]["chaos_old_epoch_everywhere"] == 1


def test_gate_zero_tolerates_mixed_epoch(tmp_path):
    perf_ledger, perf_gate = _tools()
    clean, _ = perf_ledger.normalize(_replica_artifact(tmp_path))
    leaked, _ = perf_ledger.normalize(
        _replica_artifact(tmp_path, mixed=1))
    verdict = perf_gate.gate(leaked, [clean])
    bad = {c["metric"] for c in verdict["checks"]
           if c["verdict"] == "REGRESSED"}
    assert "mixed_epoch_responses" in bad and not verdict["ok"]
    assert perf_gate.gate(clean, [clean])["ok"]


# ---------------------------------------------------------------------
# slow: the real tier — 2 replica processes, parity, merged metrics,
# and the kill-mid-swap chaos rehearsal (the ci_check.sh stage)


@pytest.mark.slow
def test_two_replica_front_end_to_end(tmp_path):
    input_dir = _write_corpus(tmp_path / "input", 12, seed=7)
    serve_cfg = ServeConfig(
        max_batch=8, cache_entries=256,
        snapshot_dir=str(tmp_path / "snap"), replicas=2,
        replica_timeout_s=240.0,
        faults="replica_prepare:fatal:n=1:match=replica=2 boot=0")
    front = ReplicatedFront(input_dir, _cfg(), serve_cfg, k=5)
    try:
        front.start()
        desc = front.describe()
        assert desc["live"] == 2 and front.epoch == 0

        # Parity: front-routed responses must match direct search.
        from tfidf_tpu.models.retrieval import TfidfRetriever
        oracle = TfidfRetriever(_cfg())
        oracle.index_dir(input_dir, strict=False)
        names = oracle.names

        def expect(qs, k=5):
            vals, ids = oracle.search(qs, k=k)
            return [[[names[int(d)], float(np.float32(v))]
                     for v, d in zip(vrow, irow) if d >= 0]
                    for vrow, irow in zip(vals, ids)]

        queries = ["w1 w2 w3", "w7", "w11 w5", "w2 w2 w9"]
        for q in queries:
            resp = front.query([q], k=5, use_cache=False)
            got = [[nm, float(np.float32(v))]
                   for nm, v in resp["results"][0]]
            assert got == expect([q])[0]
            assert resp["epoch"] == 0

        # Merged metrics: the two-live-replicas pin. The merged view
        # carries both replicas' registries under {process=...}
        # labels, and the merged counter is the SUM.
        snap = front.metrics_snapshot()
        assert set(snap["per_replica"]) == {"r1", "r2"}
        merged_reqs = snap["merged"]["serve_requests_total"]
        per = [s["registry"]["serve_requests_total"]
               for s in snap["per_replica"].values()]
        assert merged_reqs == sum(per) and merged_reqs >= len(queries)
        prom = front.metrics_prom()
        assert 'process="r1"' in prom and 'process="r2"' in prom
        assert "serve_front_routed_total" in prom

        # Chaos: replica 2's armed fault SIGKILLs it between its
        # prepare-ack and the commit. The swap must abort with every
        # surviving replica still on the OLD epoch.
        with pytest.raises(SwapAborted):
            front.swap_index(input_dir)
        assert front.epoch == 0
        for rep in front.describe()["replicas"].values():
            assert rep["epoch"] == 0

        # Queries keep flowing (re-routed off the dead replica) and
        # never observe an epoch the front has not committed.
        for q in queries:
            resp = front.query([q], k=5)
            assert "error" not in resp and resp["epoch"] == 0

        # Supervised restart: replica 2 comes back at boot 1 from the
        # shared snapshot; the retried swap then commits tier-wide.
        deadline = time.time() + 180
        while time.time() < deadline:
            d = front.describe()["replicas"]
            if all(r["state"] == "live" for r in d.values()) \
                    and d["2"]["boot"] >= 1:
                break
            time.sleep(0.25)
        d = front.describe()["replicas"]
        assert d["2"]["state"] == "live" and d["2"]["boot"] >= 1

        second = None
        for _ in range(5):
            try:
                second = front.swap_index(input_dir)
                break
            except SwapAborted:
                time.sleep(1.0)
        assert second == 1 and front.epoch == 1
        for rep in front.describe()["replicas"].values():
            assert rep["epoch"] == 1

        # Post-swap parity + epoch echo on the served responses.
        resp = front.query(queries[:2], k=5, use_cache=False)
        assert resp["epoch"] == 1
        want = expect(queries[:2])
        got = [[[nm, float(np.float32(v))] for nm, v in row]
               for row in resp["results"]]
        assert got == want

        # Zero steady-state recompiles, per replica.
        info = front.replica_info()
        assert all(v.get("recompiles_after_warm") == 0
                   for v in info.values())
    finally:
        front.close()
    # Idempotent close, and the tier really is gone.
    front.close()
    assert all(r.proc is None or r.proc.poll() is not None
               for r in front._replicas.values())
