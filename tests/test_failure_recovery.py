"""Failure injection: a streaming job SIGKILLed mid-corpus must resume
from its checkpoint and produce byte-identical output to a clean run.

The reference has no failure story at all — every error path is
``exit()`` and a lost rank hangs the barriers (SURVEY §5, failure row).
Here the crash window is real: a subprocess is killed with SIGKILL (no
atexit, no flush) partway through pass 1, and a fresh process must pick
up from the last committed checkpoint.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The victim streams with a per-batch sleep so the parent can kill it
# mid-corpus deterministically-enough: it prints BATCH after each
# checkpointed minibatch and the parent kills after seeing >= 2.
_VICTIM = r"""
import sys, time
import tfidf_tpu.streaming as streaming
from tfidf_tpu import checkpoint as ckpt
from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus, discover_names
import os

input_dir, ck = sys.argv[1], sys.argv[2]
cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=256,
                     topk=3, max_doc_len=32, doc_chunk=32)
stream = streaming.StreamingTfidf(cfg)
names = discover_names(input_dir, strict=True)
start = 0
if ckpt.exists(ck):
    stream.load_state(ckpt.restore_state(ck))
    start = stream.docs_seen
for lo in range(start, len(names), 8):
    docs = []
    for n in names[lo:lo + 8]:
        with open(os.path.join(input_dir, n), "rb") as f:
            docs.append(f.read())
    stream.update(stream.pack(Corpus(names=names[lo:lo + 8], docs=docs),
                              fixed_len=32))
    ckpt.save_state(ck, stream.state_dict(), force_npz=True)
    print("BATCH", stream.docs_seen, flush=True)
    time.sleep(0.3)
print("DONE", stream.docs_seen, flush=True)
"""


@pytest.fixture()
def stream_corpus(tmp_path):
    ind = tmp_path / "input"
    ind.mkdir()
    rng = np.random.default_rng(3)
    for i in range(1, 41):
        (ind / f"doc{i}").write_text(
            " ".join(f"w{rng.integers(0, 40)}" for _ in range(12)))
    return str(ind)


def _run_victim(input_dir, ck, kill_after_batches=None, timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _VICTIM, input_dir, ck],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True, env=env)
    seen = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        seen.append(line.strip())
        if line.startswith("DONE"):
            break
        if (kill_after_batches is not None
                and len([s for s in seen if s.startswith("BATCH")])
                >= kill_after_batches):
            proc.send_signal(signal.SIGKILL)  # no cleanup, no flush
            break
    proc.wait(timeout=30)
    return proc.returncode, seen


class TestCrashResume:
    def test_sigkill_mid_stream_resumes_identically(self, stream_corpus,
                                                    tmp_path):
        ck_crash = str(tmp_path / "ck_crash")
        ck_clean = str(tmp_path / "ck_clean")

        # Clean run: 40 docs in 5 batches, DF state checkpointed at end.
        rc, seen = _run_victim(stream_corpus, ck_clean)
        assert rc == 0 and seen[-1] == "DONE 40", seen

        # Crashed run: SIGKILL after the 2nd committed batch.
        rc, seen = _run_victim(stream_corpus, ck_crash, kill_after_batches=2)
        assert rc == -signal.SIGKILL, (rc, seen)
        assert seen[-1].startswith("BATCH"), seen

        # The checkpoint left behind must be committed and restorable.
        from tfidf_tpu import checkpoint as ckpt
        state = ckpt.restore_state(ck_crash)
        assert 0 < int(state["docs_seen"]) < 40

        # Resume in a fresh process: finishes the stream...
        rc, seen = _run_victim(stream_corpus, ck_crash)
        assert rc == 0 and seen[-1] == "DONE 40", seen

        # ...and the final DF state equals the never-crashed run's.
        a = ckpt.restore_state(ck_crash)
        b = ckpt.restore_state(ck_clean)
        assert set(a) == set(b)
        for key in a:
            np.testing.assert_array_equal(a[key], b[key], err_msg=key)
