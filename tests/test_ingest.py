"""Overlapped chunked ingest (tfidf_tpu/ingest.py) vs the single-batch
pipeline: same DF, same top-k scores, on both the native and Python
pack paths."""

import os

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, discover_corpus
from tfidf_tpu.config import VocabMode
from tfidf_tpu.ingest import run_overlapped
from tfidf_tpu.io.corpus import pack_corpus
from tfidf_tpu.pipeline import TfidfPipeline


@pytest.fixture
def corpus_dir(tmp_path):
    rng = np.random.default_rng(11)
    for i in range(1, 41):
        words = [f"w{rng.integers(0, 60)}" for _ in range(int(rng.integers(1, 40)))]
        (tmp_path / f"doc{i}").write_text(" ".join(words))
    return str(tmp_path)


def _cfg(**kw):
    base = dict(vocab_mode=VocabMode.HASHED, vocab_size=1 << 10,
                max_doc_len=64, doc_chunk=64, topk=5, engine="sparse")
    base.update(kw)
    return PipelineConfig(**base)


@pytest.fixture(params=["resident", "streaming", "streaming-cached"])
def ingest_path(request, monkeypatch):
    """Run the test under the run_overlapped regimes: the fused
    resident path (default at test sizes), the pure two-pass streaming
    path (resident threshold zeroed, triple cache zeroed), and
    streaming with the device triple cache (pass B scores pass A's
    resident triples — the round-4 default)."""
    if request.param.startswith("streaming"):
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        monkeypatch.setenv(
            "TFIDF_TPU_TRIPLE_CACHE_BYTES",
            "0" if request.param == "streaming" else str(4 << 30))
    return request.param


class TestOverlappedIngest:
    def test_matches_single_batch(self, corpus_dir, ingest_path):
        cfg = _cfg()
        ref = TfidfPipeline(cfg).run_packed(
            pack_corpus(discover_corpus(corpus_dir), cfg, want_words=False))
        got = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        assert got.num_docs == 40
        assert (got.df == ref.df).all()
        # both paths ship full float32 scores (the round-2 bf16 wire
        # compaction is gone — the link is latency-bound)
        np.testing.assert_allclose(got.topk_vals, ref.topk_vals, rtol=1e-6)
        assert (got.lengths == ref.lengths[:40]).all()

    def test_score_dtype_rides_the_wire(self, corpus_dir, ingest_path):
        # A non-default score_dtype must come back in that dtype on BOTH
        # regimes — the resident wire ships scores in score_dtype itself
        # (round-3 review finding: an f32-only wire silently downcast
        # wider runs). The dtype is JAX-canonicalized: float64 computes
        # as float64 only under jax_enable_x64, so pin against what the
        # reference pipeline actually produced.
        import jax
        cfg = _cfg(score_dtype="float64")
        got = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        ref = TfidfPipeline(cfg).run_packed(
            pack_corpus(discover_corpus(corpus_dir), cfg, want_words=False))
        want = jax.dtypes.canonicalize_dtype(np.float64)
        assert got.topk_vals.dtype == want
        assert np.asarray(ref.topk_vals).dtype == want
        np.testing.assert_allclose(got.topk_vals, ref.topk_vals, rtol=1e-6)

    def test_single_chunk_covers_all(self, corpus_dir, ingest_path):
        cfg = _cfg()
        a = run_overlapped(corpus_dir, cfg, chunk_docs=64, doc_len=64)
        b = run_overlapped(corpus_dir, cfg, chunk_docs=7, doc_len=64)
        assert (a.df == b.df).all()
        rtol = 5e-3 if ingest_path == "resident" else 1e-6
        np.testing.assert_allclose(a.topk_vals, b.topk_vals, rtol=rtol)

    def test_python_fallback_matches_native(self, corpus_dir):
        import tfidf_tpu.io.fast_tokenizer as ft

        if not ft.loader_available():
            pytest.skip("native loader not built")  # else both runs = python
        cfg = _cfg()
        native = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        os.environ["TFIDF_TPU_NO_NATIVE"] = "1"
        try:
            ft._load_failed, ft._lib, ft._has_loader = False, None, False
            python = run_overlapped(corpus_dir, cfg, chunk_docs=16,
                                    doc_len=64)
        finally:
            del os.environ["TFIDF_TPU_NO_NATIVE"]
            ft._load_failed, ft._lib, ft._has_loader = False, None, False
        assert (native.df == python.df).all()
        np.testing.assert_allclose(native.topk_vals, python.topk_vals,
                                   rtol=1e-6)

    def test_truncation_is_explicit(self, tmp_path):
        (tmp_path / "doc1").write_text(" ".join(["a"] * 100))
        cfg = _cfg(topk=1)
        got = run_overlapped(str(tmp_path), cfg, chunk_docs=4, doc_len=16)
        assert got.lengths[0] == 16  # truncated to the static L

    def test_requires_hashed_and_topk(self, corpus_dir):
        with pytest.raises(ValueError):
            run_overlapped(corpus_dir, _cfg(vocab_mode=VocabMode.EXACT))
        with pytest.raises(ValueError):
            run_overlapped(corpus_dir, _cfg(topk=None))
        with pytest.raises(ValueError):
            run_overlapped(corpus_dir, _cfg(), spill="bogus")

    def test_spill_modes_agree(self, corpus_dir, monkeypatch):
        # Spill only matters on the streaming path with the triple
        # cache off (cached chunks never touch the spill store).
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        cfg = _cfg()
        host = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                              spill="host")
        reread = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                                spill="reread")
        assert (host.df == reread.df).all()
        np.testing.assert_array_equal(host.topk_vals, reread.topk_vals)
        np.testing.assert_array_equal(host.topk_ids, reread.topk_ids)

    def test_compile_flat_in_chunk_count(self, corpus_dir, monkeypatch):
        """More chunks must not mean more compiled programs: both phases
        are one executable each, keyed only on the [chunk, L] shape."""
        from tfidf_tpu import ingest as mod

        if not hasattr(mod._phase_a, "_cache_size"):
            pytest.skip("jit cache-size introspection unavailable")
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")  # streaming
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        cfg = _cfg()
        run_overlapped(corpus_dir, cfg, chunk_docs=8, doc_len=64)  # 5 chunks
        a0 = mod._phase_a._cache_size()
        b0 = mod._phase_b._cache_size()
        run_overlapped(corpus_dir, cfg, chunk_docs=2, doc_len=64)  # 20 chunks
        # One new entry per phase at most (the new [2, L] chunk shape).
        assert mod._phase_a._cache_size() <= a0 + 1
        assert mod._phase_b._cache_size() <= b0 + 1


class TestTripleCache:
    """Round 4 (VERDICT r3 item 5): pass-A triples stay device-resident
    up to TFIDF_TPU_TRIPLE_CACHE_BYTES; pass B re-sorts nothing for
    cached chunks. Values must not depend on how many chunks fit."""

    def test_partial_cache_equals_uncached(self, corpus_dir, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        cfg = _cfg()
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        plain = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        assert plain.phases["triple_cached_chunks"] == 0
        # Budget for exactly one 16x64 chunk (9 B/slot + 4 B/len):
        # chunk 1 rides the cache, chunks 2-3 take the two-pass flow.
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES",
                           str(16 * 64 * 9 + 16 * 4))
        partial = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        assert partial.phases["triple_cached_chunks"] == 1
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", str(1 << 30))
        full = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        assert full.phases["triple_cached_chunks"] == 3
        for got in (partial, full):
            np.testing.assert_array_equal(plain.df, got.df)
            np.testing.assert_array_equal(plain.topk_ids, got.topk_ids)
            np.testing.assert_allclose(plain.topk_vals, got.topk_vals,
                                       rtol=1e-6)

    def test_cache_skips_host_spill_copy(self, corpus_dir, monkeypatch):
        # A triple-cached chunk must not also hold a spill="host" copy
        # (the cache replaces the host RAM cost, not adds to it) — and
        # the spill modes must still agree when only SOME chunks cache.
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES",
                           str(16 * 64 * 9 + 16 * 4))
        cfg = _cfg()
        host = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                              spill="host")
        reread = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                                spill="reread")
        np.testing.assert_array_equal(host.topk_ids, reread.topk_ids)
        np.testing.assert_array_equal(np.asarray(host.df),
                                      np.asarray(reread.df))


class TestResidentFusedPath:
    def test_resident_equals_streaming(self, tmp_path, monkeypatch):
        # The fused resident path (chunked async uploads + one sorted
        # program) must equal the forced two-pass streaming pipeline
        # exactly — including with multiple chunks, where only the final
        # chunk carries padding rows.
        ind = tmp_path / "input"
        ind.mkdir()
        rng = np.random.default_rng(11)
        for i in range(1, 25):
            (ind / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 64)}"
                         for _ in range(rng.integers(3, 30))))
        cfg = _cfg(vocab_size=256, max_doc_len=32, doc_chunk=32, topk=4)
        for chunk_docs in (64, 8):  # single-chunk and multi-chunk concat
            fused = run_overlapped(str(ind), cfg, chunk_docs=chunk_docs,
                                   doc_len=32)
            monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
            streamed = run_overlapped(str(ind), cfg, chunk_docs=chunk_docs,
                                      doc_len=32)
            monkeypatch.delenv("TFIDF_TPU_RESIDENT_ELEMS")
            np.testing.assert_array_equal(fused.df, streamed.df)
            np.testing.assert_allclose(fused.topk_vals, streamed.topk_vals,
                                       rtol=1e-6)
            assert (fused.topk_ids == streamed.topk_ids).all()
            assert fused.names == streamed.names
            np.testing.assert_array_equal(fused.lengths, streamed.lengths)


class TestFlatPacker:
    def test_native_matches_python_flat(self, corpus_dir, monkeypatch):
        # The ragged wire's two producers (native loader_fill_flat_u16
        # and the Python mask-flatten fallback) must emit identical
        # streams — they feed the same compiled program.
        from tfidf_tpu.ingest import make_flat_packer
        from tfidf_tpu.io import fast_tokenizer
        from tfidf_tpu.io.corpus import discover_names
        if not fast_tokenizer.flat_available():
            pytest.skip("native flat packer unavailable")
        cfg = _cfg()
        names = discover_names(corpus_dir, strict=True)
        nat = make_flat_packer(corpus_dir, cfg, 16, 64)(names[:13])
        monkeypatch.setenv("TFIDF_TPU_NO_NATIVE", "1")
        py = make_flat_packer(corpus_dir, cfg, 16, 64)(names[:13])
        assert nat[2] == py[2]  # total live ids
        np.testing.assert_array_equal(nat[1], py[1])  # lengths (padded)
        np.testing.assert_array_equal(nat[0][:nat[2]], py[0][:py[2]])

    def test_ids_only_wire_matches(self, corpus_dir):
        # wire_vals=False (exact-terms fetch diet): vals None, same ids
        # except invalid slots read bucket 0 instead of -1 (harmless by
        # construction for the rerank — see _score_pack_wire).
        cfg = _cfg()
        full = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        slim = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                              wire_vals=False)
        assert slim.topk_vals is None
        np.testing.assert_array_equal(np.maximum(full.topk_ids, 0),
                                      slim.topk_ids)
        # and the exact rerank is insensitive to the difference
        from tfidf_tpu.rerank import exact_topk
        a = exact_topk(corpus_dir, full.names, full.topk_ids,
                       full.num_docs, cfg, k=3, max_tokens=64)
        b = exact_topk(corpus_dir, slim.names, slim.topk_ids,
                       slim.num_docs, cfg, k=3, max_tokens=64)
        assert a == b

    def test_all_empty_chunk(self, tmp_path):
        # A chunk of only whitespace/empty docs yields a zero-length
        # flat stream; the wire must pad to >= one bucket or the device
        # gather fails at trace time (round-3 review finding).
        for i in range(1, 9):
            (tmp_path / f"doc{i}").write_bytes(b"  \n ")
        (tmp_path / "doc9").write_bytes(b"alpha beta")
        cfg = _cfg()
        got = run_overlapped(str(tmp_path), cfg, chunk_docs=4, doc_len=64)
        assert got.num_docs == 9
        assert (got.topk_ids[:8] == -1).all()
        assert (got.topk_ids[8] >= 0).any()

    def test_wide_vocab_uses_padded_wire(self, corpus_dir, ingest_path):
        # vocab > 2^16 cannot ride the uint16 flat wire: the resident
        # regime falls back to the padded int32 chunk kernel
        # (_chunk_sort_fold) and the streaming regime to the padded
        # two-pass kernels (_phase_a/_phase_b) — both must match the
        # single-batch reference.
        cfg = _cfg(vocab_size=1 << 17)
        got = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        assert got.path == ingest_path.split("-")[0]  # regime, not cache
        ref = TfidfPipeline(cfg).run_packed(
            pack_corpus(discover_corpus(corpus_dir), cfg, want_words=False))
        np.testing.assert_array_equal(np.asarray(got.df), ref.df)
        np.testing.assert_array_equal(got.topk_ids, ref.topk_ids)
        np.testing.assert_allclose(got.topk_vals, ref.topk_vals, rtol=1e-6)


class TestProfilerCacheSharing:
    def test_profiler_adds_no_compiles(self, corpus_dir, monkeypatch):
        # profile_resident must dispatch the EXACT programs production
        # compiled — a second cache entry for the final program cost
        # ~104 s of silent XLA recompile per bench run before the
        # shared call sites (_chunk_step/_finish_wire) fixed it.
        import tfidf_tpu.ingest as ing
        if not hasattr(ing._score_pack_wire, "_cache_size"):
            pytest.skip("jax jit cache introspection unavailable")
        # Pin the resident regime: an inherited TFIDF_TPU_RESIDENT_ELEMS
        # would route run_overlapped to streaming and fail spuriously.
        monkeypatch.delenv("TFIDF_TPU_RESIDENT_ELEMS", raising=False)
        cfg = _cfg()
        ing.run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        before = (ing._score_pack_wire._cache_size(),
                  ing._chunk_ragged._cache_size())
        ing.profile_resident(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        after = (ing._score_pack_wire._cache_size(),
                 ing._chunk_ragged._cache_size())
        assert after == before, "profiler compiled new programs"


class TestPathReporting:
    def test_result_reports_regime(self, corpus_dir, monkeypatch):
        cfg = _cfg()
        assert run_overlapped(corpus_dir, cfg, chunk_docs=16,
                              doc_len=64).path == "resident"
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        assert run_overlapped(corpus_dir, cfg, chunk_docs=16,
                              doc_len=64).path == "streaming"


class TestMeshIngest:
    """Docs-sharded overlapped ingest (VERDICT r3 item 1): the flagship
    perf path composed with the multi-chip mesh. Value contract: the
    sharded run equals the single-device resident run exactly."""

    def _plan(self, docs=4):
        import jax

        from tfidf_tpu.parallel.mesh import MeshPlan
        return MeshPlan.create(docs=docs, devices=jax.devices()[:docs])

    def test_matches_single_device(self, corpus_dir):
        cfg = _cfg()
        single = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        mesh = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                              plan=self._plan())
        assert mesh.path == "resident-mesh"
        np.testing.assert_array_equal(np.asarray(single.df),
                                      np.asarray(mesh.df))
        np.testing.assert_array_equal(single.topk_ids, mesh.topk_ids)
        np.testing.assert_allclose(single.topk_vals, mesh.topk_vals,
                                   rtol=1e-6)
        np.testing.assert_array_equal(single.lengths, mesh.lengths)

    def test_uneven_chunks_and_shards(self, corpus_dir):
        # 40 docs, chunk 13 -> chunk rounds up to a shard multiple and
        # the tail chunk carries padding rows on every shard.
        cfg = _cfg()
        single = run_overlapped(corpus_dir, cfg, chunk_docs=13, doc_len=64)
        mesh = run_overlapped(corpus_dir, cfg, chunk_docs=13, doc_len=64,
                              plan=self._plan(8))
        np.testing.assert_array_equal(single.topk_ids, mesh.topk_ids)
        np.testing.assert_allclose(single.topk_vals, mesh.topk_vals,
                                   rtol=1e-6)

    def test_ids_only_wire(self, corpus_dir):
        # wire_vals=False on the mesh path: vals stay on device (None),
        # ids match the full fetch and keep -1 in invalid slots.
        cfg = _cfg()
        full = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                              plan=self._plan())
        diet = run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64,
                              plan=self._plan(), wire_vals=False)
        assert diet.topk_vals is None
        np.testing.assert_array_equal(full.topk_ids, diet.topk_ids)

    def test_docs_axis_only(self, corpus_dir):
        import jax

        from tfidf_tpu.parallel.mesh import MeshPlan
        plan = MeshPlan.create(docs=2, vocab=2,
                               devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="docs axis only"):
            run_overlapped(corpus_dir, _cfg(), chunk_docs=16, doc_len=64,
                           plan=plan)

    def test_resident_budget_scales_with_shards(self, corpus_dir,
                                                monkeypatch):
        # Per-shard HBM holds corpus/S: a corpus over the single-chip
        # budget but under S x budget rides the resident path; over
        # S x budget the docs-sharded STREAMING regime takes over
        # (round 4: the mesh composition covers both regimes).
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "1024")
        plan = self._plan(4)  # 40 docs x 64 = 2560 elems <= 4 x 1024
        mesh = run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                              doc_len=64, plan=plan)
        assert mesh.path == "resident-mesh"
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "256")
        streamed = run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                  doc_len=64, plan=plan)
        assert streamed.path == "streaming-mesh"
        np.testing.assert_array_equal(np.asarray(mesh.df),
                                      np.asarray(streamed.df))
        np.testing.assert_array_equal(mesh.topk_ids, streamed.topk_ids)
        np.testing.assert_allclose(mesh.topk_vals, streamed.topk_vals,
                                   rtol=1e-6)

    def test_streaming_mesh_matches_single_streaming(self, corpus_dir,
                                                     monkeypatch):
        # The docs-sharded streaming regime == single-device streaming
        # on the same corpus, with and without the triple cache.
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        for cache in ("0", str(1 << 30)):
            monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", cache)
            single = run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                    doc_len=64)
            mesh = run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                  doc_len=64, plan=self._plan())
            assert single.path == "streaming"
            assert mesh.path == "streaming-mesh"
            want_cached = 0 if cache == "0" else 3
            assert mesh.phases["triple_cached_chunks"] == want_cached
            np.testing.assert_array_equal(np.asarray(single.df),
                                          np.asarray(mesh.df))
            np.testing.assert_array_equal(single.topk_ids, mesh.topk_ids)
            np.testing.assert_allclose(single.topk_vals, mesh.topk_vals,
                                       rtol=1e-6)
            np.testing.assert_array_equal(single.lengths, mesh.lengths)

    def test_chunk_int32_guard(self, corpus_dir):
        with pytest.raises(ValueError, match="int32"):
            run_overlapped(corpus_dir, _cfg(), chunk_docs=1 << 22,
                           doc_len=1 << 10)


class TestOccupancyWire:
    def test_df_occupied_matches_df(self, corpus_dir, ingest_path):
        # The 4-byte wire tail (margin_check's feed) must equal the
        # true occupied-bucket count of the DF vector on every regime.
        got = run_overlapped(corpus_dir, _cfg(), chunk_docs=16, doc_len=64)
        assert got.df_occupied == int((np.asarray(got.df) > 0).sum())

    def test_df_occupied_on_mesh(self, corpus_dir):
        import jax

        from tfidf_tpu.parallel.mesh import MeshPlan
        plan = MeshPlan.create(docs=4, devices=jax.devices()[:4])
        for wire_vals in (True, False):
            got = run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64, plan=plan,
                                 wire_vals=wire_vals)
            assert got.df_occupied == int((np.asarray(got.df) > 0).sum())


class TestAlignedWire:
    """Granule-aligned flat wire (round 5): the device rebuild gathers
    [L/G]-granule rows instead of per-id scalars (67.5 ms -> ~4 ms per
    32k chunk on the real chip, tools/trace_capture.py)."""

    def test_granule_decode_matches_scalar_decode(self):
        import numpy as np
        from tfidf_tpu.ingest import _ragged_to_padded
        rng = np.random.default_rng(0)
        g, length = 8, 20  # length NOT a multiple of g on purpose
        lens = np.array([20, 7, 0, 13, 1], np.int32)
        # Build both layouts from the same docs.
        docs = [rng.integers(1, 60000, n).astype(np.uint16) for n in lens]
        flat1 = np.concatenate([d for d in docs if d.size] or
                               [np.zeros(1, np.uint16)])
        parts = []
        for d in docs:
            al = -(-d.size // g) * g if d.size else 0
            parts.append(np.pad(d, (0, al - d.size)))
        flatg = np.concatenate([p for p in parts if p.size] or
                               [np.zeros(g, np.uint16)])
        flatg = np.pad(flatg, (0, (-flatg.size) % g))
        tok1 = np.asarray(_ragged_to_padded(flat1, lens, length, 1))
        tokg = np.asarray(_ragged_to_padded(flatg, lens, length, g))
        mask = np.arange(length)[None, :] < lens[:, None]
        np.testing.assert_array_equal(np.where(mask, tok1, -1),
                                      np.where(mask, tokg, -1))

    def test_native_and_python_packers_agree_on_layout(self, tmp_path):
        import numpy as np
        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.ingest import make_flat_packer, _WIRE_ALIGN
        from tfidf_tpu.io import fast_tokenizer as ft
        if not ft.flat_available():
            import pytest
            pytest.skip("native flat packer not built")
        d = tmp_path / "input"
        d.mkdir()
        rng = np.random.default_rng(1)
        names = []
        for i in range(1, 8):
            (d / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 500)}"
                         for _ in range(rng.integers(1, 40))))
            names.append(f"doc{i}")
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=4096)
        native = make_flat_packer(str(d), cfg, 8, 32)(names)
        # Force the Python fallback by pretending native is absent.
        import unittest.mock as mock
        with mock.patch.object(ft, "flat_available", lambda: False):
            fallback = make_flat_packer(str(d), cfg, 8, 32)(names)
        nf, nl, nt = native
        pf, pl, pt = fallback
        assert nt == pt  # identical aligned totals
        np.testing.assert_array_equal(nl, pl)
        np.testing.assert_array_equal(nf[:nt], pf[:pt])
        if _WIRE_ALIGN > 1:
            assert nt % _WIRE_ALIGN == 0


def test_score_pack_wire_sortjoin_value_parity(tmp_path, monkeypatch):
    # The resident finish program's sort-join lowering (TPU default)
    # must produce the identical wire as the gather join — run the
    # whole overlapped ingest both ways on the same corpus.
    import numpy as np
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import run_overlapped
    d = tmp_path / "input"
    d.mkdir()
    rng = np.random.default_rng(7)
    for i in range(1, 40):
        (d / f"doc{i}").write_text(
            " ".join(f"w{rng.integers(0, 300)}"
                     for _ in range(rng.integers(1, 50))))
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=4096,
                         topk=5, engine="sparse")
    monkeypatch.setenv("TFIDF_TPU_JOIN", "gather")
    r_g = run_overlapped(str(d), cfg, chunk_docs=16, doc_len=64)
    monkeypatch.setenv("TFIDF_TPU_JOIN", "sort")
    r_s = run_overlapped(str(d), cfg, chunk_docs=16, doc_len=64)
    np.testing.assert_array_equal(r_g.topk_ids, r_s.topk_ids)
    np.testing.assert_array_equal(r_g.topk_vals, r_s.topk_vals)
    np.testing.assert_array_equal(np.asarray(r_g.df), np.asarray(r_s.df))
