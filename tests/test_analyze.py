"""The static-analysis suite (tools/analyze) — checker units on
planted fixtures, baseline mechanics, and the tier-1 gate that the
real repo analyzes clean against the committed baseline.

The fixture tests build throwaway mini-repos (a ``tfidf_tpu/`` dir
with one planted hazard each) and assert the checker both FIRES on
the planted violation and stays quiet on the adjacent correct idiom —
every lint here is only as good as its negative cases. The drift
demonstrations copy the real repo, delete one docs/CONFIG.md row /
rename one span label, and watch the gate fail — the acceptance
contract of docs/ANALYSIS.md.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analyze import contracts, jax_lints, run, threads  # noqa: E402
from tools.analyze.core import Baseline, Finding, Tree  # noqa: E402


def mini_tree(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return Tree(str(tmp_path))


def codes(findings):
    return sorted({f.code for f in findings})


# --- J001: use-after-donate ------------------------------------------

_DONOR = """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update(buf, x):
        return buf + x
"""


class TestUseAfterDonate:
    def test_planted_use_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": _DONOR + """
    def step(buf, x):
        out = update(buf, x)
        return buf.sum() + out      # buf's memory was donated
"""})
        finds = jax_lints.check(tree)
        assert [f.code for f in finds] == ["J001"]
        assert finds[0].symbol == "step:buf"

    def test_rebind_and_return_are_clean(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": _DONOR + """
    def ok_rebind(buf, x):
        buf = update(buf, x)        # result rebinds the name
        return buf

    def ok_return(buf, x):
        return update(buf, x)       # control leaves the scope

    def ok_branches(buf, x, flag):
        if flag:
            return update(buf, x)
        return buf * 2              # other branch: never donated
"""})
        assert jax_lints.check(tree) == []

    def test_closure_params_do_not_leak_scope(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": _DONOR + """
    def outer(buf, x):
        def inner(buf):
            return update(buf, x)
        return inner(buf) + inner(buf)   # outer buf never donated
"""})
        assert jax_lints.check(tree) == []

    def test_donate_argnames_kwarg(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    import functools
    import jax

    @functools.partial(jax.jit, donate_argnames=("buf",))
    def update(x, buf=None):
        return buf + x

    def step(buf, x):
        out = update(x, buf=buf)
        print(buf)
        return out
"""})
        finds = jax_lints.check(tree)
        assert [f.code for f in finds] == ["J001"]


# --- J002: host sync inside a device-hot span ------------------------

class TestHostSyncInSpan:
    def test_asarray_in_device_span_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    import numpy as np
    from tfidf_tpu import obs

    def go(x):
        with obs.device_span("phase_b", chunk=0):
            y = np.asarray(x)        # forces a host sync mid-span
        return y
"""})
        finds = jax_lints.check(tree)
        assert [f.code for f in finds] == ["J002"]
        assert "np.asarray" in finds[0].symbol

    def test_item_and_float_fire(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    from tfidf_tpu import obs

    def go(x):
        with obs.span("dispatch", chunk=0):
            a = x.item()
            b = float(x)
        return a + b
"""})
        assert len(jax_lints.check(tree)) == 2

    def test_host_side_spans_are_exempt(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    import numpy as np
    from tfidf_tpu import obs

    def go(x):
        with obs.span("fetch", bytes=8):
            y = np.asarray(x)        # fetch IS the sync — by design
        with obs.span("drain", chunk=0):
            z = np.asarray(x)
        return y, z
"""})
        assert jax_lints.check(tree) == []


# --- J003: traced control flow ---------------------------------------

class TestTracedControlFlow:
    def test_branch_on_traced_param_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("k",))
    def f(x, k):
        if x > 0:
            return x * k
        return -x
"""})
        finds = jax_lints.check(tree)
        assert [f.code for f in finds] == ["J003"]
        assert finds[0].symbol == "f:x"

    def test_static_shape_and_none_tests_are_clean(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("k", "topk"))
    def f(x, k, topk=None):
        if k > 2:                    # static: branch is fine
            x = x * 2
        if topk is None:             # identity test: fine
            return x
        if x.shape[0] > 4:           # shape metadata: fine
            return x[:4]
        while len(x.shape) < 3:
            x = x[None]
        return x
"""})
        assert jax_lints.check(tree) == []


# --- T001: unlocked cross-thread writes ------------------------------

_THREADED = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while True:
                {worker_write}

        def bump(self):
            {main_write}
"""


class TestThreadDiscipline:
    def test_unlocked_two_domain_write_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": _THREADED.format(
            worker_write="self.count += 1",
            main_write="self.count += 1")})
        finds = threads.check(tree)
        assert [f.code for f in finds] == ["T001"]
        assert finds[0].symbol == "Worker.count"

    def test_locked_writes_are_clean(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": _THREADED.format(
            worker_write="self._bump()",
            main_write="self._bump()") + """
        def _bump(self):
            with self._lock:
                self.count += 1
"""})
        assert threads.check(tree) == []

    def test_callsite_lock_inference(self, tmp_path):
        # the _pop_batch idiom: the helper holds no lock itself, but
        # every call site does
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": _THREADED.format(
            worker_write="self._locked_bump()",
            main_write="self._locked_bump()") + """
        def _locked_bump(self):
            with self._lock:
                self._bump()

        def _bump(self):
            self.count += 1
"""})
        assert threads.check(tree) == []

    def test_single_domain_write_is_clean(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": _THREADED.format(
            worker_write="self.count += 1",
            main_write="pass")})
        assert threads.check(tree) == []

    def test_executor_submit_opens_a_domain(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    import concurrent.futures as cf

    class Pool:
        def __init__(self):
            self._ex = cf.ThreadPoolExecutor(max_workers=1)
            self.done = 0

        def kick(self):
            def job():
                self.done += 1       # worker domain
            self._ex.submit(job)

        def reset(self):
            self.done = 0            # main domain
"""})
        finds = threads.check(tree)
        assert [f.code for f in finds] == ["T001"]
        assert finds[0].symbol == "Pool.done"

    def test_no_thread_no_findings(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    class Plain:
        def __init__(self):
            self.n = 0

        def a(self):
            self.n += 1

        def b(self):
            self.n -= 1
"""})
        assert threads.check(tree) == []


# --- contract gates on planted drift ---------------------------------

_CONFIG_MD = """
    # knobs
    | Variable | Default | Bounds | Touch it when |
    |---|---|---|---|
    | `TFIDF_TPU_DOCUMENTED` | `1` | a knob | never |
"""


class TestContractGates:
    def test_undocumented_knob_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {
            "docs/CONFIG.md": _CONFIG_MD,
            "tfidf_tpu/m.py": """
    import os
    A = os.environ.get("TFIDF_TPU_DOCUMENTED")
    B = os.environ.get("TFIDF_TPU_PHANTOM_KNOB")
"""})
        finds = contracts.check_knobs(tree)
        assert [(f.code, f.symbol) for f in finds] == [
            ("C001", "TFIDF_TPU_PHANTOM_KNOB")]

    def test_stale_doc_row_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {
            "docs/CONFIG.md": _CONFIG_MD,
            "tfidf_tpu/m.py": "X = 1\n"})
        finds = contracts.check_knobs(tree)
        assert [(f.code, f.symbol) for f in finds] == [
            ("C002", "TFIDF_TPU_DOCUMENTED")]

    def test_undeclared_span_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    from tfidf_tpu import obs

    def go():
        with obs.span("zorp"):
            pass
"""})
        finds = contracts.check_spans(tree)
        assert [(f.code, f.symbol) for f in finds] == [("C005", "zorp")]

    def test_declared_span_is_clean(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    from tfidf_tpu import obs

    def go():
        with obs.span("dispatch", chunk=0):
            pass
"""})
        assert contracts.check_spans(tree) == []

    def test_unconsulted_seam_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {
            "tfidf_tpu/faults.py": 'SEAMS = ("swap", "drain")\n',
            "tfidf_tpu/m.py": """
    from tfidf_tpu import faults

    def go(worker):
        faults.fire("swap" if worker else "drain")
"""})
        assert contracts.check_seams(tree) == []
        tree2 = mini_tree(tmp_path / "b", {
            "tfidf_tpu/faults.py": 'SEAMS = ("swap", "ghost_seam")\n',
            "tfidf_tpu/m.py": """
    from tfidf_tpu import faults

    def go():
        faults.fire("swap")
"""})
        finds = contracts.check_seams(tree2)
        assert [(f.code, f.symbol) for f in finds] == [
            ("C009", "ghost_seam")]

    def test_undeclared_seam_at_fire_site_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {
            "tfidf_tpu/faults.py": 'SEAMS = ("swap",)\n',
            "tfidf_tpu/m.py": """
    from tfidf_tpu import faults

    def go():
        faults.fire("not_a_seam")
"""})
        assert ("C010", "not_a_seam") in [
            (f.code, f.symbol) for f in contracts.check_seams(tree)]

    def test_undeclared_flight_event_fires(self, tmp_path):
        tree = mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    from tfidf_tpu.obs import log as obs_log

    def go():
        obs_log.log_event("info", "zorp_event", msg="hi")
"""})
        finds = contracts.check_flight_events(tree)
        assert [(f.code, f.symbol) for f in finds] == [
            ("C012", "zorp_event")]


# --- baseline mechanics ----------------------------------------------

class TestBaseline:
    def test_roundtrip_and_split(self, tmp_path):
        f1 = Finding("J001", "a.py", 3, "f:x", "msg")
        f2 = Finding("C001", "b.py", 9, "TFIDF_TPU_Z", "msg")
        b = Baseline({f1.key: "known issue"})
        new, suppressed, stale = b.split([f1, f2])
        assert [f.key for f in new] == [f2.key]
        assert [f.key for f in suppressed] == [f1.key]
        assert stale == []
        path = str(tmp_path / "baseline.json")
        b.entries["ghost:key"] = "gone"
        b.save(path)
        b2 = Baseline.load(path)
        assert b2.entries == b.entries
        _, _, stale = b2.split([f1])
        assert stale == ["ghost:key"]

    def test_baseline_requires_justification(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps(
            {"version": 1, "entries": [{"key": "a:b:c"}]}))
        with pytest.raises(ValueError, match="justification"):
            Baseline.load(str(path))

    def test_run_suppresses_via_baseline(self, tmp_path):
        mini_tree(tmp_path, {"tfidf_tpu/m.py": """
    from tfidf_tpu import obs

    def go():
        with obs.span("zorp"):
            pass
"""})
        report = run(root=str(tmp_path), checkers=["contracts"])
        assert not report["ok"]
        keys = [f["key"] for f in report["findings"]]
        assert any(":zorp" in k for k in keys)
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"version": 1, "entries": [
            {"key": k, "justification": "test"} for k in keys]}))
        report = run(root=str(tmp_path), checkers=["contracts"],
                     baseline_path=str(bl))
        assert report["ok"]
        assert len(report["suppressed"]) == len(keys)


# --- the real repo ---------------------------------------------------

class TestRepoGate:
    def test_repo_analyzes_clean_against_committed_baseline(self):
        report = run(root=REPO)
        assert report["ok"], (
            "new static-analysis findings:\n" + "\n".join(
                f"  {f['code']} {f['path']}:{f['line']} {f['message']}"
                for f in report["findings"]))
        assert report["stale_baseline"] == [], (
            "baseline entries that no longer fire — delete them: "
            f"{report['stale_baseline']}")

    def test_runner_exit_codes(self, tmp_path):
        # clean repo -> 0; a planted violation -> 1 (the CI contract)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze"],
            capture_output=True, cwd=REPO)
        assert proc.returncode == 0, proc.stdout.decode()[-2000:]
        mini_tree(tmp_path, {
            "docs/CONFIG.md": _CONFIG_MD,
            "tfidf_tpu/m.py": """
    import os
    from tfidf_tpu import obs

    B = os.environ.get("TFIDF_TPU_PHANTOM_KNOB")

    def go():
        with obs.span("zorp"):
            pass
"""})
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", "--root",
             str(tmp_path), "--json"],
            capture_output=True, cwd=REPO)
        assert proc.returncode == 1
        report = json.loads(proc.stdout.decode())
        got = {f["code"] for f in report["findings"]}
        assert {"C001", "C005"} <= got


def _copy_repo(tmp_path):
    """The contract-gate surface of the real repo (no tests/native/
    artifacts), cheap enough to copy per drift demonstration."""
    dst = tmp_path / "repo"
    dst.mkdir()
    for d in ("tfidf_tpu", "tools", "docs"):
        shutil.copytree(
            os.path.join(REPO, d), dst / d,
            ignore=shutil.ignore_patterns("__pycache__", "*.so"))
    shutil.copy(os.path.join(REPO, "bench.py"), dst / "bench.py")
    return dst


class TestDriftDemonstrations:
    def test_deleting_a_config_row_fails_the_gate(self, tmp_path):
        dst = _copy_repo(tmp_path)
        cfg = dst / "docs" / "CONFIG.md"
        lines = [ln for ln in cfg.read_text().splitlines()
                 if not ln.startswith("| `TFIDF_TPU_FETCH_AHEAD`")]
        cfg.write_text("\n".join(lines) + "\n")
        report = run(root=str(dst), checkers=["contracts"])
        assert not report["ok"]
        assert ("C001", "TFIDF_TPU_FETCH_AHEAD") in [
            (f["code"], f["symbol"]) for f in report["findings"]]

    def test_renaming_a_span_label_fails_the_gate(self, tmp_path):
        dst = _copy_repo(tmp_path)
        ing = dst / "tfidf_tpu" / "ingest.py"
        ing.write_text(ing.read_text().replace('"dispatch"',
                                               '"dispatchx"'))
        report = run(root=str(dst), checkers=["contracts"])
        assert not report["ok"]
        pairs = [(f["code"], f["symbol"]) for f in report["findings"]]
        assert ("C005", "dispatchx") in pairs      # undeclared label
        assert ("C006", "dispatch") in pairs       # doctor went dark
