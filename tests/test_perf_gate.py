"""Perf ledger + gate (ISSUE 6): the bench trajectory record and the
regression tripwire over it.

Pins: the backfill ingests the repo's archived BENCH_r0X/SERVE_r0X
artifacts (idempotently, schema-versioned, skipping the rc=1 round-1
crash artifact), the gate flags an injected 2x latency regression
against that ledger, and — the false-positive floor — passes the same
artifact re-run unchanged.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    # perf_gate does `import perf_ledger`; make the sibling visible
    # under its plain name first.
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


perf_ledger = _load_tool("perf_ledger")
perf_gate = _load_tool("perf_gate")


@pytest.fixture
def ledger(tmp_path):
    """A tmp ledger backfilled from the repo's archived artifacts."""
    path = str(tmp_path / "LEDGER.jsonl")
    appended, skipped = perf_ledger.append(
        perf_ledger.backfill_paths(), path, quiet=True)
    assert appended >= 3   # r02-r05 bench + SERVE_r01 at minimum
    return path


class TestLedger:
    def test_backfill_contents_and_schema(self, ledger):
        records = perf_ledger.load_ledger(ledger)
        assert all(r["schema"] == perf_ledger.SCHEMA for r in records)
        kinds = {r["source"]: r["kind"] for r in records}
        assert kinds.get("SERVE_r01.json") == "serve_bench"
        assert kinds.get("BENCH_r05.json") == "bench"
        # The rc=1 round-1 crash artifact carries no measurements.
        assert "BENCH_r01.json" not in kinds
        r05 = next(r for r in records
                   if r["source"] == "BENCH_r05.json")
        assert r05["metrics"]["docs_per_sec"] == 31273.1
        assert r05["context"]["n_docs"] == 32768
        assert "captured_at" in r05

    def test_backfill_is_idempotent(self, ledger):
        before = perf_ledger.load_ledger(ledger)
        appended, skipped = perf_ledger.append(
            perf_ledger.backfill_paths(), ledger, quiet=True)
        assert appended == 0 and skipped == len(before) + 1  # +r01
        assert perf_ledger.load_ledger(ledger) == before

    def test_changed_metrics_append_as_new_record(self, ledger,
                                                  tmp_path):
        doc = json.load(open(os.path.join(REPO, "SERVE_r01.json")))
        doc["throughput_qps"] *= 1.1
        fresh = tmp_path / "SERVE_r01.json"  # same source NAME
        fresh.write_text(json.dumps(doc))
        appended, _ = perf_ledger.append([str(fresh)], ledger,
                                         quiet=True)
        assert appended == 1  # dedup is by content, not filename

    def test_schema_mismatch_refuses_to_load(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        path.write_text(json.dumps({"schema": 99, "kind": "bench"})
                        + "\n")
        with pytest.raises(ValueError, match="schema"):
            perf_ledger.load_ledger(str(path))

    def test_wrapped_and_bare_artifacts_normalize_identically(
            self, tmp_path):
        wrapped = json.load(open(os.path.join(REPO, "BENCH_r05.json")))
        bare = tmp_path / "bare.json"
        bare.write_text(json.dumps(wrapped["parsed"]))
        rec_w, _ = perf_ledger.normalize(
            os.path.join(REPO, "BENCH_r05.json"))
        rec_b, _ = perf_ledger.normalize(str(bare))
        assert rec_w["metrics"] == rec_b["metrics"]
        assert rec_w["context"] == rec_b["context"]


class TestGate:
    def test_unchanged_artifact_passes(self, ledger):
        for source in ("SERVE_r01.json", "BENCH_r05.json"):
            cand, _ = perf_ledger.normalize(os.path.join(REPO, source))
            verdict = perf_gate.gate(
                cand, perf_ledger.load_ledger(ledger))
            assert verdict["ok"], (source, verdict)
            assert verdict["baseline_runs"] >= 1

    def test_flags_2x_latency_regression(self, ledger, tmp_path):
        # 2x the rolling BASELINE (the median over every comparable
        # serve round — SERVE_r01 + SERVE_r02 as of round 19), so the
        # test stays valid as the ledger accumulates rounds.
        import statistics
        records = perf_ledger.load_ledger(ledger)
        doc = json.load(open(os.path.join(REPO, "SERVE_r01.json")))
        base = [r for r in records if r["kind"] == "serve_bench"]
        for pct in ("p50", "p99"):
            med = statistics.median(r["metrics"][f"{pct}_ms"]
                                    for r in base
                                    if f"{pct}_ms" in r["metrics"])
            doc["latency_ms"][pct] = med * 2
        bad = tmp_path / "regressed.json"
        bad.write_text(json.dumps(doc))
        cand, _ = perf_ledger.normalize(str(bad))
        verdict = perf_gate.gate(cand, perf_ledger.load_ledger(ledger))
        assert not verdict["ok"]
        regressed = {c["metric"] for c in verdict["checks"]
                     if c["verdict"] == "REGRESSED"}
        assert {"p50_ms", "p99_ms"} <= regressed

    def test_flags_halved_bench_throughput(self, ledger, tmp_path):
        doc = json.load(open(os.path.join(REPO, "BENCH_r05.json")))
        doc["parsed"]["value"] /= 2
        doc["parsed"]["vs_baseline"] /= 2
        bad = tmp_path / "slow_bench.json"
        bad.write_text(json.dumps(doc))
        cand, _ = perf_ledger.normalize(str(bad))
        verdict = perf_gate.gate(cand, perf_ledger.load_ledger(ledger))
        assert not verdict["ok"]
        assert any(c["metric"] == "docs_per_sec"
                   and c["verdict"] == "REGRESSED"
                   for c in verdict["checks"])

    def test_recompiles_gate_is_absolute(self, ledger, tmp_path):
        doc = json.load(open(os.path.join(REPO, "SERVE_r01.json")))
        doc["recompiles_after_warmup"] = 3
        bad = tmp_path / "recompiling.json"
        bad.write_text(json.dumps(doc))
        cand, _ = perf_ledger.normalize(str(bad))
        verdict = perf_gate.gate(cand, perf_ledger.load_ledger(ledger))
        assert any(c["metric"] == "recompiles_after_warmup"
                   and c["verdict"] == "REGRESSED"
                   for c in verdict["checks"])

    def test_incomparable_context_means_no_baseline(self, ledger,
                                                    tmp_path):
        doc = json.load(open(os.path.join(REPO, "SERVE_r01.json")))
        doc["docs"] = 999_999          # different corpus size
        other = tmp_path / "other_shape.json"
        other.write_text(json.dumps(doc))
        cand, _ = perf_ledger.normalize(str(other))
        verdict = perf_gate.gate(cand, perf_ledger.load_ledger(ledger))
        assert verdict["baseline_runs"] == 0
        assert all(c["verdict"] == "skipped"
                   for c in verdict["checks"])

    def test_mutate_artifact_classifies_and_gates(self, tmp_path):
        """The --mutate artifact (ISSUE 12) is its own ledger kind:
        parity and the zero-recompile pin gate absolutely; lag/pause
        percentiles gate directionally."""
        doc = {
            "metric": "serve_bench", "mode": "mutate",
            "backend": "cpu", "docs": 300, "k": 10, "requests": 64,
            "max_batch": 64, "throughput_qps": 4000.0,
            "latency_ms": {"p50": 4.0, "p99": 30.0},
            "recompiles_after_warmup": 0,
            "mutate": {
                "rate": 50.0, "ops": 24, "mutation_qps": 100.0,
                "delta_docs": 16, "compact_at": 2,
                "visibility_lag_ms": {"p50": 2.0, "p99": 6.0,
                                      "max": 8.0},
                "compaction": {"count": 1,
                               "pause_ms": {"p50": 1.0, "p99": 2.0,
                                            "max": 2.0},
                               "compactor_restarts": 0,
                               "compactor_dead": 0},
                "xla_recompiles_after_warm": 0, "parity_ok": 1,
            },
        }
        good = tmp_path / "MUTATE_r01.json"
        good.write_text(json.dumps(doc))
        cand, _ = perf_ledger.normalize(str(good))
        assert cand["kind"] == "mutate"
        assert cand["metrics"]["parity_ok"] == 1
        assert cand["metrics"]["visibility_lag_p99_ms"] == 6.0
        assert cand["context"]["delta_docs"] == 16
        ledger = str(tmp_path / "L.jsonl")
        perf_ledger.append([str(good)], ledger, quiet=True)
        # unchanged re-run passes by construction
        verdict = perf_gate.gate(cand, perf_ledger.load_ledger(ledger))
        assert verdict["ok"] and verdict["baseline_runs"] == 1
        # a parity break or a steady-state recompile is zero-tolerance
        doc["mutate"]["parity_ok"] = 0
        doc["recompiles_after_warmup"] = 2
        bad = tmp_path / "MUTATE_bad.json"
        bad.write_text(json.dumps(doc))
        cand_bad, _ = perf_ledger.normalize(str(bad))
        verdict = perf_gate.gate(cand_bad,
                                 perf_ledger.load_ledger(ledger))
        regressed = {c["metric"] for c in verdict["checks"]
                     if c["verdict"] == "REGRESSED"}
        assert {"parity_ok", "recompiles_after_warmup"} <= regressed
        assert not verdict["ok"]

    def test_ingest_mh_artifact_classifies_and_gates(self, tmp_path):
        """The multi-process sharded ingest artifact (round 19) is its
        own ledger kind: parity zero-tolerance, upload wall lower-is-
        better, n_workers comparability context."""
        doc = {
            "metric": "ingest_mh", "backend": "cpu", "n_docs": 32768,
            "doc_len": 256, "chunk_docs": 8192, "n_workers": 2,
            "wire": "ragged", "parity_ok": 1,
            "upload_s": 0.5, "upload_s_1p": 1.0, "upload_ratio": 0.5,
            "speedup_vs_1p": 2.0, "wall_s": 4.0, "wall_s_1p": 7.0,
            "link_utilization": [0.2, 0.21],
        }
        good = tmp_path / "INGEST_MH_t.json"
        good.write_text(json.dumps(doc))
        cand, reason = perf_ledger.normalize(str(good))
        assert reason is None and cand["kind"] == "ingest_mh"
        assert cand["metrics"]["upload_s"] == 0.5
        assert cand["context"]["n_workers"] == 2
        ledger = str(tmp_path / "L.jsonl")
        perf_ledger.append([str(good)], ledger, quiet=True)
        verdict = perf_gate.gate(cand, perf_ledger.load_ledger(ledger))
        assert verdict["ok"] and verdict["baseline_runs"] == 1
        # parity flip = zero-tolerance fail; 2x upload wall = fail
        doc["parity_ok"] = 0
        doc["upload_s"] = 1.1
        bad = tmp_path / "INGEST_MH_bad.json"
        bad.write_text(json.dumps(doc))
        cand_bad, _ = perf_ledger.normalize(str(bad))
        verdict = perf_gate.gate(cand_bad,
                                 perf_ledger.load_ledger(ledger))
        regressed = {c["metric"] for c in verdict["checks"]
                     if c["verdict"] == "REGRESSED"}
        assert {"parity_ok", "upload_s"} <= regressed
        assert not verdict["ok"]
        # a 4-worker run is a DIFFERENT protocol: no baseline match
        doc["n_workers"] = 4
        other = tmp_path / "INGEST_MH_4w.json"
        other.write_text(json.dumps(doc))
        cand4, _ = perf_ledger.normalize(str(other))
        verdict = perf_gate.gate(cand4, perf_ledger.load_ledger(ledger))
        assert verdict["baseline_runs"] == 0

    def test_serve_slab_receipts_gate(self, tmp_path):
        """--ab-slab receipts (round 19): slab parity zero-tolerance;
        allocs/batch must stay 0 (absolute zero-baseline rule) and
        h2d copies/batch must stay 1."""
        doc = {
            "metric": "serve_bench", "mode": "closed", "backend": "cpu",
            "docs": 4096, "k": 10, "requests": 512, "max_batch": 64,
            "throughput_qps": 3000.0, "throughput_rps": 1200.0,
            "latency_ms": {"p50": 0.03, "p99": 100.0},
            "recompiles_after_warmup": 0,
            "slab": {"parity_ok": 1, "allocs_per_batch": 0.0,
                     "h2d_copies_per_batch": 1.0, "batches": 100},
        }
        good = tmp_path / "SERVE_slab.json"
        good.write_text(json.dumps(doc))
        cand, _ = perf_ledger.normalize(str(good))
        assert cand["kind"] == "serve_bench"
        assert cand["metrics"]["slab_allocs_per_batch"] == 0.0
        assert cand["metrics"]["slab_h2d_per_batch"] == 1.0
        ledger = str(tmp_path / "L2.jsonl")
        perf_ledger.append([str(good)], ledger, quiet=True)
        verdict = perf_gate.gate(cand, perf_ledger.load_ledger(ledger))
        assert verdict["ok"]
        doc["slab"] = {"parity_ok": 0, "allocs_per_batch": 0.5,
                       "h2d_copies_per_batch": 2.0, "batches": 100}
        bad = tmp_path / "SERVE_slab_bad.json"
        bad.write_text(json.dumps(doc))
        cand_bad, _ = perf_ledger.normalize(str(bad))
        verdict = perf_gate.gate(cand_bad,
                                 perf_ledger.load_ledger(ledger))
        regressed = {c["metric"] for c in verdict["checks"]
                     if c["verdict"] == "REGRESSED"}
        assert {"slab_parity_ok", "slab_allocs_per_batch",
                "slab_h2d_per_batch"} <= regressed

    def test_bench_link_columns_map_and_gate(self, tmp_path):
        """bench.py's round-19 link split: upload_s/sync_s ride the
        ledger and gate lower-is-better, separately from link_tax_s."""
        doc = {
            "metric": "m", "unit": "docs/sec", "value": 1000.0,
            "vs_baseline": 4.0, "backend": "cpu", "n_docs": 32768,
            "engine": "sparse", "wire": "ragged",
            "link_tax_s": 1.0,
            "link": {"upload_s": 0.4, "sync_s": 0.6, "n_workers": 1,
                     "link_utilization": [0.3]},
        }
        good = tmp_path / "BENCH_link.json"
        good.write_text(json.dumps(doc))
        cand, _ = perf_ledger.normalize(str(good))
        assert cand["kind"] == "bench"
        assert cand["metrics"]["upload_s"] == 0.4
        assert cand["metrics"]["sync_s"] == 0.6
        ledger = str(tmp_path / "L3.jsonl")
        perf_ledger.append([str(good)], ledger, quiet=True)
        doc["link"]["upload_s"] = 1.2  # 3x the column, inside the
        doc["link_tax_s"] = 1.3        # aggregate's noise band? no —
        bad = tmp_path / "BENCH_link_bad.json"
        bad.write_text(json.dumps(doc))
        cand_bad, _ = perf_ledger.normalize(str(bad))
        verdict = perf_gate.gate(cand_bad,
                                 perf_ledger.load_ledger(ledger))
        regressed = {c["metric"] for c in verdict["checks"]
                     if c["verdict"] == "REGRESSED"}
        assert "upload_s" in regressed

    def test_noise_widens_tolerance(self):
        # Three noisy baseline runs: the spread-derived tolerance must
        # beat the base 30%, so a value inside the band passes.
        runs = []
        for i, qps in enumerate((1000.0, 2000.0, 3000.0)):
            runs.append({"schema": 1, "kind": "serve_bench",
                         "source": f"r{i}.json", "captured_at": "x",
                         "context": {"backend": "cpu", "docs": 1,
                                     "k": 1, "max_batch": 1},
                         "metrics": {"throughput_qps": qps}})
        cand = dict(runs[0], metrics={"throughput_qps": 1000.0})
        verdict = perf_gate.gate(cand, runs)
        check = next(c for c in verdict["checks"]
                     if c["metric"] == "throughput_qps")
        # median 2000, spread (3000-1000)/2/2000 = 0.5 -> tol 0.75:
        # the 50% drop to 1000 stays inside the observed noise band.
        assert check["tolerance"] == 0.75
        assert verdict["ok"]

    def test_cli_roundtrip(self, tmp_path):
        """The two tools as a pipeline, the way CI runs them — pure
        stdlib subprocesses, no jax import."""
        ledger = str(tmp_path / "L.jsonl")
        env = dict(os.environ)
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "perf_ledger.py"),
             "--backfill", "--ledger", ledger],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert rc.returncode == 0, rc.stderr
        ok = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "perf_gate.py"),
             os.path.join(REPO, "SERVE_r01.json"), "--ledger", ledger,
             "--json"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        assert json.loads(ok.stdout)["ok"] is True
        doc = json.load(open(os.path.join(REPO, "SERVE_r01.json")))
        doc["latency_ms"]["p99"] *= 2
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(doc))
        fail = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "perf_gate.py"),
             str(bad), "--ledger", ledger],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert fail.returncode == 1
        assert "REGRESSED" in fail.stdout


@pytest.mark.slow
class TestQuickBenchGateSmoke:
    """End-to-end CPU smoke: run a tiny serve_bench, append its
    artifact to a fresh ledger, and gate a re-run of the same artifact
    — the tier-1-runnable form of the ledger/gate workflow."""

    def test_serve_bench_feeds_ledger_and_gate(self, tmp_path):
        out = tmp_path / "SERVE_smoke.json"
        ledger = str(tmp_path / "L.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "serve_bench.py"),
             "--requests", "48", "--docs", "128", "--doc-len", "32",
             "--out", str(out)],
            capture_output=True, text=True, timeout=540, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        appended, _ = perf_ledger.append([str(out)], ledger,
                                         quiet=True)
        assert appended == 1
        gate_rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "perf_gate.py"),
             str(out), "--ledger", ledger, "--require-baseline"],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=REPO)
        assert gate_rc.returncode == 0, gate_rc.stdout + gate_rc.stderr
