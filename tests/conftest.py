"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Per SURVEY §4: multi-chip code paths (shard_map / psum over the docs
axis) are exercised without TPUs by forcing the host platform to expose
8 devices.

NOTE on this machine: a sitecustomize hook imports jax at interpreter
startup with JAX_PLATFORMS=axon (single tunneled TPU), so jax's config
has already read the env by the time conftest runs — setting os.environ
here is too late for the platform choice. jax.config.update() still
works because *backend initialization* is lazy; XLA_FLAGS is also still
unread at this point. Tests must never touch the axon platform: the
tunnel admits one client, so a second process hangs forever.
"""

import os

# Read by the CPU client at first backend init (still lazy here).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses we spawn

import jax  # noqa: E402  (already imported by sitecustomize; this is a no-op)

jax.config.update("jax_platforms", "cpu")

import random  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu", (
    "tests must run on the virtual CPU mesh, not the tunneled TPU; "
    f"got {jax.default_backend()}")
assert len(jax.devices()) >= 8, (
    f"expected 8 virtual CPU devices, got {len(jax.devices())}")


@pytest.fixture(autouse=True)
def _seed_everything():
    random.seed(1234)
    np.random.seed(1234)


WORDS = [b"the", b"quick", b"brown", b"fox", b"jumps", b"over", b"lazy",
         b"dog", b"tpu", b"mesh", b"shard", b"psum", b"tfidf", b"corpus",
         b"vector", b"kernel"]


@pytest.fixture
def toy_corpus_dir(tmp_path):
    """A reference-contract input dir: input/doc1..doc6, <=16 distinct
    words, all tokens <16 chars — inside the reference's valid envelope
    (SURVEY §2.5)."""
    rng = random.Random(7)
    input_dir = tmp_path / "input"
    input_dir.mkdir()
    for i in range(1, 7):
        n = rng.randint(3, 40)
        toks = [rng.choice(WORDS) for _ in range(n)]
        (input_dir / f"doc{i}").write_bytes(b" ".join(toks) + b"\n")
    return str(input_dir)
