"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Per SURVEY §4: multi-chip code paths (shard_map / psum over the docs
axis) are exercised without TPUs by forcing the host platform to expose
8 devices.

NOTE on this machine: a sitecustomize hook imports jax at interpreter
startup with JAX_PLATFORMS=axon (single tunneled TPU), so jax's config
has already read the env by the time conftest runs — setting os.environ
here is too late for the platform choice. jax.config.update() still
works because *backend initialization* is lazy; XLA_FLAGS is also still
unread at this point. Tests must never touch the axon platform: the
tunnel admits one client, so a second process hangs forever.
"""

import os

# Read by the CPU client at first backend init (still lazy here).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses we spawn

import jax  # noqa: E402  (already imported by sitecustomize; this is a no-op)

jax.config.update("jax_platforms", "cpu")

import random  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu", (
    "tests must run on the virtual CPU mesh, not the tunneled TPU; "
    f"got {jax.default_backend()}")
assert len(jax.devices()) >= 8, (
    f"expected 8 virtual CPU devices, got {len(jax.devices())}")


def _probe_shard_map():
    """Collection-time probe: can THIS environment run the exact
    ``shard_map(... mesh=...)`` call the mesh code paths make? The
    call goes through the round-18 compat shim
    (``tfidf_tpu.parallel.compat``), which falls back from the
    top-level ``jax.shard_map`` export to
    ``jax.experimental.shard_map`` on 0.4.x builds — so on this env
    the probe passes and the mesh tests RUN. The skip machinery stays
    for environments where neither spelling works: there every mesh
    test fails on the same import/lowering error before touching any
    product logic. Returns None when shard_map works, else the error
    string, which becomes the skip reason so the tier-1 signal stays
    clean WITHOUT hiding real regressions: only the known
    shard_map-dependent tests are skipped, and only with the probe's
    actual error attached."""
    try:
        from jax.sharding import PartitionSpec as P

        from tfidf_tpu.parallel.compat import shard_map
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("docs",))
        fn = shard_map(lambda x: x + 1, mesh=mesh,
                       in_specs=P("docs"), out_specs=P("docs"))
        out = np.asarray(jax.jit(fn)(np.zeros((2,), np.int32)))
        if not (out == 1).all():
            return f"probe returned wrong values: {out!r}"
        return None
    except Exception as e:  # noqa: BLE001 — any failure means "skip"
        return f"{type(e).__name__}: {e}"


_SHARD_MAP_ERROR = _probe_shard_map()

# The known shard_map-dependent tier-1 tests (every mesh / sharded /
# multi-process path goes through jax.shard_map). Kept as an explicit
# list rather than a name heuristic so a NEW test that breaks for a
# different reason still fails loudly; new shard_map tests opt in with
# @pytest.mark.shard_map instead of growing this list.
_SHARD_MAP_NODES = (
    "test_chargram.py::TestDeviceChargram::"
    "test_mesh_chargram_stays_on_device_and_matches",
    "test_chargram.py::TestDeviceChargram::"
    "test_mesh_chargram_seq_shards_use_host_path",
    "test_chargram.py::TestDeviceChargram::"
    "test_sharded_sparse_chargram_matches_single",
    "test_checkpoint.py::TestStreamMesh::test_cli_stream_mesh_matches_single",
    "test_cli.py::TestCli::test_mesh_composes_with_overlapped_ingest",
    "test_cli.py::TestCli::test_sharded_mesh_flag",
    "test_cli.py::TestCli::test_query_sharded",
    "test_exact_ids.py::TestDeviceExact::"
    "test_cli_exact_terms_with_mesh_uses_hashed_engine",
    "test_ingest.py::TestMeshIngest::test_matches_single_device",
    "test_ingest.py::TestMeshIngest::test_uneven_chunks_and_shards",
    "test_ingest.py::TestMeshIngest::test_ids_only_wire",
    "test_ingest.py::TestMeshIngest::test_resident_budget_scales_with_shards",
    "test_ingest.py::TestMeshIngest::"
    "test_streaming_mesh_matches_single_streaming",
    "test_ingest.py::TestOccupancyWire::test_df_occupied_on_mesh",
    "test_multihost.py::TestTwoProcess::test_distributed_smoke_localhost",
    "test_multihost.py::TestTwoProcessIngest::"
    "test_flagship_mesh_ingest_across_processes",
    "test_multihost.py::TestTwoProcessStreamingMesh::"
    "test_streaming_mesh_across_processes",
    "test_parallel.py::TestShardedMatchesSingleDevice::"
    "test_counts_df_scores_equal",
    "test_parallel.py::TestShardedMatchesSingleDevice::"
    "test_golden_bytes_mesh_invariant",
    "test_parallel.py::TestShardedMatchesSingleDevice::"
    "test_pallas_shard_body_equals_xla",
    "test_parallel.py::TestShardedMatchesSingleDevice::"
    "test_mesh_shape_config_dispatch",
    "test_parallel.py::TestShardedMatchesSingleDevice::"
    "test_run_packed_pads_unplanned_batch",
    "test_parallel.py::TestShardedMatchesSingleDevice::"
    "test_sharded_topk_matches_dense",
    "test_parallel.py::TestLongDoc::test_mesh_wide_histogram_exact",
    "test_parallel.py::TestLongDoc::test_composes_with_df_scoring",
    "test_rerank.py::TestCliExactTerms::test_exact_terms_on_padding_mesh",
    "test_retrieval.py::TestSharded::test_matches_single_device",
    "test_retrieval.py::TestSharded::test_width_path_independent",
    "test_sparse.py::TestSparsePipeline::test_sharded_sparse_matches_single",
    "test_streaming.py::TestStreamingSparseEngine::"
    "test_mesh_sparse_matches_single",
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "shard_map: test needs a working jax.shard_map; auto-skipped "
        "(with the probe's error) where the environment lacks it")
    config.addinivalue_line(
        "markers",
        "slow: subprocess/IO-heavy test excluded from the tier-1 run "
        "(-m 'not slow') so the hermetic suite stays fast; run "
        "explicitly with -m slow")


def pytest_collection_modifyitems(config, items):
    if _SHARD_MAP_ERROR is None:
        return
    skip = pytest.mark.skip(
        reason=f"jax.shard_map unusable in this environment "
               f"({_SHARD_MAP_ERROR})")
    for item in items:
        bare = item.nodeid.split("/")[-1].split("[")[0]
        if bare.startswith("tests::"):  # defensive: nodeid shapes vary
            bare = bare[len("tests::"):]
        if bare in _SHARD_MAP_NODES or item.get_closest_marker("shard_map"):
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seed_everything():
    random.seed(1234)
    np.random.seed(1234)


WORDS = [b"the", b"quick", b"brown", b"fox", b"jumps", b"over", b"lazy",
         b"dog", b"tpu", b"mesh", b"shard", b"psum", b"tfidf", b"corpus",
         b"vector", b"kernel"]


@pytest.fixture
def toy_corpus_dir(tmp_path):
    """A reference-contract input dir: input/doc1..doc6, <=16 distinct
    words, all tokens <16 chars — inside the reference's valid envelope
    (SURVEY §2.5)."""
    rng = random.Random(7)
    input_dir = tmp_path / "input"
    input_dir.mkdir()
    for i in range(1, 7):
        n = rng.randint(3, 40)
        toks = [rng.choice(WORDS) for _ in range(n)]
        (input_dir / f"doc{i}").write_bytes(b" ".join(toks) + b"\n")
    return str(input_dir)
