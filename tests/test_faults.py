"""Serving that survives (ISSUE 8): fault-injection seams, supervised
retry/restart, circuit breaking, poison-batch quarantine.

The acceptance pins: a deterministic fault plan armed at the named
seams makes the REAL recovery paths run — transient dispatch faults
are absorbed by retry (responses stay bit-identical), a poison query
is isolated by bisection (its future fails typed, innocent co-batched
queries are served, resubmission 4xxes at the gate), the breaker
trips into degraded admission and recovers, crashed workers restart
inside their budget, and a swap racing close either completes or
raises the typed ServerClosed — never deadlocks. The ad-hoc
injections earlier rounds scattered across tests (monkeypatched
search fns, fake never-beating workers) have a single registry-driven
mechanism here that exercises the production seams themselves.
"""

import importlib.util
import os
import random
import sys
import threading
import time

import numpy as np
import pytest

from tfidf_tpu import faults, obs
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.obs.health import DEGRADED, OK, UNHEALTHY, set_monitor
from tfidf_tpu.obs.log import EventLog
from tfidf_tpu.serve import (CircuitBreaker, PoisonQuery, QuarantineList,
                             RetryPolicy, ServeError, ServerClosed,
                             SupervisedDispatch, TfidfServer)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4", "doc5"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig fig",
          b"grape grape grape grape"])
QUERIES = ["apple cherry", "banana date", "grape", "fig elder"]


@pytest.fixture(scope="module")
def retriever():
    return TfidfRetriever(CFG).index(CORPUS)


@pytest.fixture(autouse=True)
def _clean_faults_and_obs():
    """Every test runs with a private event log, no armed plan and no
    global health monitor — and leaks none of them."""
    obs.set_log(EventLog(echo="off"))
    faults.disarm()
    set_monitor(None)
    yield
    faults.disarm()
    set_monitor(None)
    obs.set_log(None)


def quick_cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_entries", 64)
    kw.setdefault("retry_backoff_ms", 1.0)
    return ServeConfig(**kw)


# ---------------------------------------------------------------------
class TestFaultPlan:
    def test_parse_grammar(self):
        plan = faults.FaultPlan.parse(
            "device_dispatch:transient:n=3;"
            "pack_worker:fatal:at=2;"
            "batcher_loop:sleep:s=0.25;"
            "swap:transient:p=0.5;"
            "device_dispatch:fatal:match=zzz", seed=7)
        assert len(plan.rules) == 5
        r = plan.rules_for("device_dispatch")
        assert r[0].kind == "transient" and r[0].n == 3
        assert r[1].match == "zzz" and r[1].n == -1  # poison: unlimited
        assert plan.rules_for("pack_worker")[0].at == 2
        assert plan.rules_for("batcher_loop")[0].sleep_s == 0.25
        assert plan.rules_for("swap")[0].p == 0.5

    def test_parse_rejects_garbage(self):
        for bad in ("nota_seam:transient", "swap:nota_kind",
                    "swap", "swap:fatal:bogus=1", "swap:fatal:n",
                    "", "swap:transient:p=2.0"):
            with pytest.raises(ValueError):
                faults.FaultPlan.parse(bad)

    def test_probabilistic_rules_replay_with_seed(self):
        def fires(seed):
            plan = faults.FaultPlan.parse("swap:transient:p=0.5:n=-1",
                                          seed=seed)
            reg = faults.FaultRegistry().arm(plan)
            out = []
            for _ in range(64):
                try:
                    reg.fire("swap")
                    out.append(0)
                except faults.TransientFault:
                    out.append(1)
            return out

        assert fires(3) == fires(3)          # replayable
        assert fires(3) != fires(4)          # and seed-sensitive
        assert 0 < sum(fires(3)) < 64        # actually probabilistic


class TestFaultRegistry:
    def test_disarmed_fire_is_noop(self):
        faults.fire("device_dispatch", text="anything")
        assert not faults.get_registry().armed

    def test_typed_faults_and_counts(self):
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:transient:n=2;swap:fatal:n=1"))
        with pytest.raises(faults.TransientFault) as ei:
            faults.fire("device_dispatch")
        assert ei.value.seam == "device_dispatch"
        with pytest.raises(faults.TransientFault):
            faults.fire("device_dispatch")
        faults.fire("device_dispatch")       # budget n=2 spent
        with pytest.raises(faults.FatalFault):
            faults.fire("swap")
        snap = faults.get_registry().snapshot()
        assert snap["device_dispatch:transient:n=2"]["fired"] == 2
        assert snap["swap:fatal:n=1"]["fired"] == 1

    def test_match_rule_selects_poison_text(self):
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:fatal:match=zzpoison"))
        faults.fire("device_dispatch", text="clean queries only")
        with pytest.raises(faults.FatalFault):
            faults.fire("device_dispatch", text="a zzpoison b")
        # poison stays poison (unlimited fires)
        with pytest.raises(faults.FatalFault):
            faults.fire("device_dispatch", text="zzpoison again")

    def test_at_delays_first_fire(self):
        faults.arm(faults.FaultPlan.parse("drain:transient:at=3"))
        faults.fire("drain")
        faults.fire("drain")
        with pytest.raises(faults.TransientFault):
            faults.fire("drain")

    def test_firing_logs_flight_event(self):
        log = EventLog(echo="off")
        obs.set_log(log)
        faults.arm(faults.FaultPlan.parse("swap:transient:n=1"))
        with pytest.raises(faults.TransientFault):
            faults.fire("swap")
        evs = [e for e in log.events() if e["event"] == "fault_injected"]
        assert evs and evs[0]["seam"] == "swap"

    def test_configure_reads_env(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_FAULTS", "swap:fatal:n=1")
        monkeypatch.setenv("TFIDF_TPU_FAULT_SEED", "9")
        plan = faults.configure()
        assert plan is not None and plan.seed == 9
        assert faults.get_registry().armed


# ---------------------------------------------------------------------
class TestCircuitBreaker:
    def test_trips_after_threshold_and_recovers(self):
        from tfidf_tpu.obs.registry import MetricsRegistry
        reg = MetricsRegistry()
        b = CircuitBreaker(threshold=3, cooldown_s=0.05, registry=reg)
        assert b.state == "closed"
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"
        assert b.record_failure()            # the tripping failure
        assert b.state == "open"
        assert reg.snapshot()["serve_breaker_trips_total"] == 1
        assert reg.snapshot()["serve_breaker_open"]["value"] == 1
        value, reason = b.health_signal()
        assert value == "open" and "breaker" in reason
        time.sleep(0.06)
        assert b.state == "half_open"        # cooldown elapsed: trial
        b.record_success()
        assert b.state == "closed"
        assert b.health_signal()[1] is None
        assert reg.snapshot()["serve_breaker_open"]["value"] == 0

    def test_halfopen_failure_reopens(self):
        b = CircuitBreaker(threshold=1, cooldown_s=0.05)
        b.record_failure()
        time.sleep(0.06)
        assert b.state == "half_open"
        b.record_failure()                   # trial failed
        assert b.state == "open"
        assert b.cooldown_remaining() > 0


class TestQuarantineList:
    def test_add_contains_cap(self):
        from tfidf_tpu.obs.registry import MetricsRegistry
        reg = MetricsRegistry()
        q = QuarantineList(cap=2, registry=reg)
        assert q.add("a") and not q.add("a")   # dedup
        q.add("b")
        q.add("c")                             # evicts oldest (a)
        assert len(q) == 2
        assert not q.contains("a") and q.contains("c")
        assert reg.snapshot()["serve_quarantined_total"] == 3
        assert reg.snapshot()["serve_quarantine_size"]["value"] == 2
        q.clear()
        assert len(q) == 0


# ---------------------------------------------------------------------
def _fake_rows(q):
    """Deterministic per-query result row for the fake dispatcher."""
    h = sum(q.encode()) % 251
    return (np.array([h, h + 1], np.float32),
            np.array([h % 5, (h + 1) % 5], np.int64))


def _fake_dispatch(poison):
    calls = []

    def fn(queries, k, group):
        calls.append(list(queries))
        if any(q in poison for q in queries):
            raise RuntimeError("kernel rejected poison")
        vals = np.stack([_fake_rows(q)[0] for q in queries])
        ids = np.stack([_fake_rows(q)[1] for q in queries])
        return vals, ids

    fn.calls = calls
    return fn


class TestSupervisedDispatch:
    def test_transient_absorbed_within_budget(self):
        fn = _fake_dispatch(set())
        d = SupervisedDispatch(fn, RetryPolicy(max_attempts=3,
                                               backoff_ms=1))
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:transient:n=2"))
        vals, ids, poison = d.run_batch(["a", "b"], 2, None)
        assert poison == []
        np.testing.assert_array_equal(vals[0], _fake_rows("a")[0])
        assert len(fn.calls) == 1            # faults fired pre-dispatch

    def test_transient_past_budget_fails_batch_not_poison(self):
        fn = _fake_dispatch(set())
        d = SupervisedDispatch(fn, RetryPolicy(max_attempts=2,
                                               backoff_ms=1))
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:transient:n=10"))
        with pytest.raises(faults.TransientFault):
            d.run_batch(["a", "b"], 2, None)

    def test_bisection_isolates_exactly_the_poison(self):
        """Property: for random batches and random poison subsets, the
        bisection isolates EXACTLY the poison queries and returns the
        bit-identical rows a clean dispatch would give the rest."""
        rng = random.Random(1234)
        for trial in range(40):
            n = rng.randint(1, 12)
            queries = [f"q{trial}_{i}" for i in range(n)]
            n_poison = rng.randint(1, n)
            poison_set = set(rng.sample(queries, n_poison))
            d = SupervisedDispatch(_fake_dispatch(poison_set),
                                   RetryPolicy(max_attempts=1))
            vals, ids, poison = d.run_batch(queries, 2, None)
            want = sorted(i for i, q in enumerate(queries)
                          if q in poison_set)
            assert poison == want, (trial, queries, poison_set)
            if len(want) == n:
                assert vals is None and ids is None
            else:
                for i, q in enumerate(queries):
                    if i not in poison:
                        np.testing.assert_array_equal(
                            vals[i], _fake_rows(q)[0], err_msg=q)
                        np.testing.assert_array_equal(
                            ids[i], _fake_rows(q)[1], err_msg=q)

    def test_non_separable_failure_raises(self):
        # Fails only when >= 2 queries batch together: no subset of
        # size 1 fails, so bisection finds no poison and the final
        # full retry surfaces the batch error.
        def fn(queries, k, group):
            if len(queries) >= 2:
                raise RuntimeError("batch-shape dependent")
            vals = np.stack([_fake_rows(q)[0] for q in queries])
            ids = np.stack([_fake_rows(q)[1] for q in queries])
            return vals, ids

        d = SupervisedDispatch(fn, RetryPolicy(max_attempts=1))
        with pytest.raises(RuntimeError, match="batch-shape"):
            d.run_batch(["a", "b", "c"], 2, None)

    def test_breaker_records_attempts(self):
        b = CircuitBreaker(threshold=2, cooldown_s=10.0)
        d = SupervisedDispatch(_fake_dispatch({"bad"}),
                               RetryPolicy(max_attempts=1), breaker=b)
        with pytest.raises(RuntimeError):
            d.run(["bad"], 2, None)
        with pytest.raises(RuntimeError):
            d.run(["bad"], 2, None)
        assert b.state == "open"
        # run_batch on a clean batch closes it again (cooldown is long
        # but half-open is reached by the explicit wait in run()).
        b._open_since -= 11                  # fast-forward the clock
        vals, ids, poison = d.run_batch(["ok"], 2, None)
        assert poison == [] and b.state == "closed"


# ---------------------------------------------------------------------
class TestServerSurvives:
    """The serve-layer integration: the same injections the old tests
    did with monkeypatches, driven through the registry seams."""

    def test_transient_faults_keep_responses_bit_identical(self,
                                                           retriever):
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:transient:n=2"))
        with TfidfServer(retriever, quick_cfg()) as srv:
            got = srv.submit(QUERIES[:2], k=3,
                             use_cache=False).result(timeout=30)
            snap = srv.metrics.registry.snapshot()
        want = retriever.search(QUERIES[:2], k=3)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])
        assert snap["serve_dispatch_retries_total"] >= 1

    def test_poison_query_quarantined_innocents_served(self, retriever):
        log = EventLog(echo="off")
        obs.set_log(log)
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:fatal:match=zzpoison"))
        srv = TfidfServer(retriever, quick_cfg(max_wait_ms=40,
                                               cache_entries=0))
        try:
            futs = {q: srv.submit([q], k=3) for q in
                    [QUERIES[0], "zzpoison attack", QUERIES[1]]}
            with pytest.raises(PoisonQuery) as ei:
                futs["zzpoison attack"].result(timeout=30)
            assert ei.value.queries == ["zzpoison attack"]
            for q in (QUERIES[0], QUERIES[1]):
                got = futs[q].result(timeout=30)
                want = retriever.search([q], k=3)
                np.testing.assert_array_equal(got[0], want[0])
                np.testing.assert_array_equal(got[1], want[1])
            # 4xx thereafter: the gate fails fast, no device work.
            with pytest.raises(PoisonQuery):
                srv.submit(["zzpoison attack"], k=3)
            snap = srv.metrics.registry.snapshot()
            assert snap["serve_quarantined_total"] == 1
            assert snap["serve_poisoned_total"] == 2
        finally:
            srv.close()
        events = {e["event"] for e in log.events()}
        assert "poison_isolated" in events
        assert "query_quarantined" in events
        outcomes = [d["outcome"] for d in log.digests()]
        assert outcomes.count("poisoned") == 2

    def test_breaker_trips_into_degraded_admission(self, retriever):
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:transient:n=40"))
        srv = TfidfServer(retriever, quick_cfg(
            queue_depth=8, dispatch_retries=0, breaker_threshold=3,
            breaker_cooldown_ms=50, cache_entries=0))
        try:
            for _ in range(3):
                with pytest.raises(faults.TransientFault):
                    srv.submit([QUERIES[0]], k=3,
                               use_cache=False).result(timeout=30)
            assert srv.breaker.state in ("open", "half_open")
            hz = srv.healthz()
            assert hz["status"] == DEGRADED
            assert any("breaker" in r for r in hz["reasons"])
            assert hz["admission_bound"] == 4      # 8 -> 4 degraded
            faults.disarm()
            time.sleep(0.06)                       # past the cooldown
            srv.submit([QUERIES[0]], k=3,
                       use_cache=False).result(timeout=30)
            assert srv.breaker.state == "closed"
            srv.healthz()
            assert srv.healthz()["status"] == OK   # second eval clean
        finally:
            srv.close()

    def test_batcher_loop_restarts_and_serves(self, retriever):
        log = EventLog(echo="off")
        obs.set_log(log)
        faults.arm(faults.FaultPlan.parse("batcher_loop:fatal:n=1"))
        with TfidfServer(retriever, quick_cfg(restart_budget=2)) as srv:
            got = srv.submit(QUERIES[:1], k=3,
                             use_cache=False).result(timeout=30)
            assert srv._batcher.restarts == 1
        want = retriever.search(QUERIES[:1], k=3)
        np.testing.assert_array_equal(got[0], want[0])
        evs = [e for e in log.events() if e["event"] == "worker_restart"]
        assert evs and evs[0]["worker"] == "batcher"

    def test_restart_budget_exhaustion_kills_batcher_typed(self,
                                                           retriever):
        faults.arm(faults.FaultPlan.parse("batcher_loop:fatal:n=99"))
        srv = TfidfServer(retriever, quick_cfg(restart_budget=1))
        try:
            f = srv.submit(QUERIES[:1], k=3, use_cache=False)
            with pytest.raises((ServeError, faults.FatalFault)):
                f.result(timeout=30)
            deadline = time.monotonic() + 10
            while not srv._batcher._dead and time.monotonic() < deadline:
                time.sleep(0.01)
            assert srv._batcher._dead
            faults.disarm()     # a dead batcher stays dead
            with pytest.raises(ServeError, match="dead"):
                srv._batcher.submit(["x"], k=1)
        finally:
            srv.close()

    def test_sleep_fault_stalls_batcher_to_unhealthy(self, retriever):
        """The registry-driven version of the old fake-worker stall
        injection: a real batcher, really stalled, flips readyz."""
        faults.arm(faults.FaultPlan.parse(
            "batcher_loop:sleep:s=0.8:at=2"))
        srv = TfidfServer(retriever, quick_cfg(
            stall_after_ms=100, cache_entries=0, max_wait_ms=1))
        try:
            srv.submit(QUERIES[:1], k=3).result(timeout=30)
            # The loop's next wake hits the sleep rule; work queued
            # behind it makes the batcher busy-but-silent.
            f = srv.submit(QUERIES[1:2], k=3)
            deadline = time.monotonic() + 5
            state = None
            while time.monotonic() < deadline:
                state = srv.health.evaluate().state
                if state == UNHEALTHY:
                    break
                time.sleep(0.02)
            assert state == UNHEALTHY
            assert not srv.readyz()["ready"]
            f.result(timeout=30)               # stall ends, work flows
            deadline = time.monotonic() + 5
            while (srv.health.evaluate().state != OK
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert srv.readyz()["ready"]       # recovered
        finally:
            srv.close()

    def test_server_arms_and_disarms_config_plan(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(
            faults="device_dispatch:transient:n=1"))
        assert faults.get_registry().armed
        srv.close()
        assert not faults.get_registry().armed


# ---------------------------------------------------------------------
class TestSwapCloseRace:
    def test_swap_mid_drain_completes_or_raises_serverclosed(
            self, retriever):
        """A swap landing while close(drain=True) drains must either
        complete or raise the typed ServerClosed — and the whole dance
        must finish (no deadlock)."""
        twin = TfidfRetriever(CFG).index(CORPUS)
        all_results = []
        for _ in range(5):
            srv = TfidfServer(retriever, quick_cfg(
                max_wait_ms=20, cache_entries=0))
            for q in QUERIES:
                srv.submit([q], k=3)           # backlog to drain
            results = []
            go = threading.Event()

            def swapper():
                go.wait()                      # race close() for real
                for _ in range(8):
                    try:
                        results.append(("ok", srv.swap_index(twin)))
                    except ServerClosed:
                        results.append(("closed", None))
                    except ServeError as e:    # pragma: no cover
                        results.append(("other", repr(e)))

            t = threading.Thread(target=swapper)
            t.start()
            go.set()
            srv.close(drain=True)
            t.join(timeout=30)
            assert not t.is_alive(), "swap vs close deadlocked"
            assert results and all(kind in ("ok", "closed")
                                   for kind, _ in results)
            all_results += results
        # The typed refusal itself shows up deterministically once the
        # server IS closed (pinned below); across five staged races
        # at least the terminal swaps after close land as 'closed'.
        assert any(kind == "closed" for kind, _ in all_results)

    def test_submit_after_close_raises_serverclosed(self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        srv.close()
        with pytest.raises(ServerClosed):
            srv.submit(QUERIES[:1], k=2)
        with pytest.raises(ServerClosed):
            srv.swap_index(retriever)


# ---------------------------------------------------------------------
class TestIngestWorkerRestart:
    def test_pack_and_drain_transients_restart_identically(
            self, toy_corpus_dir):
        from tfidf_tpu.ingest import run_overlapped
        log = EventLog(echo="off")
        obs.set_log(log)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        clean = run_overlapped(toy_corpus_dir, cfg, doc_len=16,
                               chunk_docs=2)
        faults.arm(faults.FaultPlan.parse(
            "pack_worker:transient:n=1;drain:transient:n=1"))
        faulted = run_overlapped(toy_corpus_dir, cfg, doc_len=16,
                                 chunk_docs=2)
        np.testing.assert_array_equal(np.asarray(clean.df),
                                      np.asarray(faulted.df))
        restarts = [e for e in log.events()
                    if e["event"] == "worker_restart"]
        workers = {e["worker"] for e in restarts}
        assert {"packer", "drainer"} <= workers

    def test_fatal_fault_propagates(self, toy_corpus_dir):
        from tfidf_tpu.ingest import run_overlapped
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        faults.arm(faults.FaultPlan.parse("pack_worker:fatal:n=1"))
        with pytest.raises(faults.FatalFault):
            run_overlapped(toy_corpus_dir, cfg, doc_len=16,
                           chunk_docs=2)

    def test_restart_budget_env_bounds_retries(self, toy_corpus_dir,
                                               monkeypatch):
        from tfidf_tpu.ingest import run_overlapped
        monkeypatch.setenv("TFIDF_TPU_RESTART_BUDGET", "1")
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        faults.arm(faults.FaultPlan.parse("pack_worker:transient:n=5"))
        with pytest.raises(faults.TransientFault):
            run_overlapped(toy_corpus_dir, cfg, doc_len=16,
                           chunk_docs=2)


# ---------------------------------------------------------------------
def _load_tool(name):
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestRecoveryObservability:
    def test_retry_spans_nest_and_poisoned_outcome_validates(
            self, retriever, tmp_path):
        """trace_check: dispatch_retry spans nest inside their batched
        span; a quarantined request's span ends outcome=poisoned."""
        path = str(tmp_path / "chaos_trace.json")
        obs.set_tracer(obs.Tracer(), path)
        try:
            faults.arm(faults.FaultPlan.parse(
                "device_dispatch:transient:n=1;"
                "device_dispatch:fatal:match=zzpoison"))
            with TfidfServer(retriever, quick_cfg(
                    cache_entries=0)) as srv:
                srv.submit(QUERIES[:2], k=3,
                           use_cache=False).result(timeout=30)
                with pytest.raises(PoisonQuery):
                    srv.submit(["zzpoison x"], k=3).result(timeout=30)
            out = obs.export()
        finally:
            obs.set_tracer(None)
        assert out == path
        tc = _load_tool("trace_check")
        errors, notes = tc.check_trace(path, mode="serve",
                                       min_threads=2)
        assert errors == [], (errors, notes)
        events = tc.load_chrome_trace(path)
        outcomes = {(e.get("args") or {}).get("outcome")
                    for e in events if e.get("ph") == "X"
                    and e.get("name") == "request"}
        assert "poisoned" in outcomes
        retries = [e for e in events if e.get("ph") == "X"
                   and e.get("name") == "dispatch_retry"]
        assert retries, "retry left no span"

    def test_mangled_retry_span_fails_trace_check(self, tmp_path):
        """A dispatch_retry span floating OUTSIDE any batched span on
        its lane is an instrumentation regression."""
        import json
        events = [
            {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
             "args": {"name": "batcher"}},
            {"ph": "X", "name": "request", "pid": 1, "tid": 1,
             "ts": 0.0, "dur": 100.0, "args": {"outcome": "drained"}},
            {"ph": "X", "name": "batched", "pid": 1, "tid": 1,
             "ts": 10.0, "dur": 20.0, "args": {"batch": 0}},
            {"ph": "X", "name": "dispatch_retry", "pid": 1, "tid": 1,
             "ts": 50.0, "dur": 10.0, "args": {"batch": 0}},
        ]
        path = tmp_path / "bad.json"
        path.write_text(json.dumps({"traceEvents": events}))
        tc = _load_tool("trace_check")
        errors, _ = tc.check_trace(str(path), mode="serve",
                                   min_threads=1)
        assert any("dispatch_retry" in e for e in errors)

    def test_quarantine_cross_check_trace_vs_flight(self, tmp_path):
        import json
        tc = _load_tool("trace_check")
        log = EventLog(echo="off")
        log.log("error", "query_quarantined", size=1)
        flight = str(tmp_path / "f.jsonl")
        log.dump(flight)

        def trace_with(outcome):
            path = tmp_path / f"t_{outcome}.json"
            path.write_text(json.dumps({"traceEvents": [
                {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
                 "args": {"name": "main"}},
                {"ph": "X", "name": "request", "pid": 1, "tid": 1,
                 "ts": 0.0, "dur": 5.0, "args": {"outcome": outcome}},
            ]}))
            return str(path)

        notes = []
        # quarantine in flight + poisoned terminal in trace: clean
        assert tc._cross_check_quarantine(
            trace_with("poisoned"), flight, notes) == []
        assert notes
        # quarantine in flight but NO poisoned request span: flagged
        errs = tc._cross_check_quarantine(
            trace_with("drained"), flight, [])
        assert errs and "poisoned" in errs[0]

    def test_doctor_reports_faults_and_gates_breaker_open(
            self, tmp_path):
        import json
        log = EventLog(echo="off")
        log.log("warning", "dispatch_retry", attempt=1, batch=0)
        log.log("warning", "worker_restart", worker="packer", chunk=0)
        log.log("error", "breaker_trip", consecutive=5)
        log.log("error", "query_quarantined", size=1)
        log.digest(outcome="poisoned", queries=1, k=3, ms=1.0)
        flight = str(tmp_path / "f.jsonl")
        log.dump(flight)
        trace = tmp_path / "t.json"
        trace.write_text(json.dumps({"traceEvents": [
            {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
             "args": {"name": "main"}},
            {"ph": "X", "name": "request", "pid": 1, "tid": 1,
             "ts": 0.0, "dur": 10.0, "args": {"outcome": "poisoned"}},
        ]}))
        doctor = _load_tool("doctor")
        report = doctor.diagnose(str(trace), flight,
                                 str(tmp_path / "no_ledger.jsonl"))
        fa = report["flight"]["faults"]
        assert fa["dispatch_retry"] == 1
        assert fa["worker_restart"] == 1
        assert fa["breaker_trip"] == 1
        assert fa["query_quarantined"] == 1
        assert fa["breaker_open_at_exit"] is True
        assert fa["restarts_by_worker"] == {"packer": 1}
        assert any("breaker OPEN at exit" in v
                   for v in report["violations"])
        assert not report["ok"]
        # allow flag tolerates; a later breaker_close clears entirely
        report = doctor.diagnose(str(trace), flight,
                                 str(tmp_path / "no_ledger.jsonl"),
                                 allow_breaker_open=True)
        assert report["ok"]
        log.log("info", "breaker_close")
        log.dump(flight)
        report = doctor.diagnose(str(trace), flight,
                                 str(tmp_path / "no_ledger.jsonl"))
        assert report["flight"]["faults"]["breaker_open_at_exit"] \
            is False
        assert report["ok"]
        rendered = doctor.render(report)
        assert "faults:" in rendered

    def test_chaos_artifact_normalizes_and_gates(self, tmp_path):
        import json
        ledger = _load_tool("perf_ledger")
        gate = _load_tool("perf_gate")
        artifact = {
            "metric": "serve_bench", "backend": "cpu", "docs": 128,
            "k": 10, "requests": 64, "mode": "closed",
            "concurrency": 4, "max_batch": 64,
            "throughput_qps": 1500.0, "throughput_rps": 400.0,
            "latency_ms": {"p50": 1.0, "p99": 4.0},
            "chaos": {"plan": "device_dispatch:transient:n=2",
                      "seed": 0, "retries": 2, "worker_restarts": 0,
                      "breaker_trips": 0, "breaker_open_at_exit": 0,
                      "quarantined": 1, "poisoned_requests": 1,
                      "shed_requests": 0, "parity_checked": 60,
                      "parity_mismatches": 0, "parity_ok": 1},
        }
        path = tmp_path / "CHAOS_t.json"
        path.write_text(json.dumps(artifact))
        rec, reason = ledger.normalize(str(path))
        assert reason is None and rec["kind"] == "chaos"
        assert rec["metrics"]["parity_ok"] == 1
        assert rec["context"]["plan"] == "device_dispatch:transient:n=2"
        verdict = gate.gate(rec, [rec])
        assert verdict["ok"]
        # Parity break or a breaker left open fails zero-tolerance.
        for key, val in (("parity_ok", 0), ("breaker_open_at_exit", 1)):
            bad = json.loads(json.dumps(artifact))
            bad["chaos"][key] = val
            bpath = tmp_path / f"CHAOS_bad_{key}.json"
            bpath.write_text(json.dumps(bad))
            brec, _ = ledger.normalize(str(bpath))
            bverdict = gate.gate(brec, [rec])
            assert not bverdict["ok"], key
