"""Serving layer: micro-batching, cache, admission, SLOs, parity.

The hard invariant everywhere: a served response is BIT-IDENTICAL to a
direct ``TfidfRetriever.search`` of the same queries — under
coalescing, caching, concurrent submission, and across hot index
swaps. Per-query results are independent of batch composition (each
query is one column of the [V, Q] block), so this is a real contract,
not an approximation.
"""

import io
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, ServeConfig
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.serve import (DeadlineExceeded, MicroBatcher, Overloaded,
                             ResultCache, ServeError, ServeMetrics,
                             TfidfServer, normalize_query)

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4", "doc5"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig fig",
          b"grape grape grape grape"])
CORPUS_B = Corpus(
    names=["doc1", "doc2", "doc3"],
    docs=[b"zebra yak apple",
          b"yak yak quokka",
          b"quokka zebra grape"])
QUERIES = ["apple cherry", "banana", "grape date", "fig", "elder",
           "apple fig", "date banana cherry"]


@pytest.fixture(scope="module")
def retriever():
    return TfidfRetriever(CFG).index(CORPUS)


def quick_cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_entries", 64)
    return ServeConfig(**kw)


def assert_identical(got, want):
    gv, gi = got
    wv, wi = want
    np.testing.assert_array_equal(np.asarray(gv), np.asarray(wv))
    np.testing.assert_array_equal(np.asarray(gi), np.asarray(wi))


class TestMicroBatcher:
    def _searcher(self, retriever, calls=None):
        def fn(queries, k, group):
            if calls is not None:
                calls.append(list(queries))
            return retriever.search(queries, k)
        return fn

    def test_single_request_parity(self, retriever):
        b = MicroBatcher(self._searcher(retriever), max_batch=8,
                         max_wait_ms=1)
        try:
            got = b.submit(QUERIES[:3], k=4).result(timeout=10)
            assert_identical(got, retriever.search(QUERIES[:3], k=4))
        finally:
            b.close()

    def test_coalesces_concurrent_submits(self, retriever):
        calls = []
        m = ServeMetrics()
        # Long window: all three submits land before the first flush.
        b = MicroBatcher(self._searcher(retriever, calls), max_batch=64,
                         max_wait_ms=250, metrics=m)
        try:
            futs = [b.submit([q], k=3) for q in QUERIES[:3]]
            for f, q in zip(futs, QUERIES[:3]):
                assert_identical(f.result(timeout=10),
                                 retriever.search([q], k=3))
        finally:
            b.close()
        assert len(calls) == 1 and len(calls[0]) == 3
        assert m.snapshot()["batch"]["count"] == 1

    def test_full_batch_flushes_before_deadline(self, retriever):
        calls = []
        b = MicroBatcher(self._searcher(retriever, calls), max_batch=2,
                         max_wait_ms=60_000)  # deadline would be "never"
        try:
            t0 = time.monotonic()
            f1 = b.submit([QUERIES[0]], k=2)
            f2 = b.submit([QUERIES[1]], k=2)
            f1.result(timeout=10)
            f2.result(timeout=10)
            assert time.monotonic() - t0 < 30  # not the 60 s window
        finally:
            b.close()

    def test_deadline_flushes_partial_batch(self, retriever):
        b = MicroBatcher(self._searcher(retriever), max_batch=1024,
                         max_wait_ms=20)
        try:
            t0 = time.monotonic()
            got = b.submit([QUERIES[0]], k=2).result(timeout=10)
            took = time.monotonic() - t0
            assert_identical(got, retriever.search([QUERIES[0]], k=2))
            assert took < 10  # flushed by the 20 ms window, not by fill
        finally:
            b.close()

    def test_mixed_k_never_shares_a_batch(self, retriever):
        calls = []
        b = MicroBatcher(self._searcher(retriever, calls), max_batch=64,
                         max_wait_ms=100)
        try:
            f2 = b.submit([QUERIES[0]], k=2)
            f3 = b.submit([QUERIES[1]], k=3)
            assert_identical(f2.result(timeout=10),
                             retriever.search([QUERIES[0]], k=2))
            assert_identical(f3.result(timeout=10),
                             retriever.search([QUERIES[1]], k=3))
        finally:
            b.close()
        assert len(calls) == 2  # one batch per k

    def test_mixed_group_never_shares_a_batch(self, retriever):
        calls = []
        b = MicroBatcher(self._searcher(retriever, calls), max_batch=64,
                         max_wait_ms=100)
        try:
            fa = b.submit([QUERIES[0]], k=2, group="epoch0")
            fb = b.submit([QUERIES[1]], k=2, group="epoch1")
            fa.result(timeout=10)
            fb.result(timeout=10)
        finally:
            b.close()
        assert len(calls) == 2

    def test_oversize_request_stays_atomic(self, retriever):
        calls = []
        b = MicroBatcher(self._searcher(retriever, calls), max_batch=2,
                         max_wait_ms=5)
        try:
            got = b.submit(QUERIES, k=3).result(timeout=10)  # 7 > 2
            assert_identical(got, retriever.search(QUERIES, k=3))
        finally:
            b.close()
        assert [len(c) for c in calls] == [len(QUERIES)]

    def test_search_error_propagates_to_all_coalesced(self):
        def boom(queries, k, group):
            raise RuntimeError("kernel exploded")
        b = MicroBatcher(boom, max_batch=64, max_wait_ms=100)
        try:
            futs = [b.submit(["x"], k=1) for _ in range(3)]
            for f in futs:
                with pytest.raises(RuntimeError, match="kernel exploded"):
                    f.result(timeout=10)
        finally:
            b.close()

    def test_expired_deadline_sheds_before_device(self, retriever):
        calls = []
        m = ServeMetrics()
        b = MicroBatcher(self._searcher(retriever, calls), max_batch=8,
                         max_wait_ms=20, metrics=m)
        try:
            f = b.submit([QUERIES[0]], k=2,
                         deadline=time.monotonic())  # already expired
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=10)
        finally:
            b.close()
        assert calls == []  # never reached the search fn
        assert m.snapshot()["shed"]["deadline"] == 1

    def test_close_drains_queued_work(self, retriever):
        b = MicroBatcher(self._searcher(retriever), max_batch=1024,
                         max_wait_ms=60_000)
        futs = [b.submit([q], k=2) for q in QUERIES[:3]]
        b.close(drain=True)  # must not wait for the 60 s window
        for f, q in zip(futs, QUERIES[:3]):
            assert_identical(f.result(timeout=0),
                             retriever.search([q], k=2))

    def test_close_without_drain_fails_pending(self, retriever):
        b = MicroBatcher(self._searcher(retriever), max_batch=1024,
                         max_wait_ms=60_000)
        f = b.submit([QUERIES[0]], k=2)
        b.close(drain=False)
        with pytest.raises(ServeError):
            f.result(timeout=10)

    def test_submit_after_close_raises(self, retriever):
        b = MicroBatcher(self._searcher(retriever))
        b.close()
        with pytest.raises(ServeError):
            b.submit(["x"], k=1)


class TestResultCache:
    def test_hit_miss_counters_and_lru_eviction(self):
        c = ResultCache(entries=2)
        row = (np.arange(3, dtype=np.float32), np.arange(3))
        k1, k2, k3 = (c.key((b"a",), 3, 0), c.key((b"b",), 3, 0),
                      c.key((b"c",), 3, 0))
        assert c.get(k1) is None and c.misses == 1
        c.put(k1, *row)
        c.put(k2, *row)
        assert c.get(k1) is not None  # touches k1: k2 becomes LRU
        c.put(k3, *row)               # evicts k2
        assert c.get(k2) is None
        assert c.get(k3) is not None
        assert c.hits == 2 and c.misses == 2
        assert len(c) == 2

    def test_normalization_collapses_whitespace(self):
        assert (normalize_query("  apple\t cherry \n", CFG)
                == normalize_query("apple cherry", CFG)
                == (b"apple", b"cherry"))
        # truncation participates: keys match scoring equality
        cfg_trunc = PipelineConfig(vocab_mode=VocabMode.HASHED,
                                   truncate_tokens_at=4)
        assert (normalize_query("apples", cfg_trunc)
                == normalize_query("appleXYZ", cfg_trunc))

    def test_epoch_is_part_of_the_key(self):
        c = ResultCache(entries=8)
        row = (np.zeros(2, np.float32), np.zeros(2, np.int32))
        c.put(c.key((b"a",), 2, epoch=0), *row)
        assert c.get(c.key((b"a",), 2, epoch=1)) is None
        assert c.get(c.key((b"a",), 2, epoch=0)) is not None

    def test_disabled_cache_never_counts(self):
        c = ResultCache(entries=0)
        key = c.key((b"a",), 2, 0)
        c.put(key, np.zeros(2, np.float32), np.zeros(2, np.int32))
        assert c.get(key) is None
        assert c.hits == 0 and c.misses == 0
        assert not c.enabled

    def test_cached_rows_are_immutable(self):
        c = ResultCache(entries=4)
        vals = np.arange(3, dtype=np.float32)
        key = c.key((b"a",), 3, 0)
        c.put(key, vals, np.arange(3))
        vals[0] = 99  # caller mutates its own array after put
        got = c.get(key)
        assert got[0][0] == 0  # cache kept its own copy
        with pytest.raises(ValueError):
            got[0][0] = 7  # and hands out read-only views


class TestTfidfServer:
    def test_sequential_parity_mixed_sizes(self, retriever):
        with TfidfServer(retriever, quick_cfg()) as srv:
            for size in (1, 2, 3, 5, 7):
                qs = QUERIES[:size]
                assert_identical(srv.search(qs, k=4),
                                 retriever.search(qs, k=4))

    def test_stress_concurrent_parity(self, retriever):
        """N threads x mixed-size requests; every response bit-identical
        to a direct search of the same queries (the ISSUE's stress
        pin)."""
        srv = TfidfServer(retriever, quick_cfg(max_wait_ms=2))
        results = {}
        errors = []

        def work(tid):
            try:
                rng = np.random.default_rng(tid)
                out = []
                for _ in range(5):
                    qs = [QUERIES[i] for i in rng.integers(
                        0, len(QUERIES), size=int(rng.integers(1, 6)))]
                    out.append((qs, srv.search(qs, k=3, timeout=30)))
                results[tid] = out
            except Exception as e:  # noqa: BLE001 — surface in-main
                errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        srv.close()
        assert not errors
        assert len(results) == 8
        for out in results.values():
            for qs, got in out:
                assert_identical(got, retriever.search(qs, k=3))

    def test_cache_hit_is_bit_identical_and_counted(self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        try:
            first = srv.search(QUERIES[:2], k=3)
            before = srv.metrics_snapshot()["cache"]
            second = srv.search(QUERIES[:2], k=3)
            after = srv.metrics_snapshot()["cache"]
            assert_identical(second, first)
            assert_identical(second, retriever.search(QUERIES[:2], k=3))
            assert after["hits"] == before["hits"] + 2
            assert after["misses"] == before["misses"]
        finally:
            srv.close()

    def test_partial_cache_hit_assembles_exactly(self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        try:
            srv.search([QUERIES[0]], k=3)  # prime one of three
            got = srv.search(QUERIES[:3], k=3)
            assert_identical(got, retriever.search(QUERIES[:3], k=3))
            assert srv.metrics_snapshot()["cache"]["hits"] >= 1
        finally:
            srv.close()

    def test_overload_sheds_with_typed_error(self, retriever):
        # Window long enough that submits stay queued: the 3rd of three
        # 1-query requests exceeds queue_depth=2 at admission.
        srv = TfidfServer(retriever, quick_cfg(
            queue_depth=2, max_batch=1024, max_wait_ms=5_000,
            cache_entries=0))
        try:
            f1 = srv.submit([QUERIES[0]], k=2)
            f2 = srv.submit([QUERIES[1]], k=2)
            with pytest.raises(Overloaded):
                srv.submit([QUERIES[2]], k=2)
            assert srv.metrics_snapshot()["shed"]["overload"] == 1
        finally:
            srv.close(drain=True)
        # the admitted two still completed correctly on drain
        assert_identical(f1.result(timeout=0),
                         retriever.search([QUERIES[0]], k=2))
        assert_identical(f2.result(timeout=0),
                         retriever.search([QUERIES[1]], k=2))

    def test_inflight_releases_after_completion(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(queue_depth=2,
                                               cache_entries=0))
        try:
            srv.search([QUERIES[0]], k=2)
            srv.search([QUERIES[1]], k=2)  # would raise if depth leaked
            assert srv.metrics_snapshot()["queue"]["depth"] == 0
        finally:
            srv.close()

    def test_deadline_shed_is_typed_and_counted(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(cache_entries=0))
        try:
            f = srv.submit([QUERIES[0]], k=2, deadline_ms=0)
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=10)
            assert srv.metrics_snapshot()["shed"]["deadline"] == 1
        finally:
            srv.close()

    def test_default_deadline_from_config(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(default_deadline_ms=0,
                                               cache_entries=0))
        try:
            with pytest.raises(DeadlineExceeded):
                srv.search([QUERIES[0]], k=2, timeout=10)
        finally:
            srv.close()

    def test_swap_index_serves_new_corpus(self, retriever):
        new = TfidfRetriever(CFG).index(CORPUS_B)
        srv = TfidfServer(retriever, quick_cfg())
        try:
            assert_identical(srv.search(["zebra yak"], k=2),
                             retriever.search(["zebra yak"], k=2))
            assert srv.swap_index(new) == 1
            assert srv.epoch == 1
            # post-swap responses are parity with the NEW index
            assert_identical(srv.search(["zebra yak"], k=2),
                             new.search(["zebra yak"], k=2))
            assert srv.num_docs == 3
        finally:
            srv.close()

    def test_swap_invalidates_cache(self, retriever):
        # Swap to an identical index: bytes stay equal, but the cache
        # must re-miss (epoch key + clear), never serve epoch-0 rows.
        twin = TfidfRetriever(CFG).index(CORPUS)
        srv = TfidfServer(retriever, quick_cfg())
        try:
            first = srv.search(QUERIES[:2], k=3)
            srv.swap_index(twin)
            before = srv.metrics_snapshot()["cache"]
            again = srv.search(QUERIES[:2], k=3)
            after = srv.metrics_snapshot()["cache"]
            assert after["misses"] == before["misses"] + 2
            assert after["hits"] == before["hits"]
            assert_identical(again, first)  # identical index -> same bytes
        finally:
            srv.close()

    def test_drain_on_shutdown_resolves_everything(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(max_batch=1024,
                                               max_wait_ms=60_000,
                                               cache_entries=0))
        futs = [srv.submit([q], k=2) for q in QUERIES[:4]]
        srv.close(drain=True)
        for f, q in zip(futs, QUERIES[:4]):
            assert_identical(f.result(timeout=0),
                             retriever.search([q], k=2))
        with pytest.raises(ServeError):
            srv.submit(["x"], k=1)

    def test_metrics_snapshot_schema(self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        try:
            srv.search(QUERIES[:2], k=3)
            snap = srv.metrics_snapshot()
        finally:
            srv.close()
        json.dumps(snap)  # JSON-serializable end to end
        assert snap["requests"] == 1 and snap["queries"] == 2
        assert {"overload", "deadline", "rate"} <= snap["shed"].keys()
        assert {"hits", "misses", "hit_rate"} <= snap["cache"].keys()
        assert {"count", "mean_occupancy"} <= snap["batch"].keys()
        lat = snap["latency_s"]
        assert lat["count"] == 1 and lat["p99"] >= lat["p50"] > 0
        assert 0 < snap["batch"]["mean_occupancy"] <= 1

    def test_snapshot_superset_of_pr4_pinned_schema(self, retriever):
        """Satellite (ISSUE 6): the serve metrics snapshot keys must
        stay a SUPERSET of the round-9 documented schema — the perf
        ledger normalizes by these exact paths, so a silent field
        rename would corrupt the trajectory record. Growing the
        snapshot is fine; renaming/removing is the regression."""
        PR4_SCHEMA = {
            "requests": None, "queries": None,
            "shed": {"overload", "deadline", "rate"},
            "cache": {"hits", "misses", "hit_rate"},
            "batch": {"count", "mean_occupancy"},
            "queue": {"depth", "peak"},
            "latency_s": {"count", "mean", "min", "max",
                          "p50", "p95", "p99"},
        }
        srv = TfidfServer(retriever, quick_cfg())
        try:
            srv.search(QUERIES[:2], k=3)
            snap = srv.metrics_snapshot()
        finally:
            srv.close()
        for key, inner in PR4_SCHEMA.items():
            assert key in snap, f"pinned key {key!r} disappeared"
            if inner is not None:
                assert inner <= snap[key].keys(), (
                    f"pinned inner keys of {key!r} shrank: "
                    f"{inner - snap[key].keys()}")
        # Round-16 additions pinned alongside: the slo object (the
        # "SLO snapshot" the serve CLI metrics-op docstring promises)
        # and the slow-query counter are part of the schema now.
        assert "slo" in snap and "configured" in snap["slo"]
        assert "slow_queries" in snap

    def test_metrics_slo_snapshot_promise(self, retriever):
        """Satellite (ISSUE 11): cli.py's metrics-op docstring
        promises an "SLO snapshot" — true now: without an objective
        the slo object is the typed not-configured marker; with
        --slo-ms / ServeConfig.slo_ms it carries windowed compliance
        and fast/slow burn rates."""
        with TfidfServer(retriever, quick_cfg()) as srv:
            assert srv.metrics_snapshot()["slo"] == {
                "configured": False}
        srv = TfidfServer(retriever, quick_cfg(slo_ms=10_000.0))
        try:
            srv.search(QUERIES[:2], k=3)
            slo = srv.metrics_snapshot()["slo"]
        finally:
            srv.close()
        assert slo["configured"] is True
        assert {"objective_ms", "target", "compliance", "fast_burn",
                "slow_burn", "good", "total"} <= slo.keys()
        assert slo["total"] >= 1 and slo["good"] >= 1
        assert slo["compliance"] == 1.0  # 10 s objective: all good
        assert slo["fast_burn"] == 0.0

    def test_snapshot_is_self_describing(self, retriever):
        """Satellite (ISSUE 6): uptime_s / epoch / build fingerprint
        ride every snapshot, so a ledgered artifact says what it
        measured."""
        twin = TfidfRetriever(CFG).index(CORPUS)
        srv = TfidfServer(retriever, quick_cfg())
        try:
            srv.search(QUERIES[:1], k=2)
            snap = srv.metrics_snapshot()
            assert snap["uptime_s"] >= 0
            assert snap["epoch"] == 0
            fp = snap["fingerprint"]
            assert set(fp) == {"config_sha", "backend", "num_docs",
                               "vocab_size"}
            assert len(fp["config_sha"]) == 12
            assert fp["num_docs"] == 5 and fp["vocab_size"] == 512
            # Stable across snapshots, bumps with a swap.
            assert srv.metrics_snapshot()["fingerprint"] == fp
            srv.swap_index(twin)
            snap2 = srv.metrics_snapshot()
            assert snap2["epoch"] == 1
            json.dumps(snap2)  # still artifact-serializable
        finally:
            srv.close()

    def test_empty_request_resolves_immediately(self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        try:
            vals, idx = srv.search([], k=3)
            assert vals.shape == (0, 3) and idx.shape == (0, 3)
        finally:
            srv.close()

    def test_unindexed_retriever_rejected(self):
        with pytest.raises(ValueError):
            TfidfServer(TfidfRetriever(CFG), quick_cfg())

    def test_swap_unindexed_rejected(self, retriever):
        with TfidfServer(retriever, quick_cfg()) as srv:
            with pytest.raises(ValueError):
                srv.swap_index(TfidfRetriever(CFG))

    def test_serve_config_validation_and_env(self, monkeypatch):
        with pytest.raises(ValueError):
            ServeConfig(max_batch=0)
        with pytest.raises(ValueError):
            ServeConfig(queue_depth=0)
        with pytest.raises(ValueError):
            ServeConfig(cache_entries=-1)
        monkeypatch.setenv("TFIDF_TPU_MAX_BATCH", "16")
        monkeypatch.setenv("TFIDF_TPU_MAX_WAIT_MS", "7.5")
        monkeypatch.setenv("TFIDF_TPU_QUEUE_DEPTH", "99")
        monkeypatch.setenv("TFIDF_TPU_CACHE_ENTRIES", "3")
        cfg = ServeConfig.from_env()
        assert (cfg.max_batch, cfg.max_wait_ms,
                cfg.queue_depth, cfg.cache_entries) == (16, 7.5, 99, 3)
        # explicit overrides beat the env (the CLI resolution order)
        assert ServeConfig.from_env(max_batch=4).max_batch == 4


class TestSearchBucketing:
    """Satellite: ad-hoc repeated searches must not re-jit per query
    count — Q pads to power-of-two buckets inside search."""

    def test_compile_count_pinned_across_counts(self):
        from tfidf_tpu.models.retrieval import _search_tiled
        # Fresh shape signature (unique vocab+k) so other tests' cache
        # entries can't mask or inflate the delta. Round 21: the tiled
        # scorer is the default dispatch, so the pin moves to it.
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=1024,
                             max_doc_len=16, doc_chunk=16)
        r = TfidfRetriever(cfg).index(CORPUS)
        base = _search_tiled._cache_size()
        for n in (3, 4):           # same bucket (4)
            r.search(["apple"] * n, k=5)
        assert _search_tiled._cache_size() == base + 1
        for n in (5, 7, 6, 8):     # all bucket 8
            r.search(["banana"] * n, k=5)
        assert _search_tiled._cache_size() == base + 2
        for n in (1, 2, 3, 4, 5, 6, 7, 8):  # buckets 1,2 are new
            r.search(["fig"] * n, k=5)
        assert _search_tiled._cache_size() == base + 4

    def test_compile_count_pinned_untiled_fallback(self, monkeypatch):
        from tfidf_tpu.models.retrieval import _search_bcoo
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "off")
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=1024,
                             max_doc_len=16, doc_chunk=16)
        r = TfidfRetriever(cfg).index(CORPUS)
        base = _search_bcoo._cache_size()
        for n in (3, 4):           # same bucket (4)
            r.search(["apple"] * n, k=5)
        assert _search_bcoo._cache_size() == base + 1
        for n in (5, 7, 6, 8):     # all bucket 8
            r.search(["banana"] * n, k=5)
        assert _search_bcoo._cache_size() == base + 2

    def test_bucketed_results_match_per_count(self, retriever):
        # Padded zero columns must stay inert: each query's row is the
        # same whether searched alone or inside any batch size.
        whole = retriever.search(QUERIES, k=4)
        for i, q in enumerate(QUERIES):
            alone = retriever.search([q], k=4)
            np.testing.assert_array_equal(alone[0][0], whole[0][i])
            np.testing.assert_array_equal(alone[1][0], whole[1][i])

    def test_empty_query_list(self, retriever):
        vals, idx = retriever.search([], k=3)
        assert vals.shape == (0, 3) and idx.shape == (0, 3)


class TestServeCli:
    def _run(self, lines, argv, monkeypatch, capsys):
        from tfidf_tpu.cli import main
        monkeypatch.setattr("sys.stdin",
                            io.StringIO("\n".join(lines) + "\n"))
        rc = main(argv)
        out = capsys.readouterr().out
        return rc, [json.loads(l) for l in out.splitlines() if l]

    @pytest.fixture
    def distinct_corpus_dir(self, tmp_path):
        d = tmp_path / "input"
        d.mkdir()
        for i, text in enumerate(
                [b"apple banana", b"cherry date", b"elder fig grape",
                 b"apple grape"], start=1):
            (d / f"doc{i}").write_bytes(text)
        return str(d)

    def test_jsonl_request_loop(self, distinct_corpus_dir, monkeypatch,
                                capsys):
        rc, resp = self._run(
            [json.dumps({"id": 1, "queries": ["cherry date"], "k": 2}),
             json.dumps({"op": "metrics"}),
             json.dumps({"op": "shutdown"})],
            ["serve", "--input", distinct_corpus_dir,
             "--vocab-size", "512", "--max-wait-ms", "1"],
            monkeypatch, capsys)
        assert rc == 0
        by_id = {r.get("id"): r for r in resp if "results" in r}
        hits = by_id[1]["results"][0]
        assert hits and hits[0][0] == "doc2" and hits[0][1] > 0
        metrics = next(r for r in resp if "metrics" in r)
        assert "latency_s" in metrics["metrics"]

    def test_bad_requests_get_error_lines(self, distinct_corpus_dir,
                                          monkeypatch, capsys):
        rc, resp = self._run(
            ["this is not json",
             json.dumps({"id": 7, "queries": "not-a-list"}),
             json.dumps({"op": "nope"}),
             json.dumps({"op": "shutdown"})],
            ["serve", "--input", distinct_corpus_dir,
             "--vocab-size", "512", "--max-wait-ms", "1"],
            monkeypatch, capsys)
        assert rc == 0
        assert len(resp) == 3 and all("error" in r for r in resp)

    def test_swap_index_op(self, distinct_corpus_dir, tmp_path,
                           monkeypatch, capsys):
        other = tmp_path / "other"
        other.mkdir()
        # two docs: a 1-doc corpus has idf = log(1/1) = 0 everywhere
        (other / "doc1").write_bytes(b"zebra yak")
        (other / "doc2").write_bytes(b"aardvark wolf")
        rc, resp = self._run(
            [json.dumps({"id": 1, "op": "swap_index",
                         "input": str(other)}),
             json.dumps({"id": 2, "queries": ["zebra"], "k": 1}),
             json.dumps({"op": "shutdown"})],
            ["serve", "--input", distinct_corpus_dir,
             "--vocab-size", "512", "--max-wait-ms", "1"],
            monkeypatch, capsys)
        assert rc == 0
        swap = next(r for r in resp if r.get("id") == 1)
        assert swap == {"id": 1, "swapped": True, "epoch": 1}
        hit = next(r for r in resp if r.get("id") == 2)
        assert hit["results"][0][0][0] == "doc1"

    def test_healthz_readyz_canary_ops(self, distinct_corpus_dir,
                                       monkeypatch, capsys):
        rc, resp = self._run(
            [json.dumps({"id": 1, "queries": ["apple"], "k": 2}),
             json.dumps({"id": 2, "op": "healthz"}),
             json.dumps({"id": 3, "op": "readyz"}),
             json.dumps({"id": 4, "op": "canary"}),
             json.dumps({"id": 5, "op": "metrics"}),
             json.dumps({"op": "shutdown"})],
            ["serve", "--input", distinct_corpus_dir,
             "--vocab-size", "512", "--max-wait-ms", "1"],
            monkeypatch, capsys)
        assert rc == 0
        by_id = {r.get("id"): r for r in resp}
        hz = by_id[2]["healthz"]
        assert hz["status"] == "ok"
        assert hz["admission_bound"] == hz["queue_depth"]
        assert "batcher" in hz["checks"]["workers"]
        rz = by_id[3]["readyz"]
        assert rz["ready"] is True and rz["epoch"] == 0
        # The CLI's default canary (pinned doc-prefix queries) probes
        # on demand and reports full parity on the healthy index.
        assert by_id[4]["canary"] == {"parity": 1.0}
        metrics = by_id[5]["metrics"]
        assert {"uptime_s", "epoch", "fingerprint"} <= metrics.keys()

    def test_canary_op_reports_disabled(self, distinct_corpus_dir,
                                        monkeypatch, capsys):
        rc, resp = self._run(
            [json.dumps({"id": 1, "op": "canary"}),
             json.dumps({"op": "shutdown"})],
            ["serve", "--input", distinct_corpus_dir,
             "--vocab-size", "512", "--max-wait-ms", "1",
             "--canary-period-ms", "0"],
            monkeypatch, capsys)
        assert rc == 0
        assert "disabled" in resp[0]["error"]

    def test_query_subcommand_takes_compile_cache(self, distinct_corpus_dir,
                                                  tmp_path, capsys):
        from tfidf_tpu.cli import main
        cache_dir = tmp_path / "xla_cache"
        rc = main(["query", "--input", distinct_corpus_dir,
                   "--vocab-size", "512", "--query", "apple", "-k", "2",
                   "--compile-cache", str(cache_dir)])
        assert rc == 0
        assert "doc1" in capsys.readouterr().out
        assert cache_dir.is_dir()  # cache armed before the jitted work


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
class TestServeBenchSmoke:
    """End-to-end: boot TfidfServer in-process via tools/serve_bench.py
    and pin the SERVE artifact schema + sane ranges."""

    def test_artifact_schema_and_zero_recompiles(self, tmp_path):
        out = tmp_path / "SERVE_smoke.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
             "--requests", "64", "--docs", "128", "--doc-len", "32",
             "--ab-reqtrace", "--out", str(out)],
            capture_output=True, text=True, timeout=540, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        art = json.loads(out.read_text())
        for key in ("metric", "mode", "requests", "queries", "wall_s",
                    "throughput_rps", "throughput_qps", "latency_ms",
                    "batch", "cache", "shed", "recompiles_after_warmup",
                    "slo", "slow_queries", "reqtrace"):
            assert key in art, key
        # Round-16 receipts: the SLO snapshot rode the artifact and
        # the request-identity overhead was measured on the
        # device-bound path (absolute numbers are box noise; the
        # structure and sanity bounds are the pin).
        assert art["slo"]["configured"] is True
        assert 0 <= art["slo"]["compliance"] <= 1
        assert art["slow_queries"] >= 0
        rq = art["reqtrace"]
        assert rq["p50_ms_off"] > 0 and rq["p50_ms_on"] > 0
        assert rq["p50_regression"] < 0.5  # sanity, not the 2% claim
        assert art["metric"] == "serve_bench"
        assert art["requests"] == 64
        assert art["queries"] >= 64
        assert art["throughput_qps"] > 0
        lat = art["latency_ms"]
        assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"]
        assert 0 < art["batch"]["mean_occupancy"] <= 1
        assert 0 <= art["cache"]["hit_rate"] <= 1
        assert 0 <= art["shed"]["rate"] <= 1
        # steady-state serving re-jits nothing after bucket warmup
        assert art["recompiles_after_warmup"] == 0
