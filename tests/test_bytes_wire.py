"""The bytes wire (round 14): raw UTF-8 on the wire, tokenize+hash on
device. Pins the device tokenizer bit-identical to BOTH host packers
(the Python semantics oracle and native/fast_tokenizer.so) over random
byte corpora — multi-byte UTF-8 runs, all-whitespace docs, token byte
truncation, the max-per-doc token cap, and tokens straddling bucket /
kernel-block boundaries — plus the Pallas/XLA hash-lowering parity,
run_overlapped end-to-end parity on every regime, the three-way wire
selection chain (bytes -> ragged -> padded), and the new slab /
device_tokenize trace spans."""

import os
import subprocess

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig
from tfidf_tpu import ingest as ing
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io import fast_tokenizer
from tfidf_tpu.ops.device_tokenize import (aligned_byte_lengths,
                                           fnv1a_step, fold_mod,
                                           seed_state,
                                           tokenize_hash_device,
                                           tokenize_method)
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.ops.tokenize import whitespace_tokenize

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native")


@pytest.fixture(scope="session", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE_DIR], check=True,
                   capture_output=True)


def _cfg(**kw):
    base = dict(vocab_mode=VocabMode.HASHED, vocab_size=1 << 10,
                max_doc_len=64, doc_chunk=64, topk=5, engine="sparse",
                wire="bytes")
    base.update(kw)
    return PipelineConfig(**base)


def build_slab(docs, align, bucket=1024):
    """Reference slab builder (the layout contract in
    ops/device_tokenize.py): doc bytes at aligned offsets, 0x20 fill."""
    blens = np.array([len(d) for d in docs], np.int32)
    albl = aligned_byte_lengths(blens, align)
    total = int(albl.sum())
    cap = max(total + (-total % bucket), bucket)
    slab = np.full(cap, 0x20, np.uint8)
    off = 0
    for doc, a in zip(docs, albl.tolist()):
        slab[off:off + len(doc)] = np.frombuffer(doc, np.uint8)
        off += int(a)
    return slab, blens


def host_ids(docs, length, vocab, seed, trunc):
    """The Python host packer's [D, L] contract — THE semantics oracle
    (whitespace_tokenize + words_to_ids, zero-filled padding)."""
    ids = np.zeros((len(docs), length), np.int32)
    lens = np.zeros(len(docs), np.int32)
    for i, doc in enumerate(docs):
        toks = whitespace_tokenize(doc, trunc)[:length]
        lens[i] = len(toks)
        if toks:
            ids[i, :len(toks)] = words_to_ids(toks, vocab, seed)
    return ids, lens


class TestFnvEmulation:
    """The paired-uint32-limb FNV-1a64 emulation equals Python's
    arbitrary-precision arithmetic, byte for byte."""

    def test_step_matches_bigint(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        P = 1099511628211
        h = rng.integers(0, 1 << 64, 64, dtype=np.uint64)
        b = rng.integers(0, 256, 64, dtype=np.uint64)
        hi, lo = fnv1a_step(
            jnp.asarray((h >> np.uint64(32)).astype(np.uint32)),
            jnp.asarray((h & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
            jnp.asarray(b.astype(np.uint32)))
        for i in range(64):
            ref = ((int(h[i]) ^ int(b[i])) * P) % (1 << 64)
            got = (int(hi[i]) << 32) | int(lo[i])
            assert got == ref, (i, hex(got), hex(ref))

    def test_fold_mod_matches_bigint(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        h = rng.integers(0, 1 << 64, 64, dtype=np.uint64)
        for vocab in (1 << 16, 65535, 1 << 10, 999, 7, 1):
            ids = fold_mod(
                jnp.asarray((h >> np.uint64(32)).astype(np.uint32)),
                jnp.asarray((h & np.uint64(0xFFFFFFFF))
                            .astype(np.uint32)), vocab)
            ref = [(int(x) ^ (int(x) >> 32)) % vocab for x in h]
            np.testing.assert_array_equal(np.asarray(ids), ref)

    def test_fold_mod_rejects_wide_vocab(self):
        import jax.numpy as jnp
        one = jnp.zeros((1,), jnp.uint32)
        with pytest.raises(ValueError, match="2\\^16"):
            fold_mod(one, one, (1 << 16) + 1)

    def test_seed_state(self):
        hi, lo = seed_state(0xDEADBEEF12345678)
        ref = 14695981039346656037 ^ 0xDEADBEEF12345678
        assert (int(hi) << 32) | int(lo) == ref


class TestDeviceTokenizeParity:
    """Property test: device tokenize+hash is bit-identical to the
    Python host oracle over random byte corpora — both lowerings."""

    CASES = [
        # multi-byte UTF-8 runs, empties, whitespace-only docs
        ([b"hello world", b"", b"   \t\n ",
          "héllo wörld 中文 éé".encode(),
          b"a b c d e f g h i j k l m n o p"], None),
        # the whitespace family, every separator byte
        ([b"a\tb\nc\x0bd\x0ce\rf g", b"\r\n\t", b"x"], None),
        # token byte-truncation (the reference's 16-char quirk) and a
        # token far longer than any static cap could guess
        ([b"supercalifragilisticexpialidocious tiny",
          b"w" * 5000 + b" end"], 16),
        ([b"supercalifragilisticexpialidocious tiny"], 3),
    ]

    @pytest.mark.parametrize("method", ["xla", "pallas"])
    @pytest.mark.parametrize("align", [1, 4, 16])
    def test_fixed_cases(self, method, align):
        for docs, trunc in self.CASES:
            slab, blens = build_slab(docs, align)
            tok, lens = tokenize_hash_device(
                slab, blens, length=8, vocab_size=1000, seed=7,
                truncate_at=trunc, align=align, method=method,
                interpret=True)
            eids, elens = host_ids(docs, 8, 1000, 7, trunc)
            np.testing.assert_array_equal(np.asarray(lens), elens)
            np.testing.assert_array_equal(np.asarray(tok), eids)

    @pytest.mark.parametrize("method", ["xla", "pallas"])
    def test_random_binary_corpora(self, method):
        rng = np.random.default_rng(3)
        for case in range(8):
            docs = [bytes(rng.integers(1, 256,
                                       rng.integers(0, 300))
                          .astype(np.uint8))
                    for _ in range(int(rng.integers(1, 10)))]
            trunc = [None, 4, 16][case % 3]
            length = int(rng.integers(1, 24))
            slab, blens = build_slab(docs, 16)
            tok, lens = tokenize_hash_device(
                slab, blens, length=length, vocab_size=1 << 10,
                seed=case, truncate_at=trunc, align=16, method=method,
                interpret=True)
            eids, elens = host_ids(docs, length, 1 << 10, case, trunc)
            np.testing.assert_array_equal(np.asarray(lens), elens)
            np.testing.assert_array_equal(np.asarray(tok), eids)

    def test_token_straddles_bucket_boundary(self):
        # One doc engineered so a token's bytes cross the 1024-byte
        # slab bucket (and any power-of-two kernel block) boundary.
        doc = b"x" * 1019 + b" straddler " + b"y" * 50
        slab, blens = build_slab([doc], 16, bucket=1024)
        assert slab.size > 1024  # the straddler crossed the bucket
        tok, lens = tokenize_hash_device(
            slab, blens, length=4, vocab_size=1 << 10, seed=0,
            align=16, method="xla")
        eids, elens = host_ids([doc], 4, 1 << 10, 0, None)
        np.testing.assert_array_equal(np.asarray(tok), eids)
        np.testing.assert_array_equal(np.asarray(lens), elens)

    def test_max_per_doc_cap(self):
        # More tokens than L: device lengths cap at L and ids carry
        # the FIRST L tokens, like TokenizeHashInto's max_out.
        doc = b" ".join(f"t{i}".encode() for i in range(40))
        slab, blens = build_slab([doc], 16)
        tok, lens = tokenize_hash_device(
            slab, blens, length=10, vocab_size=1 << 10, seed=0,
            align=16, method="xla")
        assert int(lens[0]) == 10
        eids, _ = host_ids([doc], 10, 1 << 10, 0, None)
        np.testing.assert_array_equal(np.asarray(tok), eids)

    @pytest.mark.skipif(not fast_tokenizer.loader_available(),
                        reason="native loader not built")
    def test_matches_native_packer(self, tmp_path):
        rng = np.random.default_rng(9)
        docs, paths = [], []
        for i in range(12):
            words = [f"w{rng.integers(0, 500)}"
                     for _ in range(int(rng.integers(0, 30)))]
            doc = " ".join(words).encode()
            p = tmp_path / f"doc{i + 1}"
            p.write_bytes(doc)
            docs.append(doc)
            paths.append(str(p))
        native = fast_tokenizer.load_pack_paths(
            paths, 1 << 12, seed=5, truncate_at=16, fixed_len=16,
            pad_docs_to=16)
        assert native is not None
        slab, blens = build_slab(docs, 16)
        blens = np.concatenate([blens,
                                np.zeros(16 - len(docs), np.int32)])
        tok, lens = tokenize_hash_device(
            slab, blens, length=16, vocab_size=1 << 12, seed=5,
            truncate_at=16, align=16, method="xla")
        np.testing.assert_array_equal(np.asarray(lens), native[1])
        np.testing.assert_array_equal(np.asarray(tok),
                                      native[0].astype(np.int32))


class TestSlabPackers:
    """Native and Python slab packers emit the identical wire."""

    def _write(self, tmp_path, docs):
        names = []
        for i, d in enumerate(docs):
            (tmp_path / f"doc{i + 1}").write_bytes(d)
            names.append(f"doc{i + 1}")
        return names

    @pytest.mark.skipif(not fast_tokenizer.slab_available(),
                        reason="native slab loader not built")
    def test_native_matches_python(self, tmp_path, monkeypatch):
        docs = [b"alpha beta", b"", b"  x  ", b"q" * 100]
        names = self._write(tmp_path, docs)
        cfg = _cfg()
        native = ing.make_bytes_packer(str(tmp_path), cfg, 8, 64)
        s_n, b_n, t_n = native(names)
        monkeypatch.setenv("TFIDF_TPU_NO_NATIVE", "1")
        python = ing.make_bytes_packer(str(tmp_path), cfg, 8, 64)
        s_p, b_p, t_p = python(names)
        assert t_n == t_p
        np.testing.assert_array_equal(b_n, b_p)
        np.testing.assert_array_equal(s_n, s_p)

    def test_stats_split(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_NO_NATIVE", "1")
        names = self._write(tmp_path, [b"a b c", b"d"])
        stats = {}
        pack = ing.make_bytes_packer(str(tmp_path), _cfg(), 4, 64,
                                     stats=stats)
        pack(names)
        assert set(stats) == {"load", "slab"}
        assert all(v >= 0 for v in stats.values())

    def test_slab_guard_names_bound(self):
        with pytest.raises(ValueError, match="int32"):
            ing._check_slab_fits_int32(1 << 31)
        ing._check_slab_fits_int32(1 << 20)  # fits


class TestRunOverlappedBytes:
    """End-to-end: --wire=bytes equals --wire=ragged on every regime —
    df, top-k ids, lengths bit-identical; scores allclose."""

    @pytest.fixture
    def corpus_dir(self, tmp_path):
        rng = np.random.default_rng(7)
        for i in range(1, 41):
            words = [f"w{rng.integers(0, 60)}"
                     for _ in range(int(rng.integers(0, 40)))]
            (tmp_path / f"doc{i}").write_text(" ".join(words))
        return str(tmp_path)

    @pytest.mark.parametrize("regime", ["resident", "streaming",
                                        "streaming-cached"])
    def test_parity(self, corpus_dir, regime, monkeypatch):
        if regime.startswith("streaming"):
            monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        if regime == "streaming":
            monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        r_b = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        r_r = ing.run_overlapped(corpus_dir, _cfg(wire="ragged"),
                                 chunk_docs=16, doc_len=64)
        assert r_b.wire == "bytes" and r_r.wire == "ragged"
        np.testing.assert_array_equal(r_b.df, r_r.df)
        np.testing.assert_array_equal(r_b.topk_ids, r_r.topk_ids)
        np.testing.assert_allclose(r_b.topk_vals, r_r.topk_vals,
                                   rtol=1e-6)
        # lengths are DEVICE-derived on the bytes wire — same values.
        np.testing.assert_array_equal(r_b.lengths, r_r.lengths)
        assert r_b.bytes_on_wire > 0
        assert r_b.bytes_on_wire_padded == r_r.bytes_on_wire_padded

    def test_truncate_parity(self, corpus_dir):
        r_b = ing.run_overlapped(corpus_dir,
                                 _cfg(truncate_tokens_at=2),
                                 chunk_docs=16, doc_len=64)
        r_r = ing.run_overlapped(corpus_dir,
                                 _cfg(wire="ragged",
                                      truncate_tokens_at=2),
                                 chunk_docs=16, doc_len=64)
        np.testing.assert_array_equal(r_b.topk_ids, r_r.topk_ids)
        np.testing.assert_array_equal(r_b.df, r_r.df)

    def test_pallas_method_parity(self, corpus_dir, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_DEVICE_TOKENIZE", "pallas")
        r_p = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        monkeypatch.setenv("TFIDF_TPU_DEVICE_TOKENIZE", "xla")
        r_x = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        np.testing.assert_array_equal(r_p.topk_ids, r_x.topk_ids)
        np.testing.assert_array_equal(r_p.df, r_x.df)
        np.testing.assert_array_equal(r_p.lengths, r_x.lengths)

    def test_pair_result_wire(self, corpus_dir):
        r_b = ing.run_overlapped(corpus_dir,
                                 _cfg(result_wire="pair"),
                                 chunk_docs=16, doc_len=64)
        r_r = ing.run_overlapped(corpus_dir,
                                 _cfg(wire="ragged",
                                      result_wire="pair"),
                                 chunk_docs=16, doc_len=64)
        assert r_b.result_wire == "pair"
        np.testing.assert_array_equal(r_b.topk_ids, r_r.topk_ids)

    def test_python_fallback_parity(self, corpus_dir, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_NO_NATIVE", "1")
        r_b = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        monkeypatch.delenv("TFIDF_TPU_NO_NATIVE")
        r_n = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        np.testing.assert_array_equal(r_b.topk_ids, r_n.topk_ids)
        np.testing.assert_array_equal(r_b.df, r_n.df)

    def test_profile_resident_bytes(self, corpus_dir):
        cfg = _cfg()
        ing.run_overlapped(corpus_dir, cfg, chunk_docs=16, doc_len=64)
        ph = ing.profile_resident(corpus_dir, cfg, chunk_docs=16,
                                  doc_len=64)
        assert ph["compute"] > 0 and ph["bytes_on_wire"] > 0


class TestWireSelection:
    """The bytes -> ragged -> padded degradation chain and the env
    override."""

    def test_config_accepts_bytes(self):
        assert _cfg().wire == "bytes"

    def test_config_rejects_unknown(self):
        with pytest.raises(ValueError, match="wire"):
            _cfg(wire="utf8")

    def test_bytes_selected(self):
        assert ing.use_bytes_wire(_cfg(), 16, 64)

    def test_wide_vocab_degrades_to_padded(self):
        cfg = _cfg(vocab_size=(1 << 16) + 1)
        assert not ing.use_bytes_wire(cfg, 16, 64)
        assert not ing.use_ragged_wire(cfg, 16, 64)

    def test_chargram_degrades(self):
        from tfidf_tpu.config import TokenizerKind
        cfg = _cfg(tokenizer=TokenizerKind.CHARGRAM)
        assert not ing.use_bytes_wire(cfg, 16, 64)

    def test_ragged_ask_never_bytes(self):
        assert not ing.use_bytes_wire(_cfg(wire="ragged"), 16, 64)

    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_WIRE", "bytes")
        assert ing.use_bytes_wire(_cfg(wire="ragged"), 16, 64)
        monkeypatch.setenv("TFIDF_TPU_WIRE", "padded")
        assert not ing.use_bytes_wire(_cfg(), 16, 64)
        assert not ing.use_ragged_wire(_cfg(), 16, 64)

    def test_env_validates(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_WIRE", "csr")
        with pytest.raises(ValueError, match="TFIDF_TPU_WIRE"):
            ing.resolve_wire(_cfg())

    def test_method_env_validates(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_DEVICE_TOKENIZE", "mosaic")
        with pytest.raises(ValueError,
                           match="TFIDF_TPU_DEVICE_TOKENIZE"):
            tokenize_method()

    def test_pack_threads_validates(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_PACK_THREADS", "0")
        with pytest.raises(ValueError, match="TFIDF_TPU_PACK_THREADS"):
            fast_tokenizer.resolve_pack_threads()
        assert fast_tokenizer.resolve_pack_threads(3) == 3


class TestTraceSpans:
    """Bytes-wire runs emit byte-stamped slab (packer lane) and
    device_tokenize (main lane) spans; tools/trace_check.py accepts
    the trace (satellite: the doctor's cost attribution feeds on
    exactly these stamps)."""

    def test_spans_and_trace_check(self, tmp_path, monkeypatch):
        rng = np.random.default_rng(5)
        cdir = tmp_path / "corpus"
        cdir.mkdir()
        for i in range(1, 31):
            words = [f"w{rng.integers(0, 40)}"
                     for _ in range(int(rng.integers(1, 30)))]
            (cdir / f"doc{i}").write_text(" ".join(words))
        trace = str(tmp_path / "trace.json")
        from tfidf_tpu import obs
        prior = obs.get_tracer()
        try:
            obs.configure(trace)
            ing.run_overlapped(str(cdir), _cfg(), chunk_docs=10,
                               doc_len=64)
            path = obs.export()
        finally:
            obs.set_tracer(prior)
        assert path
        import importlib.util as ilu
        spec = ilu.spec_from_file_location(
            "_tc", os.path.join(os.path.dirname(NATIVE_DIR), "tools",
                                "trace_check.py"))
        tc = ilu.module_from_spec(spec)
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(NATIVE_DIR),
                                        "tools"))
        try:
            spec.loader.exec_module(tc)
        finally:
            sys.path.pop(0)
        errors, notes = tc.check_trace(path, "ingest", min_threads=2)
        assert not errors, errors
        assert any("bytes wire" in n for n in notes), notes
