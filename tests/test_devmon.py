"""Device-truth telemetry (ISSUE 7): HBM accounting, compile watchdog,
per-span cost attribution, and the one-shot doctor.

The acceptance pins: the monitor runs its FULL path on a backend whose
``memory_stats()`` is None/partial (CPU tier-1) with gauges absent and
zero crashes; a fault-injected low HBM watermark degrades health and
visibly shrinks the admission bound, recovering to ok; the compile
watchdog counts real backend compiles and flags fingerprinted
recompiles after ``mark_warm`` as flight events with a windowed
degraded reason; byte-stamped spans export finite achieved GB/s; and
``tools/doctor.py`` reconciles its phase attribution with
``PhaseTimer`` to within 5%, reports zero recompiles on a clean serve
trace, and exits non-zero on fixture evidence with an injected
recompile or HBM-watermark breach.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import pytest

from tfidf_tpu import obs
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.obs import costmodel, devmon
from tfidf_tpu.obs.health import DEGRADED, OK, HealthMonitor
from tfidf_tpu.obs.log import EventLog
from tfidf_tpu.obs.registry import MetricsRegistry
from tfidf_tpu.serve import Overloaded, TfidfServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCTOR = os.path.join(REPO, "tools", "doctor.py")

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4", "doc5"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig fig",
          b"grape grape grape grape"])
QUERIES = ["apple cherry", "banana date", "grape", "fig elder"]


@pytest.fixture(scope="module")
def retriever():
    return TfidfRetriever(CFG).index(CORPUS)


@pytest.fixture(autouse=True)
def _clean_obs():
    """Private event log, no global tracer/monitor/watch — and none
    leaked back into the rest of the suite."""
    obs.set_log(EventLog(echo="off"))
    obs.set_tracer(None)
    devmon.set_watch(None)
    devmon.set_monitor(None)
    yield
    devmon.set_watch(None)
    devmon.set_monitor(None)
    obs.set_tracer(None)
    obs.set_log(None)


def quick_cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_entries", 64)
    return ServeConfig(**kw)


def _events(name=None):
    evs = obs.get_log().events()
    return [e for e in evs if name is None or e["event"] == name]


# ---------------------------------------------------------------------
class TestCostModel:
    def test_stage_bytes_matches_retired_roofline_model(self):
        # The exact arithmetic tools/roofline.py carried privately
        # before round 12 (d=32768, L=256, k=16, 4-byte elements).
        s = costmodel.stage_bytes(32768, 256, topk=16)
        n = 32768 * 256
        assert s["row_sort"] == n * 4 * 2 * (8 * 9 // 2)
        assert s["rle"] == n * 4 * 6
        assert s["df_global_sort"] == n * 4 * 2 * (23 * 24 // 2)
        assert s["score_topk"] == n * 4 * 4 + 32768 * 16 * 8
        model = costmodel.bytes_model(32768, 256, topk=16)
        assert model["total_gb"] == pytest.approx(21.2777, rel=1e-3)
        assert model["hbm_bound_s"] == pytest.approx(
            model["total_gb"] / costmodel.HBM_PEAK_GBS_DEFAULT)

    def test_hbm_peak_lookup(self):
        assert costmodel.hbm_peak_gbs("TPU v5 lite") == 819.0
        assert costmodel.hbm_peak_gbs("TPU v4") == 1228.0
        assert costmodel.hbm_peak_gbs("TPU v99") == \
            costmodel.HBM_PEAK_GBS_DEFAULT  # unknown TPU -> default
        assert costmodel.hbm_peak_gbs("cpu") is None
        assert costmodel.hbm_peak_gbs(None) is None

    def test_achieved_gbps_degenerate_is_none_not_inf(self):
        assert costmodel.achieved_gbps(1 << 20, 0.0) is None
        assert costmodel.achieved_gbps(-1, 0.5) is None
        assert costmodel.achieved_gbps(2e9, 2.0) == pytest.approx(1.0)

    def test_span_gbps_reads_chrome_event(self):
        ev = {"ph": "X", "dur": 1000.0,  # 1 ms
              "args": {"bytes": 1_000_000}}
        assert costmodel.span_gbps(ev) == pytest.approx(1.0)
        assert costmodel.span_gbps({"ph": "X", "dur": 5.0}) is None


class TestTracerCostExport:
    def test_byte_stamped_span_exports_finite_gbps(self, tmp_path):
        t = obs.Tracer()
        obs.set_tracer(t, str(tmp_path / "t.json"))
        with obs.span("dispatch", bytes=1 << 20):
            time.sleep(0.002)
        evs = [e for e in t.chrome_events() if e.get("ph") == "X"]
        assert len(evs) == 1
        gb_s = evs[0]["args"]["gb_s"]
        assert 0 < gb_s < 1e6 and gb_s == gb_s
        assert gb_s == pytest.approx(
            (1 << 20) / (evs[0]["dur"] * 1e3), rel=0.01)
        # The ring's own args dict stays unannotated (export copies).
        _name, _tid, _t0, _dur, args = t.events()[0]
        assert "gb_s" not in args
        # And the export is valid JSON end to end.
        json.dumps(t.chrome_events())

    def test_ingest_spans_carry_bytes(self, tmp_path, toy_corpus_dir):
        from tfidf_tpu.ingest import run_overlapped
        obs.set_tracer(obs.Tracer())
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        run_overlapped(toy_corpus_dir, cfg, doc_len=16, chunk_docs=2)
        path = str(tmp_path / "t.json")
        obs.export(path)
        by_name = {}
        for e in obs.load_chrome_trace(path):
            if e.get("ph") == "X":
                by_name.setdefault(e["name"], []).append(e)
        for name in ("dispatch", "drain"):
            assert by_name.get(name), f"no {name} spans"
            for e in by_name[name]:
                assert e["args"]["bytes"] > 0


# ---------------------------------------------------------------------
class TestDeviceMonitor:
    def test_cpu_full_path_with_gauges_absent(self):
        """The graceful-degradation contract: CPU memory_stats() is
        None, yet sample/census/watermark/health all run — with no
        gauges ever created."""
        reg = MetricsRegistry()
        mon = devmon.DeviceMonitor(registry=reg)
        snap = mon.sample()
        snap2 = mon.sample()
        assert snap["memory_pressure"] == 0.0
        assert len(snap["devices"]) == len(jax.devices())
        for dev in snap["devices"]:
            assert "bytes_in_use" not in dev  # CPU reports nothing
        assert reg.snapshot() == {}           # gauges absent
        assert snap2["samples"] == 2
        assert mon.peak_bytes == 0
        value, reason = mon.health_signal()
        assert value == 0.0 and reason is None
        json.dumps(mon.census())              # serializable, no crash

    def test_partial_stats_publish_only_present_keys(self):
        reg = MetricsRegistry()
        mon = devmon.DeviceMonitor(
            registry=reg, stats_fn=lambda d: {"bytes_in_use": 128})
        snap = mon.sample()
        names = set(reg.snapshot())
        assert any(n.startswith("hbm_bytes_in_use_d") for n in names)
        assert not any(n.startswith("hbm_peak_bytes") for n in names)
        assert not any(n.startswith("hbm_bytes_limit") for n in names)
        # No limit -> pressure undefined -> stays 0.0, never a crash.
        assert snap["memory_pressure"] == 0.0

    def test_census_attributes_owners_and_skips_broken_ones(self):
        mon = devmon.DeviceMonitor()
        x = jnp.zeros((64, 32), jnp.float32)
        y = jnp.ones((16,), jnp.int32)
        jax.block_until_ready((x, y))
        mon.register_owner("index", lambda: [x, None])
        mon.register_owner("broken", lambda: 1 / 0)
        c = mon.census()
        assert c["owners"]["index"]["bytes"] == x.nbytes
        assert c["owners"]["index"]["arrays"] == 1
        assert "broken" not in c["owners"]
        assert c["total_bytes"] >= x.nbytes + y.nbytes
        assert c["owners"]["other"]["bytes"] >= y.nbytes
        assert any(tuple(s["shape"]) == (64, 32)
                   for s in c["top_shapes"])
        # log_census lands the same data in the flight ring.
        mon.log_census()
        ev = _events("hbm_census")
        assert ev and ev[-1]["owners"]["index"]["bytes"] == x.nbytes

    def test_watermark_events_are_edge_triggered(self):
        state = {"use": 10}
        mon = devmon.DeviceMonitor(
            watermarks=(0.8, 0.95),
            stats_fn=lambda d: {"bytes_in_use": state["use"],
                                "bytes_limit": 100})
        mon.sample()
        assert _events("hbm_watermark") == []
        state["use"] = 85
        mon.sample()
        mon.sample()   # still above: no repeat
        warns = _events("hbm_watermark")
        assert len(warns) == 1 and warns[0]["level"] == "warning"
        assert warns[0]["watermark"] == 0.8
        state["use"] = 99
        mon.sample()
        errs = _events("hbm_watermark")
        assert len(errs) == 2 and errs[-1]["level"] == "error"
        value, reason = mon.health_signal()
        assert value == pytest.approx(0.99)
        assert "watermark" in reason
        state["use"] = 10
        mon.sample()
        assert _events("hbm_watermark_clear")
        assert mon.health_signal() == (pytest.approx(0.1), None)

    def test_background_thread_samples(self):
        mon = devmon.DeviceMonitor(period_s=0.02)
        mon.start()
        try:
            deadline = time.monotonic() + 2.0
            while mon._samples == 0 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert mon._samples > 0
        finally:
            mon.stop()

    def test_configure_respects_env(self, monkeypatch):
        monkeypatch.delenv("TFIDF_TPU_DEVMON", raising=False)
        assert devmon.configure() is None
        monkeypatch.setenv("TFIDF_TPU_DEVMON", "1")
        monkeypatch.setenv("TFIDF_TPU_DEVMON_PERIOD_MS", "50")
        mon = devmon.configure()
        try:
            assert mon is not None and mon.period_s == 0.05
            assert devmon.configure() is mon  # idempotent
        finally:
            mon.stop()
            devmon.set_monitor(None)


class TestMemoryPressureShed:
    def test_pressure_signal_degrades_health_monitor(self):
        state = {"use": 10}
        mon = devmon.DeviceMonitor(
            stats_fn=lambda d: {"bytes_in_use": state["use"],
                                "bytes_limit": 100})
        hm = HealthMonitor()
        hm.add_signal("memory_pressure", mon.health_signal)
        mon.sample()
        assert hm.evaluate().state == OK
        state["use"] = 90
        mon.sample()
        status = hm.evaluate()
        assert status.state == DEGRADED
        assert status.checks["memory_pressure"] == pytest.approx(0.9)
        assert any("memory pressure" in r for r in status.reasons)
        assert hm.admission_bound(100) == 50
        state["use"] = 10
        mon.sample()
        assert hm.evaluate().state == OK

    def test_forced_low_watermark_sheds_and_recovers(self, retriever):
        """THE acceptance pin: fault-injected HBM pressure -> health
        degraded -> admission bound visibly shrinks -> submit sheds ->
        pressure released -> ok again."""
        state = {"use": 10}
        mon = devmon.DeviceMonitor(
            stats_fn=lambda d: {"bytes_in_use": state["use"],
                                "bytes_limit": 100})
        srv = TfidfServer(retriever, quick_cfg(queue_depth=4))
        try:
            srv.attach_device_monitor(mon)
            mon.sample()
            assert srv.healthz()["status"] == OK
            state["use"] = 90          # forced low watermark
            mon.sample()
            hz = srv.healthz()
            assert hz["status"] == DEGRADED
            assert any("memory pressure" in r for r in hz["reasons"])
            assert hz["admission_bound"] == 2   # 4 -> 2 while degraded
            with pytest.raises(Overloaded, match="admission bound 2"):
                srv.submit(QUERIES[:3], k=2)
            state["use"] = 10          # pressure released
            mon.sample()
            # two evaluations: the first still sees the shed we just
            # provoked inside its rate window (test_health pins that
            # decay); the second is clean.
            srv.healthz()
            hz = srv.healthz()
            assert hz["status"] == OK
            assert hz["admission_bound"] == 4
            # and the index shows up as a census owner
            c = mon.census()
            assert c["owners"]["resident_index"]["bytes"] > 0
        finally:
            srv.close(drain=True)


# ---------------------------------------------------------------------
class TestCompileWatch:
    def test_backend_compile_listener_counts(self):
        reg = MetricsRegistry()
        watch = devmon.CompileWatch(registry=reg)
        devmon.set_watch(watch)
        size = int(time.time() * 1e3) % 977 + 31  # fresh jit shape
        jax.jit(lambda v: v * 3 + 1)(
            jnp.zeros((size,), jnp.float32)).block_until_ready()
        assert watch.compiles >= 1
        assert watch.compile_seconds > 0
        assert reg.snapshot()["xla_compiles_total"] >= 1

    def test_note_before_warm_is_breadcrumb_after_is_recompile(self):
        watch = devmon.CompileWatch(recent_s=0.08)
        devmon.set_watch(watch)
        devmon.note_compile("search_bcoo", queries=4, k=8)
        assert watch.recompile_count == 0
        assert _events("xla_recompile") == []
        watch.mark_warm()
        devmon.note_compile("search_bcoo", queries=16, k=8)
        assert watch.recompile_count == 1
        evs = _events("xla_recompile")
        assert evs and evs[0]["program"] == "search_bcoo"
        assert evs[0]["queries"] == 16
        n, reason = watch.health_signal()
        assert n == 1 and "recompile" in reason
        time.sleep(0.1)   # the degraded window DECAYS
        assert watch.health_signal() == (1, None)

    def test_note_compile_without_watch_is_noop(self):
        devmon.note_compile("anything", k=1)   # must not raise

    def test_search_path_fingerprints_fresh_program(self):
        # A corpus shape nothing else in the suite compiles, so the
        # first bucket-2 search provably misses the global jit cache.
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=389,
                             max_doc_len=24, doc_chunk=24)
        corpus = Corpus(names=[f"d{i}" for i in range(9)],
                        docs=[b"alpha beta gamma delta"] * 9)
        r = TfidfRetriever(cfg).index(corpus)
        watch = devmon.CompileWatch()
        devmon.set_watch(watch)
        watch.mark_warm()
        r.search(["alpha beta", "gamma"], k=3)   # bucket 2: fresh
        assert watch.recompile_count >= 1
        fp = watch.recompiles_after_warm()[0]
        # Round 21: tiled scoring is the default search program; the
        # fingerprint must name the path that actually compiled.
        assert fp["program"] == "search_tiled"
        assert fp["queries"] == 2 and fp["k"] == 3
        # warmed shape again: no new note
        before = watch.recompile_count
        r.search(["alpha", "beta"], k=3)
        assert watch.recompile_count == before

    def test_server_installs_watch_and_uninstalls_on_close(
            self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        assert devmon.get_watch() is srv.compile_watch
        srv.close(drain=True)
        assert devmon.get_watch() is None

    def test_recompile_reason_degrades_server_health(self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        try:
            srv.mark_warm()
            srv.compile_watch.note("search_bcoo", queries=32, k=9)
            hz = srv.healthz()
            assert hz["status"] == DEGRADED
            assert any("recompile" in r for r in hz["reasons"])
            assert hz["checks"]["xla_recompiles_after_warm"] == 1
        finally:
            srv.close(drain=True)

    def test_batcher_stamps_recompile_instant(self, tmp_path):
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=401,
                             max_doc_len=24, doc_chunk=24)
        corpus = Corpus(names=[f"d{i}" for i in range(11)],
                        docs=[b"red green blue cyan"] * 11)
        r = TfidfRetriever(cfg).index(corpus)
        obs.set_tracer(obs.Tracer(), str(tmp_path / "t.json"))
        srv = TfidfServer(r, quick_cfg(cache_entries=0))
        try:
            r.search(["red"], k=2)        # warm bucket 1 only
            srv.mark_warm()
            srv.search(["red", "green", "blue"], k=2)  # bucket 4: fresh
        finally:
            srv.close(drain=True)
        assert srv.compile_watch.recompile_count >= 1
        instants = [e for e in obs.get_tracer().chrome_events()
                    if e.get("ph") == "i"
                    and e["name"] == "recompile_in_batch"]
        assert instants, "recompile not pinned to its serve batch"


# ---------------------------------------------------------------------
def _load_tool(name):
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


class TestDoctor:
    def test_phase_attribution_reconciles_with_phase_timer(
            self, tmp_path, toy_corpus_dir):
        """THE acceptance pin: doctor's per-phase totals, read from
        the trace, reconcile with the PhaseTimer-style phases dict the
        ingest returns — within 5% (plus a 5 ms cushion for phases at
        the CPU timer's noise floor)."""
        from tfidf_tpu.ingest import run_overlapped
        obs.set_tracer(obs.Tracer())
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        r = run_overlapped(toy_corpus_dir, cfg, doc_len=16,
                           chunk_docs=2)
        trace = str(tmp_path / "t.json")
        obs.export(trace)
        doctor = _load_tool("doctor")
        report = doctor.diagnose(trace, None,
                                 str(tmp_path / "no_ledger.jsonl"))
        phases = report["phases"]
        ph = r.phases

        def close(a, b):
            return abs(a - b) <= max(0.05 * max(a, b), 0.005)

        # Pairs recorded over the SAME interval by construction
        # (the phase timer and the span wrap one block of code).
        assert close(ph["pack"], phases["pack_wait"]["total_s"])
        assert close(ph["put"], phases["dispatch"]["total_s"])
        assert close(ph["pack_host"], phases["pack"]["total_s"])
        assert close(ph["fetch_host"], phases["drain"]["total_s"])
        assert close(ph["fetch"],
                     phases.get("fetch_wait", {}).get("total_s", 0.0)
                     + phases.get("fetch", {}).get("total_s", 0.0))
        assert report["ok"] and report["violations"] == []
        assert 0.0 <= report["overlap_efficiency"] <= 1.0
        # byte-stamped phases carry their MB
        assert phases["dispatch"]["bytes"] > 0

    def _fixture_trace(self, tmp_path):
        t = obs.Tracer()
        obs.set_tracer(t, None)
        with obs.span("dispatch", chunk=0, bytes=1024):
            time.sleep(0.001)
        trace = str(tmp_path / "fixture.json")
        t.export(trace)
        return trace

    def test_exits_nonzero_on_injected_recompile(self, tmp_path):
        trace = self._fixture_trace(tmp_path)
        log = obs.get_log()
        log.warning("xla_recompile", program="search_bcoo", queries=8,
                    k=5)
        flight = str(tmp_path / "fixture.flight.jsonl")
        log.dump(flight)
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--flight", flight],
            capture_output=True, text=True)
        assert out.returncode == 1, out.stdout + out.stderr
        assert "recompile" in out.stdout.lower()
        # the same evidence passes with the budget raised
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--flight", flight,
             "--allow-recompiles", "1"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_exits_nonzero_on_watermark_breach(self, tmp_path):
        trace = self._fixture_trace(tmp_path)
        log = obs.get_log()
        log.error("hbm_watermark", pressure=0.97, watermark=0.95)
        flight = str(tmp_path / "fixture.flight.jsonl")
        log.dump(flight)
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--flight", flight],
            capture_output=True, text=True)
        assert out.returncode == 1, out.stdout + out.stderr
        assert "watermark" in out.stdout.lower()

    def test_phase_budget_violation(self, tmp_path):
        trace = self._fixture_trace(tmp_path)
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--budget",
             "dispatch=0.0000001", "--json"],
            capture_output=True, text=True)
        assert out.returncode == 1
        report = json.loads(out.stdout)
        assert any("budget" in v for v in report["violations"])

    def test_unreadable_input_exits_2(self, tmp_path):
        out = subprocess.run(
            [sys.executable, DOCTOR, str(tmp_path / "missing.json")],
            capture_output=True, text=True)
        assert out.returncode == 2

    @pytest.mark.slow
    def test_serve_trace_flight_doctor_end_to_end(self, tmp_path,
                                                  retriever):
        """serve -> trace -> flight -> doctor on CPU: the clean-run
        smoke. Zero recompiles after warm-up, doctor healthy."""
        trace = str(tmp_path / "serve.json")
        obs.set_tracer(obs.Tracer(), trace)
        srv = TfidfServer(retriever, quick_cfg())
        try:
            for b in (1, 2, 4, 8):
                retriever.search([QUERIES[0]] * b, k=3)
            srv.mark_warm()
            for i in range(12):
                srv.search([QUERIES[i % 4]], k=3)
            srv.search(QUERIES[:2], k=3)
            srv.search(QUERIES[:4], k=3)
        finally:
            srv.close(drain=True)
        obs.export(trace)
        flight = str(tmp_path / "serve.json.flight.jsonl")
        obs.get_log().dump(flight)
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--flight", flight,
             "--json"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        report = json.loads(out.stdout)
        assert report["ok"]
        assert report["recompile_instants"] == 0
        assert report["flight"]["recompiles"] == []
        assert report["serve"]["requests"] == 14
        # trace_check accepts the same cost-annotated serve trace
        tc = _load_tool("trace_check")
        errors, notes = tc.check_trace(trace, mode="serve",
                                       min_threads=2)
        assert errors == [], (errors, notes)


class TestTraceCheckCostContract:
    def _trace_with(self, tmp_path, args):
        doc = {"traceEvents": [
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "main"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "packer"}},
            {"ph": "X", "pid": 1, "tid": 1, "name": "pack", "ts": 0.0,
             "dur": 5.0, "args": {"chunk": 0}},
            {"ph": "X", "pid": 1, "tid": 0, "name": "dispatch",
             "ts": 1.0, "dur": 5.0, "args": args},
        ]}
        path = str(tmp_path / "t.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def test_dispatch_without_bytes_fails_ingest_mode(self, tmp_path):
        tc = _load_tool("trace_check")
        path = self._trace_with(tmp_path, {"chunk": 0})
        errors, _ = tc.check_trace(path, mode="ingest", min_threads=1)
        assert any("bytes stamp" in e for e in errors)
        path = self._trace_with(tmp_path, {"chunk": 0, "bytes": 4096})
        errors, _ = tc.check_trace(path, mode="ingest", min_threads=1)
        assert not any("bytes stamp" in e for e in errors)

    def test_negative_bytes_or_bad_gbps_fails_schema(self, tmp_path):
        tc = _load_tool("trace_check")
        path = self._trace_with(tmp_path, {"bytes": -5})
        errors, _ = tc.check_trace(path, mode="schema", min_threads=1)
        assert any("bytes" in e for e in errors)
        path = self._trace_with(tmp_path, {"bytes": 5, "gb_s": -1.0})
        errors, _ = tc.check_trace(path, mode="schema", min_threads=1)
        assert any("gb_s" in e for e in errors)


# ---------------------------------------------------------------------
class TestLedgerDeviceTruth:
    def test_multichip_artifacts_normalize_and_gate(self, tmp_path):
        perf_ledger = _load_tool("perf_ledger")
        perf_gate = _load_tool("perf_gate")
        rec, reason = perf_ledger.normalize(
            os.path.join(REPO, "MULTICHIP_r05.json"))
        assert reason is None
        assert rec["kind"] == "multichip"
        assert rec["metrics"]["ok"] == 1        # bool -> gated 0/1
        assert rec["context"]["n_devices"] == 8
        ledger_path = str(tmp_path / "L.jsonl")
        appended, _ = perf_ledger.append(
            perf_ledger.backfill_paths(), ledger_path, quiet=True)
        records = perf_ledger.load_ledger(ledger_path)
        multichip = [r for r in records if r["kind"] == "multichip"]
        assert len(multichip) == 5              # r01-r05 backfilled
        # unchanged artifact passes; a broken mesh run fails
        verdict = perf_gate.gate(rec, records)
        assert verdict["ok"]
        bad = json.loads(json.dumps(rec))
        bad["metrics"]["ok"] = 0
        verdict = perf_gate.gate(bad, records)
        assert not verdict["ok"]
        # and the backfill stays idempotent with multichip in the mix
        appended2, _ = perf_ledger.append(
            perf_ledger.backfill_paths(), ledger_path, quiet=True)
        assert appended2 == 0

    def test_memory_and_compile_metrics_gate_directionally(
            self, tmp_path):
        perf_ledger = _load_tool("perf_ledger")
        perf_gate = _load_tool("perf_gate")
        base = {"metric": "serve_bench", "backend": "cpu", "docs": 64,
                "k": 5, "max_batch": 8, "requests": 10,
                "throughput_qps": 100.0, "peak_hbm_bytes": 1_000_000,
                "xla_compiles": 12}
        ledger_path = str(tmp_path / "L.jsonl")
        for i in range(3):
            p = str(tmp_path / f"a{i}.json")
            with open(p, "w") as f:
                json.dump(base, f)
            perf_ledger.append([p], ledger_path, quiet=True)
        ledger = perf_ledger.load_ledger(ledger_path)
        # doubled peak HBM regresses past the 10% tolerance
        worse = dict(base, peak_hbm_bytes=2_000_000)
        p = str(tmp_path / "worse.json")
        with open(p, "w") as f:
            json.dump(worse, f)
        cand, _ = perf_ledger.normalize(p)
        verdict = perf_gate.gate(cand, ledger)
        checks = {c["metric"]: c for c in verdict["checks"]}
        assert checks["peak_hbm_bytes"]["verdict"] == "REGRESSED"
        assert not verdict["ok"]
        # compile-count explosion regresses too; equality passes
        worse = dict(base, xla_compiles=30)
        with open(p, "w") as f:
            json.dump(worse, f)
        cand, _ = perf_ledger.normalize(p)
        checks = {c["metric"]: c
                  for c in perf_gate.gate(cand, ledger)["checks"]}
        assert checks["xla_compiles"]["verdict"] == "REGRESSED"
        with open(p, "w") as f:
            json.dump(base, f)
        cand, _ = perf_ledger.normalize(p)
        assert perf_gate.gate(cand, ledger)["ok"]


class TestServeBenchArtifact:
    @pytest.mark.slow
    def test_serve_bench_embeds_device_truth(self, tmp_path):
        """serve_bench on CPU: xla_compiles present; the HBM keys are
        honestly ABSENT (memory_stats() is None here), not zero."""
        out_path = str(tmp_path / "SERVE_t.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("TFIDF_TPU_TRACE", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "serve_bench.py"),
             "--requests", "24", "--docs", "48", "--doc-len", "16",
             "--concurrency", "2", "--out", out_path],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        with open(out_path) as f:
            artifact = json.load(f)
        assert artifact["xla_compiles"] >= 1
        assert artifact["recompiles_after_warmup"] == 0
        assert "peak_hbm_bytes" not in artifact
