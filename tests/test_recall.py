"""Top-k recall harness (tfidf_tpu/recall.py) vs the native oracle.

Pins the north star's second half: on a collision-free corpus the
hashed-vocab TPU top-k recalls the oracle's exact-string top-k at 1.0.
"""

import os
import subprocess

import numpy as np
import pytest

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.ingest import run_overlapped
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.recall import corpus_recall, doc_recall, parse_oracle_output

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native", "tfidf_ref")


def _ensure_native():
    if not os.path.exists(NATIVE):
        subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                       check=True, capture_output=True)


class TestParse:
    def test_parse_and_filter(self, tmp_path):
        p = tmp_path / "out.txt"
        p.write_bytes(b"doc1@apple\t0.5000000000000000\n"
                      b"doc1@pear\t0.2500000000000000\n"
                      b"doc2@plum\t0.1000000000000000\n")
        full = parse_oracle_output(str(p))
        assert full["doc1"] == [(b"apple", 0.5), (b"pear", 0.25)]
        only2 = parse_oracle_output(str(p), docs=["doc2"])
        assert list(only2) == ["doc2"]


class TestDocRecall:
    def test_perfect(self):
        ref = [(b"a", 0.9), (b"b", 0.5), (b"c", 0.1)]
        ids = words_to_ids([b"a", b"b"], 1 << 20)
        assert doc_recall(ref, ids, [0.9, 0.5], 2, 1 << 20) == 1.0

    def test_miss(self):
        ref = [(b"a", 0.9), (b"b", 0.5)]
        ids = words_to_ids([b"a", b"zzz"], 1 << 20)
        assert doc_recall(ref, ids, [0.9, 0.5], 2, 1 << 20) == 0.5

    def test_ties_at_k_are_acceptable(self):
        # b and c tie at the k=2 boundary: either pick scores 1.0.
        ref = [(b"a", 0.9), (b"b", 0.5), (b"c", 0.5)]
        for pick in (b"b", b"c"):
            ids = words_to_ids([b"a", pick], 1 << 20)
            assert doc_recall(ref, ids, [0.9, 0.5], 2, 1 << 20) == 1.0

    def test_tie_cannot_substitute_for_missed_mandatory(self):
        # b/c tie at the k=2 boundary, but a (strictly above) is
        # mandatory: a top-2 of {b, c} that drops the argmax term must
        # NOT score 1.0 — tie hits only fill tie slots.
        ref = [(b"a", 0.9), (b"b", 0.5), (b"c", 0.5)]
        ids = words_to_ids([b"b", b"c"], 1 << 20)
        assert doc_recall(ref, ids, [0.5, 0.5], 2, 1 << 20) == 0.5

    def test_collisions_count_once(self):
        # vocab 1: every word folds to bucket 0; one hit covers all.
        ref = [(b"a", 0.9), (b"b", 0.5)]
        assert doc_recall(ref, [0], [0.9], 2, 1) == 1.0

    def test_undefined_when_all_zero(self):
        assert doc_recall([(b"a", 0.0)], [3], [0.1], 2, 16) is None

    def test_padding_ignored(self):
        ref = [(b"a", 0.9)]
        ids = list(words_to_ids([b"a"], 1 << 20)) + [-1]
        assert doc_recall(ref, ids, [0.9, 0.0], 2, 1 << 20) == 1.0


class TestEndToEndRecall:
    @pytest.fixture
    def corpus_dir(self, tmp_path):
        rng = np.random.default_rng(7)
        words = [f"term{i}" for i in range(120)]
        input_dir = tmp_path / "input"
        input_dir.mkdir()
        for i in range(1, 33):
            n = int(rng.integers(5, 40))
            picks = rng.choice(words, size=n)
            (input_dir / f"doc{i}").write_text(" ".join(picks))
        return str(input_dir), words

    def test_recall_is_one_collision_free(self, corpus_dir, tmp_path):
        input_dir, words = corpus_dir
        vocab = 1 << 20
        ids = words_to_ids([w.encode() for w in words], vocab)
        assert len(set(ids.tolist())) == len(words), "pick a bigger vocab"

        _ensure_native()
        out = str(tmp_path / "oracle.txt")
        subprocess.run([NATIVE, input_dir, out, "4"], check=True,
                       stdout=subprocess.DEVNULL)
        per_doc = parse_oracle_output(out)

        k = 8
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=vocab,
                             max_doc_len=64, doc_chunk=64, topk=k,
                             engine="sparse")
        got = run_overlapped(input_dir, cfg, chunk_docs=16, doc_len=64)
        r = corpus_recall(per_doc, got.names, got.topk_ids, got.topk_vals,
                          k, vocab)
        assert r == 1.0
