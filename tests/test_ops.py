"""Unit tests for the core ops: tokenize, hash, histogram, scoring, topk."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from tfidf_tpu.ops.hashing import (device_ngram_ids, fnv1a_hash_words,
                                   words_to_ids)
from tfidf_tpu.ops.histogram import df_from_counts, tf_counts, tf_counts_chunked
from tfidf_tpu.ops.scoring import idf_from_df, tfidf_dense
from tfidf_tpu.ops.tokenize import char_ngrams, whitespace_tokenize
from tfidf_tpu.ops.topk import topk_global, topk_per_doc, topk_terms


def _fnv1a_scalar(data: bytes, seed: int = 0) -> int:
    h = 14695981039346656037 ^ seed
    for b in data:
        h = ((h ^ b) * 1099511628211) % (1 << 64)
    return h


class TestTokenize:
    def test_matches_c_isspace_set(self):
        # fscanf("%s") splits on the C isspace set (TFIDF.c:142-147).
        data = b"  a\tbb\ncc\x0bdd\x0cee\rff  gg\n"
        assert whitespace_tokenize(data) == [b"a", b"bb", b"cc", b"dd",
                                             b"ee", b"ff", b"gg"]

    def test_empty_and_all_space(self):
        assert whitespace_tokenize(b"") == []
        assert whitespace_tokenize(b" \n\t ") == []

    def test_truncation_knob(self):
        assert whitespace_tokenize(b"abcdef xy", truncate_at=3) == [b"abc", b"xy"]

    def test_char_ngrams_order_and_count(self):
        grams = char_ngrams(b"abcd", 2, 3)
        assert grams == [b"ab", b"abc", b"bc", b"bcd", b"cd"]


class TestHashing:
    def test_fnv1a_matches_scalar_reference(self):
        words = [b"", b"a", b"hello", b"the quick brown fox"]
        got = fnv1a_hash_words(words)
        want = [_fnv1a_scalar(w) for w in words]
        assert [int(x) for x in got] == want

    def test_seed_changes_hashes(self):
        a = fnv1a_hash_words([b"word"], seed=0)
        b = fnv1a_hash_words([b"word"], seed=1)
        assert int(a[0]) != int(b[0])

    def test_fold_in_range_and_deterministic(self):
        ids = words_to_ids([b"alpha", b"beta", b"alpha"], 1 << 16)
        assert ids.dtype == np.int32
        assert (0 <= ids).all() and (ids < 1 << 16).all()
        assert ids[0] == ids[2]

    def test_device_ngram_ids_match_host_hash_structure(self):
        data = b"abcdef"
        arr = jnp.array(np.frombuffer(data, np.uint8).astype(np.int32))
        ids, valid = device_ngram_ids(arr, len(data), n=3, vocab_size=97)
        assert ids.shape == (6,)
        assert valid.tolist() == [True, True, True, True, False, False]
        # same window bytes -> same id
        arr2 = jnp.array(np.frombuffer(b"xbcdef", np.uint8).astype(np.int32))
        ids2, _ = device_ngram_ids(arr2, 6, n=3, vocab_size=97)
        assert ids[1:4].tolist() == ids2[1:4].tolist()
        assert int(ids[0]) != int(ids2[0]) or data[0:3] == b"xbc"


class TestHistogram:
    def test_counts_and_docsize_invariant(self):
        toks = jnp.array([[0, 1, 1, 2, 9, 9], [3, 3, 3, 0, 0, 0]], jnp.int32)
        lens = jnp.array([4, 3], jnp.int32)
        c = tf_counts(toks, lens, vocab_size=8)
        assert c.shape == (2, 8)
        # docSize invariant (TFIDF.c:141-143): row sums == lengths.
        assert c.sum(axis=1).tolist() == [4, 3]
        assert c[0, 0] == 1 and c[0, 1] == 2 and c[0, 2] == 1
        assert c[1, 3] == 3

    def test_padding_never_counted(self):
        toks = jnp.array([[5, 5, 5, 5]], jnp.int32)
        c = tf_counts(toks, jnp.array([0], jnp.int32), vocab_size=8)
        assert int(c.sum()) == 0

    def test_chunked_equals_unchunked(self):
        rng = np.random.default_rng(0)
        toks = jnp.array(rng.integers(0, 50, size=(5, 64)), jnp.int32)
        lens = jnp.array([64, 10, 0, 33, 17], jnp.int32)
        full = tf_counts(toks, lens, 50)
        chunked = tf_counts_chunked(toks, lens, 50, chunk=16)
        assert (np.asarray(full) == np.asarray(chunked)).all()

    def test_df_counts_documents_not_tokens(self):
        # The currDoc dedup semantics (TFIDF.c:171-188): a word occurring
        # 3x in one doc contributes 1 to DF.
        toks = jnp.array([[7, 7, 7], [7, 1, 2]], jnp.int32)
        lens = jnp.array([3, 3], jnp.int32)
        df = df_from_counts(tf_counts(toks, lens, 8))
        assert int(df[7]) == 2 and int(df[1]) == 1 and int(df[0]) == 0


class TestScoring:
    def test_idf_universal_word_is_zero(self):
        # A word in all docs scores exactly 0 (SURVEY §2.5-10).
        df = jnp.array([4, 2, 0], jnp.int32)
        idf = idf_from_df(df, 4)
        assert float(idf[0]) == 0.0
        assert float(idf[1]) == pytest.approx(math.log(2), rel=1e-6)
        assert float(idf[2]) == 0.0  # empty hash bucket guard

    def test_dense_scores_match_manual(self):
        counts = jnp.array([[2, 0], [1, 1]], jnp.int32)
        lens = jnp.array([2, 2], jnp.int32)
        df = jnp.array([2, 1], jnp.int32)
        s = tfidf_dense(counts, lens, df, 2)
        assert float(s[0, 0]) == 0.0  # word in all docs
        assert float(s[1, 1]) == pytest.approx(0.5 * math.log(2), rel=1e-6)


class TestTopK:
    def test_per_doc_and_global(self):
        s = jnp.array([[0.1, 0.9, 0.5], [0.8, 0.0, 0.2]], jnp.float32)
        vals, ids = topk_per_doc(s, 2)
        assert ids[0].tolist() == [1, 2] and ids[1].tolist() == [0, 2]
        gv, gd, gi = topk_global(s, 2)
        assert gd.tolist() == [0, 1] and gi.tolist() == [1, 0]
        tv, ti = topk_terms(s, 1)
        assert ti.tolist() == [1] or ti.tolist() == [0]

    def test_global_two_stage_matches_flat(self):
        # the beyond-int32 lowering (no D*V flat index) must select the
        # same records as the flat lowering at any shape — pinned here
        # at a small one with distinct scores
        from tfidf_tpu.ops.topk import _topk_global_two_stage
        rng = np.random.default_rng(4)
        s = jnp.asarray(rng.permutation(60).reshape(6, 10)
                        .astype(np.float32))
        for k in (1, 4, 9):
            fv, fd, fi = topk_global(s, k)
            tv, td, ti = _topk_global_two_stage(s, k)
            assert fv.tolist() == tv.tolist()
            assert fd.tolist() == td.tolist()
            assert fi.tolist() == ti.tolist()

    def test_global_overflow_guard_names_bound(self):
        # trace-time guard: past 2^31 flat slots even the two-stage
        # survivors can overflow — eval_shape triggers the static check
        # without allocating anything
        import jax

        from tfidf_tpu.ops.topk import _topk_global_two_stage
        huge = jax.ShapeDtypeStruct((1 << 16, 1 << 16), jnp.float32)
        with pytest.raises(ValueError, match="int32"):
            jax.eval_shape(
                lambda s: _topk_global_two_stage(s, 1 << 16), huge)
        # within bounds, the two-stage shape is well-formed
        out = jax.eval_shape(lambda s: _topk_global_two_stage(s, 8),
                             jax.ShapeDtypeStruct((1 << 10, 1 << 10),
                                                  jnp.float32))
        assert out[0].shape == (8,)


class TestUint16WireFormat:
    """uint16-packed batches (native loader, vocab <= 2^16) must behave
    identically to int32 through every histogram/sparse entry point —
    in particular at vocab_size == 65536, where the padding sentinel V
    is unrepresentable in uint16 unless ops upcast first."""

    def test_tf_counts_sentinel_at_full_uint16_vocab(self):
        from tfidf_tpu.ops.histogram import tf_counts

        v = 1 << 16
        toks = jnp.asarray(np.array([[1, 2, 7, 7]], np.uint16))
        lens = jnp.asarray(np.array([2], np.int32))
        counts = tf_counts(toks, lens, v)
        assert int(counts.sum()) == 2  # padding really dropped
        assert int(counts[0, 1]) == 1 and int(counts[0, 2]) == 1

    def test_sparse_matches_int32(self):
        from tfidf_tpu.ops.sparse import sorted_term_counts

        rng = np.random.default_rng(5)
        t32 = rng.integers(0, 1 << 16, (4, 16)).astype(np.int32)
        lens = jnp.asarray(rng.integers(0, 17, 4).astype(np.int32))
        a = sorted_term_counts(jnp.asarray(t32), lens)
        b = sorted_term_counts(jnp.asarray(t32.astype(np.uint16)), lens)
        for x, y in zip(a, b):
            assert (np.asarray(x) == np.asarray(y)).all()


class TestFusedNgramSweep:
    def test_multi_matches_per_n_calls(self):
        # The fused Horner sweep (device_ngram_ids_multi) must be
        # bit-identical to independent per-n calls — same Horner state,
        # finalizer applied to a copy at each emit (VERDICT r4 item 6).
        import numpy as np
        from tfidf_tpu.ops.hashing import (device_ngram_ids,
                                           device_ngram_ids_multi)
        rng = np.random.default_rng(3)
        docs = rng.integers(0, 256, (5, 64)).astype(np.uint8)
        lens = np.array([64, 10, 3, 1, 0], np.int32)
        streams = device_ngram_ids_multi(docs, lens, 2, 5, 1 << 20, seed=7)
        assert len(streams) == 4
        for n, (ids_m, valid_m) in zip(range(2, 6), streams):
            ids_1, valid_1 = device_ngram_ids(docs, lens, n, 1 << 20,
                                              seed=7)
            np.testing.assert_array_equal(np.asarray(ids_m),
                                          np.asarray(ids_1))
            np.testing.assert_array_equal(np.asarray(valid_m),
                                          np.asarray(valid_1))
