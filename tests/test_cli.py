"""CLI driver tests: both backends end-to-end through main()."""

import pytest

from tfidf_tpu.cli import main
from tfidf_tpu.golden import golden_output
from tfidf_tpu import discover_corpus


class TestCli:
    def test_tpu_backend_golden_output(self, toy_corpus_dir, tmp_path):
        out = tmp_path / "out.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--backend", "tpu"])
        assert rc == 0
        assert out.read_bytes() == golden_output(discover_corpus(toy_corpus_dir))

    def test_mpi_backend_golden_output(self, toy_corpus_dir, tmp_path):
        out = tmp_path / "out.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--backend", "mpi", "--nranks", "3"])
        assert rc == 0
        assert out.read_bytes() == golden_output(discover_corpus(toy_corpus_dir))

    def test_mpi_process_comm_flag(self, toy_corpus_dir, tmp_path):
        # --comm process runs the fork+socketpair OS-process backend —
        # same bytes as the default thread backend and the golden spec.
        out = tmp_path / "proc.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--backend", "mpi", "--nranks", "3",
                   "--comm", "process"])
        assert rc == 0
        assert out.read_bytes() == golden_output(
            discover_corpus(toy_corpus_dir))

    def test_backends_agree(self, toy_corpus_dir, tmp_path):
        a, b = tmp_path / "a.txt", tmp_path / "b.txt"
        assert main(["run", "--input", toy_corpus_dir, "--output", str(a),
                     "--backend", "tpu"]) == 0
        assert main(["run", "--input", toy_corpus_dir, "--output", str(b),
                     "--backend", "mpi"]) == 0
        assert a.read_bytes() == b.read_bytes()

    def test_topk_report(self, toy_corpus_dir, tmp_path):
        out = tmp_path / "topk.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--backend", "tpu", "--topk", "2"])
        assert rc == 0
        data = out.read_bytes().splitlines()
        assert data, "topk report should be non-empty"
        assert all(b"@" in l and b"\t" in l for l in data)
        # Ordering contract: every emit path is raw-line strcmp-sorted
        # (TFIDF.c:273) so output never depends on discovery order.
        assert data == sorted(data)

    def test_hashed_topk_rides_overlapped_ingest(self, toy_corpus_dir,
                                                 tmp_path):
        # Round 3: --doc-len opts single-device hashed top-k CLI runs
        # into run_overlapped (the measured scalable pipeline) instead
        # of packing the whole corpus in Python. Output must agree with
        # the batch TfidfPipeline on the same config (toy docs are all
        # shorter than --doc-len, so truncation is a no-op here).
        out = tmp_path / "ov.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--vocab-mode", "hashed", "--vocab-size", "4096",
                   "--topk", "2", "--doc-len", "64"])
        assert rc == 0
        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.pipeline import TfidfPipeline
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=4096,
                             topk=2, engine="sparse")
        ref = TfidfPipeline(cfg).run(discover_corpus(toy_corpus_dir))
        want = {}
        for d in range(ref.num_docs):
            for v, s in zip(ref.topk_ids[d], ref.topk_vals[d]):
                if s > 0:
                    want[(ref.names[d], int(v))] = float(s)
        got = {}
        for line in out.read_bytes().splitlines():
            key, score = line.rsplit(b"\t", 1)
            doc, word = key.split(b"@", 1)
            assert word.startswith(b"id:")  # hashed mode: ids, no words
            got[(doc.decode(), int(word[3:]))] = float(score)
        assert set(got) == set(want)
        for kk in want:
            assert got[kk] == pytest.approx(want[kk], rel=1e-6)
        # Knobs: a tiny chunk size and explicit spill policy must not
        # change the output bytes (chunking is an execution detail).
        out2 = tmp_path / "ov2.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out2),
                   "--vocab-mode", "hashed", "--vocab-size", "4096",
                   "--topk", "2", "--doc-len", "64",
                   "--chunk-docs", "4", "--spill", "reread"])
        assert rc == 0
        assert out2.read_bytes() == out.read_bytes()

    def test_mesh_composes_with_overlapped_ingest(self, toy_corpus_dir,
                                                  tmp_path):
        # Round 4: --mesh + --doc-len run the docs-sharded overlapped
        # ingest (ingest._run_overlapped_mesh) — same bytes as the
        # single-device overlapped run.
        single, mesh = tmp_path / "single.txt", tmp_path / "mesh.txt"
        base = ["run", "--input", toy_corpus_dir,
                "--vocab-mode", "hashed", "--vocab-size", "4096",
                "--topk", "2", "--doc-len", "64", "--chunk-docs", "4"]
        assert main(base + ["--output", str(single)]) == 0
        assert main(base + ["--output", str(mesh),
                            "--mesh", "4,1,1"]) == 0
        assert mesh.read_bytes() == single.read_bytes()
        # seq/vocab meshes cannot ride the ingest path: refuse loudly.
        assert main(base + ["--output", str(mesh),
                            "--mesh", "2,1,2"]) == 2

    def test_sharded_mesh_flag(self, toy_corpus_dir, tmp_path):
        out = tmp_path / "out.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--backend", "tpu", "--vocab-mode", "hashed",
                   "--vocab-size", "32768", "--mesh", "4,1,2"])
        assert rc == 0
        assert out.read_bytes() == golden_output(discover_corpus(toy_corpus_dir))

    def test_timing_flag(self, toy_corpus_dir, tmp_path, capsys):
        out = tmp_path / "out.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--backend", "tpu", "--timing"])
        assert rc == 0
        err = capsys.readouterr().err
        for phase in ("discover", "pack", "transfer", "compute", "fetch",
                      "emit", "docs/sec"):
            assert phase in err, f"missing {phase} in timing report"

    def test_topk_larger_than_vocab_clamped(self, toy_corpus_dir, tmp_path):
        # EXACT mode: V derived from corpus (16 words) < topk=50 — must
        # clamp, not crash (review finding).
        out = tmp_path / "topk.txt"
        rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
                   "--backend", "tpu", "--topk", "50"])
        assert rc == 0

    def test_query_subcommand(self, toy_corpus_dir, capsys):
        rc = main(["query", "--input", toy_corpus_dir,
                   "--query", "the quick", "--query", "zzz_nohit", "-k", "2"])
        assert rc == 0
        lines = capsys.readouterr().out.splitlines()
        assert lines[0] == "query: the quick"
        hits = [l for l in lines[1:lines.index("query: zzz_nohit")] if l]
        assert hits, "expected at least one retrieval hit"
        assert all("\t" in h for h in hits)
        assert lines[-1] == "query: zzz_nohit"  # no hits printed after

    def test_query_sharded(self, toy_corpus_dir, capsys):
        rc = main(["query", "--input", toy_corpus_dir,
                   "--query", "the quick", "-k", "2", "--mesh-docs", "4"])
        assert rc == 0
        assert "query: the quick" in capsys.readouterr().out


def test_inspect_prints_reference_debug_tables(toy_corpus_dir, tmp_path,
                                               capfd):
    # --inspect mirrors the reference's TF Job / IDF Job stdout dumps
    # (TFIDF.c:199-205,236-239): word@document\tcount/docSize then
    # word@document\tnumDocs/df, before the normal run output.
    from tfidf_tpu.cli import main
    out = tmp_path / "o.txt"
    rc = main(["run", "--input", toy_corpus_dir, "--output", str(out)])
    base = capfd.readouterr().out
    rc = main(["run", "--input", toy_corpus_dir, "--output", str(out),
               "--inspect"])
    assert rc == 0
    got = capfd.readouterr().out
    assert "-------------TF Job-------------" in got
    assert "------------IDF Job-------------" in got
    tf_sec = got.split("TF Job-------------\n")[1] \
        .split("------------IDF")[0]
    # every TF record is word@doc\tcount/size with integer fields
    rows = [l for l in tf_sec.splitlines() if l]
    assert rows
    for l in rows:
        key, frac = l.split("\t")
        w, doc = key.split("@", 1)
        c, size = frac.split("/")
        assert int(c) >= 1 and int(size) >= int(c) and w and doc
    assert base in got or base == ""  # normal run output still present
