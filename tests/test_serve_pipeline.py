"""Pipelined serve execution (round 22): the depth-D in-flight window.

The pins, in the order the docstring of ``serve/batcher.py`` promises
them:

* one drain worker == batch-major resolution — batches resolve in
  dispatch order no matter how the per-batch device latencies land;
* responses are BIT-IDENTICAL to direct search at every depth (1, 2,
  4), including when the supervisor's poison bisection runs at drain
  time;
* swap/close drain the window to zero — a batch admitted at epoch E
  resolves against E, and ``close(drain=True)`` returns with nothing
  in flight;
* the slab ring pre-provisions ``pipeline_depth`` slots per bucket so
  a full window never forces a mid-stream allocation;
* the replica front's two-phase commit still waits out a non-empty
  window before any replica flips;
* heartbeat liveness (the satellite-3 fix): a dispatch worker parked
  on a full window keeps beating, so a busy pipeline is never falsely
  stalled — while a device silently wedged past ``stall_after_s``
  still flips the monitor.
"""

import threading
import time

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, ServeConfig, faults, obs
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.obs.health import (OK, UNHEALTHY, HealthMonitor,
                                  HealthThresholds, set_monitor)
from tfidf_tpu.obs.log import EventLog
from tfidf_tpu.ops.queryslab import QuerySlab
from tfidf_tpu.serve import MicroBatcher, PoisonQuery, TfidfServer

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4", "doc5"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig fig",
          b"grape grape grape grape"])
CORPUS_B = Corpus(
    names=["doc1", "doc2", "doc3"],
    docs=[b"zebra yak apple",
          b"yak yak quokka",
          b"quokka zebra grape"])
QUERIES = ["apple cherry", "banana", "grape date", "fig", "elder",
           "apple fig", "date banana cherry"]


@pytest.fixture(scope="module")
def retriever():
    return TfidfRetriever(CFG).index(CORPUS)


@pytest.fixture(autouse=True)
def _clean_faults_and_obs():
    obs.set_log(EventLog(echo="off"))
    faults.disarm()
    set_monitor(None)
    yield
    faults.disarm()
    set_monitor(None)
    obs.set_log(None)


def _cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_entries", 0)
    return ServeConfig(**kw)


def assert_identical(got, want):
    gv, gi = got
    wv, wi = want
    np.testing.assert_array_equal(np.asarray(gv), np.asarray(wv))
    np.testing.assert_array_equal(np.asarray(gi), np.asarray(wi))


# ---------------------------------------------------------------------
# A fake device with a controllable materialize: dispatch returns
# instantly (the async-issue contract), the drain's materialize blocks
# on the delay/gate — the timing envelope real jax dispatch has.
def _rows(queries, k=2):
    h = [sum(q.encode()) % 251 for q in queries]
    vals = np.stack([np.arange(k, dtype=np.float32) + x for x in h])
    ids = np.stack([(np.arange(k) + x) % 5 for x in h])
    return vals, ids


class _FakePending:
    def __init__(self, queries, k, delay=0.0, gate=None):
        self._queries, self._k = list(queries), k
        self._delay, self._gate = delay, gate

    def materialize(self):
        if self._gate is not None:
            assert self._gate.wait(timeout=30), "gate never opened"
        if self._delay:
            time.sleep(self._delay)
        return _rows(self._queries, self._k)


def _fake_batcher(depth, delays=None, gates=None, **kw):
    """MicroBatcher over the fake device: per-dispatch delay/gate are
    consumed in dispatch order."""
    seq = []

    def dispatch(queries, k, group):
        i = len(seq)
        seq.append(list(queries))
        delay = delays[i % len(delays)] if delays else 0.0
        gate = gates[i] if gates is not None else None
        return _FakePending(queries, k, delay=delay, gate=gate)

    def search(queries, k, group):
        return _rows(queries, k)

    b = MicroBatcher(search, pipeline_depth=depth, dispatch_fn=dispatch,
                     **kw)
    b.dispatched = seq
    return b


class TestDrainOrder:
    def test_batch_major_resolution_under_jittered_device(self):
        """Property: whatever per-batch device latencies the fake
        draws, futures resolve strictly in dispatch order — one drain
        worker IS the ordering proof."""
        rng = np.random.default_rng(22)
        delays = [float(d) for d in rng.uniform(0, 0.02, size=16)]
        b = _fake_batcher(4, delays=delays, max_batch=4, max_wait_ms=1)
        done = []
        try:
            futs = []
            for i in range(16):
                # Distinct groups: one request == one batch == one
                # pipeline slot, so submit order is dispatch order.
                f = b.submit([QUERIES[i % len(QUERIES)]], k=2, group=i)
                f.add_done_callback(
                    lambda fut, i=i: done.append(i))
                futs.append(f)
            for i, f in enumerate(futs):
                assert_identical(f.result(timeout=30),
                                 _rows([QUERIES[i % len(QUERIES)]], 2))
        finally:
            b.close()
        assert done == sorted(done), done
        assert len(done) == 16


class TestDepthParity:
    @pytest.mark.parametrize("depth", [1, 2, 4])
    def test_served_equals_direct_search(self, retriever, depth):
        with TfidfServer(retriever, _cfg(pipeline_depth=depth)) as srv:
            for size in (1, 2, 3, 5, 7):
                qs = QUERIES[:size]
                assert_identical(srv.search(qs, k=4),
                                 retriever.search(qs, k=4))
            # A concurrent burst keeps the window genuinely full.
            futs = [srv.submit([q], k=3) for q in QUERIES]
            for f, q in zip(futs, QUERIES):
                assert_identical(f.result(timeout=30),
                                 retriever.search([q], k=3))

    @pytest.mark.parametrize("depth", [1, 2, 4])
    def test_poison_bisection_at_drain(self, retriever, depth):
        """The supervisor story is depth-invariant: a poison query is
        isolated by the drain-time bisection, its co-batched innocents
        still resolve bit-identically, and the quarantine gate fails
        the resubmit fast."""
        faults.arm(faults.FaultPlan.parse(
            "device_dispatch:fatal:match=zzpoison"))
        srv = TfidfServer(retriever, _cfg(pipeline_depth=depth,
                                          max_wait_ms=40))
        try:
            futs = {q: srv.submit([q], k=3) for q in
                    [QUERIES[0], "zzpoison attack", QUERIES[1]]}
            with pytest.raises(PoisonQuery) as ei:
                futs["zzpoison attack"].result(timeout=30)
            assert ei.value.queries == ["zzpoison attack"]
            for q in (QUERIES[0], QUERIES[1]):
                assert_identical(futs[q].result(timeout=30),
                                 retriever.search([q], k=3))
            with pytest.raises(PoisonQuery):
                srv.submit(["zzpoison attack"], k=3)
        finally:
            srv.close()


class TestWindowLifecycle:
    def test_close_drains_window_to_zero(self):
        """close(drain=True) with dispatched-but-unmaterialized
        batches: every future resolves, nothing is left in flight."""
        gates = [threading.Event() for _ in range(3)]
        b = _fake_batcher(2, gates=gates, max_batch=4, max_wait_ms=1)
        futs = [b.submit([QUERIES[i]], k=2, group=i) for i in range(3)]
        deadline = time.monotonic() + 10
        while (b.inflight_batches() < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert b.inflight_batches() == 2  # window capped at depth

        def open_gates():
            time.sleep(0.05)
            for g in gates:
                g.set()

        threading.Thread(target=open_gates, daemon=True).start()
        b.close(drain=True)  # blocks through the gated materializes
        assert b.inflight_batches() == 0
        for i, f in enumerate(futs):
            assert_identical(f.result(timeout=0), _rows([QUERIES[i]], 2))

    def test_swap_pins_admitted_epoch(self, retriever):
        """Queries admitted before a hot swap resolve against the OLD
        index even when they execute after it — the group snapshot
        rides the in-flight entry."""
        new = TfidfRetriever(CFG).index(CORPUS_B)
        srv = TfidfServer(retriever, _cfg(pipeline_depth=2,
                                          max_wait_ms=100))
        try:
            futs = [srv.submit([q], k=2) for q in QUERIES[:4]]
            assert srv.swap_index(new) == 1  # races the queued burst
            for f, q in zip(futs, QUERIES[:4]):
                assert_identical(f.result(timeout=30),
                                 retriever.search([q], k=2))
            assert_identical(srv.search(["zebra yak"], k=2),
                             new.search(["zebra yak"], k=2))
        finally:
            srv.close()


class TestSlabDepthGuard:
    def test_min_depth_preprovisions_ring(self):
        slab = QuerySlab(64, max_bucket=8, min_depth=2)
        b0, _, s0 = slab.checkout(4)
        assert slab.ring_depth(4) == 2      # first touch: DEPTH slots
        assert slab.stats()["allocs"] == 2
        b1, _, s1 = slab.checkout(4)        # window full: no growth
        assert b1 is not b0
        assert slab.stats()["allocs"] == 2
        slab.checkout(4)                    # beyond depth: grows by 1
        assert slab.stats()["allocs"] == 3
        slab.release(s0)
        slab.release(s1)

    def test_reserve_raises_depth_on_touched_rings(self):
        slab = QuerySlab(64, max_bucket=8)
        _, _, s = slab.checkout(4)
        slab.release(s)
        assert slab.ring_depth(4) == 1      # legacy single-slot start
        slab.reserve(3)
        assert slab.min_depth == 3
        assert slab.ring_depth(4) == 3      # touched ring topped up
        slab.checkout(8)
        assert slab.ring_depth(8) == 3      # new rings born at depth
        with pytest.raises(ValueError):
            slab.reserve(0)
        with pytest.raises(ValueError):
            QuerySlab(64, max_bucket=8, min_depth=0)

    def test_server_wires_pipeline_depth_into_slab(self):
        r = TfidfRetriever(CFG).index(CORPUS)
        srv = TfidfServer(r, _cfg(pipeline_depth=3))
        try:
            assert r.slab_depth == 3
            srv.search(QUERIES[:2], k=3)    # touches the 2-bucket ring
            assert r._slab is not None
            assert r._slab.min_depth >= 3
            assert r._slab.ring_depth(2) >= 3
        finally:
            srv.close()

    def test_full_window_steady_state_allocs_zero(self):
        """The acceptance receipt at unit scale: with the ring
        pre-provisioned to the pipeline depth, a full window of
        batches allocates nothing after warm-up."""
        r = TfidfRetriever(CFG).index(CORPUS)
        srv = TfidfServer(r, _cfg(pipeline_depth=2, max_wait_ms=1))
        try:
            for n in (1, 2, 4):             # warm every bucket the
                srv.search(QUERIES[:n], k=3)  # burst below can land in
            a0 = r._slab.stats()["allocs"]
            for _ in range(4):
                futs = [srv.submit([q], k=3) for q in QUERIES[:4]]
                for f in futs:
                    f.result(timeout=30)
            assert r._slab.stats()["allocs"] == a0
        finally:
            srv.close()


class TestFrontTwoPhaseWindow:
    def test_commit_waits_out_nonempty_window(self, tmp_path):
        """The mixed-epoch pin with the pipeline window live: the
        front's commit round must not start while any prepared replica
        still has in-flight work (futures resolve at drain, so the
        per-replica inflight count covers dispatched batches too)."""
        from tfidf_tpu.serve.front import ReplicatedFront
        serve_cfg = ServeConfig(snapshot_dir=str(tmp_path / "snap"),
                                replicas=3)
        pipe_cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                                  vocab_size=4096, max_doc_len=64)
        front = ReplicatedFront(str(tmp_path), pipe_cfg, serve_cfg)
        ops = []

        def fake_rpc(rank, msg, **kw):
            ops.append((msg["op"], rank))
            # Commit acks carry the installed epoch (prepare/ping
            # messages name the target; commit must answer with it).
            return {"ok": True,
                    "epoch": msg.get("epoch", front._epoch + 1)}

        try:
            for rep in front._replicas.values():
                rep.state = "live"
            front._ctrl_rpc = fake_rpc
            front._replicas[1].inflight = 2   # a non-empty window
            result = {}
            t = threading.Thread(
                target=lambda: result.update(
                    front._two_phase("compact", {})), daemon=True)
            t.start()
            deadline = time.monotonic() + 10
            while (sum(1 for op, _ in ops if op == "ping") < 3
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            # Prepared + pinged everywhere; the gate is closed and the
            # commit round is parked behind the in-flight drain.
            time.sleep(0.1)
            assert not any(op == "commit" for op, _ in ops)
            assert not front._admission.is_set()
            with front._lock:
                front._replicas[1].inflight = 0
            t.join(timeout=10)
            assert not t.is_alive()
            assert sum(1 for op, _ in ops if op == "commit") == 3
            assert result["epoch"] == 1 and front._epoch == 1
            assert front._admission.is_set()  # gate reopened
            # Strict phase ordering: every prepare and ping precedes
            # every commit.
            last_ping = max(i for i, (op, _) in enumerate(ops)
                            if op in ("prepare", "ping"))
            first_commit = min(i for i, (op, _) in enumerate(ops)
                               if op == "commit")
            assert last_ping < first_commit
        finally:
            for rep in front._replicas.values():
                rep.state = "dead"  # close() must not RPC the fakes
            front.close()


class TestHeartbeatLiveness:
    def _batcher_with_monitor(self, gates, stall_s):
        # Monitor first: the batcher's threads beat the moment they
        # start (heartbeat auto-registers; register() then installs
        # the real busy_fn idempotently — the server wiring order).
        m = HealthMonitor(thresholds=HealthThresholds(
            stall_after_s=stall_s))
        b = _fake_batcher(2, gates=gates, max_batch=4, max_wait_ms=1,
                          heartbeat=lambda: m.heartbeat("batcher"))
        m.register("batcher", busy_fn=lambda: (
            b.queued_queries() > 0 or b.inflight_batches() > 0))
        m.heartbeat("batcher")
        return b, m

    def test_full_window_wait_keeps_beating(self):
        """Satellite 3: a dispatch worker parked on a FULL window with
        work queued behind it keeps heartbeating — a healthy pipeline
        crunching a slow device is busy, not stalled."""
        gates = [threading.Event() for _ in range(4)]
        b, m = self._batcher_with_monitor(gates, stall_s=0.25)
        try:
            futs = [b.submit([QUERIES[i]], k=2, group=i)
                    for i in range(4)]
            time.sleep(0.6)  # > 2 stall windows, gates still shut
            assert b.inflight_batches() == 2
            assert b.queued_queries() > 0    # genuinely busy
            assert m.evaluate().state == OK  # ... and genuinely live
            for g in gates:
                g.set()
            for i, f in enumerate(futs):
                assert_identical(f.result(timeout=30),
                                 _rows([QUERIES[i]], 2))
            assert m.evaluate().state == OK
        finally:
            b.close()

    def test_wedged_device_still_flags_after_threshold(self):
        """The other half of the pin: liveness is not unconditional.
        A drain blocked in materialize past ``stall_after_s`` with no
        dispatch activity left to beat flips the monitor — and the
        first drained batch recovers it."""
        gates = [threading.Event()]
        b, m = self._batcher_with_monitor(gates, stall_s=0.15)
        try:
            f = b.submit([QUERIES[0]], k=2)
            deadline = time.monotonic() + 5
            state = None
            while time.monotonic() < deadline:
                state = m.evaluate().state
                if state == UNHEALTHY:
                    break
                time.sleep(0.02)
            assert state == UNHEALTHY
            gates[0].set()
            assert_identical(f.result(timeout=30),
                             _rows([QUERIES[0]], 2))
            deadline = time.monotonic() + 5
            while (m.evaluate().state != OK
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert m.evaluate().state == OK
        finally:
            b.close()
