"""The packed device→host result wire and the chunked async drain
(ops/downlink + ingest round 7): word pack/unpack round-trip property
(sign-bit sentinel, NaN pass-through, bf16 bit-exactness), packed-vs-
pair engine parity on the resident, streaming, pipeline, and scoring
paths, the _DrainAhead ordering/depth contracts, the wire-selection
fallbacks, and the Pallas packing variant's bit-identity."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from tfidf_tpu import PipelineConfig
from tfidf_tpu import ingest as ing
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus, pack_corpus
from tfidf_tpu.ops import downlink as dl
from tfidf_tpu.pipeline import TfidfPipeline


def _cfg(**kw):
    base = dict(vocab_mode=VocabMode.HASHED, vocab_size=1 << 10,
                max_doc_len=64, doc_chunk=64, topk=5, engine="sparse")
    base.update(kw)
    return PipelineConfig(**base)


@pytest.fixture
def corpus_dir(tmp_path):
    rng = np.random.default_rng(11)
    for i in range(1, 41):
        words = [f"w{rng.integers(0, 60)}"
                 for _ in range(int(rng.integers(0, 40)))]
        (tmp_path / f"doc{i}").write_text(" ".join(words))
    return str(tmp_path)


# fp16 carries 11 significand bits: relative rounding error <= 2^-11.
FP16_RTOL = 1e-3


class TestWordRoundTrip:
    """pack -> unpack is the identity on ids and the 16-bit rounding
    of scores; invalid slots decode to the (0, -1) contract."""

    def test_property_random(self):
        rng = np.random.default_rng(5)
        for _ in range(10):
            d, k = int(rng.integers(1, 30)), int(rng.integers(1, 9))
            vals = np.abs(rng.normal(size=(d, k))).astype(np.float32)
            tids = rng.integers(0, 1 << 16, (d, k)).astype(np.int32)
            # force invalid slots (sub-k docs) into every draw
            inv = rng.random((d, k)) < 0.25
            tids[inv] = -1
            words = np.asarray(dl.pack_result_words(vals, tids))
            assert words.dtype == np.uint32 and words.shape == (d, k)
            v, t = dl.unpack_result_words(words)
            np.testing.assert_array_equal(t, np.where(inv, -1, tids))
            assert (v[inv] == 0).all()
            np.testing.assert_allclose(v[~inv], vals[~inv],
                                       rtol=FP16_RTOL, atol=1e-7)

    def test_id_boundary_and_zero_score(self):
        # id 2^16-1 is the last carriable id; a legitimate 0.0 score
        # (a term in every doc) must survive as VALID, not sentinel.
        vals = np.array([[0.0, 1.5]], np.float32)
        tids = np.array([[65535, 0]], np.int32)
        v, t = dl.unpack_result_words(
            np.asarray(dl.pack_result_words(vals, tids)))
        np.testing.assert_array_equal(t, tids)
        assert v[0, 0] == 0.0 and abs(v[0, 1] - 1.5) < 1e-3

    def test_nan_passes_through(self):
        # NaN compares False against the sign test, so it survives as
        # NaN instead of being misread as the invalid sentinel.
        vals = np.array([[np.nan, 2.0]], np.float32)
        tids = np.array([[7, 9]], np.int32)
        v, t = dl.unpack_result_words(
            np.asarray(dl.pack_result_words(vals, tids)))
        assert np.isnan(v[0, 0]) and t[0, 0] == 7
        assert t[0, 1] == 9

    def test_bf16_bits_are_float32_high_half(self):
        # On a bfloat16 run the word's score half IS the float32 high
        # half — the round trip is bit-exact at bf16 precision.
        rng = np.random.default_rng(6)
        vals32 = np.abs(rng.normal(size=(6, 4))).astype(np.float32)
        vals = jnp.asarray(vals32, jnp.bfloat16)
        tids = rng.integers(0, 1 << 16, (6, 4)).astype(np.int32)
        words = np.asarray(dl.pack_result_words(vals, tids))
        v, t = dl.unpack_result_words(words, score_dtype=jnp.bfloat16)
        np.testing.assert_array_equal(
            v.view(np.uint16), np.asarray(vals).view(np.uint16))
        np.testing.assert_array_equal(t, tids)

    def test_pallas_pack_bit_identical(self):
        from tfidf_tpu.ops.pallas_kernels import pack_words_pallas
        rng = np.random.default_rng(8)
        vals = np.abs(rng.normal(size=(20, 5))).astype(np.float32)
        tids = rng.integers(-1, 1 << 16, (20, 5)).astype(np.int32)
        np.testing.assert_array_equal(
            np.asarray(pack_words_pallas(vals, tids, interpret=True)),
            np.asarray(dl.pack_result_words(vals, tids)))


class TestWireSelection:
    """result_wire resolution: packed by default, pair forced or
    degraded-to automatically when the word cannot carry the run."""

    def test_config_validates(self):
        with pytest.raises(ValueError, match="result wire"):
            _cfg(result_wire="zip")

    def test_default_is_packed(self):
        assert dl.use_packed_result_wire(_cfg())

    def test_forced_pair(self):
        assert not dl.use_packed_result_wire(_cfg(result_wire="pair"))

    def test_no_topk_degrades(self):
        assert not dl.use_packed_result_wire(_cfg(topk=None))

    def test_wide_vocab_degrades(self):
        assert dl.use_packed_result_wire(_cfg(vocab_size=1 << 16))
        assert not dl.use_packed_result_wire(
            _cfg(vocab_size=(1 << 16) + 1))
        # explicit vocab bound (padded mesh vocab) wins over config's
        assert not dl.use_packed_result_wire(
            _cfg(), vocab_size=(1 << 16) + 8)

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_RESULT_WIRE", "pair")
        assert not dl.use_packed_result_wire(_cfg())
        monkeypatch.setenv("TFIDF_TPU_RESULT_WIRE", "brotli")
        with pytest.raises(ValueError, match="TFIDF_TPU_RESULT_WIRE"):
            dl.use_packed_result_wire(_cfg())

    def test_wide_vocab_run_reports_pair(self, corpus_dir):
        r = ing.run_overlapped(corpus_dir,
                               _cfg(vocab_size=(1 << 16) + 8),
                               chunk_docs=16, doc_len=64)
        assert r.result_wire == "pair"


class TestEngineParity:
    """The packed wire is bit-exact on ids and within fp16 rounding on
    scores vs the pair wire, on every path that ships results."""

    @pytest.mark.parametrize("regime", ["resident", "streaming"])
    def test_run_overlapped(self, corpus_dir, regime, monkeypatch):
        if regime == "streaming":
            monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
            monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        r_w = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        r_p = ing.run_overlapped(corpus_dir, _cfg(result_wire="pair"),
                                 chunk_docs=16, doc_len=64)
        assert r_w.result_wire == "packed" and r_p.result_wire == "pair"
        np.testing.assert_array_equal(r_w.topk_ids, r_p.topk_ids)
        np.testing.assert_allclose(r_w.topk_vals, r_p.topk_vals,
                                   rtol=FP16_RTOL, atol=1e-7)
        np.testing.assert_array_equal(np.asarray(r_w.df),
                                      np.asarray(r_p.df))
        # the packed run's df is ALWAYS a host ndarray
        assert isinstance(r_w.df, np.ndarray)
        # byte receipt: one uint32 word vs (int32 id, float32 score)
        assert r_w.bytes_off_wire == r_w.bytes_off_wire_pair // 2
        assert r_p.bytes_off_wire > r_w.bytes_off_wire

    @pytest.mark.parametrize("engine", ["sparse", "dense"])
    def test_pipeline_run_packed(self, engine):
        docs = [b"apple banana apple", b"", b"cherry date fig " * 8,
                b"kiwi"]
        corpus = Corpus(names=[f"doc{i}" for i in range(1, 5)],
                        docs=docs)
        cfg_w = _cfg(engine=engine, vocab_size=1 << 12, topk=4)
        cfg_p = _cfg(engine=engine, vocab_size=1 << 12, topk=4,
                     result_wire="pair")
        r_w = TfidfPipeline(cfg_w).run_packed(pack_corpus(corpus, cfg_w))
        r_p = TfidfPipeline(cfg_p).run_packed(pack_corpus(corpus, cfg_p))
        np.testing.assert_array_equal(np.asarray(r_w.topk_ids),
                                      np.asarray(r_p.topk_ids))
        np.testing.assert_allclose(np.asarray(r_w.topk_vals),
                                   np.asarray(r_p.topk_vals),
                                   rtol=FP16_RTOL, atol=1e-7)
        np.testing.assert_array_equal(np.asarray(r_w.df),
                                      np.asarray(r_p.df))

    def test_streaming_score(self):
        from tfidf_tpu.streaming import StreamingTfidf
        docs = [b"alpha beta alpha gamma", b"", b"delta " * 30]
        corpus = Corpus(names=["doc1", "doc2", "doc3"], docs=docs)
        cfg_w, cfg_p = _cfg(topk=3), _cfg(topk=3, result_wire="pair")
        s_w, s_p = StreamingTfidf(cfg_w), StreamingTfidf(cfg_p)
        b_w = s_w.pack(corpus, fixed_len=32)
        b_p = s_p.pack(corpus, fixed_len=32)
        s_w.update(b_w)
        s_p.update(b_p)
        v_w, i_w = s_w.score(b_w)
        v_p, i_p = s_p.score(b_p)
        # packed score() lands as host arrays, already decoded
        assert isinstance(v_w, np.ndarray) and isinstance(i_w, np.ndarray)
        np.testing.assert_array_equal(i_w, np.asarray(i_p))
        np.testing.assert_allclose(v_w, np.asarray(v_p),
                                   rtol=FP16_RTOL, atol=1e-7)


class TestDrainAhead:
    """_DrainAhead's contracts: chunk-major retirement regardless of
    per-chunk unpack cost, bounded in-flight depth, and join-on-error
    exception safety (context manager)."""

    def test_results_chunk_major(self):
        # chunk 0's unpack is the SLOWEST: a completion-ordered drain
        # would retire 4..1 first. The single ordered worker must still
        # hand results back chunk-major.
        def unpack(arr):
            i = int(arr[0])
            time.sleep(0.03 if i == 0 else 0.001)
            return i
        with ing._DrainAhead(unpack, depth=8) as d:
            for i in range(5):
                d.put(i, jnp.full((4,), i, jnp.uint32))
            assert d.results() == [0, 1, 2, 3, 4]

    def test_depth_guard_bounds_in_flight(self):
        done = []

        def unpack(arr):
            time.sleep(0.01)
            done.append(int(arr[0]))
            return int(arr[0])
        with ing._DrainAhead(unpack, depth=1) as d:
            for i in range(6):
                d.put(i, jnp.full((2,), i, jnp.uint32))
                if i >= 2:
                    # past the depth window, put() blocked until the
                    # oldest outstanding drain retired
                    assert len(done) >= i - 1
            assert d.results() == list(range(6))

    def test_depth_validation(self, monkeypatch):
        with pytest.raises(ValueError, match="TFIDF_TPU_FETCH_AHEAD"):
            ing._DrainAhead(lambda a: a, depth=0)
        monkeypatch.setenv("TFIDF_TPU_FETCH_AHEAD", "0")
        with pytest.raises(ValueError, match="TFIDF_TPU_FETCH_AHEAD"):
            ing._DrainAhead(lambda a: a)
        monkeypatch.setenv("TFIDF_TPU_FETCH_AHEAD", "3")
        with ing._DrainAhead(lambda a: a) as d:
            assert d._depth == 3

    def test_context_joins_on_error(self):
        held = []
        with pytest.raises(RuntimeError, match="boom"):
            with ing._DrainAhead(lambda a: np.asarray(a)) as d:
                held.append(d)
                d.put(0, jnp.zeros((2,), jnp.uint32))
                raise RuntimeError("boom")
        assert held[0]._ex._shutdown  # worker joined, queue cancelled

    def test_pack_ahead_context_joins_on_error(self):
        held = []
        with pytest.raises(RuntimeError, match="boom"):
            with ing._PackAhead(lambda item: item, list(range(4))) as p:
                held.append(p)
                p.get(0)
                raise RuntimeError("boom")
        assert held[0]._ex._shutdown


class TestDrainOverlap:
    """Ordering contract of the chunked async drain on the real ingest
    loops: every chunk's drain is submitted before the terminal fetch
    stall, and drains retire in chunk order. Pinned to the CHUNKED
    finish — the structure whose per-chunk drains these contracts
    describe; the round-8 scanned finish (one dispatch, one drain) has
    its own ordering pins in tests/test_finish.py."""

    def _trace_run(self, corpus_dir, **kw):
        events = []
        ing._overlap_trace = events.append
        try:
            ing.run_overlapped(corpus_dir, _cfg(finish="chunked"),
                               chunk_docs=10, doc_len=64, **kw)
        finally:
            ing._overlap_trace = None
        return events

    @pytest.mark.parametrize("regime", ["resident", "streaming"])
    def test_drains_precede_fetch_stall(self, corpus_dir, regime,
                                        monkeypatch):
        if regime == "streaming":
            monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
            monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        events = self._trace_run(corpus_dir)
        submits = [i for i, e in enumerate(events)
                   if e[0] == "drain_submit"]
        fetch_start = events.index(("fetch_start", -1))
        assert len(submits) == 4  # 40 docs / 10-doc chunks
        # chunk i's drain starts while later chunks still score: every
        # submit precedes the terminal stall on the drain results.
        assert all(s < fetch_start for s in submits)
        # and the worker retires chunks in submission order
        dones = [e[1] for e in events if e[0] == "drain_done"]
        assert dones == sorted(dones) and len(dones) == 4

    def test_pair_wire_has_no_drain(self, corpus_dir):
        events = self._trace_run(corpus_dir)  # packed default
        assert any(e[0] == "drain_submit" for e in events)
        events = []
        ing._overlap_trace = events.append
        try:
            ing.run_overlapped(corpus_dir, _cfg(result_wire="pair"),
                               chunk_docs=10, doc_len=64)
        finally:
            ing._overlap_trace = None
        assert not any(e[0] == "drain_submit" for e in events)
