"""Self-watching serving (ISSUE 6): health watchdog, canary parity
probes, flight recorder.

The acceptance pins: ``healthz`` reports ``degraded`` within one
watchdog period under fault-injected saturation / worker stall and
recovers to ``ok``, with the admission bound visibly shrunk while
degraded; the canary prober detects a deliberately corrupted index
(flipped DF-derived IDF entry post-swap) via ``parity < 1.0`` while
normal stress holds ``parity == 1.0``; and a SIGTERM'd serve
subprocess leaves a complete flight-recorder dump + trace on disk,
validated by the extended ``tools/trace_check.py``.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tfidf_tpu import obs
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.obs.health import (DEGRADED, OK, UNHEALTHY, HealthMonitor,
                                  HealthThresholds, beat, set_monitor)
from tfidf_tpu.obs.log import EventLog
from tfidf_tpu.serve import (CanaryProber, Overloaded, TfidfServer,
                             pinned_queries_from_dir)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4", "doc5"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig fig",
          b"grape grape grape grape"])
QUERIES = ["apple cherry", "banana date", "grape", "fig elder"]


@pytest.fixture(scope="module")
def retriever():
    return TfidfRetriever(CFG).index(CORPUS)


@pytest.fixture(autouse=True)
def _clean_obs():
    """Tests get a private event log and no global health monitor, and
    never leak either (or a flight path) into the rest of the suite."""
    import tfidf_tpu.obs.log as obs_log
    obs.set_log(EventLog(echo="off"))
    set_monitor(None)
    prev_flight = obs_log._flight
    obs_log._flight = None
    yield
    obs_log._flight = prev_flight
    set_monitor(None)
    obs.set_log(None)


def quick_cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_entries", 64)
    return ServeConfig(**kw)


class TestHealthMonitor:
    def test_ok_with_no_signals(self):
        m = HealthMonitor()
        status = m.evaluate()
        assert status.state == OK and status.ok and status.reasons == []

    def test_stall_detected_and_recovers(self):
        m = HealthMonitor(thresholds=HealthThresholds(stall_after_s=0.5))
        m.register("worker", busy_fn=lambda: True)
        m.heartbeat("worker")
        now = time.monotonic()
        assert m.evaluate(now=now).state == OK
        # One stall_after_s with pending work and no beat: unhealthy,
        # with the worker named in the reason.
        status = m.evaluate(now=now + 1.0)
        assert status.state == UNHEALTHY
        assert any("worker" in r for r in status.reasons)
        assert status.checks["workers"]["worker"]["stalled"]
        m.heartbeat("worker")
        assert m.evaluate().state == OK  # beat resumed -> recovered

    def test_idle_worker_never_stalls(self):
        m = HealthMonitor(thresholds=HealthThresholds(stall_after_s=0.1))
        m.register("worker", busy_fn=lambda: False)  # no pending work
        m.heartbeat("worker")
        assert m.evaluate(now=time.monotonic() + 99).state == OK

    def test_queue_saturation_degrades_and_recovers(self):
        depth = [10]
        snap = lambda: {"requests": 0, "queue": {"depth": depth[0]},
                        "shed": {"overload": 0, "deadline": 0}}
        m = HealthMonitor(snapshot_fn=snap, queue_bound=10)
        status = m.evaluate()
        assert status.state == DEGRADED
        assert status.checks["queue_saturation"] == 1.0
        depth[0] = 1
        assert m.evaluate().state == OK

    def test_windowed_shed_rate_degrades(self):
        state = {"requests": 0, "over": 0}
        snap = lambda: {"requests": state["requests"],
                        "queue": {"depth": 0},
                        "shed": {"overload": state["over"], "deadline": 0}}
        m = HealthMonitor(snapshot_fn=snap, queue_bound=100)
        assert m.evaluate().state == OK      # seeds the window
        state.update(requests=10, over=10)   # 50% shed since last look
        status = m.evaluate()
        assert status.state == DEGRADED
        assert status.checks["shed_rate"] == 0.5
        # A clean window (no new traffic) decays the rate back to ok.
        assert m.evaluate().state == OK

    def test_deadline_miss_rate_is_its_own_signal(self):
        state = {"requests": 0, "dead": 0}
        snap = lambda: {"requests": state["requests"],
                        "queue": {"depth": 0},
                        "shed": {"overload": 0,
                                 "deadline": state["dead"]}}
        m = HealthMonitor(snapshot_fn=snap, queue_bound=100)
        m.evaluate()
        state.update(requests=90, dead=10)
        status = m.evaluate()
        assert status.state == DEGRADED
        assert status.checks["deadline_miss_rate"] == 0.1

    def test_admission_bound_shrinks_only_while_not_ok(self):
        m = HealthMonitor(thresholds=HealthThresholds(
            degraded_admission_factor=0.25))
        assert m.admission_bound(100) == 100
        m._status.state = DEGRADED
        assert m.admission_bound(100) == 25
        m._status.state = UNHEALTHY
        assert m.admission_bound(100) == 25
        assert m.admission_bound(2) == 1  # floor: progress possible

    def test_gauges_published(self):
        from tfidf_tpu.obs.registry import MetricsRegistry
        reg = MetricsRegistry()
        m = HealthMonitor(
            snapshot_fn=lambda: {"requests": 0, "queue": {"depth": 9},
                                 "shed": {"overload": 0, "deadline": 0}},
            queue_bound=10, registry=reg)
        m.evaluate()
        snap = reg.snapshot()
        assert snap["serve_health_state"]["value"] == 1  # degraded
        assert snap["serve_admission_bound"]["value"] == 5
        assert snap["serve_queue_saturation_milli"]["value"] == 900

    def test_state_change_logged(self):
        log = EventLog(echo="off")
        obs.set_log(log)
        m = HealthMonitor(
            snapshot_fn=lambda: {"requests": 0, "queue": {"depth": 10},
                                 "shed": {"overload": 0, "deadline": 0}},
            queue_bound=10)
        m.evaluate()
        evs = [e for e in log.events()
               if e["event"] == "health_state_change"]
        assert evs and evs[-1]["to"] == DEGRADED

    def test_module_hook_routes_beats(self):
        m = HealthMonitor()
        beat("packer")                 # no monitor installed: no-op
        assert "packer" not in m._workers
        set_monitor(m)
        beat("packer")
        assert m._workers["packer"].beats == 1

    def test_background_thread_evaluates_within_period(self):
        m = HealthMonitor(
            snapshot_fn=lambda: {"requests": 0, "queue": {"depth": 10},
                                 "shed": {"overload": 0, "deadline": 0}},
            queue_bound=10, period_s=0.02)
        m.start()
        try:
            deadline = time.monotonic() + 2.0
            while (m.status().state != DEGRADED
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert m.status().state == DEGRADED
        finally:
            m.stop()


class TestServerHealth:
    def test_healthz_ok_schema(self, retriever):
        with TfidfServer(retriever, quick_cfg()) as srv:
            srv.search(QUERIES[:2], k=3)
            hz = srv.healthz()
        json.dumps(hz)
        assert hz["status"] == OK and hz["reasons"] == []
        assert hz["admission_bound"] == srv.config.queue_depth
        assert "batcher" in hz["checks"]["workers"]
        assert hz["uptime_s"] >= 0

    def test_saturation_degrades_and_shrinks_admission(self, retriever):
        # Fault injection: a huge batching window keeps 4 admitted
        # queries parked, saturating queue_depth=4.
        srv = TfidfServer(retriever, quick_cfg(
            queue_depth=4, max_batch=1024, max_wait_ms=60_000,
            cache_entries=0))
        try:
            f1 = srv.submit(QUERIES[:2], k=2)
            f2 = srv.submit(QUERIES[2:4], k=2)
            hz = srv.healthz()
            assert hz["status"] == DEGRADED
            assert any("saturation" in r for r in hz["reasons"])
            # Admission bound visibly shrinks: 4 -> 2, so even a
            # 1-query request sheds while 4 are parked.
            assert hz["admission_bound"] == 2
            with pytest.raises(Overloaded, match="admission bound 2"):
                srv.submit([QUERIES[0]], k=2)
        finally:
            srv.close(drain=True)
        assert f1.result(timeout=0) and f2.result(timeout=0)
        # Recovery: backlog drained; the shed window decays over two
        # evaluations (the first still sees the shed delta).
        srv.health.evaluate()
        status = srv.health.evaluate()
        assert status.state == OK
        assert srv.health.admission_bound(4) == 4

    def test_worker_stall_flips_readyz(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(stall_after_ms=50))
        try:
            srv.health.register("fake", busy_fn=lambda: True)
            srv.health.heartbeat("fake")
            assert srv.readyz()["ready"]
            time.sleep(0.12)           # one stall window, no beat
            rz = srv.readyz()
            assert not rz["ready"] and rz["status"] == UNHEALTHY
            srv.health.heartbeat("fake")
            assert srv.readyz()["ready"]  # recovered
        finally:
            srv.close()

    def test_background_watchdog_runs_when_configured(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(
            health_period_ms=20, stall_after_ms=40))
        try:
            srv.health.register("fake", busy_fn=lambda: True)
            deadline = time.monotonic() + 2.0
            # No manual evaluate: the watchdog thread must notice the
            # stalled worker by itself, within its own cadence.
            while (srv.health.status().state != UNHEALTHY
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.health.status().state == UNHEALTHY
        finally:
            srv.close()

    def test_batcher_heartbeats_recorded(self, retriever):
        srv = TfidfServer(retriever, quick_cfg())
        try:
            srv.search(QUERIES[:2], k=2)
            assert srv.health._workers["batcher"].beats > 0
        finally:
            srv.close()

    def test_ingest_workers_beat_into_monitor(self, toy_corpus_dir):
        from tfidf_tpu.ingest import run_overlapped
        m = HealthMonitor()
        set_monitor(m)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        run_overlapped(toy_corpus_dir, cfg, doc_len=16, chunk_docs=4)
        assert m._workers["packer"].beats > 0
        assert m._workers["drainer"].beats > 0


class TestCanary:
    def _server(self, retriever, **kw):
        return TfidfServer(retriever, quick_cfg(**kw))

    def test_parity_one_on_healthy_index(self, retriever):
        srv = self._server(retriever)
        try:
            canary = CanaryProber(srv, QUERIES, k=3)
            assert canary.probe() == 1.0
            assert canary.parity == 1.0
            snap = srv.metrics.registry.snapshot()
            assert snap["serve_canary_parity_milli"]["value"] == 1000
            assert snap["serve_canary_probes_total"] == 1
            assert snap["serve_canary_failures_total"] == 0
        finally:
            srv.close()

    def test_detects_corrupted_index_after_swap(self, retriever):
        import jax.numpy as jnp

        from tfidf_tpu.ops.hashing import words_to_ids
        log = EventLog(echo="off")
        obs.set_log(log)
        twin = TfidfRetriever(CFG).index(CORPUS)
        srv = self._server(retriever)
        try:
            canary = CanaryProber(srv, QUERIES, k=3)
            srv.swap_index(twin)       # oracle re-captures in the swap
            assert canary.probe() == 1.0
            # Silent post-swap corruption: flip the DF-derived IDF
            # entry of a canary query term ("apple") — exactly the
            # failure a bad segment merge / hot-swap bug would plant.
            tid = int(words_to_ids([b"apple"], CFG.vocab_size,
                                   CFG.hash_seed)[0])
            idf = np.asarray(twin._idf).copy()
            idf[tid] *= 7.0
            twin._idf = jnp.asarray(idf)
            parity = canary.probe()
            assert parity is not None and parity < 1.0
            snap = srv.metrics.registry.snapshot()
            assert snap["serve_canary_parity_milli"]["value"] < 1000
            assert snap["serve_canary_failures_total"] == 1
            evs = [e for e in log.events()
                   if e["event"] == "canary_parity_failure"]
            assert evs and evs[0]["queries"]  # failing query indices
        finally:
            srv.close()

    def test_stress_holds_parity(self, retriever):
        srv = self._server(retriever, max_wait_ms=2)
        errors = []

        def work(tid):
            try:
                rng = np.random.default_rng(tid)
                for _ in range(6):
                    qs = [QUERIES[i] for i in rng.integers(
                        0, len(QUERIES), size=int(rng.integers(1, 4)))]
                    srv.search(qs, k=3, timeout=30)
            except Exception as e:  # noqa: BLE001 — surface in main
                errors.append(e)

        try:
            canary = CanaryProber(srv, QUERIES, k=3)
            threads = [threading.Thread(target=work, args=(t,))
                       for t in range(4)]
            for t in threads:
                t.start()
            parities = [canary.probe() for _ in range(5)]
            for t in threads:
                t.join()
        finally:
            srv.close()
        assert not errors
        compared = [p for p in parities if p is not None]
        assert compared and all(p == 1.0 for p in compared)

    def test_missing_oracle_skips_not_fails(self, retriever):
        srv = self._server(retriever)
        try:
            canary = CanaryProber(srv, QUERIES, k=3)
            canary._oracle.clear()     # simulate a capture race
            assert canary.probe() is None
            snap = srv.metrics.registry.snapshot()
            assert snap["serve_canary_skipped_total"] == 1
            assert snap["serve_canary_failures_total"] == 0
        finally:
            srv.close()

    def test_probe_bypasses_cache(self, retriever):
        srv = self._server(retriever)
        try:
            canary = CanaryProber(srv, QUERIES, k=3)
            before = srv.metrics.snapshot()["cache"]
            canary.probe()
            after = srv.metrics.snapshot()["cache"]
            # Neither probes nor fills: a memoized row must never mask
            # device-path corruption.
            assert after == before
        finally:
            srv.close()

    def test_pinned_queries_from_dir(self, toy_corpus_dir):
        qs = pinned_queries_from_dir(toy_corpus_dir, n=4, tokens=3)
        assert 0 < len(qs) <= 4
        assert all(isinstance(q, str) and q for q in qs)
        # Pinned: same corpus, same queries.
        assert qs == pinned_queries_from_dir(toy_corpus_dir, n=4,
                                             tokens=3)


class TestFlightRecorder:
    def test_ring_keeps_newest(self):
        log = EventLog(capacity=3, echo="off")
        for i in range(7):
            log.log("info", f"e{i}")
        assert [e["event"] for e in log.events()] == ["e4", "e5", "e6"]

    def test_rate_limit_per_event_with_suppression_receipt(self):
        log = EventLog(rate_per_s=0.001, burst=2, echo="off")
        admitted = [log.log("info", "hot", i=i) for i in range(10)]
        assert admitted.count(True) == 2       # burst, then throttled
        assert log.suppressed()["hot"] == 8
        assert all(log.log("info", f"cold{i}") for i in range(5))
        # The suppressed count surfaces on the next admitted event.
        log2 = EventLog(rate_per_s=1000.0, burst=1, echo="off")
        log2.log("info", "x")
        log2.log("info", "x")                  # throttled (burst 1)
        time.sleep(0.01)                       # refill >= 1 token
        assert log2.log("info", "x")
        assert log2.events()[-1]["suppressed"] >= 1

    def test_echo_threshold(self, capsys):
        log = EventLog(echo="warning")
        log.info("quiet", msg="should not echo")
        log.warning("loud", msg="should echo")
        err = capsys.readouterr().err
        assert "should echo" in err and "should not echo" not in err

    def test_dump_is_atomic_and_valid(self, tmp_path):
        log = EventLog(echo="off")
        log.info("boot", msg="hello", n=1)
        log.error("crashish", detail="xyz")
        log.digest(outcome="drained", queries=2, k=3, ms=1.5)
        path = str(tmp_path / "flight.jsonl")
        assert log.dump(path) == path
        assert not os.path.exists(path + ".tmp")  # renamed into place
        tc = _load_trace_check()
        errors, notes = tc.check_flight(path)
        assert errors == [], (errors, notes)
        lines = [json.loads(l) for l in open(path)]
        assert lines[0]["schema"] == "tfidf-flight/1"
        assert lines[0]["events"] == 2 and lines[0]["digests"] == 1
        assert lines[-1]["kind"] == "digest"

    def test_check_flight_catches_torn_dump(self, tmp_path):
        log = EventLog(echo="off")
        log.info("a")
        log.info("b")
        path = str(tmp_path / "flight.jsonl")
        log.dump(path)
        with open(path) as f:
            lines = f.readlines()
        with open(path, "w") as f:            # drop the last line
            f.writelines(lines[:-1])
        tc = _load_trace_check()
        errors, _ = tc.check_flight(path)
        assert errors and "torn" in errors[0]

    def test_server_records_request_digests(self, retriever):
        log = EventLog(echo="off")
        obs.set_log(log)
        srv = TfidfServer(retriever, quick_cfg(cache_entries=0))
        try:
            srv.search(QUERIES[:2], k=3)
            with pytest.raises(Exception):
                srv.submit([QUERIES[0]], k=2, deadline_ms=0
                           ).result(timeout=10)
        finally:
            srv.close()
        outcomes = [d["outcome"] for d in log.digests()]
        assert "drained" in outcomes and "shed_deadline" in outcomes
        d = log.digests()[0]
        assert d["queries"] == 2 and d["k"] == 3 and d["ms"] >= 0
        assert "epoch" in d

    def test_server_close_dumps_when_armed(self, retriever, tmp_path):
        log = EventLog(echo="off")
        obs.set_log(log)
        path = str(tmp_path / "close.flight.jsonl")
        obs.configure_flight(path)
        srv = TfidfServer(retriever, quick_cfg())
        srv.search([QUERIES[0]], k=2)
        srv.close()
        assert os.path.exists(path)
        tc = _load_trace_check()
        errors, _ = tc.check_flight(path)
        assert errors == []

    def test_flight_path_derives_from_trace(self, tmp_path):
        assert obs.flight_path() is None       # nothing armed
        obs.set_tracer(obs.Tracer(), str(tmp_path / "t.json"))
        try:
            assert obs.flight_path() == str(tmp_path / "t.json") \
                + ".flight.jsonl"
        finally:
            obs.set_tracer(None)


def _load_trace_check():
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        "trace_check", os.path.join(tools, "trace_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSigtermLeavesEvidence:
    """Acceptance: SIGTERM to a serving subprocess leaves a complete
    flight-recorder dump AND a trace on disk (atomic writes from the
    signal handler), both validated by tools/trace_check.py."""

    def test_sigterm_dumps_flight_and_trace(self, tmp_path):
        input_dir = tmp_path / "input"
        input_dir.mkdir()
        for i, text in enumerate([b"apple banana", b"cherry date",
                                  b"elder fig", b"grape apple"], 1):
            (input_dir / f"doc{i}").write_bytes(text)
        trace = str(tmp_path / "serve_trace.json")
        flight = str(tmp_path / "serve.flight.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tfidf_tpu.cli", "serve",
             "--input", str(input_dir), "--vocab-size", "512",
             "--max-wait-ms", "1", "--canary-period-ms", "0",
             "--trace", trace, "--flight", flight],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, cwd=REPO, text=True)
        try:
            # One served request so the dump carries a digest and the
            # trace carries the request span chain.
            proc.stdin.write(json.dumps(
                {"id": 1, "queries": ["cherry date"], "k": 2}) + "\n")
            proc.stdin.flush()
            deadline = time.monotonic() + 120
            line = proc.stdout.readline()      # the id-1 response
            assert line, "server never answered before SIGTERM"
            assert json.loads(line)["id"] == 1
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=60)
            assert time.monotonic() < deadline
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        assert rc == 143                       # 128 + SIGTERM
        assert os.path.exists(flight), proc.stderr.read()[-2000:]
        assert os.path.exists(trace)
        tc = _load_trace_check()
        errors, notes = tc.check_flight(flight)
        assert errors == [], (errors, notes)
        errors, notes = tc.check_trace(trace, mode="serve",
                                       min_threads=2)
        assert errors == [], (errors, notes)
        digests = [json.loads(l) for l in open(flight)][1:]
        assert any(d.get("kind") == "digest"
                   and d.get("outcome") == "drained" for d in digests)
