"""Char n-gram tests: host path, device path, and their contracts."""

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline
from tfidf_tpu.config import TokenizerKind, VocabMode
from tfidf_tpu.io.corpus import Corpus


def poly_hash_ref(window: bytes, seed: int = 0) -> int:
    """NumPy-free mirror of ops/hashing.device_ngram_ids' rolling hash."""
    h = (seed ^ 0x811C9DC5) & 0xFFFFFFFF
    for b in window:
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def chargram_counts_ref(doc: bytes, lo: int, hi: int, vocab: int, seed: int = 0):
    counts = np.zeros(vocab, np.int64)
    for n in range(lo, hi + 1):
        for i in range(len(doc) - n + 1):
            counts[poly_hash_ref(doc[i:i + n], seed) % vocab] += 1
    return counts


CORPUS = Corpus(names=["doc1", "doc2", "doc3"],
                docs=[b"abcabc", b"hello world", b"xyz"])


class TestDeviceChargram:
    def test_counts_match_python_rolling_hash(self):
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED, vocab_size=128,
                             ngram_range=(2, 3), hash_seed=7)
        r = TfidfPipeline(cfg).run_bytes(CORPUS)
        for d, doc in enumerate(CORPUS.docs):
            want = chargram_counts_ref(doc, 2, 3, 128, 7)
            assert (r.counts[d] == want).all(), f"doc{d+1}"

    def test_docsize_is_total_ngram_count(self):
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED, vocab_size=128,
                             ngram_range=(3, 5))
        r = TfidfPipeline(cfg).run_bytes(CORPUS)
        for d, doc in enumerate(CORPUS.docs):
            want = sum(max(len(doc) - n + 1, 0) for n in range(3, 6))
            assert int(r.lengths[d]) == want
        # row sums == docSize (the docSize invariant carried to n-grams)
        assert (r.counts.sum(axis=1) == r.lengths[:3]).all()

    def test_topk_mode_routes_to_device_path(self):
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED, vocab_size=128,
                             ngram_range=(2, 2), topk=4)
        r = TfidfPipeline(cfg).run(CORPUS)
        assert r.topk_vals.shape == (3, 4)
        assert r.counts is None
        assert r.id_to_word == {}  # device path: ids only

    def test_mesh_chargram_stays_on_device_and_matches(self):
        # Round-2 verdict item 9: mesh chargram used to detour through
        # the host tokenizer. A docs-only mesh now runs the sharded
        # device path; 11 docs on 8 devices exercises doc-axis padding.
        names = [f"doc{i}" for i in range(1, 12)]
        docs = [bytes(f"doc {i} body {'x' * i} tail", "ascii")
                for i in range(1, 12)]
        corpus = Corpus(names=names, docs=docs)
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED, vocab_size=128,
                             ngram_range=(2, 3), topk=4, hash_seed=3)
        single = TfidfPipeline(cfg).run(corpus)
        # Fresh construction, not dataclasses.replace: replace() re-runs
        # __post_init__ on the resolved engine and drops the
        # engine-defaulted flag, which (correctly) disables the device
        # chargram route — the CLI also constructs fresh.
        mcfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                              vocab_mode=VocabMode.HASHED, vocab_size=128,
                              ngram_range=(2, 3), topk=4, hash_seed=3,
                              mesh_shape={"docs": 8})
        mesh = TfidfPipeline(mcfg).run(corpus)
        assert mesh.id_to_word == {}  # device path, not host tokenizer
        n = len(names)
        np.testing.assert_array_equal(np.asarray(mesh.df),
                                      np.asarray(single.df))
        np.testing.assert_array_equal(np.asarray(mesh.topk_ids)[:n],
                                      np.asarray(single.topk_ids)[:n])
        np.testing.assert_allclose(np.asarray(mesh.topk_vals)[:n],
                                   np.asarray(single.topk_vals)[:n],
                                   rtol=1e-6)
        assert mesh.names[:n] == names

    def test_mesh_chargram_seq_shards_use_host_path(self):
        # seq/vocab meshes cannot shard the byte stream (n-gram windows
        # need halos) — they must fall back to the host tokenizer, which
        # carries word strings (id_to_word non-empty).
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED, vocab_size=128,
                             ngram_range=(2, 2), topk=4,
                             mesh_shape={"docs": 4, "seq": 2})
        r = TfidfPipeline(cfg).run(CORPUS)
        assert r.topk_vals.shape[1] == 4

    def test_full_output_routes_to_host_path(self):
        # Without topk, run() must use the host tokenizer so that full
        # output lines have word strings (review regression fix).
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED, vocab_size=1 << 14,
                             ngram_range=(2, 2))
        r = TfidfPipeline(cfg).run(CORPUS)
        lines = r.output_lines()  # must not KeyError
        assert lines and all(b"@" in l for l in lines)

    def test_sparse_engine_rides_device_sparse_lowering(self):
        # Round 4: explicit engine="sparse" now gets the row-sparse
        # device chargram (pipeline._chargram_sparse_forward) instead
        # of falling back to the host tokenizer.
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED, vocab_size=1 << 14,
                             ngram_range=(2, 2), engine="sparse", topk=2)
        r = TfidfPipeline(cfg).run(CORPUS)
        assert r.counts is None and r.topk_vals.shape == (3, 2)
        # Same selection as the dense device lowering on the same
        # rolling-hash universe — the engines may not diverge.
        dense = TfidfPipeline(PipelineConfig(
            tokenizer=TokenizerKind.CHARGRAM, vocab_mode=VocabMode.HASHED,
            vocab_size=1 << 14, ngram_range=(2, 2), engine="dense",
            topk=2)).run(CORPUS)
        np.testing.assert_array_equal(r.topk_ids, dense.topk_ids)
        np.testing.assert_allclose(r.topk_vals, dense.topk_vals, rtol=1e-6)
        np.testing.assert_array_equal(r.df, dense.df)

    def test_wide_vocab_sparse_chargram(self):
        # BASELINE config 4's point: vocab 2^20, where a dense [D, V]
        # histogram cannot exist. The defaulted engine must route to
        # the sparse lowering and produce DF/topk consistent with the
        # Python rolling-hash reference.
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED,
                             vocab_size=1 << 20, ngram_range=(2, 3),
                             hash_seed=7, topk=4)
        r = TfidfPipeline(cfg).run_bytes(CORPUS)
        assert r.df.shape == (1 << 20,)
        for d, doc in enumerate(CORPUS.docs):
            want = chargram_counts_ref(doc, 2, 3, 1 << 20, 7)
            # df contribution and topk scores come from these counts;
            # spot-check the top-1 id's count via its score ordering.
            got_ids = [i for i in r.topk_ids[d] if i >= 0]
            for i in got_ids:
                assert want[i] > 0

    @pytest.mark.skipif(
        __import__("jax").device_count() < 8, reason="needs 8 devices")
    def test_sharded_sparse_chargram_matches_single(self):
        import jax
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.HASHED,
                             vocab_size=1 << 14, ngram_range=(2, 3),
                             engine="sparse", topk=3)
        single = TfidfPipeline(cfg).run_bytes(CORPUS)
        mesh_cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                                  vocab_mode=VocabMode.HASHED,
                                  vocab_size=1 << 14, ngram_range=(2, 3),
                                  engine="sparse", topk=3,
                                  mesh_shape={"docs": 8})
        sharded = TfidfPipeline(mesh_cfg).run(CORPUS)
        np.testing.assert_array_equal(single.df, sharded.df)
        n = len(CORPUS)
        np.testing.assert_array_equal(single.topk_ids,
                                      sharded.topk_ids[:n])
        np.testing.assert_allclose(single.topk_vals,
                                   sharded.topk_vals[:n], rtol=1e-6)

    def test_exact_mode_uses_host_strings(self):
        cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                             vocab_mode=VocabMode.EXACT, ngram_range=(2, 2))
        r = TfidfPipeline(cfg).run(CORPUS)
        # host path: id_to_word holds real n-gram strings
        assert b"ab" in set(r.id_to_word.values())

    def test_host_fallback_flag(self):
        base = dict(engine="dense", tokenizer=TokenizerKind.CHARGRAM,
                    vocab_mode=VocabMode.HASHED, vocab_size=256,
                    ngram_range=(2, 3))
        dev = TfidfPipeline(PipelineConfig(**base)).run_bytes(CORPUS)
        host = TfidfPipeline(
            PipelineConfig(chargram_on_device=False, **base)).run(CORPUS)
        # Different hash universes, same aggregate invariants.
        assert (dev.counts.sum(axis=1) == host.counts.sum(axis=1)).all()
        assert host.counts.shape == dev.counts.shape
