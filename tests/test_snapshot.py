"""Crash-fast index snapshot/restore (ISSUE 8): the resident index
persists through checkpoint.py's seq+LATEST atomic protocol and a
killed server resumes serving WITHOUT re-ingesting the corpus.

The acceptance pins: snapshot -> restore is bit-identical on every
query; a corrupted payload or a mismatched config fingerprint raises
the typed SnapshotMismatch instead of silently serving wrong bytes;
``swap_index`` snapshots the NEW epoch before flipping (the
swap-then-crash hole); and — slow-marked — a serve CLI process
SIGKILLed mid-traffic restarts from ``--snapshot-dir`` with the
corpus DELETED from disk, still answering bit-identically. The chaos
smoke at the bottom is the ISSUE's full acceptance scenario.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tfidf_tpu import checkpoint as ckpt
from tfidf_tpu import faults, obs
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.models.retrieval import config_fingerprint
from tfidf_tpu.obs.log import EventLog
from tfidf_tpu.serve import TfidfServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4", "doc5"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig fig",
          b"grape grape grape grape"])
QUERIES = ["apple cherry", "banana date", "grape", "fig elder"]


@pytest.fixture(scope="module")
def retriever():
    return TfidfRetriever(CFG).index(CORPUS)


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.set_log(EventLog(echo="off"))
    faults.disarm()
    yield
    faults.disarm()
    obs.set_log(None)


class TestCheckpointIndex:
    def test_save_restore_roundtrip_with_checksums(self, tmp_path):
        root = str(tmp_path / "snap")
        arrays = {"a": np.arange(12, dtype=np.int32).reshape(3, 4),
                  "b": np.linspace(0, 1, 7, dtype=np.float32)}
        meta = {"num_docs": 3, "epoch": 2, "config_sha": "abc"}
        assert ckpt.save_index(root, arrays, meta) == root
        assert ckpt.exists(root)
        got, gmeta = ckpt.restore_index(root)
        assert gmeta == meta
        for k in arrays:
            np.testing.assert_array_equal(got[k], arrays[k])

    def test_supersede_keeps_latest_only(self, tmp_path):
        root = str(tmp_path / "snap")
        ckpt.save_index(root, {"x": np.zeros(2)}, {"epoch": 0})
        ckpt.save_index(root, {"x": np.ones(2)}, {"epoch": 1})
        got, meta = ckpt.restore_index(root)
        assert meta["epoch"] == 1
        np.testing.assert_array_equal(got["x"], np.ones(2))
        payloads = [e for e in os.listdir(root)
                    if e.startswith("ckpt-")]
        assert len(payloads) == 1    # superseded payload reclaimed

    def test_corrupted_payload_raises_mismatch(self, tmp_path):
        root = str(tmp_path / "snap")
        ckpt.save_index(root, {"x": np.arange(64, dtype=np.int64)},
                        {"epoch": 0})
        payload = ckpt._committed_payload(root)[0]
        npz = os.path.join(payload, "index.npz")
        blob = bytearray(open(npz, "rb").read())
        blob[len(blob) // 2] ^= 0xFF   # bit-rot inside the payload
        open(npz, "wb").write(bytes(blob))
        # Either layer may catch it: the zip CRC on read, or our own
        # sha256 re-verification — silent success is the only failure.
        with pytest.raises(Exception):
            ckpt.restore_index(root)

    def test_missing_snapshot_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            ckpt.restore_index(str(tmp_path / "nothing"))

    def test_state_checkpoint_is_not_an_index(self, tmp_path):
        root = str(tmp_path / "state")
        ckpt.save_state(root, {"df": np.zeros(4)}, force_npz=True)
        with pytest.raises(ckpt.SnapshotMismatch):
            ckpt.restore_index(root)
        # and the state path still restores as state
        assert "df" in ckpt.restore_state(root)


class TestRetrieverSnapshot:
    def test_roundtrip_bit_identical_search(self, retriever, tmp_path):
        root = str(tmp_path / "snap")
        retriever.snapshot(root, epoch=3)
        twin, meta = TfidfRetriever.restore(root, CFG)
        assert meta["epoch"] == 3
        assert twin.names == retriever.names
        assert twin._num_docs == retriever._num_docs
        for q in QUERIES + ["", "unseen words zz"]:
            a = retriever.search([q], k=4)
            b = twin.search([q], k=4)
            np.testing.assert_array_equal(a[0], b[0], err_msg=q)
            np.testing.assert_array_equal(a[1], b[1], err_msg=q)

    def test_config_fingerprint_gates_restore(self, retriever,
                                              tmp_path):
        root = str(tmp_path / "snap")
        retriever.snapshot(root)
        other = PipelineConfig(vocab_mode=VocabMode.HASHED,
                               vocab_size=512, hash_seed=99,
                               max_doc_len=16, doc_chunk=16)
        assert config_fingerprint(other) != config_fingerprint(CFG)
        with pytest.raises(ckpt.SnapshotMismatch, match="fingerprint"):
            TfidfRetriever.restore(root, other)
        # default config (from snapshot meta) differs too -> mismatch
        with pytest.raises(ckpt.SnapshotMismatch):
            TfidfRetriever.restore(root)

    def test_fingerprint_ignores_execution_path_knobs(self):
        a = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512)
        b = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                           wire="padded", finish="chunked",
                           result_wire="pair", topk=7)
        assert config_fingerprint(a) == config_fingerprint(b)

    def test_unindexed_snapshot_raises(self, tmp_path):
        with pytest.raises(RuntimeError):
            TfidfRetriever(CFG).snapshot(str(tmp_path / "x"))


class TestServerSnapshot:
    def test_server_snapshot_and_initial_epoch(self, retriever,
                                               tmp_path):
        root = str(tmp_path / "snap")
        srv = TfidfServer(retriever, ServeConfig(
            max_batch=8, max_wait_ms=5, snapshot_dir=root))
        try:
            assert srv.snapshot() == root
        finally:
            srv.close()
        twin, meta = TfidfRetriever.restore(root, CFG)
        srv2 = TfidfServer(twin, ServeConfig(max_batch=8, max_wait_ms=5),
                           initial_epoch=int(meta["epoch"]))
        try:
            assert srv2.epoch == 0
            got = srv2.search(QUERIES[:2], k=3)
            want = retriever.search(QUERIES[:2], k=3)
            np.testing.assert_array_equal(got[0], want[0])
        finally:
            srv2.close()

    def test_swap_snapshots_new_epoch_before_flip(self, tmp_path):
        """The swap-then-crash hole: by the time swap_index returns,
        the snapshot on disk already holds the NEW epoch's index."""
        root = str(tmp_path / "snap")
        base = TfidfRetriever(CFG).index(CORPUS)
        grown = TfidfRetriever(CFG).index(Corpus(
            names=list(CORPUS.names) + ["doc6"],
            docs=list(CORPUS.docs) + [b"kumquat lychee mango"]))
        srv = TfidfServer(base, ServeConfig(
            max_batch=8, max_wait_ms=5, snapshot_dir=root))
        try:
            srv.snapshot()
            _, meta0 = ckpt.restore_index(root)
            assert meta0["epoch"] == 0 and meta0["num_docs"] == 5
            epoch = srv.swap_index(grown)
            assert epoch == 1
            restored, meta1 = TfidfRetriever.restore(root, CFG)
            assert meta1["epoch"] == 1
            assert restored._num_docs == 6     # the NEW index
            got = restored.search(["kumquat"], k=2)
            want = grown.search(["kumquat"], k=2)
            np.testing.assert_array_equal(got[0], want[0])
        finally:
            srv.close()

    def test_snapshot_without_dir_raises(self, retriever):
        srv = TfidfServer(retriever, ServeConfig(max_batch=8,
                                                 max_wait_ms=5))
        try:
            with pytest.raises(ValueError, match="snapshot dir"):
                srv.snapshot()
        finally:
            srv.close()


# ---------------------------------------------------------------------
def _write_corpus(d, extra=()):
    os.makedirs(d, exist_ok=True)
    texts = ["kumquat lychee mango kumquat",
             "nectar lychee papaya",
             "mango papaya quince raisin",
             "kumquat raisin raisin nectar"] + list(extra)
    for i, text in enumerate(texts, 1):
        with open(os.path.join(d, f"doc{i}"), "w") as f:
            f.write(text)
    return [f"doc{i}" for i in range(1, len(texts) + 1)]


def _serve_proc(args, tmp_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(tmp_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "tfidf_tpu.cli", "serve"] + args,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=env, cwd=REPO, text=True)


def _ask(proc, obj, timeout=120):
    proc.stdin.write(json.dumps(obj) + "\n")
    proc.stdin.flush()
    line = proc.stdout.readline()
    assert line, ("server died: "
                  + proc.stderr.read()[-2000:])
    resp = json.loads(line)
    # The request id (round 16) is process-unique BY DESIGN — these
    # tests compare response payloads across restarts/replicas, so
    # the identity field must not participate in the equality.
    resp.pop("rid", None)
    return resp


@pytest.mark.slow
class TestServeCliCrashRestart:
    def test_sigkill_then_snapshot_restart_serves_identically(
            self, tmp_path):
        """SIGKILL the serve CLI mid-traffic; restart with
        --snapshot-dir AFTER DELETING THE CORPUS — the restored
        server cannot possibly re-ingest, and must still answer
        bit-identically to the pre-kill server."""
        import shutil
        input_dir = str(tmp_path / "input")
        snap = str(tmp_path / "snap")
        _write_corpus(input_dir)
        queries = [{"id": i, "queries": [q], "k": 3}
                   for i, q in enumerate(["kumquat", "papaya quince",
                                          "nectar", "raisin"])]
        common = ["--input", input_dir, "--vocab-size", "512",
                  "--max-wait-ms", "1", "--canary-period-ms", "0",
                  "--devmon-period-ms", "0", "--snapshot-dir", snap]

        t0 = time.monotonic()
        proc = _serve_proc(common)
        try:
            first = [_ask(proc, q) for q in queries]
            build_wall = time.monotonic() - t0
            proc.send_signal(signal.SIGKILL)   # no flush, no atexit
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        assert ckpt.exists(snap)

        shutil.rmtree(input_dir)               # the corpus is GONE
        t0 = time.monotonic()
        proc = _serve_proc(common)
        try:
            second = [_ask(proc, q) for q in queries]
            restore_wall = time.monotonic() - t0
            proc.stdin.write('{"op": "shutdown"}\n')
            proc.stdin.flush()
            proc.wait(timeout=60)
            banner = proc.stderr.read()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        # Bit-identical responses (JSON float round-trip included).
        assert second == first
        assert "snapshot=restored" in banner
        # Crash-FAST: process-boot wall (jax import dominates both) —
        # the restored server must not be slower than build+serve was;
        # the structural pin above (corpus deleted) is the hard proof
        # that no re-ingest happened.
        assert restore_wall < build_wall * 2, (restore_wall, build_wall)

    def test_chaos_smoke_acceptance(self, tmp_path):
        """THE ISSUE acceptance: one plan mixing transient dispatch
        faults, a poison query, a pack-worker kill (ingest leg) and a
        SIGKILL+restart (serve leg). Every non-shed non-poisoned query
        bit-identical to an unfaulted run; server ends ok with the
        breaker closed; restore serves without re-ingesting."""
        import shutil

        # --- ingest leg: pack-worker kill, restarted, identical ---
        from tfidf_tpu.ingest import run_overlapped
        corpus_dir = str(tmp_path / "ing")
        _write_corpus(corpus_dir)
        icfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                              vocab_size=1 << 12)
        clean = run_overlapped(corpus_dir, icfg, doc_len=16,
                               chunk_docs=2)
        faults.arm(faults.FaultPlan.parse("pack_worker:transient:n=1"))
        killed = run_overlapped(corpus_dir, icfg, doc_len=16,
                                chunk_docs=2)
        faults.disarm()
        np.testing.assert_array_equal(np.asarray(clean.df),
                                      np.asarray(killed.df))

        # --- serve leg: transients + poison + SIGKILL + restart ---
        input_dir = str(tmp_path / "input")
        snap = str(tmp_path / "snap")
        _write_corpus(input_dir)
        plan = ("device_dispatch:transient:n=2;"
                "device_dispatch:fatal:match=zzpoison")
        common = ["--input", input_dir, "--vocab-size", "512",
                  "--max-wait-ms", "1", "--canary-period-ms", "0",
                  "--devmon-period-ms", "0", "--snapshot-dir", snap]
        # Requests ride the CLI's warmed k (its default): the compile
        # watchdog must see ZERO fresh programs, or health would
        # (correctly) flag a recompile instead of the chaos story.
        reqs = [{"id": i, "queries": [q]}
                for i, q in enumerate(["kumquat", "papaya quince",
                                       "nectar", "raisin lychee"])]
        poison_req = {"id": 99, "queries": ["zzpoison mango"]}

        proc = _serve_proc(common + ["--faults", plan])
        try:
            faulted = [_ask(proc, q) for q in reqs]
            bad = _ask(proc, poison_req)
            assert bad["error"] == "poison_query", bad
            bad2 = _ask(proc, poison_req)      # 4xx thereafter
            assert bad2["error"] == "poison_query", bad2
            hz = _ask(proc, {"op": "healthz"})["healthz"]
            hz = _ask(proc, {"op": "healthz"})["healthz"]
            assert hz["status"] == "ok", hz    # breaker closed, ok
            assert hz["checks"].get("circuit_breaker") == "closed"
            m = _ask(proc, {"op": "metrics"})["metrics"]
            assert m["requests"] >= len(reqs)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        # Unfaulted oracle run answers through the same CLI path.
        oracle_proc = _serve_proc(
            ["--input", input_dir, "--vocab-size", "512",
             "--max-wait-ms", "1", "--canary-period-ms", "0",
             "--devmon-period-ms", "0"])
        try:
            oracle = [_ask(oracle_proc, q) for q in reqs]
            oracle_proc.stdin.write('{"op": "shutdown"}\n')
            oracle_proc.stdin.flush()
            oracle_proc.wait(timeout=60)
        finally:
            if oracle_proc.poll() is None:
                oracle_proc.kill()
                oracle_proc.wait(timeout=30)
        # Every non-shed non-poisoned response bit-identical to the
        # unfaulted run, despite 2 injected transients.
        assert faulted == oracle

        # Restart from snapshot with the corpus deleted: serves the
        # same bytes without any corpus to re-ingest.
        shutil.rmtree(input_dir)
        proc = _serve_proc(common)
        try:
            restored = [_ask(proc, q) for q in reqs]
            hz = _ask(proc, {"op": "healthz"})["healthz"]
            assert hz["status"] == "ok"
            proc.stdin.write('{"op": "shutdown"}\n')
            proc.stdin.flush()
            proc.wait(timeout=60)
            banner = proc.stderr.read()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        assert restored == oracle
        assert "snapshot=restored" in banner


@pytest.mark.slow
class TestChaosBenchArtifact:
    def test_serve_bench_chaos_artifact_ledger_gate(self, tmp_path):
        """serve_bench --chaos emits the chaos receipts + parity
        verdict; the ledger normalizes it as kind=chaos and the gate
        zero-tolerates parity_ok."""
        out = str(tmp_path / "CHAOS_t.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "serve_bench.py"),
             "--requests", "48", "--docs", "96", "--doc-len", "24",
             "--concurrency", "4",
             "--chaos", "device_dispatch:transient:n=2;"
                        "device_dispatch:fatal:match=__poison__",
             "--out", out],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=600)
        assert rc.returncode == 0, rc.stderr[-2000:]
        artifact = json.load(open(out))
        chaos = artifact["chaos"]
        assert chaos["parity_ok"] == 1
        assert chaos["parity_checked"] > 0
        assert chaos["retries"] >= 1
        assert chaos["quarantined"] >= 1
        assert chaos["poisoned_requests"] >= 1
        assert chaos["breaker_open_at_exit"] == 0
        assert chaos["final_health"] == "ok"

        sys.path.append(os.path.join(REPO, "tools"))
        import importlib.util as ilu
        spec = ilu.spec_from_file_location(
            "perf_ledger", os.path.join(REPO, "tools",
                                        "perf_ledger.py"))
        ledger = ilu.module_from_spec(spec)
        spec.loader.exec_module(ledger)
        rec, reason = ledger.normalize(out)
        assert reason is None and rec["kind"] == "chaos"
        spec = ilu.spec_from_file_location(
            "perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
        gate = ilu.module_from_spec(spec)
        spec.loader.exec_module(gate)
        assert gate.gate(rec, [rec])["ok"]
