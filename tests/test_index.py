"""Segmented index (ISSUE 12): live add/update/delete without
rebuilding the world.

The load-bearing contract everywhere: under ANY interleaving of
add/update/delete/seal/compaction/save+restore, a search of the
segmented index is BIT-IDENTICAL — (score bytes, doc names), tie order
included — to a from-scratch rebuild of the live corpus at the same
pinned token length. Plus the serving-side visibility pins: every
change a query could observe bumps the epoch (no stale cache hit can
serve a deleted doc), the canary oracle re-captures on every bump, and
a compactor killed mid-merge via the ``swap`` fault seam leaves the
index byte-for-byte untouched.
"""

import json
import os
import threading

import numpy as np
import pytest

from tfidf_tpu import checkpoint as ckpt
from tfidf_tpu import faults
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.index import Compactor, Segment, SegmentedIndex
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
DOCS = {
    "doc1": "apple banana apple cherry",
    "doc2": "banana banana date",
    "doc3": "cherry date elder fig",
    "doc4": "apple fig fig fig",
    "doc5": "grape grape grape grape",
}
QUERIES = ["apple cherry", "banana", "grape date", "fig", "elder",
           "apple fig", "date banana cherry", "nosuchword"]


def corpus_of(docs):
    return Corpus(names=list(docs), docs=[t.encode()
                                          for t in docs.values()])


def build(docs=DOCS, delta_docs=4, compact_at=2):
    return SegmentedIndex.from_corpus(corpus_of(docs), CFG,
                                      delta_docs=delta_docs,
                                      compact_at=compact_at)


def names_of(names, ids):
    return [[names[i] if i >= 0 else None for i in row] for row in ids]


def assert_rebuild_parity(idx, queries=QUERIES, k=3):
    """Search the segmented view and a FROM-SCRATCH retriever rebuild
    of the live corpus; (scores, names) must match byte for byte."""
    view = idx.view()
    vals, ids = view.search(queries, k)
    oracle = idx.rebuild_retriever()
    ovals, oids = oracle.search(queries, k)
    np.testing.assert_array_equal(vals, ovals)
    assert names_of(view.names, ids) == names_of(oracle.names, oids)


# --- primitives ------------------------------------------------------

def test_host_sorted_counts_matches_device():
    import jax.numpy as jnp

    from tfidf_tpu.ops.sparse import (sorted_term_counts,
                                      sorted_term_counts_host)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, size=(7, 12)).astype(np.int32)
    lens = rng.integers(0, 13, size=(7,)).astype(np.int32)
    ids_d, counts_d, head_d = sorted_term_counts(jnp.asarray(toks),
                                                 jnp.asarray(lens))
    ids_h, counts_h, head_h = sorted_term_counts_host(toks, lens)
    np.testing.assert_array_equal(np.asarray(ids_d), ids_h)
    np.testing.assert_array_equal(np.asarray(head_d), head_h)
    # counts are garbage-by-contract off head slots: compare there only
    np.testing.assert_array_equal(np.asarray(counts_d)[head_h],
                                  counts_h[head_h])


def test_masked_topk_pins():
    import jax.numpy as jnp

    from tfidf_tpu.ops.topk import masked_topk, merge_topk
    scores = jnp.asarray([[0.5, 0.9, 0.9, 0.1]])
    # all dead: every slot comes back with the sub-zero sentinel
    vals, _ = masked_topk(scores, jnp.zeros((4,), bool), k=3)
    assert np.all(np.asarray(vals) < 0)
    # dead doc cannot displace a live one; ties keep lowest index
    live = jnp.asarray([True, False, True, True])
    vals, idx = masked_topk(scores, live, k=3)
    np.testing.assert_array_equal(np.asarray(idx), [[2, 0, 3]])
    # merge keeps concat order among equal values (global insertion
    # order by construction: earlier segments concatenate first)
    mv = jnp.asarray([[0.9, 0.2, 0.9, 0.9]])
    mi = jnp.asarray([[3, 9, 11, 12]])
    vals, idx = merge_topk(mv, mi, k=3)
    np.testing.assert_array_equal(np.asarray(idx), [[3, 11, 12]])


# --- bit-parity vs from-scratch rebuild ------------------------------

def test_initial_build_parity():
    assert_rebuild_parity(build())


def test_parity_vs_natural_retriever_build():
    # The stronger oracle: a plain TfidfRetriever.index over the same
    # corpus (its own packing) — byte parity of (scores, names).
    idx = build()
    view = idx.view()
    r = TfidfRetriever(CFG).index(corpus_of(DOCS))
    vals, ids = view.search(QUERIES, 3)
    ovals, oids = r.search(QUERIES, 3)
    np.testing.assert_array_equal(vals, ovals)
    assert names_of(view.names, ids) == names_of(r.names, oids)


def test_add_update_delete_parity():
    idx = build()
    idx.add_docs(["doc6", "doc7"], ["grape melon", "melon apple date"])
    assert_rebuild_parity(idx)
    idx.add_docs(["doc2"], ["banana melon melon"])     # update
    assert_rebuild_parity(idx)
    idx.delete_docs(["doc5", "doc1"])
    assert_rebuild_parity(idx)


def test_property_random_interleavings(tmp_path):
    """The acceptance property: random mutation streams with seals,
    threshold compactions and a mid-sequence save/restore, parity
    held after every visibility change."""
    rng = np.random.default_rng(7)
    words = ["apple", "banana", "cherry", "date", "elder", "fig",
             "grape", "melon", "kiwi", "lime"]

    def synth():
        n = int(rng.integers(1, 9))
        return " ".join(words[int(rng.integers(0, len(words)))]
                        for _ in range(n))

    for seed in (0, 1):
        rng = np.random.default_rng(seed)
        idx = build(delta_docs=3, compact_at=2)
        alive = set(DOCS)
        next_id = 6
        for step in range(28):
            op = int(rng.integers(0, 4))
            if op == 0 or len(alive) <= 2:          # add
                name = f"doc{next_id}"
                next_id += 1
                idx.add_docs([name], [synth()])
                alive.add(name)
            elif op == 1:                           # update in place
                name = sorted(alive)[int(rng.integers(0, len(alive)))]
                idx.add_docs([name], [synth()])
            elif op == 2:                           # delete
                name = sorted(alive)[int(rng.integers(0, len(alive)))]
                idx.delete_docs([name])
                alive.discard(name)
            else:                                   # compact
                idx.compact(force=True)
            if step == 13:                          # crash + restore
                d = str(tmp_path / f"snap{seed}")
                idx.save(d, epoch=step)
                idx, meta = SegmentedIndex.restore(d, CFG)
                assert meta["epoch"] == 13
            assert_rebuild_parity(idx)
        assert idx.num_docs == len(alive)


def test_all_deleted_and_width():
    idx = build(delta_docs=4)
    view = idx.view()
    assert view.search(QUERIES[:2], 10)[0].shape == (2, 5)  # min(k, D)
    idx.delete_docs(list(DOCS))
    view = idx.view()
    vals, ids = view.search(QUERIES[:2], 3)
    assert vals.shape == (2, 0) and ids.shape == (2, 0)
    assert idx.num_docs == 0


def test_tie_order_matches_rebuild():
    # identical docs => identical scores; the winners must come out in
    # insertion order on both paths, across segment boundaries
    docs = {f"t{i}": "same same words" for i in range(7)}
    docs["x"] = "other content"
    idx = build(docs, delta_docs=3, compact_at=2)
    idx.add_docs(["t7", "t8"], ["same same words"] * 2)
    idx.delete_docs(["t2"])
    assert_rebuild_parity(idx, ["same words", "other"], k=6)
    idx.compact(force=True)
    assert_rebuild_parity(idx, ["same words", "other"], k=6)


# --- segment lifecycle ----------------------------------------------

def test_seal_on_full_delta():
    idx = build(delta_docs=2)
    assert idx.sealed_count == 1            # the bulk-load base
    out = idx.add_docs(["a1", "a2", "a3"], ["kiwi", "lime", "melon"])
    assert out["sealed"] == 1               # 2 filled the delta
    assert idx.sealed_count == 2
    assert idx.stats()["delta_used"] == 1
    assert_rebuild_parity(idx)


def test_compaction_drops_tombstones_preserves_order():
    idx = build(delta_docs=2, compact_at=2)
    idx.add_docs(["a1", "a2", "a3", "a4"],
                 ["kiwi", "lime", "melon", "kiwi lime"])
    idx.delete_docs(["doc2", "a1"])
    assert idx.needs_compaction
    before = idx.stats()["tombstones"]
    assert before >= 2
    summary = idx.compact()
    assert summary["dropped_tombstones"] >= 2
    assert idx.sealed_count == 1
    assert idx.stats()["tombstones"] == 0
    assert_rebuild_parity(idx)


def test_compact_below_threshold_noop():
    idx = build(delta_docs=8, compact_at=4)
    assert idx.compact() is None            # 1 sealed < threshold
    assert idx.compact(force=True) is None  # force still needs >= 2


def test_delete_missing_is_not_a_visibility_change():
    idx = build()
    v0 = idx.version
    out = idx.delete_docs(["nope"])
    assert out == {"deleted": 0, "missing": 1, "version": v0}


# --- persistence -----------------------------------------------------

def test_save_restore_roundtrip(tmp_path):
    idx = build(delta_docs=3)
    idx.add_docs(["a1", "a2"], ["kiwi lime", "melon"])
    idx.delete_docs(["doc3"])
    d = str(tmp_path / "snap")
    idx.save(d, epoch=5)
    idx2, meta = SegmentedIndex.restore(d, CFG)
    assert meta["epoch"] == 5 and meta["num_docs"] == idx.num_docs
    v1, i1 = idx.view().search(QUERIES, 3)
    v2, i2 = idx2.view().search(QUERIES, 3)
    np.testing.assert_array_equal(v1, v2)
    assert names_of(idx.view().names, i1) == names_of(
        idx2.view().names, i2)
    # tombstones survived: the deleted doc stays deleted
    assert "doc3" not in [n for row in names_of(idx2.view().names, i2)
                          for n in row]
    # ...and mutation continues from the restored state
    idx2.add_docs(["a3"], ["elder kiwi"])
    assert_rebuild_parity(idx2)


def test_restore_rejects_plain_retriever_snapshot(tmp_path):
    r = TfidfRetriever(CFG).index(corpus_of(DOCS))
    d = str(tmp_path / "plain")
    r.snapshot(d)
    with pytest.raises(ckpt.SnapshotMismatch):
        SegmentedIndex.restore(d, CFG)


def test_restore_rejects_config_mismatch(tmp_path):
    idx = build()
    d = str(tmp_path / "snap")
    idx.save(d)
    other = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=256,
                           max_doc_len=16, doc_chunk=16)
    with pytest.raises(ckpt.SnapshotMismatch):
        SegmentedIndex.restore(d, other)


def test_segment_validation():
    with pytest.raises(ValueError):
        Segment(0, 16, 512)
    with pytest.raises(ValueError):
        SegmentedIndex(CFG, delta_docs=0)
    with pytest.raises(ValueError):
        SegmentedIndex(CFG, compact_at=1)
    with pytest.raises(ValueError):
        SegmentedIndex(PipelineConfig())    # EXACT vocab


# --- serving integration --------------------------------------------

def serve_cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_entries", 64)
    return ServeConfig(**kw)


@pytest.fixture
def served():
    from tfidf_tpu.serve import TfidfServer
    idx = build(delta_docs=2, compact_at=2)
    server = TfidfServer(idx.view(), serve_cfg())
    server.attach_segments(idx)
    yield server, idx
    server.close(drain=True)


def test_every_visibility_change_bumps_epoch(served):
    """The cache-staleness satellite: add (plain), add-causing-seal,
    delete, and compaction install EACH bump the epoch exactly once;
    a no-op delete bumps nothing."""
    server, idx = served
    e = server.epoch
    out = server.add_docs(["a1"], ["kiwi"])           # plain add
    assert out["epoch"] == e + 1 == server.epoch
    out = server.add_docs(["a2", "a3"], ["lime", "melon"])  # seals
    assert out["sealed"] == 1 and out["epoch"] == e + 2
    out = server.delete_docs(["a1"])                  # delete
    assert out["epoch"] == e + 3
    out = server.delete_docs(["a1"])                  # no-op delete
    assert out["deleted"] == 0 and out["epoch"] == e + 3
    assert server.epoch == e + 3
    summary = server.compact_now(force=True)          # compaction
    assert summary is not None and summary["epoch"] == e + 4


def test_no_stale_cache_hit_serves_a_deleted_doc(served):
    server, idx = served
    vals, ids = server.search(["grape grape"], k=3)
    assert server.doc_names()[ids[0][0]] == "doc5"
    # hot row is cached now; the delete must invalidate it
    server.search(["grape grape"], k=3)
    server.delete_docs(["doc5"])
    vals, ids = server.search(["grape grape"], k=3)
    got = [server.doc_names()[i] for i in ids[0] if i >= 0]
    assert "doc5" not in got
    # parity with rebuild on the exact query that was cached
    oracle = idx.rebuild_retriever()
    ovals, _ = oracle.search(["grape grape"], k=3)
    np.testing.assert_array_equal(vals, ovals)


def test_served_responses_bit_identical_under_mutation(served):
    server, idx = served
    server.add_docs(["a1", "a2", "a3"],
                    ["kiwi lime", "melon kiwi", "lime lime"])
    server.delete_docs(["doc4"])
    server.compact_now(force=True)
    vals, ids = server.submit(QUERIES, 4,
                              use_cache=False).result(timeout=30)
    oracle = idx.rebuild_retriever()
    ovals, oids = oracle.search(QUERIES, 4)
    np.testing.assert_array_equal(vals, ovals)
    assert names_of(server.doc_names(), ids) == names_of(
        oracle.names, oids)


def test_canary_recaptures_on_every_visibility_bump(served):
    from tfidf_tpu.serve import CanaryProber
    server, idx = served
    canary = CanaryProber(server, ["apple cherry", "grape grape"], k=3)
    try:
        assert canary.probe() == 1.0
        server.add_docs(["a1"], ["grape kiwi"])   # changes grape DF
        assert canary.probe() == 1.0              # oracle re-captured
        server.delete_docs(["doc5"])
        assert canary.probe() == 1.0
        server.compact_now(force=True)
        assert canary.probe() == 1.0
        snap = server.metrics.registry.snapshot()
        assert snap.get("serve_canary_failures_total", 0) == 0
    finally:
        canary.close()


def test_canary_races_mutation_skips_not_fails(served):
    """A probe straddling a visibility bump must SKIP (epoch moved
    between submit and compare), never alarm."""
    from tfidf_tpu.serve import CanaryProber
    server, idx = served
    canary = CanaryProber(server, ["apple cherry"], k=3)
    try:
        orig = server.submit

        def racing_submit(queries, k=10, **kw):
            fut = orig(queries, k, **kw)
            server.add_docs([f"race{server.epoch}"], ["kiwi race"])
            return fut

        server.submit = racing_submit
        try:
            assert canary.probe() is None
        finally:
            server.submit = orig
        snap = server.metrics.registry.snapshot()
        assert snap.get("serve_canary_skipped_total", 0) >= 1
        assert snap.get("serve_canary_failures_total", 0) == 0
    finally:
        canary.close()


def test_segment_gauges_published(served):
    server, idx = served
    server.add_docs(["a1"], ["kiwi"])
    snap = server.metrics.registry.snapshot()
    stats = idx.stats()
    assert snap["serve_segment_count"]["value"] == stats["segments"]
    assert snap["serve_delta_fill_milli"]["value"] == int(
        round(stats["delta_fill"] * 1000))
    assert snap["serve_tombstones"]["value"] == stats["tombstones"]


def test_swap_index_fallback_bit_identical_and_detaches(served):
    server, idx = served
    server.add_docs(["a1", "a2"], ["kiwi lime", "melon"])
    server.delete_docs(["doc1"])
    before = server.submit(QUERIES, 3,
                           use_cache=False).result(timeout=30)
    names_before = names_of(server.doc_names(), before[1])
    # the full-rebuild fallback: swap in a from-scratch retriever of
    # the same live corpus — responses must not move a byte
    rebuild = idx.rebuild_retriever()
    server.swap_index(rebuild)
    after = server.submit(QUERIES, 3,
                          use_cache=False).result(timeout=30)
    np.testing.assert_array_equal(before[0], after[0])
    assert names_before == names_of(server.doc_names(), after[1])
    # the swap detached the segmented index: mutations now reject
    with pytest.raises(RuntimeError):
        server.add_docs(["a3"], ["x"])
    assert server.compact_now(force=True) is None


def test_mutation_without_segments_raises():
    from tfidf_tpu.serve import TfidfServer
    r = TfidfRetriever(CFG).index(corpus_of(DOCS))
    server = TfidfServer(r, serve_cfg())
    try:
        with pytest.raises(RuntimeError):
            server.add_docs(["a"], ["x"])
        with pytest.raises(RuntimeError):
            server.delete_docs(["a"])
        assert server.compact_now() is None
    finally:
        server.close(drain=True)


def test_inflight_requests_keep_their_admitted_view(served):
    """Batcher epoch grouping: a request admitted before a mutation
    scores on the pre-mutation view even when it drains after."""
    server, idx = served
    expect, _ = idx.rebuild_retriever().search(["grape grape"], k=3)
    fut = server.submit(["grape grape"], k=3, use_cache=False)
    # the admitted (epoch, view) pair rides the batch group; the
    # mutation lands while the request may still be queued
    server.delete_docs(["doc5"])
    vals, _ids = fut.result(timeout=30)
    # whichever epoch the batch drained under, the response must equal
    # THAT epoch's from-scratch rebuild — never a mix of the two
    after, _ = idx.rebuild_retriever().search(["grape grape"], k=3)
    assert (np.array_equal(vals, expect)
            or np.array_equal(vals, after[:, :vals.shape[1]]))


# --- compactor chaos -------------------------------------------------

def test_compactor_killed_mid_merge_leaves_index_untouched():
    idx = build(delta_docs=2, compact_at=2)
    idx.add_docs(["a1", "a2", "a3"], ["kiwi", "lime", "melon"])
    assert idx.needs_compaction
    v0 = idx.version
    before = idx.view().search(QUERIES, 3)
    faults.arm(faults.FaultPlan.parse("swap:fatal:n=1"))
    try:
        with pytest.raises(faults.FatalFault):
            idx.compact()
    finally:
        faults.disarm()
    # mid-merge kill: no visibility change, no state change, parity
    assert idx.version == v0
    assert idx.sealed_count >= 2
    after = idx.view().search(QUERIES, 3)
    np.testing.assert_array_equal(before[0], after[0])
    assert_rebuild_parity(idx)
    # the retry (post-fault) succeeds and parity still holds
    assert idx.compact() is not None
    assert_rebuild_parity(idx)


def test_supervised_compactor_retries_within_budget(served):
    server, idx = served
    server.add_docs(["a1", "a2", "a3"], ["kiwi", "lime", "melon"])
    assert idx.needs_compaction
    faults.arm(faults.FaultPlan.parse("swap:fatal:n=2"))
    try:
        compactor = Compactor(server.compact_now, period_s=0.01,
                              restart_budget=3).start()
        try:
            deadline = 5.0
            import time as _t
            t0 = _t.monotonic()
            while idx.needs_compaction and _t.monotonic() - t0 < deadline:
                _t.sleep(0.02)
        finally:
            compactor.stop()
    finally:
        faults.disarm()
    assert not idx.needs_compaction        # recovered within budget
    assert compactor.restarts == 2 and not compactor.dead
    assert_rebuild_parity(idx)


def test_compactor_dies_past_budget(served):
    server, idx = served
    server.add_docs(["a1", "a2", "a3"], ["kiwi", "lime", "melon"])
    faults.arm(faults.FaultPlan.parse("swap:fatal:n=-1"))
    try:
        compactor = Compactor(server.compact_now, period_s=0.01,
                              restart_budget=1).start()
        try:
            import time as _t
            t0 = _t.monotonic()
            while not compactor.dead and _t.monotonic() - t0 < 5.0:
                _t.sleep(0.02)
        finally:
            compactor.stop()
    finally:
        faults.disarm()
    assert compactor.dead and compactor.restarts == 2
    assert idx.needs_compaction            # nothing corrupted, just
    assert_rebuild_parity(idx)             # nothing compacted


# --- serve JSONL ops -------------------------------------------------

def test_serve_ops_add_and_delete(served):
    from tfidf_tpu.cli import _serve_handle_line
    server, idx = served
    out = []
    write = out.append
    line = json.dumps({"op": "add_docs", "id": 1, "docs": [
        {"name": "a1", "text": "kiwi lime"},
        {"name": "doc2", "text": "banana melon"}]})
    assert _serve_handle_line(server, line, write, 3, None)
    assert out[-1] == {"id": 1, "added": 1, "updated": 1, "sealed": 0,
                       "epoch": server.epoch}
    line = json.dumps({"op": "delete_docs", "id": 2,
                       "names": ["doc5", "ghost"]})
    assert _serve_handle_line(server, line, write, 3, None)
    assert out[-1] == {"id": 2, "deleted": 1, "missing": 1,
                       "epoch": server.epoch}
    assert_rebuild_parity(idx)
    # malformed payloads answer typed errors, not tracebacks
    for bad in ({"op": "add_docs", "docs": []},
                {"op": "add_docs", "docs": [{"name": 3, "text": "x"}]},
                {"op": "delete_docs", "names": "doc1"}):
        _serve_handle_line(server, json.dumps(bad), write, 3, None)
        assert "error" in out[-1]


def test_serve_ops_reject_without_segments():
    from tfidf_tpu.cli import _serve_handle_line
    from tfidf_tpu.serve import TfidfServer
    r = TfidfRetriever(CFG).index(corpus_of(DOCS))
    server = TfidfServer(r, serve_cfg())
    out = []
    try:
        _serve_handle_line(server, json.dumps(
            {"op": "add_docs",
             "docs": [{"name": "a", "text": "x"}]}), out.append, 3,
            None)
        assert "error" in out[-1] and "delta-docs" in out[-1]["error"]
    finally:
        server.close(drain=True)


# --- doctor compaction section --------------------------------------

def test_doctor_reads_segment_lifecycle_events(tmp_path):
    import importlib.util
    import sys
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        "doctor", os.path.join(tools, "doctor.py"))
    doctor = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(doctor)
    dump = tmp_path / "flight.jsonl"
    lines = [json.dumps({"schema": "tfidf-flight/1", "suppressed": {}})]
    lines.append(json.dumps(
        {"kind": "event", "event": "segment_seal", "docs": 4}))
    for pause in (0.002, 0.005):
        lines.append(json.dumps(
            {"kind": "event", "event": "compaction", "pause_s": pause,
             "dropped_tombstones": 3}))
    lines.append(json.dumps(
        {"kind": "event", "event": "index_mutation", "epoch": 2}))
    dump.write_text("\n".join(lines) + "\n")
    rep = doctor.analyze_flight(str(dump))
    seg = rep["segments"]
    assert seg["seals"] == 1 and seg["compactions"] == 2
    assert seg["mutations"] == 1 and seg["tombstones_dropped"] == 6
    assert seg["total_pause_ms"] == pytest.approx(7.0)
    assert seg["max_pause_ms"] == pytest.approx(5.0)


# --- CLI acceptance (slow) -------------------------------------------

def _serve_proc(args, repo):
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "tfidf_tpu.cli", "serve"] + args,
        stdin=__import__("subprocess").PIPE,
        stdout=__import__("subprocess").PIPE,
        stderr=__import__("subprocess").PIPE, env=env, cwd=repo,
        text=True)


def _ask(proc, obj, timeout=120):
    proc.stdin.write(json.dumps(obj) + "\n")
    proc.stdin.flush()
    line = proc.stdout.readline()
    assert line, "server died: " + proc.stderr.read()[-2000:]
    resp = json.loads(line)
    resp.pop("rid", None)
    return resp


@pytest.mark.slow
def test_segmented_sigkill_restore_serves_mutated_corpus(tmp_path):
    """The mutation acceptance's crash leg: mutate over JSONL, commit
    (explicit snapshot), SIGKILL mid-traffic, restart with the CORPUS
    DELETED — the restored server answers bit-identically, mutations
    (including the tombstone) intact."""
    import shutil
    import signal
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    input_dir = str(tmp_path / "input")
    snap = str(tmp_path / "snap")
    os.makedirs(input_dir)
    for i, text in enumerate(["kumquat lychee mango kumquat",
                              "nectar lychee papaya",
                              "mango papaya quince raisin",
                              "kumquat raisin raisin nectar"], 1):
        with open(os.path.join(input_dir, f"doc{i}"), "w") as f:
            f.write(text)
    queries = [{"id": i, "queries": [q], "k": 3}
               for i, q in enumerate(["kumquat", "papaya quince",
                                      "tamarind nectar", "raisin"])]
    common = ["--input", input_dir, "--vocab-size", "512",
              "--max-wait-ms", "1", "--canary-period-ms", "0",
              "--devmon-period-ms", "0", "--snapshot-dir", snap,
              "--delta-docs", "4", "--compact-at", "2"]
    proc = _serve_proc(common, repo)
    try:
        r = _ask(proc, {"op": "add_docs", "docs": [
            {"name": "doc5", "text": "tamarind nectar tamarind"},
            {"name": "doc2", "text": "nectar quince"}]})
        assert r["added"] == 1 and r["updated"] == 1
        r = _ask(proc, {"op": "delete_docs", "names": ["doc3"]})
        assert r["deleted"] == 1
        first = [_ask(proc, q) for q in queries]
        assert "snapshot" in _ask(proc, {"op": "snapshot"})
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert ckpt.exists(snap)

    shutil.rmtree(input_dir)                   # the corpus is GONE
    proc = _serve_proc(common, repo)
    try:
        second = [_ask(proc, q) for q in queries]
        # ...and the restored index keeps mutating
        r = _ask(proc, {"op": "add_docs", "docs": [
            {"name": "doc6", "text": "quince quince"}]})
        assert r["added"] == 1
        proc.stdin.write('{"op": "shutdown"}\n')
        proc.stdin.flush()
        proc.wait(timeout=60)
        banner = proc.stderr.read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert second == first                     # bit-identical restore
    assert "segments=on" in banner
    assert "snapshot=restored" in banner
    # the deleted doc stayed deleted across the crash
    assert not any("doc3" == name
                   for resp in second for row in resp["results"]
                   for name, _score in row)


@pytest.mark.slow
def test_mutate_chaos_acceptance(tmp_path):
    """The mutation acceptance: a continuous add/update/delete stream
    with --chaos compactor kills — every served response bit-identical
    to the from-scratch rebuild oracle, compactor restarted within
    budget, final health ok, breaker closed, zero recompiles."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "MUTATE_chaos.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    rc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--requests", "48", "--mutate", "200", "--mutations", "30",
         "--docs", "128", "--delta-docs", "8", "--compact-at", "2",
         "--pool", "16", "--concurrency", "2",
         "--chaos", "swap:fatal:n=2", "--out", str(out)],
        cwd=repo, env=env, capture_output=True, timeout=600)
    assert rc.returncode == 0, rc.stdout[-2000:] + rc.stderr[-2000:]
    mut = json.loads(out.read_text())["mutate"]
    assert mut["parity_ok"] == 1
    assert mut["xla_recompiles_after_warm"] == 0
    assert mut["final_health"] == "ok"
    assert mut["breaker_open_at_exit"] == 0
    assert mut["compaction"]["compactor_restarts"] == 2  # both kills
    assert mut["compaction"]["compactor_dead"] == 0      # contained
    assert mut["chaos_plan"] == "swap:fatal:n=2"


# --- mutate bench smoke (slow) ---------------------------------------

@pytest.mark.slow
def test_mutate_bench_smoke(tmp_path):
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "MUTATE_smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    rc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--requests", "32", "--mutate", "200", "--mutations", "18",
         "--docs", "128", "--delta-docs", "8", "--compact-at", "2",
         "--pool", "16", "--concurrency", "2", "--out", str(out)],
        cwd=repo, env=env, capture_output=True, timeout=600)
    assert rc.returncode == 0, rc.stdout[-2000:] + rc.stderr[-2000:]
    artifact = json.loads(out.read_text())
    mut = artifact["mutate"]
    assert mut["parity_ok"] == 1
    assert mut["xla_recompiles_after_warm"] == 0
    assert artifact["recompiles_after_warmup"] == 0
    assert mut["ops"] == 18
    assert {"p50", "p99", "max"} <= set(mut["visibility_lag_ms"])
    assert mut["compaction"]["compactor_dead"] == 0
