"""Pallas histogram kernel tests (interpreter mode on CPU).

The kernel must be bit-equal to the XLA scatter-add path — same counts,
same DF — across padding, ragged lengths, and tile-unaligned shapes.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline, discover_corpus
from tfidf_tpu.config import VocabMode
from tfidf_tpu.golden import golden_output
from tfidf_tpu.ops.histogram import df_from_counts, tf_counts
from tfidf_tpu.ops.pallas_kernels import tf_df_pallas


def ref_counts_df(toks, lens, vocab):
    c = tf_counts(toks, lens, vocab)
    return c, df_from_counts(c)


class TestPallasHistogram:
    @pytest.mark.parametrize("shape,vocab", [
        ((8, 128), 128),     # exactly one tile
        ((8, 128), 256),     # two vocab tiles
        ((24, 256), 128),    # multiple doc tiles
        ((24, 256), 512),    # multiple doc AND vocab tiles (df revisits)
        ((5, 100), 70),      # everything unaligned -> padding paths
        ((1, 128), 1),       # degenerate
    ])
    def test_matches_xla_scatter(self, shape, vocab):
        rng = np.random.default_rng(42)
        toks = jnp.asarray(rng.integers(0, vocab, shape), jnp.int32)
        lens = jnp.asarray(rng.integers(0, shape[1] + 1, shape[0]), jnp.int32)
        pc, pdf = tf_df_pallas(toks, lens, vocab_size=vocab, interpret=True)
        rc, rdf = ref_counts_df(toks, lens, vocab)
        assert (np.asarray(pc) == np.asarray(rc)).all()
        assert (np.asarray(pdf) == np.asarray(rdf)).all()

    @pytest.mark.parametrize("offset,width", [(0, 64), (64, 64), (96, 32)])
    def test_id_offset_matches_masked_shard(self, offset, width):
        # Vocab-sharding contract: id_offset histograms only the shard's
        # id range, exactly like tf_counts_masked's offset/width.
        from tfidf_tpu.ops.histogram import tf_counts_masked
        rng = np.random.default_rng(7)
        toks = jnp.asarray(rng.integers(0, 128, (8, 128)), jnp.int32)
        lens = jnp.asarray(rng.integers(0, 129, 8), jnp.int32)
        pc, _ = tf_df_pallas(toks, lens, vocab_size=width, id_offset=offset,
                             interpret=True)
        live = jnp.arange(128)[None, :] < lens[:, None]
        rc = tf_counts_masked(toks, live, width, id_offset=offset)
        assert (np.asarray(pc) == np.asarray(rc)).all()

    def test_all_padding_docs(self):
        toks = jnp.zeros((4, 128), jnp.int32)
        lens = jnp.zeros((4,), jnp.int32)
        pc, pdf = tf_df_pallas(toks, lens, vocab_size=64, interpret=True)
        assert int(pc.sum()) == 0 and int(pdf.sum()) == 0

    def test_pipeline_use_pallas_golden_bytes(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.EXACT, use_pallas=True)
        result = TfidfPipeline(cfg).run(corpus)
        assert result.output_bytes() == golden_output(corpus)

    def test_pipeline_use_pallas_topk(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        base = dict(vocab_mode=VocabMode.HASHED, vocab_size=512, topk=3)
        pallas = TfidfPipeline(PipelineConfig(use_pallas=True, **base)).run(corpus)
        xla = TfidfPipeline(PipelineConfig(**base)).run(corpus)
        np.testing.assert_allclose(pallas.topk_vals, xla.topk_vals, rtol=1e-6)
