"""Zero-allocation query hot path (round 19): the staging-ring slab,
the in-place fill's bit-parity with the allocating packer, and the
serve-path safety properties — slot reuse only after results land,
ring wraparound order, oversize fallback, 8-thread stress parity.
"""

import threading

import numpy as np
import pytest

from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.models.retrieval import fill_query_matrix, query_matrix
from tfidf_tpu.ops.queryslab import QuerySlab, use_query_slab

VOCAB = 2048


def _corpus(n=40, seed=3):
    rng = np.random.default_rng(seed)
    docs = [" ".join(f"w{rng.integers(0, 200)}"
                     for _ in range(rng.integers(2, 30))).encode()
            for _ in range(n)]
    return Corpus(names=[f"doc{i + 1}" for i in range(n)], docs=docs)


def _queries(rng, n, pool=200, qlen=4):
    return [" ".join(f"w{rng.integers(0, pool)}" for _ in range(qlen))
            for _ in range(n)]


@pytest.fixture
def retriever():
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB)
    return TfidfRetriever(cfg).index(_corpus())


class TestFillParity:
    """One packing implementation: the in-place fill must produce the
    exact bytes query_matrix always produced."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_fill_matches_query_matrix_property(self, seed):
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=VOCAB)
        rng = np.random.default_rng(seed)
        idf = rng.random(VOCAB).astype(np.float32) * 3.0
        queries = _queries(rng, 6) + [
            "", "w1", "w1 w1 w1", "unknown zz9",
            " ".join(f"w{j}" for j in range(80))]
        ref = query_matrix(queries, cfg, idf, pad_to=16)
        out = np.full((VOCAB, 16), 7.0, np.float32)  # dirty buffer
        scratch = np.empty((VOCAB,), np.float32)
        fill_query_matrix(queries, cfg, idf, out, scratch=scratch)
        np.testing.assert_array_equal(ref, out)

    def test_refill_after_dirty_use_is_clean(self):
        """A reused ring buffer carries the previous batch's bytes;
        the fill must fully overwrite (incl. the zero columns)."""
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=VOCAB)
        idf = np.ones(VOCAB, np.float32)
        out = np.empty((VOCAB, 4), np.float32)
        fill_query_matrix(["w1 w2", "w3", "w4", "w5"], cfg, idf, out)
        fill_query_matrix(["w9"], cfg, idf, out)
        np.testing.assert_array_equal(
            out, query_matrix(["w9"], cfg, idf, pad_to=4))


class TestSlabRing:
    def test_fifo_reuse_and_wraparound(self):
        slab = QuerySlab(VOCAB, max_bucket=8)
        b0, _, s0 = slab.checkout(4)
        slab.release(s0)
        b1, _, s1 = slab.checkout(4)
        assert b1 is b0 and s1 == s0  # same buffer object, reused
        # Two in flight -> ring grows; releases then reuse FIFO.
        b2, _, s2 = slab.checkout(4)
        assert b2 is not b1
        slab.release(s1)
        slab.release(s2)
        b3, _, s3 = slab.checkout(4)
        assert s3 == s1  # oldest-released first
        st = slab.stats()
        assert st["allocs"] == 2 and st["packs"] == 4
        assert slab.ring_depth(4) == 2

    def test_buckets_are_independent(self):
        slab = QuerySlab(VOCAB, max_bucket=8)
        b4, _, _ = slab.checkout(4)
        b8, _, _ = slab.checkout(8)
        assert b4.shape == (VOCAB, 4) and b8.shape == (VOCAB, 8)
        assert slab.stats()["allocs"] == 2

    def test_oversize_bucket_raises(self):
        slab = QuerySlab(VOCAB, max_bucket=8)
        with pytest.raises(ValueError, match="max_bucket"):
            slab.checkout(16)

    def test_env_knob_parsing(self, monkeypatch):
        for raw, want in (("", True), ("1", True), ("on", True),
                          ("0", False), ("off", False),
                          ("false", False), ("no", False)):
            monkeypatch.setenv("TFIDF_TPU_QUERY_SLAB", raw)
            assert use_query_slab() is want, raw
        monkeypatch.delenv("TFIDF_TPU_QUERY_SLAB")
        assert use_query_slab() is True          # default ON
        assert use_query_slab(False) is False    # explicit wins
        assert use_query_slab(True) is True


class TestRetrieverSlabPath:
    def test_slab_on_off_bit_parity(self, retriever):
        rng = np.random.default_rng(9)
        other = TfidfRetriever(retriever.config).index(_corpus())
        other.query_slab = False
        for n in (1, 3, 8):
            qs = _queries(rng, n)
            v1, i1 = retriever.search(qs, k=5)
            v2, i2 = other.search(qs, k=5)
            np.testing.assert_array_equal(v1, v2)
            np.testing.assert_array_equal(i1, i2)

    def test_steady_state_zero_allocs_one_h2d_per_batch(self,
                                                        retriever):
        rng = np.random.default_rng(10)
        retriever.search(_queries(rng, 4), k=5)  # warm: ring allocates
        slab = retriever._slab
        st0 = slab.stats()
        for _ in range(12):
            retriever.search(_queries(rng, 4), k=5)
        st1 = slab.stats()
        assert st1["allocs"] == st0["allocs"]           # ZERO new
        assert st1["packs"] - st0["packs"] == 12
        assert st1["h2d_copies"] - st0["h2d_copies"] == 12  # ONE each
        assert st1["fallbacks"] == st0["fallbacks"]

    def test_oversize_batch_falls_back_bit_identical(self, retriever):
        rng = np.random.default_rng(11)
        qs = _queries(rng, 4)
        want = retriever.search(qs, k=5)
        slab = retriever._resolve_slab()
        slab.max_bucket = 2  # shrink under the batch's bucket
        got = retriever.search(qs, k=5)
        assert slab.stats()["fallbacks"] >= 1
        np.testing.assert_array_equal(want[0], got[0])
        np.testing.assert_array_equal(want[1], got[1])

    def test_mesh_plan_keeps_legacy_path(self):
        import jax

        from tfidf_tpu.parallel.mesh import MeshPlan
        plan = MeshPlan.create(docs=1, devices=jax.devices("cpu")[:1])
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=VOCAB)
        r = TfidfRetriever(cfg, plan=plan).index(_corpus(8))
        assert r._resolve_slab() is None
        r.search(["w1"], k=2)  # and the search path still works
        assert r._slab is None

    def test_eight_thread_stress_reuse_safety(self, retriever):
        """Concurrent slab searches: every response bit-identical to
        the single-threaded oracle — no torn staging buffer, no
        refill racing an unconsumed upload (slots release only after
        results materialize)."""
        rng = np.random.default_rng(12)
        batches = [_queries(rng, n) for n in (1, 2, 4, 8) for _ in
                   range(4)]
        oracle = [retriever.search(qs, k=5) for qs in batches]
        errors = []

        def worker(idx):
            try:
                for j in range(idx, len(batches), 8):
                    v, i = retriever.search(batches[j], k=5)
                    np.testing.assert_array_equal(v, oracle[j][0])
                    np.testing.assert_array_equal(i, oracle[j][1])
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[0]
        # The ring grew at most to the concurrency level.
        st = retriever._slab.stats()
        assert st["buffers"] <= 8 * 4
        assert st["packs"] == st["h2d_copies"]

    def test_h2d_span_byte_stamped_once_per_batch(self, retriever,
                                                  tmp_path):
        from tfidf_tpu import obs
        path = str(tmp_path / "trace.json")
        assert obs.configure(path) is not None
        try:
            rng = np.random.default_rng(13)
            for _ in range(3):
                retriever.search(_queries(rng, 4), k=5)
            out = obs.export()
        finally:
            obs.set_tracer(None)
        spans = [e for e in obs.load_chrome_trace(out)
                 if e.get("ph") == "X" and e.get("name") == "h2d"]
        assert len(spans) == 3
        for s in spans:
            assert s["args"]["bytes"] == VOCAB * 4 * 4  # [V, 4] f32


class TestServeWiring:
    def test_serve_config_env_mirror(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_QUERY_SLAB", "0")
        assert ServeConfig.from_env().query_slab is False
        monkeypatch.setenv("TFIDF_TPU_QUERY_SLAB", "on")
        assert ServeConfig.from_env().query_slab is True
        monkeypatch.delenv("TFIDF_TPU_QUERY_SLAB")
        assert ServeConfig.from_env().query_slab is None
        assert ServeConfig.from_env(query_slab=False).query_slab is False

    def test_server_applies_knob_on_install(self, retriever):
        from tfidf_tpu.serve import TfidfServer
        server = TfidfServer(retriever, ServeConfig(
            query_slab=False, cache_entries=0))
        try:
            assert retriever.query_slab is False
            rng = np.random.default_rng(14)
            qs = _queries(rng, 3)
            served = server.search(qs, k=5)
            direct = retriever.search(qs, k=5)
            np.testing.assert_array_equal(served[0], direct[0])
            np.testing.assert_array_equal(served[1], direct[1])
            assert retriever._slab is None  # off really means off
        finally:
            server.close(drain=True)

    def test_served_rows_bit_identical_slab_on(self, retriever):
        from tfidf_tpu.serve import TfidfServer
        oracle = TfidfRetriever(retriever.config).index(_corpus())
        oracle.query_slab = False
        server = TfidfServer(retriever, ServeConfig(
            query_slab=True, cache_entries=0))
        try:
            rng = np.random.default_rng(15)
            for n in (1, 2, 5):
                qs = _queries(rng, n)
                served = server.search(qs, k=5)
                want = oracle.search(qs, k=5)
                np.testing.assert_array_equal(served[0], want[0])
                np.testing.assert_array_equal(served[1], want[1])
            assert retriever._slab is not None
            assert retriever._slab.stats()["h2d_copies"] >= 3
        finally:
            server.close(drain=True)
