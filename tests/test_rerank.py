"""Exact-string re-rank (tfidf_tpu/rerank.py) vs a pure-Python exact
oracle, under forced hash collisions."""

import math
import os

import numpy as np
import pytest

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.ingest import run_overlapped
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.rerank import exact_topk

VOCAB = 32  # tiny on purpose: ~60 distinct words -> heavy collisions


@pytest.fixture
def collide_dir(tmp_path):
    corpus = tmp_path / "corpus"  # own dir: tmp_path also holds outputs
    corpus.mkdir()
    rng = np.random.default_rng(23)
    words = [f"word{i}".encode() for i in range(60)]
    for i in range(1, 17):
        picks = rng.choice(60, size=rng.integers(6, 40))
        (corpus / f"doc{i}").write_bytes(
            b" ".join(words[int(p)] for p in picks))
    return str(corpus)


def exact_oracle(input_dir, k):
    """Float64 exact TF-IDF top-k per doc, straight from the strings."""
    import os
    names = sorted(os.listdir(input_dir), key=lambda n: int(n[3:]))
    docs = {n: open(os.path.join(input_dir, n), "rb").read().split()
            for n in names}
    n = len(names)
    df = {}
    for words in docs.values():
        for w in set(words):
            df[w] = df.get(w, 0) + 1
    out = {}
    for name, words in docs.items():
        counts = {}
        for w in words:
            counts[w] = counts.get(w, 0) + 1
        scored = [(w, (c / len(words)) * math.log(n / df[w]))
                  for w, c in counts.items()]
        scored = [(w, s) for w, s in scored if s > 0]
        scored.sort(key=lambda t: (-t[1], t[0]))
        out[name] = scored[:k]
    return out


class TestExactRerank:
    def test_collisions_present(self, collide_dir):
        # The fixture must actually force collisions, else the test
        # proves nothing.
        words = [f"word{i}".encode() for i in range(60)]
        ids = words_to_ids(words, VOCAB)
        assert len(set(int(i) for i in ids)) < len(words)

    def test_rerank_recovers_exact_topk(self, collide_dir):
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                             max_doc_len=64, doc_chunk=64, topk=16,
                             engine="sparse")
        r = run_overlapped(collide_dir, cfg, chunk_docs=8, doc_len=64)
        got = exact_topk(collide_dir, r.names, r.topk_ids, r.num_docs,
                         cfg, k=3, max_tokens=64)
        want = exact_oracle(collide_dir, k=3)
        for name in want:
            got_words = [w for w, _ in got[name]]
            want_words = [w for w, _ in want[name]]
            assert got_words == want_words, (name, got[name], want[name])
            for (gw, gs), (ww, ws) in zip(got[name], want[name]):
                assert gs == pytest.approx(ws, rel=1e-12)

    def test_native_matches_python(self, collide_dir, monkeypatch):
        # native/rerank.cc vs the Python implementation (the semantics
        # oracle): identical words AND bit-identical float64 scores on
        # a heavy-collision corpus.
        import subprocess

        from tfidf_tpu.io import fast_tokenizer
        # ALWAYS rebuild (no-op when fresh): gating on symbol presence
        # would silently validate edited rerank.cc against a stale .so.
        built = subprocess.run(
            ["make", "-C", "native", "fast_tokenizer.so"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True)
        if built.returncode != 0:
            if fast_tokenizer.rerank_available():
                # A stale loadable .so would make a silent green run.
                pytest.fail("native build failed with a stale .so "
                            f"present:\n{built.stderr[-1500:]}")
            pytest.skip("native toolchain unavailable and no prebuilt .so")
        if not fast_tokenizer.rerank_available():
            pytest.skip("native rerank engine unavailable")
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                             max_doc_len=64, doc_chunk=64, topk=16,
                             engine="sparse")
        r = run_overlapped(collide_dir, cfg, chunk_docs=8, doc_len=64)
        native = exact_topk(collide_dir, r.names, r.topk_ids, r.num_docs,
                            cfg, k=5, max_tokens=64)
        monkeypatch.setenv("TFIDF_TPU_NO_NATIVE", "1")
        python = exact_topk(collide_dir, r.names, r.topk_ids, r.num_docs,
                            cfg, k=5, max_tokens=64)
        assert set(native) == set(python)
        for name in python:
            assert native[name] == python[name], name  # incl. exact scores

    def test_subset_and_empty_doc(self, tmp_path):
        (tmp_path / "doc1").write_bytes(b"alpha beta alpha")
        (tmp_path / "doc2").write_bytes(b"   ")  # whitespace-only
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=64,
                             max_doc_len=16, doc_chunk=16, topk=4,
                             engine="sparse")
        r = run_overlapped(str(tmp_path), cfg, chunk_docs=4, doc_len=16)
        got = exact_topk(str(tmp_path), r.names, r.topk_ids, r.num_docs,
                         cfg, k=2, docs=["doc2"], max_tokens=16)
        assert got == {"doc2": []}


class TestCliExactTerms:
    def test_exact_terms_report(self, collide_dir, tmp_path):
        # This corpus packs ~60 words into 32 buckets (extreme collision
        # pressure), so the default 2x margin genuinely misses — the
        # documented residual failure mode (rerank.py docstring). A
        # margin covering the whole vocab (11*3 > 32) must be exact.
        from tfidf_tpu.cli import main
        out = tmp_path / "exact.txt"
        rc = main(["run", "--input", collide_dir, "--output", str(out),
                   "--vocab-mode", "hashed", "--vocab-size", str(VOCAB),
                   "--topk", "3", "--exact-terms", "--exact-margin", "11"])
        assert rc == 0
        lines = out.read_bytes().splitlines()
        # Exact words, not bucket representatives or id:N fallbacks.
        assert lines and all(b"@word" in l for l in lines), lines[:3]
        # Emit is raw-line strcmp-sorted (TFIDF.c:273); per-doc rank is
        # recovered from the printed scores, then checked vs the oracle.
        assert lines == sorted(lines)
        got = {}
        for l in lines:
            key, score = l.rsplit(b"\t", 1)
            doc, word = key.split(b"@", 1)
            got.setdefault(doc.decode(), []).append((word, float(score)))
        want = exact_oracle(collide_dir, k=3)
        for name, terms in want.items():
            if terms:
                ranked = [w for w, _ in sorted(got[name],
                                               key=lambda t: (-t[1], t[0]))]
                assert ranked == [w for w, _ in terms], name

    def test_exact_terms_on_padding_mesh(self, tmp_path):
        # 11 docs on an 8-way docs mesh pads the doc axis with '' rows;
        # exact_topk pass 1 must skip them like pass 2 does (round-2
        # advisor finding: it opened input_dir/'' — the directory —
        # and crashed with IsADirectoryError).
        corpus = tmp_path / "corpus"
        corpus.mkdir()
        rng = np.random.default_rng(31)
        words = [f"word{i}".encode() for i in range(60)]
        for i in range(1, 12):
            picks = rng.choice(60, size=rng.integers(6, 40))
            (corpus / f"doc{i}").write_bytes(
                b" ".join(words[int(p)] for p in picks))
        from tfidf_tpu.cli import main
        out = tmp_path / "mesh_exact.txt"
        rc = main(["run", "--input", str(corpus), "--output", str(out),
                   "--vocab-mode", "hashed", "--vocab-size", str(VOCAB),
                   "--topk", "3", "--exact-terms", "--exact-margin", "11",
                   "--mesh", "8,1,1"])
        assert rc == 0
        flat = tmp_path / "flat_exact.txt"
        rc = main(["run", "--input", str(corpus), "--output", str(flat),
                   "--vocab-mode", "hashed", "--vocab-size", str(VOCAB),
                   "--topk", "3", "--exact-terms", "--exact-margin", "11"])
        assert rc == 0
        # Mesh and single-device runs agree byte-for-byte: the emit is
        # strcmp-sorted (TFIDF.c:273), so ordering cannot depend on the
        # mesh shape or discovery order.
        assert out.read_bytes() == flat.read_bytes()

    def test_exact_terms_requires_hashed_topk(self, collide_dir, tmp_path):
        from tfidf_tpu.cli import main
        rc = main(["run", "--input", collide_dir,
                   "--output", str(tmp_path / "x.txt"), "--exact-terms"])
        assert rc == 2
