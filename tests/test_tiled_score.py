"""Round-21 tiled scoring: bit-parity properties, the one-dispatch
segmented pin, recompile discipline, and the float64 truncation
contract (VERDICT weak-6).

The tiled scorer (``ops.sparse.score_topk_tiled``) must be
BIT-identical to the untiled reference — scores, ids AND tie order —
on every consumer path, because ``--score-tiling=off`` is documented
as an exact fallback and serve's canary compares raw arrays. These
tests pin that claim where it is most likely to break: ragged last
tiles, ties straddling tile boundaries, fully-tombstoned tiles, and
query counts on both sides of the legacy 64-query block split.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.experimental import sparse as jsparse

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.ops.sparse import (score_tile_rows, score_tiling,
                                  score_topk_tiled,
                                  score_topk_tiled_cache_size)
from tfidf_tpu.ops.topk import _DEAD


def ref_score_topk(data, cols, live, qmat, k):
    """The untiled oracle: one whole-corpus BCOO dot + one top_k —
    exactly the legacy lowering the tiled scan must reproduce."""
    d = data.shape[0]
    mat = jsparse.BCOO((data, cols[..., None]),
                       shape=(d, qmat.shape[0]))
    sims = jsparse.bcoo_dot_general(
        mat, qmat, dimension_numbers=(((1,), (0,)), ((), ())))
    if live is not None:
        sims = jnp.where(live[:, None], sims, _DEAD)
    vals, idx = lax.top_k(sims.T, min(k, d))
    return np.asarray(vals), np.asarray(idx)


def random_triple(rng, d, length, vocab, quantize=True, live_p=None):
    """A random row-sparse block. Quantized weights (multiples of 0.5)
    make exact score ties COMMON — the tie-order property is vacuous
    on continuous random floats."""
    cols = jnp.asarray(rng.integers(0, vocab, (d, length)), jnp.int32)
    if quantize:
        data = jnp.asarray(
            rng.integers(0, 4, (d, length)) * 0.5, jnp.float32)
    else:
        data = jnp.asarray(rng.random((d, length)), jnp.float32)
    live = None
    if live_p is not None:
        live = jnp.asarray(rng.random(d) < live_p)
    return data, cols, live


def random_queries(rng, vocab, q):
    qmat = rng.integers(0, 3, (vocab, q)) * 0.5
    return jnp.asarray(qmat, jnp.float32)


class TestTiledBitParity:
    """Property: tiled == untiled, exactly, over random corpora."""

    @pytest.mark.parametrize("q", [1, 63, 64, 65, 256])
    def test_parity_across_query_counts(self, q):
        rng = np.random.default_rng(q)
        d, length, vocab, k = 37, 8, 64, 5
        data, cols, live = random_triple(rng, d, length, vocab)
        qmat = random_queries(rng, vocab, q)
        want_v, want_i = ref_score_topk(data, cols, None, qmat, k)
        got_v, got_i = score_topk_tiled(data, cols, None, qmat, k,
                                        tile=16)  # ragged: 37 = 2x16+5
        np.testing.assert_array_equal(np.asarray(got_v), want_v)
        np.testing.assert_array_equal(np.asarray(got_i), want_i)

    @pytest.mark.parametrize("tile", [1, 3, 7, 16, 37, 64, 4096])
    def test_parity_across_tile_widths(self, tile):
        # Every width: single-row tiles, ragged last tiles, one tile
        # covering everything, and the clamped oversize default.
        rng = np.random.default_rng(tile)
        d, length, vocab, k, q = 37, 8, 64, 6, 13
        data, cols, live = random_triple(rng, d, length, vocab,
                                         live_p=0.7)
        qmat = random_queries(rng, vocab, q)
        want_v, want_i = ref_score_topk(data, cols, live, qmat, k)
        got_v, got_i = score_topk_tiled(data, cols, live, qmat, k,
                                        tile=tile)
        np.testing.assert_array_equal(np.asarray(got_v), want_v)
        np.testing.assert_array_equal(np.asarray(got_i), want_i)

    @pytest.mark.parametrize("k", [1, 5, 37, 100])
    def test_parity_across_k(self, k):
        # k past D clamps to D on both paths; k past tile exercises
        # the per-tile min(k, tile) retention argument.
        rng = np.random.default_rng(k)
        d, length, vocab, q = 37, 8, 64, 9
        data, cols, live = random_triple(rng, d, length, vocab,
                                         live_p=0.8)
        qmat = random_queries(rng, vocab, q)
        want_v, want_i = ref_score_topk(data, cols, live, qmat, k)
        got_v, got_i = score_topk_tiled(data, cols, live, qmat, k,
                                        tile=8)
        np.testing.assert_array_equal(np.asarray(got_v), want_v)
        np.testing.assert_array_equal(np.asarray(got_i), want_i)

    def test_ties_straddling_tile_boundaries(self):
        # IDENTICAL rows placed on both sides of every tile boundary:
        # every query ties them exactly, and the winner must be the
        # lowest global row — the discipline lax.top_k applies to the
        # untiled whole-corpus matrix.
        rng = np.random.default_rng(7)
        d, length, vocab, k, q = 24, 4, 16, 8, 5
        row_c = jnp.asarray(rng.integers(0, vocab, (1, length)),
                            jnp.int32)
        row_d = jnp.asarray(
            rng.integers(1, 4, (1, length)) * 0.5, jnp.float32)
        data = jnp.tile(row_d, (d, 1))
        cols = jnp.tile(row_c, (d, 1))
        qmat = random_queries(rng, vocab, q)
        for tile in (3, 4, 5, 8):
            want_v, want_i = ref_score_topk(data, cols, None, qmat, k)
            got_v, got_i = score_topk_tiled(data, cols, None, qmat, k,
                                            tile=tile)
            np.testing.assert_array_equal(np.asarray(got_v), want_v)
            # All rows tie: ids must be EXACTLY 0..k-1, in order.
            np.testing.assert_array_equal(
                np.asarray(got_i), np.tile(np.arange(k), (q, 1)))
            np.testing.assert_array_equal(np.asarray(got_i), want_i)

    def test_all_tombstoned_tile(self):
        # A fully-dead tile in the middle (and a fully-dead LAST tile)
        # must contribute nothing — its sentinel candidates lose to
        # any live row and, when only dead rows remain, tie-break by
        # lowest global row exactly like the untiled mask.
        rng = np.random.default_rng(11)
        d, length, vocab, k, q, tile = 32, 6, 32, 6, 7, 8
        data, cols, _ = random_triple(rng, d, length, vocab)
        live = np.ones(d, bool)
        live[8:16] = False   # tile 1 entirely dead
        live[24:32] = False  # last tile entirely dead
        live = jnp.asarray(live)
        want_v, want_i = ref_score_topk(data, cols, live, qmat := random_queries(rng, vocab, q), k)
        got_v, got_i = score_topk_tiled(data, cols, live, qmat, k,
                                        tile=tile)
        np.testing.assert_array_equal(np.asarray(got_v), want_v)
        np.testing.assert_array_equal(np.asarray(got_i), want_i)
        assert not np.isin(np.asarray(got_i),
                           np.arange(8, 16)).any()

    def test_everything_tombstoned(self):
        rng = np.random.default_rng(13)
        d, length, vocab, k, q = 12, 4, 16, 4, 3
        data, cols, _ = random_triple(rng, d, length, vocab)
        live = jnp.zeros(d, bool)
        qmat = random_queries(rng, vocab, q)
        want_v, want_i = ref_score_topk(data, cols, live, qmat, k)
        got_v, got_i = score_topk_tiled(data, cols, live, qmat, k,
                                        tile=5)
        np.testing.assert_array_equal(np.asarray(got_v), want_v)
        np.testing.assert_array_equal(np.asarray(got_i), want_i)

    def test_pallas_variant_ids_bit_identical(self):
        # TFIDF_TPU_SCORE=pallas scope extension: same contract as
        # phase B — ids bit-identical, scores allclose.
        rng = np.random.default_rng(17)
        d, length, vocab, k, q = 37, 8, 64, 5, 9
        data, cols, _ = random_triple(rng, d, length, vocab,
                                      quantize=False)
        qmat = jnp.asarray(rng.random((vocab, q)), jnp.float32)
        want_v, want_i = score_topk_tiled(data, cols, None, qmat, k,
                                          tile=16, method="xla")
        got_v, got_i = score_topk_tiled(data, cols, None, qmat, k,
                                        tile=16, method="pallas")
        np.testing.assert_array_equal(np.asarray(got_i),
                                      np.asarray(want_i))
        np.testing.assert_allclose(np.asarray(got_v),
                                   np.asarray(want_v), rtol=1e-6)


CORPUS = Corpus(
    names=[f"doc{i}" for i in range(23)],
    docs=[(" ".join(
        np.random.default_rng(100 + i).choice(
            ["apple", "banana", "cherry", "date", "elder", "fig",
             "grape", "kiwi", "lemon", "mango"],
            size=6 + (i % 5)).tolist())).encode()
        for i in range(23)])

QUERIES_POOL = ["apple banana", "fig", "grape kiwi lemon", "date",
                "cherry elder", "mango apple", "banana banana fig"]


def _cfg(vocab=512):
    return PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=vocab,
                          max_doc_len=16, doc_chunk=16)


class TestRetrieverParity:
    """Consumer parity: TfidfRetriever.search tiled vs the off
    fallback (which re-splits wide batches at the legacy 64)."""

    @pytest.mark.parametrize("q", [1, 63, 64, 65, 256])
    def test_flat_search_parity(self, q, monkeypatch):
        r = TfidfRetriever(_cfg()).index(CORPUS)
        queries = [QUERIES_POOL[i % len(QUERIES_POOL)]
                   for i in range(q)]
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "off")
        off_v, off_i = r.search(queries, k=5)
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "on")
        on_v, on_i = r.search(queries, k=5)
        np.testing.assert_array_equal(on_v, off_v)
        np.testing.assert_array_equal(on_i, off_i)

    def test_tile_knob_parity(self, monkeypatch):
        # TFIDF_TPU_QUERY_BLOCK (repurposed: doc tile rows) must not
        # change results at ANY width — including tile=1.
        r = TfidfRetriever(_cfg()).index(CORPUS)
        queries = [QUERIES_POOL[i % len(QUERIES_POOL)]
                   for i in range(9)]
        monkeypatch.delenv("TFIDF_TPU_QUERY_BLOCK", raising=False)
        base_v, base_i = r.search(queries, k=4)
        for width in ("1", "5", "8", "64"):
            monkeypatch.setenv("TFIDF_TPU_QUERY_BLOCK", width)
            v, i = r.search(queries, k=4)
            np.testing.assert_array_equal(v, base_v)
            np.testing.assert_array_equal(i, base_i)

    def test_knob_resolution(self, monkeypatch):
        monkeypatch.delenv("TFIDF_TPU_SCORE_TILING", raising=False)
        assert score_tiling() is True          # default ON
        for raw in ("on", "1", "true", "yes", ""):
            monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", raw)
            assert score_tiling() is True
        for raw in ("off", "0", "false", "no"):
            monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", raw)
            assert score_tiling() is False
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "maybe")
        with pytest.raises(ValueError):
            score_tiling()
        monkeypatch.delenv("TFIDF_TPU_QUERY_BLOCK", raising=False)
        assert score_tile_rows(10_000) == 4096  # default, clamped by d
        assert score_tile_rows(100) == 100
        monkeypatch.setenv("TFIDF_TPU_QUERY_BLOCK", "7")
        assert score_tile_rows(100) == 7


class TestSegmentedOneDispatch:
    """The segmented tentpole claim: K sealed segments = ONE tiled
    dispatch, flat as K grows — plus stacked-path bit-parity against
    both the per-part fallback and the rebuild oracle."""

    def _build(self, n_batches, delta_docs=4):
        from tfidf_tpu.index.segmented import SegmentedIndex
        idx = SegmentedIndex(_cfg(vocab=256), delta_docs=delta_docs,
                             compact_at=64)
        rng = np.random.default_rng(0)
        n = 0
        for _ in range(n_batches):
            names = [f"d{n + j}" for j in range(delta_docs)]
            docs = [" ".join(rng.choice(
                ["apple", "banana", "cherry", "date", "fig", "grape"],
                size=5).tolist()) for _ in range(delta_docs)]
            idx.add_docs(names, docs)
            n += delta_docs
        return idx

    def test_one_dispatch_flat_as_segments_grow(self, monkeypatch):
        import tfidf_tpu.index.segmented as seg_mod
        calls = []
        real = seg_mod.score_topk_tiled

        def counting(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(seg_mod, "score_topk_tiled", counting)
        for batches in (1, 3, 6):
            idx = self._build(batches)
            view = idx.view()
            assert view.num_segments >= min(batches, 2)
            calls.clear()
            view.search(["apple banana", "fig"], k=3)
            assert len(calls) == 1, (
                f"{view.num_segments} segments took {len(calls)} "
                "tiled dispatches; the stacked scan promises ONE")

    def test_segmented_parity_tiled_vs_off_vs_oracle(self, monkeypatch):
        idx = self._build(5)
        idx.delete_docs([f"d{j}" for j in range(3, 17, 3)])
        view = idx.view()
        queries = [QUERIES_POOL[i % len(QUERIES_POOL)]
                   for i in range(77)]
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "on")
        on_v, on_i = view.search(queries, k=6)
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "off")
        off_v, off_i = view.search(queries, k=6)
        np.testing.assert_array_equal(on_v, off_v)
        np.testing.assert_array_equal(on_i, off_i)
        # Rebuild oracle: same docs through the classic batch path.
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "on")
        oracle = idx.rebuild_retriever()
        ov, oi = oracle.search(queries, k=6)
        names = view.names
        got_names = [[None if j < 0 else names[j] for j in row]
                     for row in on_i]
        want_names = [[None if j < 0 else oracle.names[j]
                       for j in row] for row in oi]
        assert got_names == want_names
        np.testing.assert_array_equal(on_v, ov)

    def test_stacked_shape_cycles_pow2(self):
        # The stacked face pads to the next pow2 so mutation cycles a
        # warmable shape set instead of compiling per segment count.
        idx = self._build(3)
        view = idx.view()
        data, cols, live = view._stacked()
        rows = data.shape[0]
        assert rows & (rows - 1) == 0, rows


class TestRecompileDiscipline:
    def test_zero_recompiles_after_warm_q256(self):
        from tfidf_tpu.models.retrieval import _search_tiled
        r = TfidfRetriever(_cfg(vocab=768)).index(CORPUS)
        wide = [QUERIES_POOL[i % len(QUERIES_POOL)]
                for i in range(256)]
        r.search(wide, k=9)                    # warm bucket 256
        warm = _search_tiled._cache_size()
        for q in (129, 200, 255, 256):         # all bucket 256
            r.search(wide[:q], k=9)
        assert _search_tiled._cache_size() == warm

    def test_segmented_zero_recompiles_under_mutation(self):
        from tfidf_tpu.index.segmented import SegmentedIndex
        idx = SegmentedIndex(_cfg(vocab=384), delta_docs=4,
                             compact_at=64)
        rng = np.random.default_rng(1)
        n = 0

        def add_batch():
            nonlocal n
            names = [f"d{n + j}" for j in range(4)]
            docs = [" ".join(rng.choice(
                ["apple", "banana", "cherry", "fig"],
                size=4).tolist()) for _ in range(4)]
            idx.add_docs(names, docs)
            n += 4
        for _ in range(2):
            add_batch()
        queries = [QUERIES_POOL[i % len(QUERIES_POOL)]
                   for i in range(8)]
        idx.view().search(queries, k=3)        # warm at 8 rows stacked
        warm = score_topk_tiled_cache_size()
        for _ in range(2):                     # 8 -> 16 rows: one new
            add_batch()                        # pow2 shape, then flat
        idx.view().search(queries, k=3)
        grew = score_topk_tiled_cache_size()
        for _ in range(2):                     # still 16 -> 32... the
            add_batch()                        # NEXT pow2 only
        idx.view().search(queries, k=3)
        idx.view().search(queries, k=3)
        assert score_topk_tiled_cache_size() <= grew + 1


class TestFloat64Truncation:
    """VERDICT weak-6 pinned: where x64 is unavailable, a float64
    score-dtype request truncates to float32 SILENTLY (zero warnings)
    and bit-identically to asking for float32 outright."""

    def test_truncation_contract(self):
        if jax.config.jax_enable_x64:
            pytest.skip("x64 enabled: no truncation to pin")

        def run(dtype):
            cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                                 vocab_size=512, max_doc_len=16,
                                 doc_chunk=16, score_dtype=dtype)
            r = TfidfRetriever(cfg).index(CORPUS)
            return r.search(QUERIES_POOL, k=4)

        with warnings.catch_warnings():
            # ANY truncation warning ("Explicitly requested dtype ...
            # is not available") fails the test: the contract is a
            # silent, canonicalized collapse (ops.scoring
            # canonical_score_dtype), not a warned one.
            warnings.simplefilter("error")
            v64, i64 = run("float64")
        v32, i32 = run("float32")
        assert np.asarray(v64).dtype == np.float32
        np.testing.assert_array_equal(v64, v32)
        np.testing.assert_array_equal(i64, i32)

    def test_idf_canonicalizes_silently(self):
        from tfidf_tpu.ops.scoring import (canonical_score_dtype,
                                           idf_from_df, tfidf_dense)
        if jax.config.jax_enable_x64:
            pytest.skip("x64 enabled: no truncation to pin")
        assert canonical_score_dtype("float64") == jnp.float32
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            idf = idf_from_df(jnp.array([1, 2, 0]), 10,
                              dtype=np.float64)
            dense = tfidf_dense(jnp.ones((2, 3), jnp.int32),
                                jnp.array([3, 3]),
                                jnp.array([1, 2, 2]), 2,
                                dtype=np.float64)
        assert idf.dtype == jnp.float32
        assert dense.dtype == jnp.float32
