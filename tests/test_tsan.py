"""Race detection for the native thread-comm runtime.

The reference's hybrid (OpenMP) variant has real data races on its
shared index counters and scratch buffers (SURVEY §2.5-8). Our thread
backend replaces that with barrier-fenced mailbox collectives — this
test builds the ThreadSanitizer binary and runs a multi-rank job under
TSAN, failing on any reported race.
"""

import os
import shutil
import subprocess

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                          "native")
TSAN_BIN = os.path.join(NATIVE_DIR, "tfidf_ref_tsan")


@pytest.mark.skipif(shutil.which("g++") is None or shutil.which("make") is None,
                    reason="needs g++ and make")
def test_thread_backend_race_free(toy_corpus_dir, tmp_path):
    build = subprocess.run(["make", "-C", NATIVE_DIR, "tfidf_ref_tsan"],
                           capture_output=True)
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable: {build.stderr.decode()[-200:]}")
    out = tmp_path / "out.txt"
    proc = subprocess.run(
        [TSAN_BIN, toy_corpus_dir, str(out), "6"],
        capture_output=True,
        env={**os.environ, "TSAN_OPTIONS": "halt_on_error=1 exitcode=66"})
    assert proc.returncode != 66, f"TSAN race:\n{proc.stderr.decode()[-2000:]}"
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    # and the TSAN build still produces correct bytes
    from tfidf_tpu import discover_corpus
    from tfidf_tpu.golden import golden_output
    assert out.read_bytes() == golden_output(discover_corpus(toy_corpus_dir))
