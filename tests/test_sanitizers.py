"""ASan/UBSan drives of the native hot loops.

The TSan target (tests/test_tsan.py) proves the thread-comm reference
backend race-free; these tests do the same for the ctypes library's
memory story: the slab fill (bytes wire), the threaded ragged fill
(``loader_fill_flat_u16_v3`` — the round-14 OpenMP move), the padded
loader fills and the tokenizer itself run under AddressSanitizer and
UndefinedBehaviorSanitizer builds (``make -C native sanitizers``)
against an adversarial corpus (multi-byte UTF-8, NUL bytes, 0x80–0xFF
binary runs, over-long tokens, empty/whitespace-only docs), and their
output must be byte-identical to the plain build's.

Mechanics: the sanitizer .so loads through the real ctypes bindings
via ``TFIDF_TPU_NATIVE_LIB`` in a subprocess (ASan's runtime must be
preloaded into the uninstrumented python host — ``LD_PRELOAD``), the
module itself loaded standalone so no jax ever rides under the
sanitizer. A clean run exits 0 with no report; any heap overflow /
UB aborts with the sanitizer's exit code and fails the assert with
the report text.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "native")

# Runs standalone (no jax, no package import): loads the ctypes module
# by path, drives every loader entry point over the corpus, prints one
# JSON digest line. Exit 3 = native library unavailable (skip).
_DRIVER = r"""
import glob, hashlib, importlib.util, json, os, sys

import numpy as np

repo, corpus = sys.argv[1], sys.argv[2]
spec = importlib.util.spec_from_file_location(
    "_ft", os.path.join(repo, "tfidf_tpu", "io", "fast_tokenizer.py"))
ft = importlib.util.module_from_spec(spec)
spec.loader.exec_module(ft)
if not (ft.available() and ft.loader_available()
        and ft.flat_available() and ft.slab_available()):
    print("SKIP: native loader unavailable")
    sys.exit(3)

paths = sorted(glob.glob(os.path.join(corpus, "*.txt")))
docs = [open(p, "rb").read() for p in paths]


def digest(*arrays):
    m = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(a)
        m.update(str(a.dtype).encode())
        m.update(str(a.shape).encode())
        m.update(a.tobytes())
    return m.hexdigest()[:32]


out = {}
# tokenizer parity path (incl. the reference's 16-byte truncation)
out["tok"] = digest(*[ft.tokenize_hash_ids(d, 1 << 16, seed=7)
                      for d in docs])
out["tok_trunc"] = digest(
    *[ft.tokenize_hash_ids(d, 1 << 16, seed=7, truncate_at=16)
      for d in docs])
# threaded ragged fill: 1 thread = serial v1/v2 fill, >1 = v3
# work-stolen fill — every width must land the identical stream
for n in (1, 2, 4, 8):
    r = ft.load_pack_flat(paths, 1 << 16, seed=7, max_per_doc=64,
                          n_threads=n, align=16)
    if r is None:
        print("SKIP: flat packer unavailable")
        sys.exit(3)
    flat, lens, total = r
    # digest the real stream only: without cap_ids the serial v1
    # fill leaves the scaffold tail past `total` uninitialized by
    # contract (the wire ships cap_ids-rounded buffers, where the
    # v2/v3 fills zero the tail in C++)
    out["flat_t%d" % n] = digest(flat[:total], lens) + ":%d" % total
# bytes-wire slab fill
for n in (1, 4):
    r = ft.load_slab_paths(paths, n_threads=n, align=16,
                           cap_round=4096)
    if r is None:
        print("SKIP: slab loader unavailable")
        sys.exit(3)
    slab, blens, total = r
    out["slab_t%d" % n] = digest(slab, blens) + ":%d" % total
# padded loader, both element widths
ids, lens = ft.load_pack_paths(paths, 1 << 16, seed=7, n_threads=4)
out["pad_u16"] = digest(ids, lens)
ids, lens = ft.load_pack_paths(paths, (1 << 16) + 7, seed=7,
                               n_threads=4)
out["pad_i32"] = digest(ids, lens)
print(json.dumps(out, sort_keys=True))
"""


@pytest.fixture(scope="module")
def hazard_corpus(tmp_path_factory):
    """Docs chosen to stress every boundary the fills index over."""
    d = tmp_path_factory.mktemp("san_corpus")
    docs = {
        "plain": b"the quick brown fox jumps over the lazy dog " * 40,
        "utf8": ("中文 tokens mixed with café naïve "
                 "über " * 30).encode(),
        "empty": b"",
        "spaces": b" \t\n  \r  " * 16,
        "longtok": b"x" * 300 + b" y " + b"z" * 4096,
        "nul": b"alpha\x00beta gamma \x00 delta",
        "binary": bytes(range(0x80, 0x100)) * 8,
        "overflow": (b"w " * 500),          # > max_per_doc tokens
        "big": (b"lorem ipsum dolor sit amet consectetur " * 1500),
    }
    for i in range(16):                      # give the pool real work
        docs[f"doc{i:02d}"] = (f"doc {i} body words " * (i * 7 + 3)
                               ).encode()
    for name, body in docs.items():
        (d / f"{name}.txt").write_bytes(body)
    return str(d)


def _build(target):
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("needs g++ and make")
    r = subprocess.run(["make", "-C", NATIVE_DIR, target],
                       capture_output=True)
    if r.returncode != 0:
        pytest.skip(f"{target} build unavailable: "
                    f"{r.stderr.decode()[-200:]}")
    return os.path.join(NATIVE_DIR, target)


def _run_driver(corpus, extra_env):
    env = {k: v for k, v in os.environ.items()
           if k not in ("TFIDF_TPU_NO_NATIVE", "TFIDF_TPU_NATIVE_LIB",
                        "TFIDF_TPU_PACK_THREADS", "LD_PRELOAD")}
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-c", _DRIVER, REPO, corpus],
        capture_output=True, env=env, timeout=300)


@pytest.fixture(scope="module")
def reference_digests(hazard_corpus):
    """The plain build's answer — what the sanitized runs must match."""
    _build("fast_tokenizer.so")
    proc = _run_driver(hazard_corpus, {})
    if proc.returncode == 3:
        pytest.skip(proc.stdout.decode().strip())
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    return json.loads(proc.stdout.decode().strip().splitlines()[-1])


def _sanitizer_env(kind):
    if kind == "asan":
        runtime = subprocess.run(
            ["gcc", "-print-file-name=libasan.so"],
            capture_output=True, text=True).stdout.strip()
        if not os.path.isabs(runtime):
            pytest.skip("libasan.so runtime not found")
        # detect_leaks=0: the python *host* leaks by design; the .so's
        # own heap errors still abort with exitcode=66.
        return {"LD_PRELOAD": runtime,
                "ASAN_OPTIONS": "detect_leaks=0:exitcode=66"}
    return {"UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1"}


@pytest.mark.parametrize("kind", ["asan", "ubsan"])
def test_sanitized_native_paths_clean_and_identical(
        kind, hazard_corpus, reference_digests):
    lib = _build(f"fast_tokenizer_{kind}.so")
    proc = _run_driver(hazard_corpus, dict(
        _sanitizer_env(kind), TFIDF_TPU_NATIVE_LIB=lib))
    stderr = proc.stderr.decode()
    assert proc.returncode != 66, f"AddressSanitizer report:\n{stderr[-4000:]}"
    assert proc.returncode == 0, f"{kind} run failed:\n{stderr[-4000:]}"
    for marker in ("AddressSanitizer", "runtime error",
                   "UndefinedBehaviorSanitizer"):
        assert marker not in stderr, f"{kind} report:\n{stderr[-4000:]}"
    got = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert got == reference_digests, (
        f"{kind} build diverged from the plain build")
