"""Row-sparse engine tests: exactness vs dense, BCOO export, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline, discover_corpus
from tfidf_tpu.config import VocabMode
from tfidf_tpu.golden import golden_output
from tfidf_tpu.ops.sparse import (sorted_term_counts, sparse_df,
                                  to_bcoo)
from tfidf_tpu.parallel import MeshPlan, ShardedPipeline


class TestSortedTermCounts:
    def test_rle_matches_bincount(self):
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, 20, (4, 32)), jnp.int32)
        lens = jnp.asarray([32, 5, 0, 17], jnp.int32)
        ids, counts, head = sorted_term_counts(toks, lens)
        for d in range(4):
            got = {int(ids[d, i]): int(counts[d, i])
                   for i in range(32) if head[d, i]}
            want_arr = np.bincount(np.asarray(toks)[d, : int(lens[d])],
                                   minlength=20)
            want = {v: int(c) for v, c in enumerate(want_arr) if c}
            assert got == want

    def test_df_matches_dense(self):
        from tfidf_tpu.ops.histogram import df_from_counts, tf_counts
        rng = np.random.default_rng(1)
        toks = jnp.asarray(rng.integers(0, 50, (6, 24)), jnp.int32)
        lens = jnp.asarray([24, 24, 3, 0, 10, 24], jnp.int32)
        ids, _, head = sorted_term_counts(toks, lens)
        dense_df = df_from_counts(tf_counts(toks, lens, 50))
        assert (np.asarray(sparse_df(ids, head, 50)) == np.asarray(dense_df)).all()

    def test_df_methods_agree(self):
        # The TPU-friendly sort+searchsorted lowering and the scatter
        # lowering are interchangeable by contract.
        rng = np.random.default_rng(7)
        toks = jnp.asarray(rng.integers(0, 97, (16, 40)), jnp.int32)
        lens = jnp.asarray(rng.integers(0, 41, (16,)), jnp.int32)
        ids, _, head = sorted_term_counts(toks, lens)
        a = sparse_df(ids, head, 97, method="scatter")
        b = sparse_df(ids, head, 97, method="sort")
        assert (np.asarray(a) == np.asarray(b)).all()
        with pytest.raises(ValueError):
            sparse_df(ids, head, 97, method="bogus")


class TestSparsePipeline:
    def test_golden_bytes_equal_dense_engine(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        dense = TfidfPipeline(PipelineConfig.golden()).run(corpus)
        sparse = TfidfPipeline(
            PipelineConfig(vocab_mode=VocabMode.EXACT, engine="sparse")
        ).run(corpus)
        assert sparse.counts is None  # [D, V] never materialized
        assert sparse.output_bytes() == dense.output_bytes()
        assert sparse.output_bytes() == golden_output(corpus)

    def test_sparse_topk_matches_dense_topk(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        base = dict(vocab_mode=VocabMode.HASHED, vocab_size=512, topk=3)
        dense = TfidfPipeline(PipelineConfig(**base)).run(corpus)
        sparse = TfidfPipeline(PipelineConfig(engine="sparse", **base)).run(corpus)
        np.testing.assert_allclose(sparse.topk_vals, dense.topk_vals,
                                   rtol=1e-6)
        # ids agree wherever scores are distinct & positive
        agree = (sparse.topk_vals > 0) & (dense.topk_vals > 0)
        assert (sparse.topk_ids[agree] == dense.topk_ids[agree]).all()

    def test_sub_k_docs_masked(self):
        from tfidf_tpu.io.corpus import Corpus
        corpus = Corpus(names=["doc1", "doc2"], docs=[b"a b", b"c"])
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=64,
                             engine="sparse", topk=4)
        r = TfidfPipeline(cfg).run(corpus)
        assert (r.topk_ids[1, 1:] == -1).all()  # doc2 has 1 distinct term

    @pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
    def test_sharded_sparse_matches_single(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=256,
                             engine="sparse", topk=3, max_doc_len=64,
                             doc_chunk=64)
        single = TfidfPipeline(cfg).run(corpus)
        plan = MeshPlan.create(docs=8, seq=1, vocab=1)
        sharded = ShardedPipeline(plan, cfg).run(corpus)
        d = single.topk_vals.shape[0]
        assert (sharded.df == single.df).all()
        np.testing.assert_allclose(sharded.topk_vals[:d], single.topk_vals,
                                   rtol=1e-6)

    @pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
    def test_sharded_sparse_requires_docs_only_mesh(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=256,
                             engine="sparse")
        plan = MeshPlan.create(docs=4, seq=1, vocab=2)
        with pytest.raises(ValueError, match="docs axis only"):
            ShardedPipeline(plan, cfg).run(corpus)


class TestBcooExport:
    def test_bcoo_todense_matches_counts(self):
        from tfidf_tpu.ops.histogram import tf_counts
        rng = np.random.default_rng(2)
        toks = jnp.asarray(rng.integers(0, 30, (3, 16)), jnp.int32)
        lens = jnp.asarray([16, 7, 0], jnp.int32)
        ids, counts, head = sorted_term_counts(toks, lens)
        bcoo = to_bcoo(ids, counts, head, 30)
        dense = tf_counts(toks, lens, 30)
        assert (np.asarray(bcoo.todense()) == np.asarray(dense)).all()

    def test_bcoo_matmul(self):
        # The sparse term-doc matmul of the north star: S @ q on MXU.
        toks = jnp.asarray([[1, 1, 2, 3], [3, 3, 3, 0]], jnp.int32)
        lens = jnp.asarray([4, 4], jnp.int32)
        ids, counts, head = sorted_term_counts(toks, lens)
        bcoo = to_bcoo(ids, counts, head, 8)
        q = jnp.zeros((8,), jnp.float32).at[3].set(1.0)
        out = bcoo @ q
        assert out.tolist() == [1.0, 3.0]


class TestSortJoin:
    """Round 5: the sort-join DF->score lowering must be value-identical
    to the [V]-table gather join (same integers, same idf_from_df
    formula) — it replaced the 59.8 ms/call gather the trace found."""

    def _batch(self, d=17, length=33, vocab=97, seed=2):
        import numpy as np
        rng = np.random.default_rng(seed)
        ids = rng.integers(0, vocab, (d, length)).astype(np.int32)
        lens = rng.integers(0, length + 1, d).astype(np.int32)
        return ids, lens, vocab

    def test_df_join_matches_sparse_df_and_gather(self):
        import numpy as np
        from tfidf_tpu.ops.scoring import idf_from_df
        from tfidf_tpu.ops.sparse import (df_join_sorted, sorted_term_counts,
                                          sparse_df, sparse_scores,
                                          sparse_scores_joined)
        tok, lens, vocab = self._batch()
        ids, counts, head = sorted_term_counts(tok, lens)
        df_ref = np.asarray(sparse_df(ids, head, vocab, method="scatter"))
        df_j, df_slot = df_join_sorted(ids, head, vocab)
        np.testing.assert_array_equal(np.asarray(df_j), df_ref)
        # per-slot join == gather of the DF vector at head slots
        h = np.asarray(head)
        gathered = df_ref[np.where(h, np.asarray(ids), 0)]
        np.testing.assert_array_equal(
            np.where(h, np.asarray(df_slot), -1),
            np.where(h, gathered, -1))
        # scores bit-identical between the two joins
        import jax.numpy as jnp
        idf = idf_from_df(jnp.asarray(df_ref), 17, jnp.float32)
        s_gather = np.asarray(sparse_scores(ids, counts, head, lens, idf))
        s_join = np.asarray(sparse_scores_joined(counts, head, lens,
                                                 df_slot, 17, jnp.float32))
        np.testing.assert_array_equal(s_gather, s_join)

    def test_sparse_forward_join_lowerings_agree(self):
        import numpy as np
        from tfidf_tpu.ops.sparse import sparse_forward
        import jax.numpy as jnp
        tok, lens, vocab = self._batch(d=9, length=21, vocab=64, seed=5)
        out_g = sparse_forward(tok, lens, 9, vocab_size=vocab,
                               score_dtype=jnp.float32, topk=4,
                               join="gather")
        out_s = sparse_forward(tok, lens, 9, vocab_size=vocab,
                               score_dtype=jnp.float32, topk=4,
                               join="sort")
        for a, b in zip(out_g, out_s):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_empty_and_degenerate_rows(self):
        import numpy as np
        import jax.numpy as jnp
        from tfidf_tpu.ops.sparse import sparse_forward
        tok = np.zeros((3, 8), np.int32)
        lens = np.array([0, 8, 1], np.int32)  # empty, uniform, single
        out_g = sparse_forward(tok, lens, 3, vocab_size=16,
                               score_dtype=jnp.float32, topk=2,
                               join="gather")
        out_s = sparse_forward(tok, lens, 3, vocab_size=16,
                               score_dtype=jnp.float32, topk=2,
                               join="sort")
        for a, b in zip(out_g, out_s):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
