"""Streaming minibatch tests: incremental DF == batch DF, checkpointing."""

import jax
import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.parallel import MeshPlan
from tfidf_tpu.streaming import StreamingTfidf


def corpus_batches():
    docs = [b"a b c", b"a a d", b"b d e f", b"a", b"c c g", b"h b"]
    names = [f"doc{i+1}" for i in range(len(docs))]
    full = Corpus(names=names, docs=docs)
    batches = [Corpus(names=names[i:i+2], docs=docs[i:i+2])
               for i in range(0, 6, 2)]
    return full, batches


CFG = PipelineConfig(engine="dense", vocab_mode=VocabMode.HASHED, vocab_size=256,
                     max_doc_len=8, doc_chunk=8)


class TestStreaming:
    def test_incremental_df_equals_batch_df(self):
        full, batches = corpus_batches()
        stream = StreamingTfidf(CFG)
        for b in batches:
            stream.update(stream.pack(b))
        batch_result = TfidfPipeline(CFG).run(full)
        assert stream.docs_seen == len(full)
        assert (stream.df() == batch_result.df).all()

    def test_post_pass_scores_match_batch_pipeline(self):
        full, batches = corpus_batches()
        stream = StreamingTfidf(CFG)
        packed = [stream.pack(b) for b in batches]
        for p in packed:
            stream.update(p)
        got = np.concatenate([np.asarray(stream.score(p)) for p in packed])
        want = TfidfPipeline(CFG).run(full).scores
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_checkpoint_roundtrip(self):
        full, batches = corpus_batches()
        a = StreamingTfidf(CFG)
        a.update(a.pack(batches[0]))
        state = a.state_dict()
        b = StreamingTfidf(CFG)
        b.load_state(state)
        for batch in batches[1:]:
            a.update(a.pack(batch))
            b.update(b.pack(batch))
        assert (a.df() == b.df()).all() and a.docs_seen == b.docs_seen

    def test_exact_mode_rejected(self):
        with pytest.raises(ValueError):
            StreamingTfidf(PipelineConfig(vocab_mode=VocabMode.EXACT))

    @pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
    def test_sharded_streaming_matches_single(self):
        full, batches = corpus_batches()
        plan = MeshPlan.create(docs=2, seq=2, vocab=2)
        sharded = StreamingTfidf(CFG, plan)
        single = StreamingTfidf(CFG)
        for b in batches:
            sharded.update(sharded.pack(b))
            single.update(single.pack(b))
        assert (sharded.df() == single.df()).all()


def _sparse_cfg(topk=4):
    return PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=256,
                          max_doc_len=8, doc_chunk=8, topk=topk)


class TestStreamingSparseEngine:
    """Round 4 (VERDICT r3 item 4): the stream path follows the engine
    doctrine — sort+RLE by default, pinned equal to the dense lowering."""

    def test_default_engine_is_sparse(self):
        assert StreamingTfidf(_sparse_cfg())._engine == "sparse"

    def test_sparse_df_equals_dense_df(self):
        full, batches = corpus_batches()
        sparse = StreamingTfidf(_sparse_cfg())
        dense = StreamingTfidf(PipelineConfig(
            engine="dense", vocab_mode=VocabMode.HASHED, vocab_size=256,
            max_doc_len=8, doc_chunk=8, topk=4))
        for b in batches:
            sparse.update(sparse.pack(b))
            dense.update(dense.pack(b))
        assert (sparse.df() == dense.df()).all()

    def test_sparse_topk_equals_dense_topk(self):
        full, batches = corpus_batches()
        sparse = StreamingTfidf(_sparse_cfg())
        dense = StreamingTfidf(PipelineConfig(
            engine="dense", vocab_mode=VocabMode.HASHED, vocab_size=256,
            max_doc_len=8, doc_chunk=8, topk=4))
        packed = [sparse.pack(b) for b in batches]
        for p in packed:
            sparse.update(p)
            dense.update(p)
        for p in packed:
            sv, si = (np.asarray(a) for a in sparse.score(p))
            dv, di = (np.asarray(a) for a in dense.score(p))
            # Compare the positive-score selections as (doc, id, score)
            # sets: tie ORDER may differ between a [V]-wide and an
            # [L]-wide top_k, the selected content may not.
            for d in range(p.num_docs):
                got = {(int(i), round(float(v), 6))
                       for v, i in zip(sv[d], si[d]) if v > 0}
                want = {(int(i), round(float(v), 6))
                        for v, i in zip(dv[d], di[d]) if v > 0}
                assert got == want

    @pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
    def test_mesh_sparse_matches_single(self):
        full, batches = corpus_batches()
        plan = MeshPlan.create(docs=4, devices=jax.devices()[:4])
        sharded = StreamingTfidf(_sparse_cfg(), plan)
        single = StreamingTfidf(_sparse_cfg())
        assert sharded._engine == "sparse"
        packed_sh = [sharded.pack(b) for b in batches]
        packed_si = [single.pack(b) for b in batches]
        for ps, pi in zip(packed_sh, packed_si):
            sharded.update(ps)
            single.update(pi)
        assert (sharded.df() == single.df()).all()
        for ps, pi in zip(packed_sh, packed_si):
            sv, si = (np.asarray(a) for a in sharded.score(ps))
            dv, di = (np.asarray(a) for a in single.score(pi))
            n = pi.num_docs
            np.testing.assert_array_equal(si[:n], di[:n])
            np.testing.assert_allclose(sv[:n], dv[:n], rtol=1e-6)

    @pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
    def test_explicit_sparse_on_vocab_mesh_errors(self):
        plan = MeshPlan.create(docs=2, vocab=2, devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="docs axis only"):
            StreamingTfidf(PipelineConfig(
                engine="sparse", vocab_mode=VocabMode.HASHED,
                vocab_size=256, topk=4), plan)
        # A measured DEFAULT falls back to dense silently (capability,
        # not preference).
        assert StreamingTfidf(_sparse_cfg(), plan)._engine == "dense"
