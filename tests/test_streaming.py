"""Streaming minibatch tests: incremental DF == batch DF, checkpointing."""

import jax
import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.parallel import MeshPlan
from tfidf_tpu.streaming import StreamingTfidf


def corpus_batches():
    docs = [b"a b c", b"a a d", b"b d e f", b"a", b"c c g", b"h b"]
    names = [f"doc{i+1}" for i in range(len(docs))]
    full = Corpus(names=names, docs=docs)
    batches = [Corpus(names=names[i:i+2], docs=docs[i:i+2])
               for i in range(0, 6, 2)]
    return full, batches


CFG = PipelineConfig(engine="dense", vocab_mode=VocabMode.HASHED, vocab_size=256,
                     max_doc_len=8, doc_chunk=8)


class TestStreaming:
    def test_incremental_df_equals_batch_df(self):
        full, batches = corpus_batches()
        stream = StreamingTfidf(CFG)
        for b in batches:
            stream.update(stream.pack(b))
        batch_result = TfidfPipeline(CFG).run(full)
        assert stream.docs_seen == len(full)
        assert (stream.df() == batch_result.df).all()

    def test_post_pass_scores_match_batch_pipeline(self):
        full, batches = corpus_batches()
        stream = StreamingTfidf(CFG)
        packed = [stream.pack(b) for b in batches]
        for p in packed:
            stream.update(p)
        got = np.concatenate([np.asarray(stream.score(p)) for p in packed])
        want = TfidfPipeline(CFG).run(full).scores
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_checkpoint_roundtrip(self):
        full, batches = corpus_batches()
        a = StreamingTfidf(CFG)
        a.update(a.pack(batches[0]))
        state = a.state_dict()
        b = StreamingTfidf(CFG)
        b.load_state(state)
        for batch in batches[1:]:
            a.update(a.pack(batch))
            b.update(b.pack(batch))
        assert (a.df() == b.df()).all() and a.docs_seen == b.docs_seen

    def test_exact_mode_rejected(self):
        with pytest.raises(ValueError):
            StreamingTfidf(PipelineConfig(vocab_mode=VocabMode.EXACT))

    @pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
    def test_sharded_streaming_matches_single(self):
        full, batches = corpus_batches()
        plan = MeshPlan.create(docs=2, seq=2, vocab=2)
        sharded = StreamingTfidf(CFG, plan)
        single = StreamingTfidf(CFG)
        for b in batches:
            sharded.update(sharded.pack(b))
            single.update(single.pack(b))
        assert (sharded.df() == single.df()).all()
