"""Sharded-execution tests on the 8-device virtual CPU mesh.

Pin the SURVEY §2.3 checklist: document data-parallelism (docs axis),
vocab sharding (TP analog), sequence sharding for long docs (SP analog),
and the psum DF collective — all must agree exactly with the
single-device pipeline, and golden output must be byte-stable under any
mesh shape (the rank-count-invariance property of the reference,
TFIDF.c:130 static schedule).
"""

import jax
import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline, discover_corpus
from tfidf_tpu.config import VocabMode
from tfidf_tpu.golden import golden_output
from tfidf_tpu.parallel import MeshPlan, ShardedPipeline


def needs_devices(n):
    return pytest.mark.skipif(len(jax.devices()) < n,
                              reason=f"needs {n} virtual devices")


MESH_CASES = [
    dict(docs=8, seq=1, vocab=1),   # pure document DP
    dict(docs=4, seq=1, vocab=2),   # DP x vocab (TP analog)
    dict(docs=2, seq=2, vocab=2),   # DP x SP x TP
    dict(docs=1, seq=8, vocab=1),   # pure sequence parallelism
]


@needs_devices(8)
class TestShardedMatchesSingleDevice:
    @pytest.mark.parametrize("mesh_kw", MESH_CASES)
    def test_counts_df_scores_equal(self, toy_corpus_dir, mesh_kw):
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(engine="dense", vocab_mode=VocabMode.HASHED,
                             vocab_size=64, max_doc_len=64, doc_chunk=64)
        single = TfidfPipeline(cfg).run(corpus)
        plan = MeshPlan.create(**mesh_kw)
        sharded = ShardedPipeline(plan, cfg).run(corpus)
        d = single.counts.shape[0]
        assert (sharded.counts[:d] == single.counts).all()
        assert (sharded.df == single.df).all()
        np.testing.assert_allclose(sharded.scores[:d], single.scores,
                                   rtol=1e-5, atol=1e-7)

    @pytest.mark.parametrize("mesh_kw", MESH_CASES[:2])
    def test_golden_bytes_mesh_invariant(self, toy_corpus_dir, mesh_kw):
        # Same property the native oracle pins over nranks: parallel
        # degree must never change output bytes.
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=1 << 15,
                             max_doc_len=64, doc_chunk=64)
        plan = MeshPlan.create(**mesh_kw)
        assert ShardedPipeline(plan, cfg).run(corpus).output_bytes() == \
            golden_output(corpus)

    @pytest.mark.parametrize("mesh_kw", MESH_CASES)
    def test_pallas_shard_body_equals_xla(self, toy_corpus_dir, mesh_kw):
        # The Pallas kernel under shard_map (interpret mode on the CPU
        # mesh) must agree exactly with the XLA scatter lowering for
        # every mesh shape, vocab offsets and seq residuals included.
        corpus = discover_corpus(toy_corpus_dir)
        base = dict(engine="dense", vocab_mode=VocabMode.HASHED,
                    vocab_size=256, max_doc_len=64, doc_chunk=64)
        plan = MeshPlan.create(**mesh_kw)
        xla = ShardedPipeline(plan, PipelineConfig(**base)).run(corpus)
        pallas = ShardedPipeline(
            plan, PipelineConfig(use_pallas=True, **base)).run(corpus)
        assert (pallas.counts == xla.counts).all()
        assert (pallas.df == xla.df).all()
        np.testing.assert_allclose(pallas.scores, xla.scores,
                                   rtol=1e-6, atol=1e-7)

    def test_mesh_shape_config_dispatch(self, toy_corpus_dir):
        # config.mesh_shape routes TfidfPipeline onto the mesh: results
        # must equal both the explicit ShardedPipeline and (modulo doc
        # padding) the single-device run.
        corpus = discover_corpus(toy_corpus_dir)
        base = dict(engine="dense", vocab_mode=VocabMode.HASHED,
                    vocab_size=64, max_doc_len=64, doc_chunk=64)
        meshed = TfidfPipeline(PipelineConfig(
            mesh_shape={"docs": 4, "vocab": 2}, **base)).run(corpus)
        single = TfidfPipeline(PipelineConfig(**base)).run(corpus)
        d = single.counts.shape[0]
        assert (meshed.counts[:d] == single.counts).all()
        assert (meshed.df == single.df).all()

    def test_mesh_shape_unknown_axis_raises(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             mesh_shape={"ranks": 8})
        with pytest.raises(ValueError, match="ranks"):
            TfidfPipeline(cfg).run(corpus)

    def test_run_packed_pads_unplanned_batch(self, toy_corpus_dir):
        # A batch packed without a plan (e.g. via TfidfPipeline.pack)
        # must be grown to mesh-divisible shape, not rejected.
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(engine="dense", vocab_mode=VocabMode.HASHED, vocab_size=64,
                             max_doc_len=64, doc_chunk=64)
        batch = TfidfPipeline(cfg).pack(corpus)
        plan = MeshPlan.create(docs=8, seq=1, vocab=1)
        sharded = ShardedPipeline(plan, cfg).run_packed(batch)
        single = TfidfPipeline(cfg).run_packed(batch)
        d = single.counts.shape[0]
        assert (sharded.counts[:d] == single.counts).all()
        assert (sharded.df == single.df).all()

    def test_sharded_topk_matches_dense(self, toy_corpus_dir):
        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=64,
                             max_doc_len=64, doc_chunk=64, topk=4)
        plan = MeshPlan.create(docs=2, seq=1, vocab=4)
        sharded = ShardedPipeline(plan, cfg).run(corpus)
        dense = TfidfPipeline(
            PipelineConfig(engine="dense", vocab_mode=VocabMode.HASHED, vocab_size=64,
                           max_doc_len=64, doc_chunk=64)).run(corpus)
        d = dense.counts.shape[0]
        # top-1 id agrees; top-k values agree as sorted sets
        assert (sharded.topk_ids[:d, 0] == dense.scores.argmax(1)).all()
        np.testing.assert_allclose(
            sharded.topk_vals[:d],
            -np.sort(-np.partition(dense.scores, -4, axis=1)[:, -4:], axis=1),
            rtol=1e-5, atol=1e-7)


@needs_devices(8)
class TestLongDoc:
    def test_mesh_wide_histogram_exact(self):
        from tfidf_tpu.parallel.longdoc import long_doc_histogram
        plan = MeshPlan.create(docs=2, seq=2, vocab=2)
        rng = np.random.default_rng(3)
        toks = rng.integers(0, 50, size=1024).astype(np.int32)
        length = 1000  # tail is padding
        counts = np.asarray(long_doc_histogram(plan, toks, length, 64))
        ref = np.bincount(toks[:length], minlength=64)
        assert (counts == ref).all()
        assert counts.sum() == length

    def test_composes_with_df_scoring(self):
        # A long doc's histogram slots into the same DF/IDF ops.
        from tfidf_tpu.ops.scoring import idf_from_df
        from tfidf_tpu.parallel.longdoc import long_doc_histogram
        plan = MeshPlan.create(docs=8, seq=1, vocab=1)
        toks = np.arange(256, dtype=np.int32) % 16
        counts = long_doc_histogram(plan, toks, 256, 16)
        idf = idf_from_df((counts > 0).astype(np.int32), 4)
        assert idf.shape == (16,)


@needs_devices(8)
class TestMeshPlan:
    def test_axis_sizes_and_padding(self):
        plan = MeshPlan.create(docs=2, seq=2, vocab=2,
                               devices=jax.devices()[:8])
        assert plan.n_docs_shards == 2 and plan.n_vocab_shards == 2
        assert plan.pad_docs(3) == 4 and plan.pad_docs(4) == 4
        assert plan.pad_vocab(65) == 66
        assert plan.pad_tokens(7) == 8

    def test_bad_mesh_shape_raises(self):
        with pytest.raises(ValueError):
            MeshPlan.create(docs=3, seq=1, vocab=1, devices=jax.devices()[:8])
        with pytest.raises(ValueError):
            MeshPlan.create(vocab=3, devices=jax.devices()[:8])

    def test_docs_inference(self):
        plan = MeshPlan.create(vocab=2, devices=jax.devices()[:8])
        assert plan.n_docs_shards == 4
