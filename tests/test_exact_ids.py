"""Device-exact exact-terms engine (round 4, VERDICT r3 item 3).

The intern table (native/intern.cc) assigns collision-free word ids at
pack time, so the device selection is word-exact and the host rescores
from wire integers — no corpus re-pass. Oracle: the native
bit-reference (byte-identical %.16f lines) and the Python exact_topk
semantics."""

import os
import random
import subprocess

import numpy as np
import pytest

from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.io import fast_tokenizer as ft
from tfidf_tpu.rerank import exact_terms, exact_topk_from_wire

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "tfidf_ref")

pytestmark = pytest.mark.skipif(not ft.intern_available(),
                                reason="native intern table not built")


@pytest.fixture
def corpus(tmp_path):
    rng = random.Random(5)
    d = tmp_path / "input"
    d.mkdir()
    words = [f"word{i}" for i in range(300)]
    for i in range(1, 101):
        (d / f"doc{i}").write_text(
            " ".join(rng.choice(words) for _ in range(rng.randint(1, 60))))
    # A doc of corpus-hapax words: one tie group wider than any margin —
    # the boundary-tie fallback must resolve it doc-locally.
    (d / "doc101").write_text(" ".join(f"hapax{j}" for j in range(40)))
    return str(d)


def _cfg(vocab=1 << 12, margin_k=20):
    return PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=vocab,
                          topk=margin_k, engine="sparse")


class TestDeviceExact:
    def test_byte_identical_to_oracle(self, corpus, tmp_path):
        dev, engine = exact_terms(corpus, _cfg(), k=5, doc_len=64,
                                  chunk_docs=32)
        assert engine == "device-exact"
        if not os.path.exists(NATIVE):
            subprocess.run(["make", "-C", os.path.dirname(NATIVE)],
                           check=True, capture_output=True)
        out = str(tmp_path / "oracle.txt")
        subprocess.run([NATIVE, corpus, out, "5"], check=True,
                       stdout=subprocess.DEVNULL)
        oracle_lines = set(open(out, "rb").read().splitlines())
        emitted = 0
        for name, terms in dev.items():
            for w, s in terms:
                line = b"%s@%s\t%.16f" % (name.encode(), w, s)
                assert line in oracle_lines, line
                emitted += 1
        assert emitted > 100  # real coverage, not an empty pass

    def test_tie_groups_resolve_word_asc(self, corpus):
        # doc101 is 40 equal-scoring hapax words: top-5 must be the
        # byte-lex first five (hapax0, hapax1, hapax10, hapax11,
        # hapax12), which no wire margin alone could guarantee.
        dev, engine = exact_terms(corpus, _cfg(), k=5, doc_len=64,
                                  chunk_docs=32)
        assert engine == "device-exact"
        got = [w for w, _ in dev["doc101"]]
        assert got == [b"hapax0", b"hapax1", b"hapax10", b"hapax11",
                       b"hapax12"]

    def test_overflow_falls_back_to_hashed_rerank(self, corpus, capsys):
        # 340 distinct words > 256-bucket vocab: the intern table
        # overflows and the hashed+margin+rerank engine takes over.
        dev, engine = exact_terms(corpus, _cfg(vocab=256), k=5,
                                  doc_len=64, chunk_docs=32)
        assert engine == "hashed-rerank"
        assert len(dev) == 101

    def test_wire_integers_are_exact(self, corpus):
        # The wire's (count, df) must equal a host count of the same
        # tokenization — spot-check a few docs.
        from tfidf_tpu.ingest import run_overlapped_exact
        from tfidf_tpu.ops.tokenize import whitespace_tokenize

        exact = run_overlapped_exact(corpus, _cfg(), chunk_docs=32,
                                     doc_len=64)
        id2w = exact.words
        for d in (0, 50, 100):
            name = exact.names[d]
            with open(os.path.join(corpus, name), "rb") as f:
                toks = whitespace_tokenize(f.read(), None)[:64]
            for j in range(exact.topk_ids.shape[1]):
                c = int(exact.topk_counts[d, j])
                if c == 0:
                    continue
                w = id2w[int(exact.topk_ids[d, j])]
                assert toks.count(w) == c, (name, w)

    def test_empty_and_whitespace_docs(self, tmp_path):
        # Degenerate documents must flow through the whole engine:
        # empty file, whitespace-only file, single-word file.
        d = tmp_path / "input"
        d.mkdir()
        (d / "doc1").write_bytes(b"")
        (d / "doc2").write_bytes(b"   \n\t  ")
        (d / "doc3").write_bytes(b"lonely")
        (d / "doc4").write_bytes(b"alpha beta alpha")
        dev, engine = exact_terms(str(d), _cfg(), k=3, doc_len=16,
                                  chunk_docs=4)
        assert engine == "device-exact"
        assert dev["doc1"] == [] and dev["doc2"] == []
        assert [w for w, _ in dev["doc3"]] == [b"lonely"]
        assert {w for w, _ in dev["doc4"]} == {b"alpha", b"beta"}

    def test_wide_vocab_cap_uses_i32_wire(self, corpus, tmp_path):
        # A cap past 2^16 switches the intern wire to int32 (round 4
        # extension) — same byte-exact output as the oracle.
        dev, engine = exact_terms(corpus, _cfg(vocab=1 << 17), k=5,
                                  doc_len=64, chunk_docs=32)
        assert engine == "device-exact"
        if not os.path.exists(NATIVE):
            subprocess.run(["make", "-C", os.path.dirname(NATIVE)],
                           check=True, capture_output=True)
        out = str(tmp_path / "oracle_wide.txt")
        subprocess.run([NATIVE, corpus, out, "5"], check=True,
                       stdout=subprocess.DEVNULL)
        oracle_lines = set(open(out, "rb").read().splitlines())
        for name, terms in dev.items():
            for w, s in terms:
                assert b"%s@%s\t%.16f" % (name.encode(), w, s) \
                    in oracle_lines

    def test_device_margin_strictly_exceeds_k(self):
        # Review r4: with dev margin == k the tie detector fires on
        # EVERY dense doc (tail slot IS the k-th slot) and the fast
        # path degrades to a full corpus re-read. The clamp must keep
        # kprime > k whatever cfg.topk says.
        from tfidf_tpu.rerank import _device_cfg
        for margin_topk, k in ((8, 8), (4, 8), (64, 16), (None, 5)):
            cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                                 vocab_size=4096, topk=margin_topk)
            assert _device_cfg(cfg, k).topk > k

    def test_tie_fallback_respects_truncation(self, tmp_path):
        # doc_len=None: ingest truncates at cfg.max_doc_len, and the
        # boundary-tie re-read must apply the SAME cap (review r4
        # finding: an uncapped re-read scored docSize=30 and words the
        # device never saw).
        import math

        d = tmp_path / "input"
        d.mkdir()
        (d / "doc1").write_text(" ".join(f"h{j:02d}" for j in range(30)))
        (d / "doc2").write_text("h00 x")
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=4096,
                             topk=8, max_doc_len=16, engine="sparse")
        dev, engine = exact_terms(str(d), cfg, k=4, chunk_docs=4)
        assert engine == "device-exact"
        got = dev["doc1"]
        # The tie group (h01..h15: count 1, df 1) must resolve word-asc
        # over the TRUNCATED doc: top-4 = h01..h04 at (1/16) * ln(2/1).
        want_score = (1.0 / 16.0) * math.log(2.0 / 1.0)
        assert [w for w, _ in got] == [b"h01", b"h02", b"h03", b"h04"]
        for _, s in got:
            assert s == want_score

    def test_lines_fallback_assembles_sorted_output(self, corpus,
                                                    monkeypatch):
        # exact_terms_lines' hashed-fallback branch builds the sorted
        # line bytes in Python — must match the reference ordering
        # contract and the dict-entry contract of exact_terms.
        from tfidf_tpu.rerank import exact_terms_lines
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")  # force f/b
        lines, engine, sample_fn = exact_terms_lines(
            corpus, _cfg(), k=5, doc_len=64, chunk_docs=32)
        assert engine == "hashed-rerank"
        rows = lines.splitlines()
        assert rows == sorted(rows) and rows
        sample = sample_fn(["doc3"])
        assert [b"doc3@%s\t%.16f" % (w, s) in rows
                for w, s in sample["doc3"]]

    def test_beyond_resident_falls_back_to_hashed(self, corpus,
                                                  monkeypatch, capsys):
        # The device-exact path is resident-only; past the budget the
        # hashed streaming+rerank engine must serve the same contract.
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        dev, engine = exact_terms(corpus, _cfg(), k=5, doc_len=64,
                                  chunk_docs=32)
        assert engine == "hashed-rerank"
        assert len(dev) == 101 and dev["doc101"]

    def test_cli_exact_terms_with_mesh_uses_hashed_engine(self, corpus,
                                                          tmp_path):
        # --exact-terms + --mesh: the mesh ingest provides the margin
        # selection (ids-only wire) and the hashed re-rank engine emits
        # exact words — the CLI matrix has no dead cells. That engine's
        # documented limit applies: score TIES beyond the margin pick
        # bucket-order members, not word-asc (docs/EXACT.md engine 2),
        # so the pin is oracle-score-exactness per line + per-doc
        # counts, not byte equality with the device-exact engine.
        from tfidf_tpu.cli import main
        out = tmp_path / "mesh_exact.txt"
        rc = main(["run", "--input", corpus, "--output", str(out),
                   "--vocab-mode", "hashed", "--vocab-size", "4096",
                   "--topk", "5", "--doc-len", "64", "--exact-terms",
                   "--mesh", "4,1,1"])
        assert rc == 0
        if not os.path.exists(NATIVE):
            subprocess.run(["make", "-C", os.path.dirname(NATIVE)],
                           check=True, capture_output=True)
        oracle_out = str(tmp_path / "oracle_mesh.txt")
        subprocess.run([NATIVE, corpus, oracle_out, "5"], check=True,
                       stdout=subprocess.DEVNULL)
        oracle_lines = set(open(oracle_out, "rb").read().splitlines())
        lines = open(out, "rb").read().splitlines()
        assert lines and all(l in oracle_lines for l in lines)
        # doc101's top-5 are 5 of its (all-tied) hapax words
        hapax = [l for l in lines if l.startswith(b"doc101@hapax")]
        assert len(hapax) == 5

    def test_cli_exact_terms_rides_device_engine(self, corpus, tmp_path):
        from tfidf_tpu.cli import main
        out = tmp_path / "exact.txt"
        rc = main(["run", "--input", corpus, "--output", str(out),
                   "--vocab-mode", "hashed", "--vocab-size", "4096",
                   "--topk", "5", "--doc-len", "64", "--exact-terms"])
        assert rc == 0
        data = open(out, "rb").read()
        assert b"doc101@hapax0\t" in data
        lines = data.splitlines()
        assert lines == sorted(lines)  # strcmp ordering contract


class TestAdvisorR4Fixes:
    """Regression tests for the round-4 advisor findings (ADVICE.md)."""

    def test_at_in_name_uses_full_line_byte_sort(self, tmp_path):
        # medium: names "doc" and "doc@a" break exact_emit's
        # (name+'@', word) integer rank key — "doc@xray" would sort
        # before "doc@a@beta" even though full-line bytes interleave
        # them. The '@' fallback must sort the assembled line bytes.
        from tfidf_tpu.rerank import exact_terms_lines
        d = tmp_path / "input"
        d.mkdir()
        (d / "doc").write_text("xray zulu")
        (d / "doc@a").write_text("beta alpha")
        lines, engine, _ = exact_terms_lines(str(d), _cfg(), k=4,
                                             chunk_docs=4, strict=False)
        assert engine == "device-exact"
        rows = lines.splitlines()
        assert rows == sorted(rows) and len(rows) == 4
        # The interleaving the integer key got wrong:
        assert rows[0].startswith(b"doc@a@alpha")
        assert rows[1].startswith(b"doc@a@beta")
        assert rows[2].startswith(b"doc@xray")

    def test_short_doc_cap_skips_tie_reread(self, tmp_path):
        # low: when the wire width (kprime = min(topk, doc_len)) is >=
        # a doc's token count, its full wire IS the complete term set —
        # the tie heuristic must not fire. Old behavior degraded every
        # dense doc to a doc-local re-read; here the doc file does not
        # even exist, so a fired tie would raise FileNotFoundError.
        from tfidf_tpu.ingest import ExactIngest
        exact = ExactIngest(
            names=["ghost"], lengths=np.array([3], np.int32),
            topk_ids=np.array([[0, 1, 2]], np.int32),
            topk_counts=np.array([[1, 1, 1]], np.int32),
            df=np.array([1, 1, 1], np.int32), num_docs=2,
            words=[b"a", b"b", b"c"])
        out = exact_topk_from_wire(exact, 2, str(tmp_path), _cfg())
        # All three score (1/3)ln(2), word-asc picks a then b.
        assert [w for w, _ in out["ghost"]] == [b"a", b"b"]

    def test_f32_near_tie_resolves_doc_locally(self, tmp_path):
        # low: the device ranks by float32 — candidates whose float64
        # scores are distinct but within float32 rounding distance can
        # be truncated in id order before the wire. The detector must
        # treat "within 4e-6 relative" as tied and re-read the doc,
        # recovering a true top-k member the wire never carried.
        from tfidf_tpu.ingest import ExactIngest
        d = tmp_path / "input"
        d.mkdir()
        (d / "docx").write_text("a b c d")
        # Crafted DF: s(a) clear winner; s(b), s(c) within ~4e-7
        # relative (f32-collapsible); s(d) — NOT on the wire — beats
        # both, so the wire alone would return the wrong 2nd term.
        df = np.array([2.0, 20.00001, 20.00002, 20.0])
        exact = ExactIngest(
            names=["docx"], lengths=np.array([4], np.int32),
            topk_ids=np.array([[0, 1, 2]], np.int32),
            topk_counts=np.array([[1, 1, 1]], np.int32),
            df=df, num_docs=100, words=[b"a", b"b", b"c", b"d"])
        out = exact_topk_from_wire(exact, 2, str(d), _cfg())
        got = out["docx"]
        assert [w for w, _ in got] == [b"a", b"d"]
        # np.log mirrors the production path (rerank re-read branch) —
        # math.log may differ by 1 ulp on SIMD numpy builds.
        assert got[1][1] == (1.0 / 4.0) * float(np.log(100.0 / 20.0))
