"""Checkpoint/resume of streaming DF state (tfidf_tpu/checkpoint.py +
cli stream): a killed-and-restarted stream must converge to the same
state as an uninterrupted one."""

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig
from tfidf_tpu import checkpoint as ckpt
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.streaming import StreamingTfidf


def _corpus(lo: int, hi: int) -> Corpus:
    rng = np.random.default_rng(lo)
    names, docs = [], []
    for i in range(lo, hi):
        names.append(f"doc{i}")
        docs.append(" ".join(
            f"w{rng.integers(0, 50)}" for _ in range(20)).encode())
    return Corpus(names=names, docs=docs)


def _cfg():
    return PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=256,
                          topk=4)


class TestSaveRestore:
    @pytest.mark.parametrize("force_npz", [True, False])
    def test_roundtrip(self, tmp_path, force_npz):
        path = str(tmp_path / "ck")
        state = {"df": np.arange(256, dtype=np.int32),
                 "docs_seen": np.asarray(12)}
        backend = ckpt.save_state(path, state, force_npz=force_npz)
        assert backend == (
            "npz" if force_npz or not ckpt._HAVE_ORBAX else "orbax")
        assert ckpt.exists(path)
        back = ckpt.restore_state(path)
        assert (back["df"] == state["df"]).all()
        assert int(back["docs_seen"]) == 12

    def test_overwrite_is_atomic_latest_wins(self, tmp_path):
        path = str(tmp_path / "ck")
        ckpt.save_state(path, {"df": np.zeros(4, np.int32),
                               "docs_seen": np.asarray(1)}, force_npz=True)
        ckpt.save_state(path, {"df": np.ones(4, np.int32),
                               "docs_seen": np.asarray(2)}, force_npz=True)
        assert int(ckpt.restore_state(path)["docs_seen"]) == 2

    def test_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            ckpt.restore_state(str(tmp_path / "nowhere"))


class TestStreamResume:
    def test_interrupted_stream_converges(self, tmp_path):
        path = str(tmp_path / "ck")
        full = StreamingTfidf(_cfg())
        for lo in (0, 30, 60):
            full.update(full.pack(_corpus(lo, lo + 30)))

        # "Crash" after two minibatches...
        first = StreamingTfidf(_cfg())
        for lo in (0, 30):
            first.update(first.pack(_corpus(lo, lo + 30)))
            ckpt.save_state(path, first.state_dict(), force_npz=True)
        del first

        # ...resume in a fresh engine, finish the stream.
        resumed = StreamingTfidf(_cfg())
        resumed.load_state(ckpt.restore_state(path))
        assert resumed.docs_seen == 60
        resumed.update(resumed.pack(_corpus(60, 90)))

        assert resumed.docs_seen == full.docs_seen == 90
        assert (resumed.df() == full.df()).all()

    def test_cli_stream_resume(self, tmp_path):
        from tfidf_tpu.cli import main

        ind = tmp_path / "input"
        ind.mkdir()
        rng = np.random.default_rng(0)
        for i in range(1, 21):
            (ind / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 30)}" for _ in range(15)))
        ck = str(tmp_path / "ck")
        out1, out2 = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")

        base = ["stream", "--input", str(ind), "--batch-docs", "8",
                "--vocab-size", "256", "--topk", "3"]
        assert main(base + ["--output", out1, "--checkpoint", ck]) == 0
        # Second invocation resumes at EOF (nothing left to fold) but
        # must still score the whole corpus identically.
        assert main(base + ["--output", out2, "--checkpoint", ck,
                            "--resume"]) == 0
        assert open(out1, "rb").read() == open(out2, "rb").read()


class TestCrashWindows:
    """The LATEST-pointer protocol: a crash at any point leaves a
    restorable checkpoint (old or new), and debris self-heals."""

    def _save(self, path, n):
        return ckpt.save_state(path, {"df": np.full(4, n, np.int32),
                                      "docs_seen": np.asarray(n)},
                               force_npz=True)

    def test_uncommitted_payload_debris_ignored_then_reclaimed(self, tmp_path):
        import os
        path = str(tmp_path / "ck")
        self._save(path, 1)  # commits ckpt-0
        # Simulate a crash mid-save: the next payload dir (ckpt-1) was
        # written but LATEST never repointed. Committed state must still
        # be generation 0's.
        os.makedirs(os.path.join(path, "ckpt-1"))
        assert int(ckpt.restore_state(path)["docs_seen"]) == 1
        # The next save reclaims the debris name and commits over it.
        self._save(path, 2)
        assert int(ckpt.restore_state(path)["docs_seen"]) == 2

    def test_dangling_latest_is_not_a_checkpoint(self, tmp_path):
        path = str(tmp_path / "ck")
        (tmp_path / "ck").mkdir()
        (tmp_path / "ck" / "LATEST").write_text("ckpt-7")  # dir never made
        assert not ckpt.exists(path)
        with pytest.raises(FileNotFoundError):
            ckpt.restore_state(path)

    def test_old_payload_gone_after_commit(self, tmp_path):
        import os
        path = str(tmp_path / "ck")
        self._save(path, 1)
        self._save(path, 2)
        entries = sorted(e for e in os.listdir(path) if e != "LOCK")
        assert entries == ["LATEST", "ckpt-1"]  # superseded ckpt-0 gone

    def test_orphaned_superseded_payload_reclaimed(self, tmp_path):
        # Crash window: LATEST repointed at ckpt-1 but the rmtree of
        # ckpt-0 never ran. ckpt-0's name is behind the committed seq so
        # no future save reuses it — the debris sweep must catch it.
        import os
        path = str(tmp_path / "ck")
        self._save(path, 1)   # commits ckpt-0
        self._save(path, 2)   # commits ckpt-1, normally removes ckpt-0
        os.makedirs(os.path.join(path, "ckpt-0"))  # ...but the crash kept it
        (tmp_path / "ck" / "stale.latest.tmp").write_text("ckpt-9")
        os.makedirs(os.path.join(
            path, "ckpt-2.orbax-checkpoint-tmp-123"))  # crashed orbax stage
        self._save(path, 3)
        assert sorted(e for e in os.listdir(path)
                      if e != "LOCK") == ["LATEST", "ckpt-2"]
        assert int(ckpt.restore_state(path)["docs_seen"]) == 3


class TestWriterLock:
    def test_concurrent_saver_fails_loudly(self, tmp_path):
        # save_state is single-writer per root: while one writer holds
        # the flock, a second save must raise instead of racing the
        # debris sweep (advisor finding: the sweep deletes any other
        # writer's uncommitted payload mid-write).
        import fcntl
        import os
        root = str(tmp_path / "ck")
        os.makedirs(root)
        fd = os.open(os.path.join(root, "LOCK"), os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            with pytest.raises(RuntimeError, match="single-writer"):
                ckpt.save_state(root, {"df": np.zeros(4)})
        finally:
            os.close(fd)
        # lock released -> saving works again
        assert ckpt.save_state(root, {"df": np.zeros(4)}) in ("orbax", "npz")
        assert ckpt.exists(root)


class TestStreamMesh:
    def test_cli_stream_mesh_matches_single(self, tmp_path):
        # Round 4: stream --mesh-docs shards every minibatch; output
        # bytes must equal the single-device stream.
        from tfidf_tpu.cli import main

        ind = tmp_path / "input"
        ind.mkdir()
        rng = np.random.default_rng(3)
        for i in range(1, 23):
            (ind / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 40)}" for _ in range(12)))
        single, mesh = str(tmp_path / "s.txt"), str(tmp_path / "m.txt")
        base = ["stream", "--input", str(ind), "--batch-docs", "8",
                "--vocab-size", "256", "--topk", "3"]
        assert main(base + ["--output", single]) == 0
        assert main(base + ["--output", mesh, "--mesh-docs", "4"]) == 0
        assert open(single, "rb").read() == open(mesh, "rb").read()
        # batch size must block-shard evenly: clean error otherwise
        assert main(base + ["--output", mesh, "--mesh-docs", "3"]) == 2
