"""Mesh-sharded serving (ISSUE 13): one doc-sharded index across the
chip mesh, queries fan out and merge on-device.

The acceptance pins:

* the ``shard_map`` compat shim (``tfidf_tpu/parallel/compat.py``)
  carries every mesh program on this env's 0.4.x jax (no top-level
  ``jax.shard_map`` export) with the ``check_vma``→``check_rep``
  translation, and prefers the native export where one exists;
* :class:`~tfidf_tpu.parallel.serving.MeshShardedRetriever` is
  BIT-identical — scores, doc indices, tie order — to single-device
  ``TfidfRetriever.search`` as a property over random corpora x shard
  counts, including a ragged last shard and an all-tombstoned shard;
* the full serve path holds the same parity through swap, live
  mutation and snapshot/restore, with every install re-sharded;
* the canary prober captures its oracle from the SINGLE-DEVICE source
  and probes 1.0 through the sharded path;
* the DeviceMonitor publishes the ``shard_bytes_d*`` balance gauges +
  the edge-triggered ``shard_balance`` flight event, and
  ``tools/doctor.py --shard-imbalance`` budgets it;
* ``tools/perf_ledger.py`` files mesh artifacts as kind
  ``mesh_serve`` and ``tools/perf_gate.py`` zero-tolerates parity.
"""

import importlib.util
import json
import os
import random
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from tfidf_tpu import obs
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.obs import devmon
from tfidf_tpu.obs.log import EventLog
from tfidf_tpu.parallel import compat
from tfidf_tpu.parallel.serving import (MeshShardedRetriever,
                                        make_serving_plan,
                                        mesh_search_cache_size,
                                        shard_index)
from tfidf_tpu.serve import TfidfServer
from tfidf_tpu.serve.canary import CanaryProber

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCTOR = os.path.join(REPO, "tools", "doctor.py")

pytestmark = pytest.mark.shard_map

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=32, doc_chunk=32)

WORDS = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
         "lam mu nu xi omicron pi").split()


def needs_devices(n):
    return pytest.mark.skipif(len(jax.devices()) < n,
                              reason=f"needs {n} virtual devices")


def make_corpus(n_docs, seed=0, vocab=WORDS):
    rng = random.Random(seed)
    names = [f"doc{i + 1}" for i in range(n_docs)]
    docs = [" ".join(rng.choice(vocab)
                     for _ in range(rng.randint(3, 20))).encode()
            for _ in range(n_docs)]
    return Corpus(names=names, docs=docs)


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.set_log(EventLog(echo="off"))
    obs.set_tracer(None)
    devmon.set_watch(None)
    devmon.set_monitor(None)
    yield
    devmon.set_watch(None)
    devmon.set_monitor(None)
    obs.set_tracer(None)
    obs.set_log(None)


def _load_tool(name):
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


class TestShim:
    """The shard_map compat shim — the thing that turned the 37 env
    skips back into running mesh coverage."""

    def test_shim_runs_a_mesh_program(self):
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()[:2]), ("docs",))
        fn = compat.shard_map(lambda x: x + 1, mesh=mesh,
                              in_specs=P("docs"), out_specs=P("docs"),
                              check_vma=False)
        out = np.asarray(jax.jit(fn)(np.zeros((4,), np.int32)))
        assert (out == 1).all()

    def test_fallback_branch_is_live_on_this_env(self):
        # This environment's jax (0.4.x line) lacks the top-level
        # export — the shim's whole reason to exist. If a future env
        # grows it, HAS_NATIVE_SHARD_MAP flips and the passthrough
        # branch carries the same call (covered below either way).
        assert compat.HAS_NATIVE_SHARD_MAP == hasattr(jax, "shard_map")

    def test_prefers_native_export(self, monkeypatch):
        calls = {}

        def fake(f, *, mesh, in_specs, out_specs, check_vma=True):
            calls.update(mesh=mesh, check_vma=check_vma)
            return f
        monkeypatch.setattr(jax, "shard_map", fake, raising=False)
        monkeypatch.setattr(compat, "HAS_NATIVE_SHARD_MAP", True)
        out = compat.shard_map(lambda x: x, mesh="M", in_specs="I",
                               out_specs="O", check_vma=False)
        assert out(7) == 7
        assert calls == {"mesh": "M", "check_vma": False}

    def test_fallback_translates_check_vma_to_check_rep(self,
                                                        monkeypatch):
        import jax.experimental.shard_map as esm
        calls = {}

        def fake(f, *, mesh, in_specs, out_specs, check_rep=True):
            calls.update(check_rep=check_rep)
            return f
        monkeypatch.setattr(esm, "shard_map", fake)
        monkeypatch.setattr(compat, "HAS_NATIVE_SHARD_MAP", False)
        compat.shard_map(lambda x: x, mesh="M", in_specs="I",
                         out_specs="O", check_vma=False)
        assert calls == {"check_rep": False}
        compat.shard_map(lambda x: x, mesh="M", in_specs="I",
                         out_specs="O")
        assert calls == {"check_rep": True}


@needs_devices(4)
class TestBitParity:
    """Sharded-vs-single-device bit parity: the tentpole contract."""

    def test_property_random_corpora_x_shard_counts(self):
        # Ragged last shard included by construction: 5, 6, 13 docs
        # over 2 and 4 shards pad 1-3 dead tail rows.
        for seed, n_docs in ((1, 5), (2, 6), (3, 13), (4, 16)):
            corpus = make_corpus(n_docs, seed=seed)
            single = TfidfRetriever(CFG).index(corpus)
            for shards in (2, 4):
                sharded = shard_index(single, make_serving_plan(shards))
                assert sharded.n_shards == shards
                for k in (1, 3, 10, n_docs + 7):
                    queries = ["alpha beta", "zeta", "mu nu xi pi",
                               "unknownword"]
                    v1, i1 = single.search(queries, k)
                    v2, i2 = sharded.search(queries, k)
                    assert v1.shape == v2.shape  # width min(k, docs)
                    assert np.array_equal(v1, v2), (seed, shards, k)
                    assert np.array_equal(i1, i2), (seed, shards, k)

    def test_tie_order_across_shard_boundary(self):
        # Identical docs land in DIFFERENT shards and score exactly
        # equal; the merge must reproduce lax.top_k's lowest-global-
        # index tie-break, i.e. the single-device order. The distinct
        # docs keep DF < N so idf (and the scores) stay nonzero.
        docs = [b"alpha beta", b"alpha beta", b"gamma delta",
                b"alpha beta", b"epsilon zeta", b"alpha beta"]
        corpus = Corpus(names=[f"d{i}" for i in range(len(docs))],
                        docs=docs)
        single = TfidfRetriever(CFG).index(corpus)
        for shards in (2, 3):
            sharded = shard_index(single, make_serving_plan(shards))
            v1, i1 = single.search(["alpha beta"], k=5)
            v2, i2 = sharded.search(["alpha beta"], k=5)
            assert (v1[0] > 0).sum() >= 4     # the ties actually score
            assert np.array_equal(v1, v2)
            assert np.array_equal(i1, i2), (shards, i1, i2)

    def test_query_blocking_matches(self, monkeypatch):
        # > TFIDF_TPU_QUERY_BLOCK queries split into independent
        # blocks on both paths; concatenation must stay exact.
        monkeypatch.setenv("TFIDF_TPU_QUERY_BLOCK", "4")
        corpus = make_corpus(9, seed=5)
        single = TfidfRetriever(CFG).index(corpus)
        sharded = shard_index(single, make_serving_plan(2))
        queries = [f"{WORDS[i % len(WORDS)]} {WORDS[(2 * i) % len(WORDS)]}"
                   for i in range(11)]
        v1, i1 = single.search(queries, 4)
        v2, i2 = sharded.search(queries, 4)
        assert np.array_equal(v1, v2) and np.array_equal(i1, i2)

    def test_empty_queries_and_contract_surface(self):
        corpus = make_corpus(6, seed=6)
        single = TfidfRetriever(CFG).index(corpus)
        sharded = shard_index(single, make_serving_plan(2))
        assert sharded.indexed and sharded._num_docs == 6
        assert sharded.names == single.names
        assert sharded.config is single.config
        assert sharded.parity_oracle() is single
        v, i = sharded.search([], k=3)
        assert v.shape == (0, 3) and i.shape == (0, 3)
        v1, i1 = single.search([""], k=3)
        v2, i2 = sharded.search([""], k=3)
        assert np.array_equal(v1, v2) and np.array_equal(i1, i2)

    def test_shard_index_idempotent_and_guards(self):
        corpus = make_corpus(4, seed=7)
        single = TfidfRetriever(CFG).index(corpus)
        plan = make_serving_plan(2)
        sharded = shard_index(single, plan)
        assert shard_index(sharded, plan) is sharded
        with pytest.raises(ValueError, match="indexed"):
            shard_index(TfidfRetriever(CFG), plan)
        from tfidf_tpu.parallel.mesh import MeshPlan
        bad = MeshPlan.create(docs=2, vocab=2,
                              devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="docs axis only"):
            MeshShardedRetriever(single, bad)
        dropped = shard_index(single, plan, keep_source=False)
        assert dropped.parity_oracle() is None
        with pytest.raises(ValueError, match="source"):
            dropped.snapshot("/tmp/nowhere")
        with pytest.raises(ValueError, match="source"):
            shard_index(dropped, make_serving_plan(4))

    def test_shard_stats_balanced_blocks(self):
        corpus = make_corpus(8, seed=8)
        sharded = shard_index(TfidfRetriever(CFG).index(corpus),
                              make_serving_plan(4))
        stats = sharded.shard_stats()
        assert stats["n_shards"] == 4
        assert len(stats["shard_bytes"]) == 4
        assert all(b > 0 for b in stats["shard_bytes"])
        # equal row blocks by construction
        assert stats["imbalance"] == pytest.approx(1.0)
        assert stats["total_bytes"] == sum(stats["shard_bytes"])


@needs_devices(4)
class TestSegmentedSharding:
    """A sharded IndexView: mutation-era parity, tombstones riding the
    live mask, the all-deleted-shard case."""

    def _names_scores(self, names, vals, ids):
        return [[(names[i] if i >= 0 else None,
                  float(v)) for v, i in zip(vrow, irow)]
                for vrow, irow in zip(vals, ids)]

    def test_sharded_view_matches_view_and_rebuild(self):
        from tfidf_tpu.index import SegmentedIndex
        corpus = make_corpus(10, seed=9)
        seg = SegmentedIndex.from_corpus(corpus, CFG, delta_docs=4)
        seg.add_docs(["extra1", "extra2"],
                     ["alpha kappa pi", "beta beta mu"])
        seg.delete_docs(["doc3", "doc7"])
        view = seg.view()
        queries = ["alpha beta", "kappa pi", "mu"]
        vv, vi = view.search(queries, k=6)
        for shards in (2, 4):
            sharded = shard_index(view, make_serving_plan(shards))
            sv, si = sharded.search(queries, k=6)
            # identical padded-row index space -> exact equality
            assert np.array_equal(vv, sv), shards
            assert np.array_equal(vi, si), shards
        # and the from-scratch rebuild agrees on (name, score) rows
        rebuild = seg.rebuild_retriever()
        rv, ri = rebuild.search(queries, k=6)
        assert self._names_scores(sharded.names, sv, si) == \
            self._names_scores(rebuild.names, rv, ri)

    def test_all_deleted_shard(self):
        from tfidf_tpu.index import SegmentedIndex
        # Base segment (4 rows) + delta (4 rows) -> 8 padded rows;
        # over 2 shards, deleting every base doc leaves shard 0 with
        # ZERO live rows — it must contribute only sentinel
        # candidates, never displace a live doc.
        corpus = make_corpus(4, seed=10)
        seg = SegmentedIndex.from_corpus(corpus, CFG, delta_docs=4)
        seg.add_docs(["n1", "n2", "n3"],
                     ["alpha beta gamma", "delta epsilon", "zeta pi"])
        seg.delete_docs([f"doc{i}" for i in range(1, 5)])
        view = seg.view()
        sharded = shard_index(view, make_serving_plan(2))
        live_rows = int(np.asarray(
            [r for p in view._parts for r in np.asarray(p.live)]
        ).reshape(-1)[:4].sum())
        assert live_rows == 0   # the premise: shard 0 is all dead
        queries = ["alpha beta", "zeta", "epsilon delta"]
        vv, vi = view.search(queries, k=5)
        sv, si = sharded.search(queries, k=5)
        assert np.array_equal(vv, sv) and np.array_equal(vi, si)
        rebuild = seg.rebuild_retriever()
        rv, ri = rebuild.search(queries, k=5)
        assert self._names_scores(sharded.names, sv, si) == \
            self._names_scores(rebuild.names, rv, ri)


def quick_cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("cache_entries", 0)
    return ServeConfig(**kw)


@needs_devices(4)
class TestServeIntegration:
    """TfidfServer under --mesh-shards: every install path re-shards,
    every response stays bit-identical."""

    def test_submit_parity_and_sharded_install(self):
        corpus = make_corpus(9, seed=11)
        single = TfidfRetriever(CFG).index(corpus)
        with TfidfServer(single, quick_cfg(mesh_shards=2)) as server:
            _, installed = server.current_index()
            assert isinstance(installed, MeshShardedRetriever)
            assert installed.n_shards == 2
            queries = ["alpha beta", "kappa", "mu nu"]
            sv, si = server.search(queries, k=4)
            dv, di = single.search(queries, k=4)
            assert np.array_equal(sv, dv) and np.array_equal(si, di)

    def test_mesh_shards_zero_means_all_devices(self):
        corpus = make_corpus(4, seed=12)
        single = TfidfRetriever(CFG).index(corpus)
        with TfidfServer(single, quick_cfg(mesh_shards=0)) as server:
            _, installed = server.current_index()
            assert installed.n_shards == len(jax.devices())

    def test_swap_reshards_and_holds_parity(self):
        single = TfidfRetriever(CFG).index(make_corpus(8, seed=13))
        with TfidfServer(single, quick_cfg(mesh_shards=2)) as server:
            fresh = TfidfRetriever(CFG).index(make_corpus(11, seed=14))
            epoch = server.swap_index(fresh)
            assert epoch == 1
            _, installed = server.current_index()
            assert isinstance(installed, MeshShardedRetriever)
            assert installed._num_docs == 11
            sv, si = server.search(["alpha", "pi kappa"], k=5)
            dv, di = fresh.search(["alpha", "pi kappa"], k=5)
            assert np.array_equal(sv, dv) and np.array_equal(si, di)

    def test_mutation_installs_sharded_views(self):
        from tfidf_tpu.index import SegmentedIndex
        seg = SegmentedIndex.from_corpus(make_corpus(6, seed=15), CFG,
                                         delta_docs=4)
        with TfidfServer(seg.view(),
                         quick_cfg(mesh_shards=2)) as server:
            server.attach_segments(seg)
            out = server.add_docs(["fresh1"], ["alpha omicron pi"])
            assert out["epoch"] == 1
            _, installed = server.current_index()
            assert isinstance(installed, MeshShardedRetriever)
            sv, si = server.search(["alpha omicron"], k=4)
            rebuild = seg.rebuild_retriever()
            rv, ri = rebuild.search(["alpha omicron"], k=4)
            names = installed.names
            assert np.array_equal(sv, rv)
            assert [names[i] if i >= 0 else None for i in si[0]] == \
                [rebuild.names[i] if i >= 0 else None for i in ri[0]]
            out = server.delete_docs(["fresh1"])
            assert out["deleted"] == 1 and out["epoch"] == 2
            sv2, _ = server.search(["alpha omicron"], k=4)
            rv2, _ = seg.rebuild_retriever().search(["alpha omicron"],
                                                    k=4)
            assert np.array_equal(sv2, rv2)

    def test_snapshot_and_restore_round_trip(self, tmp_path):
        single = TfidfRetriever(CFG).index(make_corpus(7, seed=16))
        snap = str(tmp_path / "snap")
        with TfidfServer(single, quick_cfg(mesh_shards=2,
                                           snapshot_dir=snap)) as server:
            server.snapshot()
            sv, si = server.search(["alpha beta"], k=4)
        restored, meta = TfidfRetriever.restore(snap, CFG)
        with TfidfServer(restored, quick_cfg(mesh_shards=2)) as server2:
            rv, ri = server2.search(["alpha beta"], k=4)
        assert np.array_equal(sv, rv) and np.array_equal(si, ri)

    def test_canary_oracle_is_single_device_source(self):
        single = TfidfRetriever(CFG).index(make_corpus(8, seed=17))
        with TfidfServer(single, quick_cfg(mesh_shards=2)) as server:
            _, installed = server.current_index()
            assert installed.parity_oracle() is single
            canary = CanaryProber(server, ["alpha beta", "kappa pi"],
                                  k=3, period_s=30)
            try:
                # capture ran at construction against the SOURCE; the
                # probe replays through the sharded path — 1.0 IS the
                # sharded-vs-single-device parity pin, live.
                assert canary.probe() == 1.0
                fresh = TfidfRetriever(CFG).index(
                    make_corpus(10, seed=18))
                server.swap_index(fresh)
                assert canary.probe() == 1.0
            finally:
                canary.close()

    def test_shard_balance_gauges_and_census(self):
        single = TfidfRetriever(CFG).index(make_corpus(8, seed=19))
        with TfidfServer(single, quick_cfg(mesh_shards=4)) as server:
            mon = devmon.DeviceMonitor(
                registry=server.metrics.registry)
            server.attach_device_monitor(mon)
            snap = mon.sample()
            shards = snap["shards"]
            assert shards["n_shards"] == 4
            assert all(b > 0 for b in shards["shard_bytes"])
            reg = server.metrics.registry.snapshot()
            for i in range(4):
                assert reg[f"shard_bytes_d{i}"]["value"] > 0
            assert reg["shard_imbalance_milli"]["value"] == 1000
            # the install is an edge: exactly one shard_balance event
            events = [e for e in obs.get_log().events()
                      if e.get("event") == "shard_balance"]
            assert len(events) == 1
            mon.sample()   # unchanged bytes -> no second event
            events = [e for e in obs.get_log().events()
                      if e.get("event") == "shard_balance"]
            assert len(events) == 1
            # the census attributes the sharded arrays to the index
            census = mon.census()
            assert census["owners"]["resident_index"]["bytes"] > 0

    def test_zero_recompiles_after_bucket_warm(self):
        single = TfidfRetriever(CFG).index(make_corpus(8, seed=20))
        cfg = quick_cfg(mesh_shards=2)
        with TfidfServer(single, cfg) as server:
            _, installed = server.current_index()
            b = 1
            while b <= cfg.max_batch:
                installed.search([""] * b, k=3)
                b *= 2
            warm = mesh_search_cache_size()
            server.mark_warm()
            for nq in (1, 2, 3, 5, 8):
                server.search([f"alpha {WORDS[nq]}"] * nq, k=3)
            assert mesh_search_cache_size() == warm
            assert server.compile_watch.recompile_count == 0


class TestDoctorShards:
    """The doctor's shards section + --shard-imbalance budget, from
    fixture evidence (no jax needed by the tool itself)."""

    def _fixture_trace(self, tmp_path):
        t = obs.Tracer()
        obs.set_tracer(t, None)
        with obs.span("dispatch", chunk=0, bytes=1024):
            time.sleep(0.001)
        trace = str(tmp_path / "fixture.json")
        t.export(trace)
        return trace

    def _fixture_flight(self, tmp_path, imbalance):
        log = obs.get_log()
        log.info("shard_balance", n_shards=2,
                 shard_bytes=[1000, 3000], imbalance=imbalance,
                 msg="fixture")
        flight = str(tmp_path / "fixture.flight.jsonl")
        log.dump(flight)
        return flight

    def test_shards_section_and_budget_exit(self, tmp_path):
        trace = self._fixture_trace(tmp_path)
        flight = self._fixture_flight(tmp_path, imbalance=1.5)
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--flight", flight],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "shards: 2 docs-shards" in out.stdout
        assert "imbalance 1.500" in out.stdout
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--flight", flight,
             "--shard-imbalance", "1.25"],
            capture_output=True, text=True)
        assert out.returncode == 1, out.stdout + out.stderr
        assert "shard imbalance" in out.stdout
        out = subprocess.run(
            [sys.executable, DOCTOR, trace, "--flight", flight,
             "--shard-imbalance", "2.0"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_newest_event_wins(self, tmp_path):
        log = obs.get_log()
        log.info("shard_balance", n_shards=2,
                 shard_bytes=[100, 100], imbalance=1.0, msg="old")
        log.info("shard_balance", n_shards=4,
                 shard_bytes=[50, 50, 50, 50], imbalance=1.0,
                 msg="new")
        flight = str(tmp_path / "f.flight.jsonl")
        log.dump(flight)
        doctor = _load_tool("doctor")
        rep = doctor.analyze_flight(flight)
        assert rep["shards"]["n_shards"] == 4
        assert rep["shards"]["installs_seen"] == 2


class TestLedgerGate:
    """kind=mesh_serve in the perf trajectory + its directional gates."""

    def _artifact(self, tmp_path, **over):
        art = {
            "metric": "serve_bench", "mode": "closed",
            "backend": "cpu", "docs": 2048, "k": 10, "requests": 256,
            "concurrency": 8, "max_batch": 64,
            "throughput_qps": 3000.0, "throughput_rps": 1200.0,
            "latency_ms": {"p50": 0.03, "p95": 30.0, "p99": 70.0},
            "cache": {"hit_rate": 0.9},
            "recompiles_after_warmup": 0,
            "slo": {"compliance": 1.0},
            "mesh": {"n_shards": 2, "shard_bytes": [100, 100],
                     "shard_imbalance": 1.0, "parity_checked": 16,
                     "parity_ok": 1},
        }
        mesh_over = over.pop("mesh", {})
        art.update(over)
        art["mesh"].update(mesh_over)
        path = tmp_path / "MESH_fixture.json"
        path.write_text(json.dumps(art))
        return str(path)

    def test_normalize_classifies_mesh_serve(self, tmp_path):
        perf_ledger = _load_tool("perf_ledger")
        rec, reason = perf_ledger.normalize(self._artifact(tmp_path))
        assert reason is None
        assert rec["kind"] == "mesh_serve"
        assert rec["metrics"]["parity_ok"] == 1
        assert rec["metrics"]["shard_imbalance"] == 1.0
        assert rec["context"]["n_shards"] == 2

    def test_committed_artifact_is_in_repo_and_gated(self):
        perf_ledger = _load_tool("perf_ledger")
        perf_gate = _load_tool("perf_gate")
        art = os.path.join(REPO, "MESH_SERVE_r01.json")
        assert os.path.exists(art)
        cand, reason = perf_ledger.normalize(art)
        assert reason is None and cand["kind"] == "mesh_serve"
        assert cand["metrics"]["parity_ok"] == 1
        assert cand["metrics"]["recompiles_after_warmup"] == 0
        ledger = perf_ledger.load_ledger(
            os.path.join(REPO, "BENCH_LEDGER.jsonl"))
        assert any(r["kind"] == "mesh_serve" for r in ledger)
        verdict = perf_gate.gate(cand, ledger)
        assert verdict["baseline_runs"] >= 1
        assert verdict["ok"], verdict

    def test_gate_flags_parity_and_qps_regressions(self, tmp_path):
        perf_ledger = _load_tool("perf_ledger")
        perf_gate = _load_tool("perf_gate")
        base, _ = perf_ledger.normalize(self._artifact(tmp_path))
        ledger = [base]

        bad_parity, _ = perf_ledger.normalize(
            self._artifact(tmp_path, mesh={"parity_ok": 0}))
        verdict = perf_gate.gate(bad_parity, ledger)
        assert not verdict["ok"]
        assert any(c["metric"] == "parity_ok"
                   and c["verdict"] == "REGRESSED"
                   for c in verdict["checks"])

        slow, _ = perf_ledger.normalize(
            self._artifact(tmp_path, throughput_qps=1000.0))
        verdict = perf_gate.gate(slow, ledger)
        assert not verdict["ok"]

        recompiled, _ = perf_ledger.normalize(
            self._artifact(tmp_path, recompiles_after_warmup=2))
        assert not perf_gate.gate(recompiled, ledger)["ok"]

        unchanged, _ = perf_ledger.normalize(self._artifact(tmp_path))
        assert perf_gate.gate(unchanged, ledger)["ok"]

    def test_different_shard_counts_not_comparable(self, tmp_path):
        perf_ledger = _load_tool("perf_ledger")
        perf_gate = _load_tool("perf_gate")
        base, _ = perf_ledger.normalize(self._artifact(tmp_path))
        four, _ = perf_ledger.normalize(
            self._artifact(tmp_path, mesh={"n_shards": 4}))
        assert perf_gate.gate(four, [base])["baseline_runs"] == 0


@pytest.mark.slow
class TestMeshServeBenchSmoke:
    """End-to-end: tools/serve_bench.py --mesh-shards over the virtual
    CPU mesh; pins the MESH artifact schema + both zero-tolerance
    receipts."""

    def test_artifact_schema_parity_and_zero_recompiles(self, tmp_path):
        out = tmp_path / "MESH_smoke.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "serve_bench.py"),
             "--requests", "64", "--docs", "128", "--doc-len", "32",
             "--mesh-shards", "2", "--out", str(out)],
            capture_output=True, text=True, timeout=540, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        art = json.loads(out.read_text())
        mesh = art["mesh"]
        assert mesh["n_shards"] == 2
        assert len(mesh["shard_bytes"]) == 2
        assert all(b > 0 for b in mesh["shard_bytes"])
        assert mesh["shard_imbalance"] == pytest.approx(1.0)
        assert mesh["parity_checked"] == 16
        assert mesh["parity_ok"] == 1
        assert art["recompiles_after_warmup"] == 0
        assert art["throughput_qps"] > 0
