"""Per-request forensics (ISSUE 11): request ids, phase breakdowns,
slow-query events, exemplars, SLO burn, and cross-process federation.

The tentpole invariants pinned here:

* every admitted request carries a unique rid, stamped on its spans
  (request/queued directly, batched/device via the batch's ``rids``)
  and returned on the Future — trace, flight and response join on one
  key, under the same 8-thread stress the serve parity suite runs;
* a fault-stalled slow request emits a ``slow_query`` flight event
  whose phase breakdown reconciles with the request's spans within
  5% + 5 ms, and ``tools/doctor.py --request RID`` renders its full
  causal timeline with rc 0 (the end-to-end forensic join);
* exemplars ride ``LatencyHistogram.merge`` (replica aggregation) and
  the Prometheus exposition (OpenMetrics ``# {rid=...}`` syntax);
* the SLO tracker's burn rates degrade health on a fast burn and
  recover when the window rolls clean;
* ``obs_export`` bundles round-trip through
  ``MetricsRegistry.import_state`` and ``tools/obs_agg.py`` renders a
  merged view whose histogram counts equal the per-process sum with
  at least one exemplar surviving (the two-live-servers acceptance is
  the slow-marked TCP test).
"""

import importlib.util
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tfidf_tpu import obs
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.obs import reqtrace
from tfidf_tpu.obs.log import EventLog
from tfidf_tpu.obs.registry import MetricsRegistry
from tfidf_tpu.obs.slo import SloTracker
from tfidf_tpu.serve import TfidfServer
from tfidf_tpu.utils.timing import LatencyHistogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig grape"])
QUERIES = ["apple cherry", "banana", "grape date", "fig", "elder"]


@pytest.fixture(scope="module")
def retriever():
    return TfidfRetriever(CFG).index(CORPUS)


@pytest.fixture(autouse=True)
def _clean_obs():
    """Fresh tracer/log/reqtrace state per test; nothing leaks into
    the rest of the suite."""
    from tfidf_tpu.obs import log as obs_log_mod
    obs.set_tracer(None)
    obs.set_log(EventLog(echo="off"))
    reqtrace.configure(None)
    flight_was = obs_log_mod._flight
    yield
    obs.set_tracer(None)
    obs.set_log(None)
    reqtrace.configure(None)
    obs_log_mod._flight = flight_was   # no tmp-path dump leakage


def quick_cfg(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 2)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_entries", 64)
    return ServeConfig(**kw)


def _load_tool(name):
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestRidMinting:
    def test_rids_unique_and_compact(self):
        rids = {reqtrace.next_rid() for _ in range(10_000)}
        assert len(rids) == 10_000
        rid = next(iter(rids))
        assert rid.startswith("r") and "-" in rid
        assert len(rid) <= 24

    def test_disabled_mints_nothing(self, monkeypatch):
        reqtrace.configure(False)
        assert reqtrace.start(1, 2) is None
        reqtrace.configure(None)
        monkeypatch.setenv("TFIDF_TPU_REQTRACE", "off")
        assert not reqtrace.enabled()
        reqtrace.configure(None)
        monkeypatch.delenv("TFIDF_TPU_REQTRACE")
        assert reqtrace.enabled()

    def test_finish_without_ctx_is_noop(self):
        assert reqtrace.finish(None, "drained", slow_ms=0.0) is None

    def test_minting_is_cheap(self):
        """The admission-path cost: start()+finish() (no slow event)
        must stay in the microsecond class — three orders of
        magnitude under the <2% p50 budget at millisecond latencies."""
        n = 20_000
        t0 = time.perf_counter_ns()
        for _ in range(n):
            reqtrace.finish(reqtrace.start(1, 10), "drained")
        per_us = (time.perf_counter_ns() - t0) / n / 1e3
        assert per_us < 50, f"start+finish costs {per_us:.1f} us"


class TestRidStamping:
    def test_rid_on_spans_digest_and_future(self, retriever):
        tracer = obs.Tracer()
        obs.set_tracer(tracer)
        srv = TfidfServer(retriever, quick_cfg(cache_entries=0))
        try:
            fut = srv.submit(QUERIES[:2], k=3)
            fut.result(timeout=10)
        finally:
            srv.close(drain=True)
        rid = fut.rid
        assert rid
        by_name = {}
        for name, _tid, _t0, _dur, args in tracer.events():
            by_name.setdefault(name, []).append(args or {})
        assert by_name["request"][0]["rid"] == rid
        assert by_name["queued"][0]["rid"] == rid
        assert rid in by_name["batched"][0]["rids"]
        assert rid in by_name["device"][0]["rids"]
        digests = [d for d in obs.get_log().digests()
                   if d.get("rid") == rid]
        assert len(digests) == 1
        assert digests[0]["outcome"] == "drained"

    def test_reqtrace_off_stamps_nothing(self, retriever):
        reqtrace.configure(False)
        tracer = obs.Tracer()
        obs.set_tracer(tracer)
        srv = TfidfServer(retriever, quick_cfg(cache_entries=0))
        try:
            fut = srv.submit(QUERIES[:1], k=2)
            fut.result(timeout=10)
        finally:
            srv.close(drain=True)
        assert fut.rid is None
        for name, _tid, _t0, _dur, args in tracer.events():
            args = args or {}
            assert "rid" not in args and "rids" not in args

    def test_stress_rids_unique_and_join(self, retriever, tmp_path):
        """8 threads x mixed sizes (the serve stress shape): every
        request span carries a UNIQUE rid, every future's rid matches
        a request span, queued rids are request rids, and trace_check
        validates the rid invariants on the exported trace."""
        tracer = obs.Tracer()
        obs.set_tracer(tracer)
        srv = TfidfServer(retriever, quick_cfg(max_wait_ms=1,
                                               cache_entries=0))
        fut_rids = []
        errors = []
        lock = threading.Lock()

        def work(tid):
            try:
                rng = np.random.default_rng(tid)
                for _ in range(5):
                    qs = [QUERIES[i] for i in rng.integers(
                        0, len(QUERIES), size=int(rng.integers(1, 4)))]
                    fut = srv.submit(qs, k=3)
                    fut.result(timeout=30)
                    with lock:
                        fut_rids.append(fut.rid)
            except Exception as e:  # noqa: BLE001 — surface in-main
                errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        srv.close(drain=True)
        assert not errors
        assert len(fut_rids) == 40
        assert len(set(fut_rids)) == 40           # unique per request
        span_rids = [(args or {}).get("rid")
                     for name, _t, _t0, _d, args in tracer.events()
                     if name == "request"]
        assert sorted(span_rids) == sorted(fut_rids)
        queued_rids = {(args or {}).get("rid")
                       for name, _t, _t0, _d, args in tracer.events()
                       if name == "queued"}
        assert queued_rids <= set(fut_rids)
        # The exported trace passes trace_check's rid invariants.
        path = str(tmp_path / "stress.json")
        tracer.export(path)
        tc = _load_tool("trace_check")
        errs, notes = tc.check_trace(path, mode="serve",
                                     min_threads=2)
        assert errs == [], (errs, notes)
        assert any("request ids" in n for n in notes)

    def test_trace_check_flags_duplicate_rids(self, tmp_path):
        doc = {"traceEvents": [
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "main"}},
            {"ph": "X", "pid": 1, "tid": 0, "name": "request",
             "ts": 0, "dur": 5,
             "args": {"outcome": "drained", "rid": "rX-1"}},
            {"ph": "X", "pid": 1, "tid": 0, "name": "request",
             "ts": 10, "dur": 5,
             "args": {"outcome": "drained", "rid": "rX-1"}},
        ]}
        path = tmp_path / "dup.json"
        path.write_text(json.dumps(doc))
        tc = _load_tool("trace_check")
        errs, _notes = tc.check_trace(str(path), mode="serve",
                                      min_threads=1)
        assert any("duplicate request ids" in e for e in errs)


class TestSlowQueryLog:
    def _serve_slow(self, retriever, **cfg_kw):
        """One stalled request (device_dispatch sleep fault) through a
        slow-query-armed server; returns (future, tracer)."""
        tracer = obs.Tracer()
        obs.set_tracer(tracer)
        cfg_kw.setdefault("slow_ms", 10.0)
        srv = TfidfServer(retriever, quick_cfg(
            cache_entries=0,
            faults="device_dispatch:sleep:s=0.06:n=1", **cfg_kw))
        try:
            fut = srv.submit(QUERIES[:2], k=3)
            fut.result(timeout=30)
        finally:
            srv.close(drain=True)
        return fut, tracer

    def test_slow_query_event_and_breakdown(self, retriever):
        fut, tracer = self._serve_slow(retriever)
        events = [e for e in obs.get_log().events()
                  if e.get("event") == "slow_query"]
        assert len(events) == 1
        ev = events[0]
        assert ev["rid"] == fut.rid
        assert ev["outcome"] == "drained"
        assert ev["batch"] is not None
        assert ev["co_occupants"] >= 2
        assert ev["epoch"] == 0
        bd = ev["breakdown"]
        assert set(bd) == set(reqtrace.PHASES)
        assert bd["device"] >= 50.0      # the injected 60 ms stall
        assert bd["total"] >= bd["device"]
        # The breakdown reconciles with the request's spans: phases
        # and spans record the same intervals (the acceptance's
        # 5% + 5 ms bound).
        spans = {}
        for name, _tid, _t0, dur, args in tracer.events():
            args = args or {}
            if args.get("rid") == fut.rid \
                    or fut.rid in (args.get("rids") or ()):
                spans.setdefault(name, []).append(dur / 1e6)  # ms
        tol = lambda ms: 0.05 * ms + 5.0  # noqa: E731
        assert abs(bd["total"] - spans["request"][0]) \
            <= tol(spans["request"][0])
        assert abs(bd["queue_wait"] - spans["queued"][0]) \
            <= tol(spans["queued"][0])
        assert abs(bd["device"] - spans["device"][0]) \
            <= tol(spans["device"][0])
        # Phases don't overlap-count: their sum stays near total.
        phase_sum = sum(v for k, v in bd.items() if k != "total")
        assert phase_sum <= bd["total"] + tol(bd["total"])

    def test_slow_queries_counter_and_metric(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(
            cache_entries=0, slow_ms=0.0))  # everything is "slow"
        try:
            srv.search(QUERIES[:1], k=2)
            snap = srv.metrics_snapshot()
        finally:
            srv.close()
        assert snap["slow_queries"] == 1
        assert srv.metrics.registry.get(
            "serve_slow_queries_total").value == 1

    def test_tail_sampling(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(
            cache_entries=0, slow_sample=1))  # sample EVERY request
        try:
            srv.search(QUERIES[:1], k=2)
        finally:
            srv.close()
        events = [e for e in obs.get_log().events()
                  if e.get("event") == "slow_query"]
        assert len(events) == 1
        assert events[0]["sampled"] is True
        assert events[0]["level"] == "info"

    def test_fast_requests_emit_nothing(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(
            cache_entries=0, slow_ms=60_000.0))
        try:
            srv.search(QUERIES[:1], k=2)
        finally:
            srv.close()
        assert not [e for e in obs.get_log().events()
                    if e.get("event") == "slow_query"]


class TestFlightKindReservation:
    def test_kind_field_cannot_tear_the_dump(self, tmp_path):
        """Regression (found driving the round-16 stall path): a
        flight event whose PAYLOAD carries a ``kind`` field — e.g.
        ``fault_injected`` used to log ``kind="sleep"`` — must not
        clobber the dump protocol's event/digest discriminator; the
        dump stays complete and trace_check-valid, with the payload
        preserved under ``field_kind``."""
        log = obs.get_log()
        log.log("warning", "fault_injected", kind="sleep", seam="x")
        log.digest(outcome="drained", kind="weird")
        path = str(tmp_path / "fl.jsonl")
        log.dump(path)
        recs = [json.loads(l) for l in open(path) if l.strip()]
        assert recs[1]["kind"] == "event"
        assert recs[1]["field_kind"] == "sleep"
        assert recs[2]["kind"] == "digest"
        tc = _load_tool("trace_check")
        errs, _notes = tc.check_flight(path)
        assert errs == [], errs

    def test_fault_events_dump_clean(self, retriever, tmp_path):
        """The real emitter: an injected fault's flight event rides a
        dump that validates — the chaos evidence chain stays whole."""
        srv = TfidfServer(retriever, quick_cfg(
            cache_entries=0,
            faults="device_dispatch:transient:n=1"))
        try:
            srv.search(QUERIES[:1], k=2)
        finally:
            srv.close(drain=True)
        events = [e for e in obs.get_log().events()
                  if e.get("event") == "fault_injected"]
        assert events and events[0]["fault_kind"] == "transient"
        path = str(tmp_path / "fl.jsonl")
        obs.get_log().dump(path)
        tc = _load_tool("trace_check")
        errs, _notes = tc.check_flight(path)
        assert errs == [], errs


class TestDoctorForensics:
    def test_request_timeline_and_slowest_table(self, retriever,
                                                tmp_path):
        fut, tracer = TestSlowQueryLog()._serve_slow(retriever)
        trace = str(tmp_path / "t.json")
        flight = str(tmp_path / "t.json.flight.jsonl")
        tracer.export(trace)
        obs.get_log().dump(flight)
        doctor = _load_tool("doctor")
        # Default report: the slowest-requests table carries the rid.
        report = doctor.analyze_trace(trace)
        slowest = report["slowest_requests"]
        assert slowest and slowest[0]["rid"] == fut.rid
        assert slowest[0]["ms"] >= 50.0
        # --request RID: the full causal timeline renders.
        rep = doctor.request_timeline(trace, flight, fut.rid)
        assert rep is not None
        span_names = {r["span"] for r in rep["spans"]}
        assert {"request", "queued", "batched", "device"} <= span_names
        assert rep["breakdown"]["device"] >= 50.0
        assert any(e.get("event") == "slow_query"
                   for e in rep["flight_events"])
        assert rep["digests"] and rep["digests"][0]["rid"] == fut.rid
        text = doctor.render_request(rep)
        assert fut.rid in text and "breakdown" in text
        # Unknown rid: None (the CLI exits 2 there).
        assert doctor.request_timeline(trace, flight, "r-nope") is None

    def test_doctor_request_subprocess_rc0(self, retriever, tmp_path):
        """The acceptance join, CLI-shaped: doctor --request RID on
        the dumped evidence exits 0 and renders the timeline."""
        fut, tracer = TestSlowQueryLog()._serve_slow(retriever)
        trace = str(tmp_path / "t.json")
        flight = str(tmp_path / "fl.jsonl")
        tracer.export(trace)
        obs.get_log().dump(flight)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "doctor.py"),
             trace, "--flight", flight, "--request", fut.rid],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert fut.rid in proc.stdout
        assert "slow_query" in proc.stdout
        # An unknown rid is unreadable evidence: rc 2.
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "doctor.py"),
             trace, "--flight", flight, "--request", "r-nope"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == 2


class TestExemplars:
    def test_record_and_merge_round_trip(self):
        a = LatencyHistogram(exemplars=True)
        b = LatencyHistogram(exemplars=True)
        a.record(0.010, exemplar="rA-1")
        a.record(0.500, exemplar="rA-2")
        b.record(0.011, exemplar="rB-1")
        b.record(5.000, exemplar="rB-2")
        a.merge(b)
        got = dict((rid, secs) for secs, rid in a.exemplars())
        # rB-1 lands in (and takes over) the same bucket as rA-1; the
        # distinct-latency exemplars all survive the merge.
        assert {"rA-2", "rB-1", "rB-2"} <= set(got)
        assert a.count == 4

    def test_state_dict_round_trip(self):
        h = LatencyHistogram(exemplars=True)
        for i, v in enumerate((0.001, 0.002, 0.02, 0.3)):
            h.record(v, exemplar=f"r-{i}")
        h2 = LatencyHistogram.from_state(h.state_dict())
        assert h2.count == h.count
        assert h2.sum_seconds == pytest.approx(h.sum_seconds)
        assert h2.min == h.min and h2.max == h.max
        for p in (50, 95, 99):
            assert h2.percentile(p) == h.percentile(p)
        assert h2.exemplars() == h.exemplars()
        # And it merges with a live histogram (same geometry).
        h.merge(h2)
        assert h.count == 8

    def test_prometheus_openmetrics_exemplar_syntax(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "latency", exemplars=True)
        h.observe(0.004, exemplar="rE-1")
        h.observe(2.0, exemplar="rE-2")
        text = reg.render_prom()
        assert '# {rid="rE-1"}' in text
        assert '# {rid="rE-2"}' in text
        # Exemplars attach to bucket lines, not the sum/count.
        for line in text.splitlines():
            if "# {rid=" in line:
                assert "_bucket{le=" in line
        # Snapshot exposes them too.
        snap = reg.snapshot()["lat_seconds"]
        assert {e["rid"] for e in snap["exemplars"]} \
            == {"rE-1", "rE-2"}

    def test_serve_latency_exemplar_is_the_rid(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(cache_entries=0))
        try:
            fut = srv.submit(QUERIES[:1], k=2)
            fut.result(timeout=10)
            text = srv.metrics_prom()
            snap = srv.metrics_snapshot()
        finally:
            srv.close()
        assert f'# {{rid="{fut.rid}"}}' in text
        assert any(e["rid"] == fut.rid
                   for e in snap["latency_s"]["exemplars"])


class TestSloTracker:
    def _tracker(self, **kw):
        clock = [1000.0]
        kw.setdefault("objective_ms", 100.0)
        kw.setdefault("target", 0.9)       # budget = 10%
        kw.setdefault("fast_window_s", 60)
        kw.setdefault("slow_window_s", 600)
        kw.setdefault("min_count", 5)
        t = SloTracker(clock=lambda: clock[0], **kw)
        return t, clock

    def test_compliance_and_burn(self):
        t, clock = self._tracker()
        for _ in range(8):
            t.record(0.050)     # good
        for _ in range(2):
            t.record(0.500)     # bad
        assert t.compliance() == pytest.approx(0.8)
        # bad rate 0.2 over budget 0.1 -> burn 2.0
        assert t.burn_rate(60) == pytest.approx(2.0)
        snap = t.snapshot()
        assert snap["good"] == 8 and snap["total"] == 10
        assert snap["fast_burn"] == pytest.approx(2.0)
        # Windows roll: 700 s later everything has aged out.
        clock[0] += 700
        assert t.compliance() == 1.0
        assert t.burn_rate(60) == 0.0

    def test_health_signal_degrades_and_recovers(self):
        t, clock = self._tracker(fast_burn_degraded=2.0)
        for _ in range(10):
            t.record(0.500)     # all bad: burn 10x
        value, reason = t.health_signal()
        assert value >= 2.0
        assert reason and "SLO fast burn" in reason
        # Below min_count no single outlier degrades.
        t2, _ = self._tracker(fast_burn_degraded=2.0)
        t2.record(0.500)
        _value, reason2 = t2.health_signal()
        assert reason2 is None
        # Recovery: the fast window rolls clean.
        clock[0] += 120
        _value, reason3 = t.health_signal()
        assert reason3 is None

    def test_gauges_publish(self):
        reg = MetricsRegistry()
        clock = [50.0]
        t = SloTracker(objective_ms=100, target=0.9, registry=reg,
                       clock=lambda: clock[0])
        t.record(0.500)
        t.snapshot()
        snap = reg.snapshot()
        assert snap["serve_slo_fast_burn_milli"]["value"] == 10_000
        assert snap["serve_slo_compliance_milli"]["value"] == 0

    def test_validation(self):
        with pytest.raises(ValueError):
            SloTracker(objective_ms=0)
        with pytest.raises(ValueError):
            SloTracker(objective_ms=10, target=1.0)
        with pytest.raises(ValueError):
            ServeConfig(slo_target=0.0)
        with pytest.raises(ValueError):
            ServeConfig(slo_ms=-1)
        with pytest.raises(ValueError):
            ServeConfig(slow_sample=-1)

    def test_serve_config_env_mirrors(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_SLOW_MS", "125")
        monkeypatch.setenv("TFIDF_TPU_SLOW_SAMPLE", "64")
        monkeypatch.setenv("TFIDF_TPU_SLO_MS", "50")
        monkeypatch.setenv("TFIDF_TPU_SLO_TARGET", "0.95")
        cfg = ServeConfig.from_env()
        assert (cfg.slow_ms, cfg.slow_sample, cfg.slo_ms,
                cfg.slo_target) == (125.0, 64, 50.0, 0.95)
        assert ServeConfig.from_env(slo_ms=75.0).slo_ms == 75.0

    def test_fast_burn_degrades_admission(self, retriever):
        """The feedback loop: a server blowing its objective goes
        degraded and its admission bound shrinks — the same path
        memory pressure drives."""
        srv = TfidfServer(retriever, quick_cfg(
            cache_entries=0, slo_ms=0.001, slo_target=0.9))
        # objective 1 us: every request is "bad" -> fast burn 10x.
        try:
            srv.slo.min_count = 5
            for _ in range(6):
                srv.search(QUERIES[:1], k=2)
            status = srv.health.evaluate()
            assert status.state == "degraded"
            assert any("SLO fast burn" in r for r in status.reasons)
            bound = srv.health.admission_bound(
                srv.config.queue_depth)
            assert bound < srv.config.queue_depth
        finally:
            srv.close()


class TestObsFederation:
    def test_obs_export_bundle_round_trip(self, retriever):
        srv = TfidfServer(retriever, quick_cfg(slo_ms=1000.0))
        try:
            srv.search(QUERIES[:2], k=3)
            bundle = srv.obs_export()
            direct = srv.metrics.registry.snapshot()
        finally:
            srv.close()
        assert bundle["schema"] == "tfidf-obs/1"
        assert bundle["epoch"] == 0
        json.dumps(bundle)   # wire-serializable end to end
        rebuilt = MetricsRegistry.import_state(bundle["registry"])
        snap = rebuilt.snapshot()
        assert snap["serve_requests_total"] \
            == direct["serve_requests_total"]
        lat = snap["serve_request_latency_seconds"]
        assert lat["count"] == 1
        assert lat["p50"] == pytest.approx(
            direct["serve_request_latency_seconds"]["p50"])
        assert lat["exemplars"]     # the rid survived the wire

    def test_merge_counts_are_sums(self, retriever):
        bundles = {}
        for i, n in enumerate((1, 2)):
            srv = TfidfServer(retriever, quick_cfg())
            try:
                for _ in range(n):
                    srv.submit(QUERIES[:1], k=2,
                               use_cache=False).result(timeout=10)
                bundles[f"p{i}"] = srv.obs_export()
            finally:
                srv.close()
        agg = _load_tool("obs_agg")
        merged, per = agg.merge_bundles(bundles)
        snap = merged.snapshot()
        assert snap["serve_requests_total"] == 3
        assert snap["serve_request_latency_seconds"]["count"] == 3
        assert snap["serve_request_latency_seconds"]["exemplars"]
        text = agg.render_prom(merged, per, bundles)
        assert "serve_request_latency_seconds_count 3" in text
        assert 'serve_requests_total{process="p0"} 1' in text
        assert 'serve_requests_total{process="p1"} 2' in text
        assert '# {rid="' in text   # an exemplar survived the merge

    def test_obs_agg_bundles_cli(self, retriever, tmp_path):
        paths = []
        for i in range(2):
            srv = TfidfServer(retriever, quick_cfg())
            try:
                srv.submit(QUERIES[:1], k=2,
                           use_cache=False).result(timeout=10)
                p = tmp_path / f"b{i}.json"
                p.write_text(json.dumps(srv.obs_export()))
                paths.append(str(p))
            finally:
                srv.close()
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs_agg.py"),
             "--bundles", *paths],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "obs_agg_processes 2" in proc.stdout
        assert "serve_requests_total 2" in proc.stdout
        assert 'process="b0.json"' in proc.stdout
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs_agg.py"),
             "--bundles", *paths, "--json"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == 0
        doc = json.loads(proc.stdout)
        assert doc["merged"]["serve_requests_total"] == 2
        assert set(doc["processes"]) == {"b0.json", "b1.json"}

    def test_bundle_schema_mismatch_rejected(self):
        agg = _load_tool("obs_agg")
        with pytest.raises(ValueError, match="schema"):
            agg.validate_bundle({"schema": "tfidf-obs/99",
                                 "registry": {}}, "x")


def _wait_port(port, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=1):
                return True
        except OSError:
            time.sleep(0.2)
    return False


@pytest.mark.slow
class TestTwoProcessAggregation:
    """The acceptance pin: obs_agg over TWO LIVE serve processes
    renders merged Prometheus whose histogram counts equal the sum of
    the per-process snapshots, with per-process labels and at least
    one exemplar surviving the merge."""

    def test_two_live_servers_merge(self, tmp_path):
        d = tmp_path / "input"
        d.mkdir()
        for i, text in enumerate(
                [b"apple banana", b"cherry date", b"elder fig",
                 b"apple grape"], start=1):
            (d / f"doc{i}").write_bytes(text)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TFIDF_TPU_LOG_ECHO="off")
        ports = [19471, 19472]
        procs = []
        try:
            for port in ports:
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "tfidf_tpu.cli", "serve",
                     "--input", str(d), "--vocab-size", "512",
                     "--max-wait-ms", "1", "--port", str(port),
                     "--canary-period-ms", "0",
                     "--health-period-ms", "0",
                     "--devmon-period-ms", "0"],
                    env=env, cwd=REPO, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE))
            for port in ports:
                assert _wait_port(port), "serve process did not bind"
            # Drive a different request count through each process.
            expect = {ports[0]: 1, ports[1]: 2}
            for port, n in expect.items():
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=10) as sock:
                    f = sock.makefile("rw")
                    for i in range(n):
                        f.write(json.dumps(
                            {"id": i,
                             "queries": ["apple banana"]}) + "\n")
                        f.flush()
                        resp = json.loads(f.readline())
                        assert "results" in resp
                        assert resp.get("rid")    # the JSONL rid
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tools", "obs_agg.py"),
                 "--endpoints",
                 ",".join(f"127.0.0.1:{p}" for p in ports)],
                capture_output=True, text=True, timeout=120, cwd=REPO)
            assert proc.returncode == 0, proc.stderr[-2000:]
            out = proc.stdout
            assert "obs_agg_processes 2" in out
            # Merged histogram count == sum of per-process counts.
            assert "serve_request_latency_seconds_count 3" in out
            for port in ports:
                assert f'process="127.0.0.1:{port}"' in out
            assert '# {rid="' in out    # exemplar survived the merge
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestServeCliForensicJoin:
    def test_jsonl_rid_to_slow_query_to_doctor(self, tmp_path,
                                               monkeypatch, capsys):
        """End-to-end acceptance: a fault-stalled request through the
        serve CLI produces a JSONL response rid, a slow_query flight
        event whose breakdown reconciles with the request's spans,
        and doctor --request RID renders the timeline with rc 0."""
        import io

        from tfidf_tpu.cli import main
        d = tmp_path / "input"
        d.mkdir()
        for i, text in enumerate(
                [b"apple banana", b"cherry date", b"elder fig",
                 b"apple grape"], start=1):
            (d / f"doc{i}").write_bytes(text)
        trace = str(tmp_path / "serve.json")
        flight = str(tmp_path / "serve.flight.jsonl")
        lines = [json.dumps({"id": 1, "queries": ["apple banana"],
                             "k": 2}),
                 json.dumps({"op": "shutdown"})]
        monkeypatch.setattr("sys.stdin",
                            io.StringIO("\n".join(lines) + "\n"))
        rc = main(["serve", "--input", str(d), "--vocab-size", "512",
                   "--max-wait-ms", "1", "--slow-ms", "10",
                   "--canary-period-ms", "0",
                   "--faults", "device_dispatch:sleep:s=0.06:n=1",
                   "--trace", trace, "--flight", flight])
        assert rc == 0
        out = capsys.readouterr().out
        resp = next(json.loads(l) for l in out.splitlines()
                    if l and "results" in l)
        rid = resp["rid"]
        assert rid
        # The flight dump carries the slow_query event on the SAME key
        # and its breakdown shows the injected stall in the device
        # phase.
        with open(flight) as f:
            recs = [json.loads(l) for l in f if l.strip()]
        slow = [r for r in recs if r.get("event") == "slow_query"]
        assert slow and slow[0]["rid"] == rid
        assert slow[0]["breakdown"]["device"] >= 50.0
        digests = [r for r in recs if r.get("kind") == "digest"
                   and r.get("rid") == rid]
        assert digests
        # Breakdown-vs-span reconciliation (5% + 5 ms) on the
        # exported trace, then doctor --request renders rc 0.
        events = [e for e in json.load(open(trace))["traceEvents"]
                  if e.get("ph") == "X"
                  and (e.get("args") or {}).get("rid") == rid]
        req_span = next(e for e in events if e["name"] == "request")
        total_ms = slow[0]["breakdown"]["total"]
        span_ms = req_span["dur"] / 1e3
        assert abs(total_ms - span_ms) <= 0.05 * span_ms + 5.0
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "doctor.py"),
             trace, "--flight", flight, "--request", rid],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert rid in proc.stdout
