"""Native runtime tests: bit-reference golden parity + fast tokenizer.

Builds ``native/`` on demand (g++ only; no MPI needed — thread comm
backend). The native binary is the ``--backend=mpi`` oracle: its output
must be byte-identical to both the Python golden oracle and the JAX
pipeline (SURVEY §7 layer 2).
"""

import os
import subprocess

import numpy as np
import pytest

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                          "native")
REF_BIN = os.path.join(NATIVE_DIR, "tfidf_ref")


@pytest.fixture(scope="session", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE_DIR], check=True, capture_output=True)


def run_ref(input_dir, output_path, nranks=3):
    return subprocess.run([REF_BIN, input_dir, str(output_path), str(nranks)],
                          capture_output=True)


class TestBitReference:
    def test_matches_golden_oracle(self, toy_corpus_dir, tmp_path):
        from tfidf_tpu import discover_corpus
        from tfidf_tpu.golden import golden_output

        out = tmp_path / "output.txt"
        proc = run_ref(toy_corpus_dir, out)
        assert proc.returncode == 0, proc.stderr
        assert out.read_bytes() == golden_output(discover_corpus(toy_corpus_dir))

    @pytest.mark.parametrize("nranks", [2, 4, 6])
    def test_rank_count_invariance(self, toy_corpus_dir, tmp_path, nranks):
        # Output must not depend on the parallel degree — the schedule
        # (TFIDF.c:130) only partitions work.
        outs = []
        for tag in ("a", "b"):
            out = tmp_path / f"out_{nranks}_{tag}.txt"
            assert run_ref(toy_corpus_dir, out, nranks).returncode == 0
            outs.append(out.read_bytes())
        ref = tmp_path / "out_ref.txt"
        assert run_ref(toy_corpus_dir, ref, 2).returncode == 0
        assert outs[0] == outs[1] == ref.read_bytes()

    @pytest.mark.parametrize("nranks", [2, 5])
    def test_process_backend_byte_identical(self, toy_corpus_dir,
                                            tmp_path, nranks):
        # Round 4 (VERDICT r3 item 6b): the fork+socketpair PROCESS
        # backend executes the reference's actual deployment model —
        # N OS processes (TFIDF.c:82-92) — and must produce the same
        # bytes as the thread backend and the golden oracle.
        from tfidf_tpu import discover_corpus
        from tfidf_tpu.golden import golden_output

        out = tmp_path / "proc.txt"
        proc = subprocess.run(
            [REF_BIN, toy_corpus_dir, str(out), str(nranks), "process"],
            capture_output=True)
        assert proc.returncode == 0, proc.stderr
        assert out.read_bytes() == golden_output(
            discover_corpus(toy_corpus_dir))

    def test_matches_jax_pipeline(self, toy_corpus_dir, tmp_path):
        from tfidf_tpu import PipelineConfig, TfidfPipeline, discover_corpus

        corpus = discover_corpus(toy_corpus_dir)
        jax_bytes = TfidfPipeline(PipelineConfig.golden()).run(corpus).output_bytes()
        out = tmp_path / "output.txt"
        assert run_ref(toy_corpus_dir, out).returncode == 0
        assert out.read_bytes() == jax_bytes

    def test_worker_guard(self, tmp_path):
        # size-1 > numDocs is a hard error (TFIDF.c:120-123).
        d = tmp_path / "input"
        d.mkdir()
        (d / "doc1").write_bytes(b"only one doc")
        proc = run_ref(str(d), tmp_path / "o.txt", nranks=4)
        assert proc.returncode == 1
        assert b"workers" in proc.stderr


@pytest.mark.skipif(bool(os.environ.get("TFIDF_TPU_NO_NATIVE")),
                    reason="native kill-switch set: these tests assert "
                           "the native path itself")
class TestFastTokenizer:
    def test_available_after_build(self):
        from tfidf_tpu.io import fast_tokenizer
        assert fast_tokenizer.available()

    def test_hash_ids_match_python_path(self):
        from tfidf_tpu.io import fast_tokenizer
        from tfidf_tpu.ops.hashing import words_to_ids
        from tfidf_tpu.ops.tokenize import whitespace_tokenize

        data = b"  the quick\tbrown fox\n jumps over the lazy dog  "
        for vocab, seed in [(1 << 16, 0), (97, 5)]:
            native = fast_tokenizer.tokenize_hash_ids(data, vocab, seed)
            python = words_to_ids(whitespace_tokenize(data), vocab, seed)
            assert native.tolist() == python.tolist()

    def test_truncation_matches(self):
        from tfidf_tpu.io import fast_tokenizer
        from tfidf_tpu.ops.hashing import words_to_ids
        from tfidf_tpu.ops.tokenize import whitespace_tokenize

        data = b"supercalifragilistic word"
        native = fast_tokenizer.tokenize_hash_ids(data, 1 << 16, 0, truncate_at=15)
        python = words_to_ids(whitespace_tokenize(data, truncate_at=15), 1 << 16)
        assert native.tolist() == python.tolist()

    def test_spans_roundtrip(self):
        from tfidf_tpu.io import fast_tokenizer
        from tfidf_tpu.ops.tokenize import whitespace_tokenize

        data = b" alpha\n beta\tgamma "
        assert fast_tokenizer.tokenize_spans(data) == whitespace_tokenize(data)

    def test_native_pack_path_matches_python(self, toy_corpus_dir):
        from tfidf_tpu import PipelineConfig, discover_corpus
        from tfidf_tpu.config import VocabMode
        from tfidf_tpu.io.corpus import pack_corpus

        corpus = discover_corpus(toy_corpus_dir)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=1 << 12)
        fast = pack_corpus(corpus, cfg, want_words=False)
        os.environ["TFIDF_TPU_NO_NATIVE"] = "1"
        try:
            import tfidf_tpu.io.fast_tokenizer as ft
            ft._load_failed = False  # re-evaluate with env var set
            ft._lib = None
            slow = pack_corpus(corpus, cfg, want_words=False)
        finally:
            del os.environ["TFIDF_TPU_NO_NATIVE"]
            ft._load_failed = False
            ft._lib = None
        assert (fast.token_ids == slow.token_ids).all()
        assert (fast.lengths == slow.lengths).all()


class TestParallelLoader:
    """native/loader.cc: thread-pool read+tokenize+hash+pack."""

    def _cfg(self):
        from tfidf_tpu import PipelineConfig
        from tfidf_tpu.config import VocabMode
        return PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=1 << 12,
                              max_doc_len=8, doc_chunk=8)

    def test_matches_python_pack(self, toy_corpus_dir):
        from tfidf_tpu import discover_corpus
        from tfidf_tpu.io.corpus import load_and_pack, pack_corpus
        from tfidf_tpu.io.fast_tokenizer import loader_available

        if not loader_available():
            pytest.skip("native loader not built")
        cfg = self._cfg()
        a = load_and_pack(toy_corpus_dir, cfg)
        b = pack_corpus(discover_corpus(toy_corpus_dir), cfg,
                        want_words=False)
        assert a.token_ids.shape == b.token_ids.shape
        assert (a.token_ids == b.token_ids).all()
        assert (a.lengths == b.lengths).all()
        assert a.names == b.names and a.num_docs == b.num_docs

    def test_mesh_padding(self, toy_corpus_dir):
        from tfidf_tpu.io.corpus import load_and_pack
        from tfidf_tpu.io.fast_tokenizer import loader_available

        if not loader_available():
            pytest.skip("native loader not built")
        batch = load_and_pack(toy_corpus_dir, self._cfg(), pad_docs_to=16)
        assert batch.token_ids.shape[0] == 16
        assert (batch.lengths[batch.num_docs:] == 0).all()
        assert batch.names[-1] == ""

    def test_missing_doc_raises(self, tmp_path):
        from tfidf_tpu.io.corpus import load_and_pack
        from tfidf_tpu.io.fast_tokenizer import loader_available

        if not loader_available():
            pytest.skip("native loader not built")
        (tmp_path / "doc1").write_text("a b c")
        (tmp_path / "doc3").write_text("d")  # strict names doc1,doc2 -> doc2 missing
        with pytest.raises(FileNotFoundError):
            load_and_pack(str(tmp_path), self._cfg())

    def test_fallback_configs_use_python_path(self, toy_corpus_dir):
        from tfidf_tpu import PipelineConfig, discover_corpus
        from tfidf_tpu.config import VocabMode
        from tfidf_tpu.io.corpus import load_and_pack, pack_corpus

        cfg = PipelineConfig(vocab_mode=VocabMode.EXACT)
        a = load_and_pack(toy_corpus_dir, cfg)
        b = pack_corpus(discover_corpus(toy_corpus_dir), cfg,
                        want_words=False)
        assert (a.token_ids == b.token_ids).all()


class TestThreadedFlatPack:
    """loader_fill_flat_u16_v3 (round 14): the ragged packer's
    tokenize+hash fill threaded over the shared ParallelFor pool — the
    reference's OpenMP move (TFIDF_extra.c:69-302) done race-free.
    Output must be bit-identical to the serial v2 fill and the Python
    flatten_aligned layout at every thread count."""

    def _corpus(self, tmp_path, n=23, seed=11):
        rng = np.random.default_rng(seed)
        paths = []
        for i in range(1, n + 1):
            words = [f"w{rng.integers(0, 300)}"
                     for _ in range(int(rng.integers(0, 40)))]
            p = tmp_path / f"doc{i}"
            p.write_text(" ".join(words))
            paths.append(str(p))
        return paths

    @pytest.mark.parametrize("threads", [2, 4, 7])
    def test_threads_match_serial(self, tmp_path, threads):
        from tfidf_tpu.io import fast_tokenizer as ft
        if not ft.flat_available():
            pytest.skip("native flat packer not built")
        paths = self._corpus(tmp_path)
        kw = dict(vocab_size=1 << 12, seed=3, truncate_at=16,
                  max_per_doc=16, pad_docs_to=32, align=16,
                  cap_ids=4096)
        serial = ft.load_pack_flat(paths, n_threads=1, **kw)
        threaded = ft.load_pack_flat(paths, n_threads=threads, **kw)
        assert serial[2] == threaded[2]
        np.testing.assert_array_equal(serial[1], threaded[1])
        # Whole-capacity equality: ids, inter-doc zero pad, AND the
        # bucket tail — the threaded fill's per-doc memsets must leave
        # the identical ship-ready buffer.
        np.testing.assert_array_equal(serial[0], threaded[0])

    def test_threads_match_python_layout(self, tmp_path):
        from tfidf_tpu import PipelineConfig
        from tfidf_tpu.config import VocabMode
        from tfidf_tpu.io import fast_tokenizer as ft
        from tfidf_tpu.io.corpus import pack_corpus, Corpus
        from tfidf_tpu.ingest import flatten_aligned
        if not ft.flat_available():
            pytest.skip("native flat packer not built")
        paths = self._corpus(tmp_path, n=9, seed=4)
        docs = [open(p, "rb").read() for p in paths]
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=1 << 12, max_doc_len=16,
                             doc_chunk=16)
        batch = pack_corpus(
            Corpus(names=[os.path.basename(p) for p in paths],
                   docs=docs), cfg, pad_docs_to=9, want_words=False)
        ids = batch.token_ids[:, :16]
        if ids.shape[1] < 16:
            ids = np.pad(ids, ((0, 0), (0, 16 - ids.shape[1])))
        flat_py, total_py = flatten_aligned(
            ids, np.minimum(batch.lengths, 16).astype(np.int32), 16)
        out = ft.load_pack_flat(paths, 1 << 12, max_per_doc=16,
                                pad_docs_to=9, align=16,
                                cap_ids=4096, n_threads=4)
        assert out[2] == total_py
        np.testing.assert_array_equal(out[0][:total_py],
                                      flat_py[:total_py])

    def test_pack_threads_env_resolution(self, monkeypatch):
        from tfidf_tpu.io import fast_tokenizer as ft
        monkeypatch.setenv("TFIDF_TPU_PACK_THREADS", "5")
        assert ft.resolve_pack_threads() == 5
        assert ft.resolve_pack_threads(2) == 2  # explicit wins


class TestBytesSlabLoader:
    """loader_fill_slab (round 14): the bytes wire's host pack — raw
    doc bytes at aligned offsets, 0x20 fill everywhere else."""

    def test_layout_contract(self, tmp_path):
        from tfidf_tpu.io import fast_tokenizer as ft
        if not ft.slab_available():
            pytest.skip("native slab loader not built")
        docs = [b"alpha beta gamma", b"", b"  x ", b"q" * 33]
        paths = []
        for i, d in enumerate(docs):
            p = tmp_path / f"doc{i + 1}"
            p.write_bytes(d)
            paths.append(str(p))
        slab, blens, total = ft.load_slab_paths(
            paths, pad_docs_to=8, align=16, cap_round=256)
        assert list(blens[:4]) == [len(d) for d in docs]
        assert (blens[4:] == 0).all()
        off = 0
        for d in docs:
            a = (len(d) + 16) // 16 * 16  # >= 1 separator byte
            assert slab[off:off + len(d)].tobytes() == d
            assert (slab[off + len(d):off + a] == 0x20).all()
            off += a
        assert off == total
        assert (slab[total:] == 0x20).all()
        assert slab.size % 256 == 0


class TestHybridOpenMP:
    """The reference's MPI+OpenMP hybrid (TFIDF_extra.c) rebuilt race-free:
    `make tfidf_ref_omp` adds intra-rank thread fan-out over each rank's
    documents and the scoring loop; output must be byte-identical to the
    plain build (the reference's own hybrid races on its shared counters,
    SURVEY §2.5-8 — ours is pinned deterministic here)."""

    def test_omp_build_byte_identical(self, toy_corpus_dir, tmp_path):
        omp_bin = os.path.join(NATIVE_DIR, "tfidf_ref_omp")
        built = subprocess.run(["make", "-C", NATIVE_DIR, "tfidf_ref_omp"],
                               capture_output=True, text=True)
        assert built.returncode == 0, built.stderr
        plain, hybrid = tmp_path / "plain.txt", tmp_path / "omp.txt"
        assert run_ref(toy_corpus_dir, plain, 4).returncode == 0
        env = dict(os.environ, OMP_NUM_THREADS="3")
        proc = subprocess.run(
            [omp_bin, toy_corpus_dir, str(hybrid), "4"],
            capture_output=True, env=env)
        assert proc.returncode == 0, proc.stderr
        assert plain.read_bytes() == hybrid.read_bytes()


class TestMpiCompileCheck:
    def test_mpi_path_typechecks(self):
        """No MPI runtime exists in this image, so the TFIDF_HAVE_MPI
        code path would otherwise be never-compiled dead code (VERDICT
        r1). `make mpi_check` type-checks every MPI call site against
        the stub <mpi.h> — it already caught a missing include once."""
        proc = subprocess.run(["make", "-C", NATIVE_DIR, "mpi_check"],
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr


class TestMpiLiteRuntime:
    """VERDICT r4 item 8: the literal MPI code path must EXECUTE, not
    just type-check. `make mpi_lite` links tfidf_ref's TFIDF_HAVE_MPI
    build against the vendored mpi_lite runtime (pairwise socketpairs)
    and `mpirun_lite -np N` launches real OS-process ranks."""

    @pytest.fixture(scope="class", autouse=True)
    def build(self):
        proc = subprocess.run(["make", "-C", NATIVE_DIR, "mpi_lite"],
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr

    @pytest.mark.parametrize("nranks", [2, 3, 5])
    def test_mpi_ranks_byte_identical(self, toy_corpus_dir, tmp_path,
                                      nranks):
        from tfidf_tpu import discover_corpus
        from tfidf_tpu.golden import golden_output

        out = tmp_path / f"mpi_{nranks}.txt"
        proc = subprocess.run(
            [os.path.join(NATIVE_DIR, "mpirun_lite"), "-np", str(nranks),
             os.path.join(NATIVE_DIR, "tfidf_ref_mpi"),
             toy_corpus_dir, str(out)],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert out.read_bytes() == golden_output(
            discover_corpus(toy_corpus_dir))

    def test_unlaunched_binary_fails_loudly(self, toy_corpus_dir,
                                            tmp_path):
        # Running the MPI binary without the launcher must not
        # silently fall back to anything — MPI_Init exits 2.
        proc = subprocess.run(
            [os.path.join(NATIVE_DIR, "tfidf_ref_mpi"), toy_corpus_dir,
             str(tmp_path / "x.txt")], capture_output=True, text=True)
        assert proc.returncode == 2
        assert "mpirun_lite" in proc.stderr
