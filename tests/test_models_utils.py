"""Vectorizer estimator and observability-utils tests."""

import math
import time

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfVectorizer
from tfidf_tpu.utils import (LatencyHistogram, PhaseTimer, Throughput,
                             trace_region)

CFG = PipelineConfig(engine="dense", vocab_mode=VocabMode.HASHED,
                     vocab_size=256,
                     max_doc_len=8, doc_chunk=8)
CORPUS = Corpus(names=["doc1", "doc2", "doc3", "doc4"],
                docs=[b"a b c", b"a a d", b"b d e", b"a c"])


class TestVectorizer:
    def test_fit_transform_matches_pipeline(self):
        vec = TfidfVectorizer(CFG, batch_docs=2)
        scores = vec.fit_transform(CORPUS)
        want = TfidfPipeline(CFG).run(CORPUS).scores
        np.testing.assert_allclose(scores, want, rtol=1e-6)

    def test_transform_out_of_corpus_uses_fitted_idf(self):
        vec = TfidfVectorizer(CFG).fit(CORPUS)
        new = Corpus(names=["x1"], docs=[b"a a b"])
        scores = vec.transform(new)
        # manual: tf(a)=2/3, idf(a)=ln(4/3) from the FITTED corpus
        from tfidf_tpu.ops.hashing import words_to_ids
        ida, idb = words_to_ids([b"a", b"b"], 256)
        assert scores[0, ida] == pytest.approx((2 / 3) * math.log(4 / 3), rel=1e-5)
        assert scores[0, idb] == pytest.approx((1 / 3) * math.log(4 / 2), rel=1e-5)

    def test_idf_property(self):
        vec = TfidfVectorizer(CFG).fit(CORPUS)
        idf = vec.idf_
        from tfidf_tpu.ops.hashing import words_to_ids
        ide = words_to_ids([b"e"], 256)[0]
        assert idf[ide] == pytest.approx(math.log(4 / 1))

    def test_refit_replaces_state_partial_fit_accumulates(self):
        a = Corpus(names=["doc1"], docs=[b"a b"])
        b = Corpus(names=["doc2"], docs=[b"c d"])
        vec = TfidfVectorizer(CFG).fit(a)
        vec.fit(b)  # sklearn semantics: REPLACES
        assert vec.num_docs_ == 1
        vec2 = TfidfVectorizer(CFG).fit(a).partial_fit(b)  # accumulates
        assert vec2.num_docs_ == 2
        assert (vec2.df_ >= vec.df_).all()

    def test_unfitted_transform_raises(self):
        with pytest.raises(RuntimeError):
            TfidfVectorizer(CFG).transform(CORPUS)

    def test_state_roundtrip(self):
        a = TfidfVectorizer(CFG).fit(CORPUS)
        b = TfidfVectorizer(CFG).load_state(a.state_dict())
        np.testing.assert_allclose(a.transform(CORPUS), b.transform(CORPUS))

    def test_exact_vocab_rejected(self):
        with pytest.raises(ValueError):
            TfidfVectorizer(PipelineConfig(vocab_mode=VocabMode.EXACT))


class TestUtils:
    def test_phase_timer_accumulates(self):
        t = PhaseTimer()
        with t.phase("a"):
            time.sleep(0.01)
        with t.phase("a"):
            time.sleep(0.01)
        with t.phase("b"):
            pass
        assert t.seconds("a") >= 0.02
        assert [n for n, _ in t.items()] == ["a", "b"]
        assert "a" in t.report() and "%" in t.report()

    def test_throughput(self):
        tp = Throughput()
        with tp.measure(100):
            time.sleep(0.01)
        assert tp.docs == 100
        assert 0 < tp.docs_per_sec <= 100 / 0.01

    def test_trace_region_noop_and_enabled(self):
        with trace_region("x", enabled=False):
            pass
        with trace_region("x", enabled=True):
            pass  # must not raise with jax importable


class TestLatencyHistogram:
    def test_percentiles_within_bucket_resolution(self):
        h = LatencyHistogram()
        for ms in range(1, 101):  # 1..100 ms uniform
            h.record(ms / 1e3)
        assert h.count == 100
        assert h.percentile(50) == pytest.approx(0.050, rel=0.05)
        assert h.percentile(95) == pytest.approx(0.095, rel=0.05)
        assert h.percentile(99) == pytest.approx(0.099, rel=0.05)
        assert h.mean == pytest.approx(0.0505, rel=1e-6)

    def test_min_max_exact_and_percentile_clamped(self):
        h = LatencyHistogram()
        for v in (0.003, 0.007, 0.011):
            h.record(v)
        assert h.min == 0.003 and h.max == 0.011
        assert h.percentile(0) == pytest.approx(0.003, rel=0.05)
        assert h.percentile(100) == 0.011  # clamped to exact max

    def test_empty_and_reset(self):
        h = LatencyHistogram()
        assert h.percentile(99) == 0.0
        assert h.as_dict()["count"] == 0
        h.record(0.5)
        h.reset()
        assert h.count == 0 and h.max == 0.0

    def test_as_dict_schema(self):
        h = LatencyHistogram()
        h.record(0.25)
        d = h.as_dict()
        assert set(d) == {"count", "mean", "min", "max",
                          "p50", "p95", "p99"}
        assert d["count"] == 1
        assert d["p50"] == pytest.approx(0.25, rel=0.05)

    def test_out_of_range_clamps_but_tracks_exact_extremes(self):
        h = LatencyHistogram(lo=1e-3, hi=1.0)
        h.record(1e-9)   # below lo -> underflow bucket
        h.record(50.0)   # above hi -> top bucket
        assert h.min == 1e-9 and h.max == 50.0
        assert h.percentile(100) == 50.0
        assert h.percentile(0) == 1e-9  # clamped to exact observed min

    def test_validation(self):
        with pytest.raises(ValueError):
            LatencyHistogram(lo=0)
        with pytest.raises(ValueError):
            LatencyHistogram(resolution=0)
        with pytest.raises(ValueError):
            LatencyHistogram().percentile(101)
