"""Vectorizer estimator and observability-utils tests."""

import math
import time

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig, TfidfPipeline
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfVectorizer
from tfidf_tpu.utils import PhaseTimer, Throughput, trace_region

CFG = PipelineConfig(engine="dense", vocab_mode=VocabMode.HASHED,
                     vocab_size=256,
                     max_doc_len=8, doc_chunk=8)
CORPUS = Corpus(names=["doc1", "doc2", "doc3", "doc4"],
                docs=[b"a b c", b"a a d", b"b d e", b"a c"])


class TestVectorizer:
    def test_fit_transform_matches_pipeline(self):
        vec = TfidfVectorizer(CFG, batch_docs=2)
        scores = vec.fit_transform(CORPUS)
        want = TfidfPipeline(CFG).run(CORPUS).scores
        np.testing.assert_allclose(scores, want, rtol=1e-6)

    def test_transform_out_of_corpus_uses_fitted_idf(self):
        vec = TfidfVectorizer(CFG).fit(CORPUS)
        new = Corpus(names=["x1"], docs=[b"a a b"])
        scores = vec.transform(new)
        # manual: tf(a)=2/3, idf(a)=ln(4/3) from the FITTED corpus
        from tfidf_tpu.ops.hashing import words_to_ids
        ida, idb = words_to_ids([b"a", b"b"], 256)
        assert scores[0, ida] == pytest.approx((2 / 3) * math.log(4 / 3), rel=1e-5)
        assert scores[0, idb] == pytest.approx((1 / 3) * math.log(4 / 2), rel=1e-5)

    def test_idf_property(self):
        vec = TfidfVectorizer(CFG).fit(CORPUS)
        idf = vec.idf_
        from tfidf_tpu.ops.hashing import words_to_ids
        ide = words_to_ids([b"e"], 256)[0]
        assert idf[ide] == pytest.approx(math.log(4 / 1))

    def test_refit_replaces_state_partial_fit_accumulates(self):
        a = Corpus(names=["doc1"], docs=[b"a b"])
        b = Corpus(names=["doc2"], docs=[b"c d"])
        vec = TfidfVectorizer(CFG).fit(a)
        vec.fit(b)  # sklearn semantics: REPLACES
        assert vec.num_docs_ == 1
        vec2 = TfidfVectorizer(CFG).fit(a).partial_fit(b)  # accumulates
        assert vec2.num_docs_ == 2
        assert (vec2.df_ >= vec.df_).all()

    def test_unfitted_transform_raises(self):
        with pytest.raises(RuntimeError):
            TfidfVectorizer(CFG).transform(CORPUS)

    def test_state_roundtrip(self):
        a = TfidfVectorizer(CFG).fit(CORPUS)
        b = TfidfVectorizer(CFG).load_state(a.state_dict())
        np.testing.assert_allclose(a.transform(CORPUS), b.transform(CORPUS))

    def test_exact_vocab_rejected(self):
        with pytest.raises(ValueError):
            TfidfVectorizer(PipelineConfig(vocab_mode=VocabMode.EXACT))


class TestUtils:
    def test_phase_timer_accumulates(self):
        t = PhaseTimer()
        with t.phase("a"):
            time.sleep(0.01)
        with t.phase("a"):
            time.sleep(0.01)
        with t.phase("b"):
            pass
        assert t.seconds("a") >= 0.02
        assert [n for n, _ in t.items()] == ["a", "b"]
        assert "a" in t.report() and "%" in t.report()

    def test_throughput(self):
        tp = Throughput()
        with tp.measure(100):
            time.sleep(0.01)
        assert tp.docs == 100
        assert 0 < tp.docs_per_sec <= 100 / 0.01

    def test_trace_region_noop_and_enabled(self):
        with trace_region("x", enabled=False):
            pass
        with trace_region("x", enabled=True):
            pass  # must not raise with jax importable
