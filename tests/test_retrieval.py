"""TfidfRetriever: cosine ranking vs a numpy oracle, BCOO vs sharded."""

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.ops.hashing import words_to_ids
from tfidf_tpu.parallel.mesh import MeshPlan

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=16, doc_chunk=16)
CORPUS = Corpus(
    names=["doc1", "doc2", "doc3", "doc4", "doc5"],
    docs=[b"apple banana apple cherry",
          b"banana banana date",
          b"cherry date elder fig",
          b"apple fig fig fig",
          b"grape grape grape grape"])


def numpy_cosine_oracle(corpus, queries, vocab=512):
    """Dense float64 TF-IDF cosine ranking, straight from the formulas."""
    docs = [d.split() for d in corpus.docs]
    n = len(docs)
    tf = np.zeros((n, vocab))
    for i, words in enumerate(docs):
        ids = words_to_ids(words, vocab)
        for v in ids:
            tf[i, v] += 1
        tf[i] /= max(len(words), 1)
    df = (np.array([np.bincount(np.unique(words_to_ids(w, vocab)),
                                minlength=vocab) for w in docs])).sum(0)
    idf = np.where(df > 0, np.log(n / np.maximum(df, 1)), 0.0)
    mat = tf * idf
    mat /= np.maximum(np.linalg.norm(mat, axis=1, keepdims=True), 1e-30)
    sims = []
    for q in queries:
        ids = words_to_ids(q.split(), vocab)
        vec = np.bincount(ids, minlength=vocab) / max(len(ids), 1) * idf
        nrm = np.linalg.norm(vec)
        sims.append(mat @ (vec / nrm if nrm > 0 else vec))
    return np.stack(sims)


class TestSingleDevice:
    def test_matches_numpy_oracle(self):
        r = TfidfRetriever(CFG).index(CORPUS)
        queries = [b"apple cherry", b"banana", b"grape date"]
        vals, idx = r.search([q.decode() for q in queries], k=5)
        want = numpy_cosine_oracle(CORPUS, queries)
        for qi in range(len(queries)):
            got = {int(d): float(v) for v, d in zip(vals[qi], idx[qi])
                   if d >= 0}
            for d, v in got.items():
                assert v == pytest.approx(want[qi, d], rel=1e-5)
            # ranking order matches the oracle's descending sims
            ranked = [d for d in np.argsort(-want[qi]) if want[qi, d] > 0]
            assert [d for d in idx[qi] if d >= 0] == ranked[:len(got)]

    def test_self_retrieval_top1(self):
        r = TfidfRetriever(CFG).index(CORPUS)
        vals, idx = r.search([d.decode() for d in CORPUS.docs], k=1)
        assert idx[:, 0].tolist() == list(range(len(CORPUS.docs)))
        # a doc against itself is cosine 1
        np.testing.assert_allclose(vals[:, 0], 1.0, rtol=1e-5)

    def test_no_match_and_empty_query(self):
        r = TfidfRetriever(CFG).index(CORPUS)
        vals, idx = r.search(["zzz_unseen_token", "   "], k=3)
        assert (idx == -1).all()
        assert (vals == 0).all()

    def test_unindexed_raises(self):
        with pytest.raises(RuntimeError):
            TfidfRetriever(CFG).search(["x"])

    def test_index_dir(self, toy_corpus_dir):
        r = TfidfRetriever(CFG).index_dir(toy_corpus_dir)
        assert r.indexed
        vals, idx = r.search(["the"], k=2)
        assert idx.shape == (1, 2)

    def test_index_dir_chunked_matches_batch(self, toy_corpus_dir):
        # Round 4: doc_len opts index_dir into the overlapped chunked
        # ingest (the scalable pipeline). With no truncation in play the
        # search results must equal the whole-corpus batch path.
        queries = ["the quick fox", "tpu mesh psum", "dog"]
        batch = TfidfRetriever(CFG).index_dir(toy_corpus_dir)
        bv, bi = batch.search(queries, k=3)
        # chunk 2 = even split; chunk 4 = the tail chunk carries
        # padding rows (6 docs -> 4 + 2+2pad), which must stay inert.
        for chunk_docs in (2, 4):
            chunked = TfidfRetriever(CFG).index_dir(
                toy_corpus_dir, doc_len=64, chunk_docs=chunk_docs)
            assert chunked.names == batch.names
            cv, ci = chunked.search(queries, k=3)
            np.testing.assert_array_equal(bi, ci)
            np.testing.assert_allclose(bv, cv, rtol=1e-6)


class TestSharded:
    def test_matches_single_device(self):
        import jax
        plan = MeshPlan.create(docs=4, devices=jax.devices()[:4])
        single = TfidfRetriever(CFG).index(CORPUS)
        sharded = TfidfRetriever(CFG, plan=plan).index(CORPUS)
        queries = ["apple cherry", "banana date fig"]
        v1, i1 = single.search(queries, k=4)
        v2, i2 = sharded.search(queries, k=4)
        np.testing.assert_allclose(v1, v2, rtol=1e-5)
        assert (i1 == i2).all()

    def test_width_path_independent(self):
        # k > num_docs: both paths must return min(k, num_docs) columns
        # (the sharded mesh pads docs to 8, the single path has 5; the
        # caller-visible width must not depend on the path).
        import jax
        plan = MeshPlan.create(docs=4, devices=jax.devices()[:4])
        single = TfidfRetriever(CFG).index(CORPUS)
        sharded = TfidfRetriever(CFG, plan=plan).index(CORPUS)
        v1, i1 = single.search(["apple banana"], k=10)
        v2, i2 = sharded.search(["apple banana"], k=10)
        assert v1.shape == v2.shape == (1, len(CORPUS.docs))
        np.testing.assert_allclose(v1, v2, rtol=1e-5)
        assert (i1 == i2).all()

    def test_requires_docs_only_mesh(self):
        plan = MeshPlan.create(docs=4, vocab=2)  # 4*2 = all 8 devices
        with pytest.raises(ValueError):
            TfidfRetriever(CFG, plan=plan)
