"""Fleet-wide distributed tracing (round 23): trace context, clock
alignment, the merge/check/doctor tooling, and (slow) a real 2-replica
tier producing ONE clock-aligned causal timeline.

The pinned contracts (docs/OBSERVABILITY.md "Trace a slow query across
the tier"):

* propagation degrades, never fails — ANY malformed/missing ``trace``
  wire field parses to ``None`` and the request runs under its local
  rid exactly as before;
* offsets live in export METADATA and are applied only at merge time —
  after alignment the front's ``route`` span must CONTAIN the owning
  replica's ``request`` span, within the summed offset uncertainty;
* a tier-wide ``swap_index`` renders as one ``txn_phase`` tree under a
  single control-plane trace id, including the front's drain-to-zero
  gap.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.obs import disttrace

REPO = os.path.join(os.path.dirname(__file__), os.pardir)


def _load_tool(name):
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _reset_disttrace():
    """Every test leaves the process-global kill switch as it found
    it (env-derived)."""
    yield
    disttrace.configure(None)


# ---------------------------------------------------------------------
# fast: trace context — mint / wire round trip / paranoid parse


def test_mint_shape_and_uniqueness():
    a, b = disttrace.mint(), disttrace.mint()
    assert disttrace.is_trace_id(a.trace)
    assert a.trace != b.trace
    assert a.parent.startswith("s") and len(a.parent) == 9


def test_wire_round_trip():
    ctx = disttrace.mint()
    back = disttrace.from_wire(disttrace.to_wire(ctx))
    assert back.trace == ctx.trace and back.parent == ctx.parent


def test_child_rebases_parent_only():
    ctx = disttrace.mint()
    kid = disttrace.child(ctx, "s12345678")
    assert kid.trace == ctx.trace and kid.parent == "s12345678"
    assert disttrace.child(None, "sx") is None


@pytest.mark.parametrize("bad", [
    None, 42, "t0123456789abcdef", [], {"id": None},
    {"id": "r0123456789abcdef-1"},          # a rid is not a trace id
    {"id": "t0123456789abcde"},             # 15 hex chars
    {"id": "t0123456789abcdeg"},            # non-hex
    {"id": "T0123456789abcdef"},            # wrong prefix case
    {"parent": "sdeadbeef"},                # id missing entirely
])
def test_from_wire_degrades_never_raises(bad):
    """The propagation-must-never-fail-a-request pin: every malformed
    wire value parses to None (the request keeps its local rid)."""
    assert disttrace.from_wire(bad) is None


def test_from_wire_sanitizes_alien_parent():
    ctx = disttrace.mint()
    wire = disttrace.to_wire(ctx)
    back = disttrace.from_wire({**wire, "parent": "x" * 65})
    assert back.trace == ctx.trace and back.parent == ""
    back = disttrace.from_wire({**wire, "parent": 7})
    assert back.parent == ""


def test_kill_switch_gates_mint_and_parse():
    ctx = disttrace.mint()
    disttrace.configure(False)
    assert not disttrace.enabled()
    assert disttrace.mint() is None
    assert disttrace.from_wire(disttrace.to_wire(ctx)) is None
    disttrace.configure(True)
    assert disttrace.mint() is not None


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("TFIDF_TPU_DISTTRACE", "off")
    disttrace.configure(None)      # drop the cache, re-derive from env
    assert not disttrace.enabled()
    monkeypatch.setenv("TFIDF_TPU_DISTTRACE", "on")
    disttrace.configure(None)
    assert disttrace.enabled()


def test_serveconfig_env_and_flag(monkeypatch):
    monkeypatch.setenv("TFIDF_TPU_DISTTRACE", "off")
    assert ServeConfig.from_env().disttrace is False
    # The flag wins over the env, the ServeConfig pick contract.
    assert ServeConfig.from_env(disttrace=True).disttrace is True
    monkeypatch.delenv("TFIDF_TPU_DISTTRACE")
    assert ServeConfig.from_env().disttrace is None


# ---------------------------------------------------------------------
# fast: clock-offset estimator under fake clocks


def _round_trip(est, t_local, true_offset, out_delay, back_delay,
                peer_hold=0):
    """Simulate one RPC under a fake pair of clocks: the peer's clock
    reads local + true_offset at every instant."""
    t_send = t_local
    t_peer = t_send + out_delay + peer_hold // 2 + true_offset
    t_recv = t_send + out_delay + peer_hold + back_delay
    est.add_sample(t_send, t_peer, t_recv)
    return t_recv


def test_estimator_exact_on_symmetric_rtt():
    est = disttrace.ClockOffsetEstimator()
    _round_trip(est, 1_000_000, true_offset=5_000_000,
                out_delay=40_000, back_delay=40_000)
    assert est.offset_ns == 5_000_000
    assert est.uncertainty_ns == (80_000 + 1) // 2
    assert est.n_samples == 1


def test_estimator_asymmetry_error_bounded_by_uncertainty():
    est = disttrace.ClockOffsetEstimator()
    # Pathological asymmetry: all delay on the outbound leg.
    _round_trip(est, 0, true_offset=1_000_000,
                out_delay=90_000, back_delay=10_000)
    err = abs(est.offset_ns - 1_000_000)
    assert err <= est.uncertainty_ns
    assert err == 40_000        # (out - back) / 2, the midpoint bias


def test_estimator_keeps_min_rtt_sample():
    est = disttrace.ClockOffsetEstimator()
    t = 0
    # A noisy burst: the long-RTT samples carry a biased offset; the
    # single fast one is symmetric and exact.
    for out, back in [(500_000, 20_000), (10_000, 10_000),
                      (300_000, 40_000)]:
        t = _round_trip(est, t, true_offset=777_000,
                        out_delay=out, back_delay=back) + 1_000
    assert est.rtt_ns == 20_000
    assert est.offset_ns == 777_000
    assert est.n_samples == 3


def test_estimator_discards_non_causal_sample():
    est = disttrace.ClockOffsetEstimator()
    est.add_sample(100, 50, 90)            # t_recv < t_send
    assert est.n_samples == 0 and est.offset_ns is None


def test_estimator_restart_reestimation():
    """A restarted replica is a NEW clock epoch: reset() must discard
    everything, and the re-estimate must track the new clock instead
    of averaging it against the dead one."""
    est = disttrace.ClockOffsetEstimator()
    _round_trip(est, 0, true_offset=2_000_000,
                out_delay=10_000, back_delay=10_000)
    assert est.offset_ns == 2_000_000
    est.reset()
    assert est.as_meta() == {"offset_ns": None, "uncertainty_ns": None,
                             "rtt_ns": None, "samples": 0}
    _round_trip(est, 10_000_000, true_offset=-9_000_000,
                out_delay=15_000, back_delay=15_000)
    assert est.offset_ns == -9_000_000
    assert est.n_samples == 1


def test_estimator_drift_tracked_by_reestimation():
    """Slow drift between estimates: each fresh estimate lands within
    its uncertainty of the drifted truth at that instant."""
    est = disttrace.ClockOffsetEstimator()
    drift_per_s = 50_000                    # 50 us/s
    t = 0
    for _ in range(4):
        est.reset()
        offset_now = 1_000_000 + drift_per_s * (t // 1_000_000_000)
        _round_trip(est, t, true_offset=offset_now,
                    out_delay=20_000, back_delay=20_000)
        assert abs(est.offset_ns - offset_now) <= est.uncertainty_ns
        t += 1_000_000_000                  # one second later


def test_clock_handshake_single_process_is_zero():
    from tfidf_tpu.parallel.multihost import clock_handshake

    class _Solo:
        rank, size = 0, 1
    meta = clock_handshake(_Solo())
    assert meta["samples"] == 0


# ---------------------------------------------------------------------
# fast: trace_merge — alignment math, lanes, error paths


def _proc_entry(process, t0_ns, offset_ns, spans, os_pid=100):
    events = [{"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
               "args": {"name": "tfidf_tpu host"}}]
    for tid in sorted({t for _, t, _, _, _ in spans}):
        events.append({"ph": "M", "pid": 1, "tid": tid,
                       "name": "thread_name",
                       "args": {"name": "main"}})
    for name, tid, ts_us, dur_us, args in spans:
        events.append({"ph": "X", "pid": 1, "tid": tid, "name": name,
                       "ts": ts_us, "dur": dur_us, "cat": "host",
                       "args": args})
    clock = {"offset_ns": offset_ns,
             "uncertainty_ns": 5_000, "rtt_ns": 10_000, "samples": 8}
    return {"process": process, "os_pid": os_pid, "t0_ns": t0_ns,
            "clock": clock, "traceEvents": events}


def test_merge_applies_offset_at_merge_time():
    tm = _load_tool("trace_merge")
    # Replica clock reads +3ms ahead of the front's; its tracer epoch
    # started 1ms (of front time) after the front's. A span at local
    # ts=0 must land at front-relative (t0_r - offset - t0_f)/1e3 us.
    front = _proc_entry("front", t0_ns=10_000_000, offset_ns=0,
                        spans=[("route", 1, 100.0, 500.0, {})])
    front["clock"] = {"offset_ns": 0, "uncertainty_ns": 0,
                      "rtt_ns": 0, "samples": 0}
    replica = _proc_entry("r1", t0_ns=14_000_000, offset_ns=3_000_000,
                          spans=[("request", 1, 0.0, 300.0, {})])
    merged = tm.merge_processes([replica, front])  # any input order
    man = merged["disttrace"]["processes"]
    assert [p["process"] for p in man] == ["front", "r1"]
    assert man[0]["reference"] and not man[1]["reference"]
    assert man[0]["shift_us"] == 0.0
    assert man[1]["shift_us"] == pytest.approx(1_000.0)  # 1ms, not 4
    req = [e for e in merged["traceEvents"]
           if e.get("name") == "request"][0]
    assert req["ts"] == pytest.approx(1_000.0)
    assert req["pid"] != [e for e in merged["traceEvents"]
                          if e.get("name") == "route"][0]["pid"]


def test_merge_unique_lanes_for_duplicate_labels():
    tm = _load_tool("trace_merge")
    a = _proc_entry("r1", 0, 0, [("request", 1, 0, 1, {})])
    b = _proc_entry("r1", 0, 0, [("request", 1, 0, 1, {})])
    man = tm.merge_processes([a, b])["disttrace"]["processes"]
    assert [p["process"] for p in man] == ["r1", "r1#2"]
    assert [p["pid"] for p in man] == [1, 2]


def test_merge_reference_selection():
    tm = _load_tool("trace_merge")
    a = _proc_entry("ingest0", 5_000, 0, [])
    b = _proc_entry("ingest1", 9_000, 0, [])
    man = tm.merge_processes([a, b])["disttrace"]["processes"]
    assert man[0]["process"] == "ingest0"          # first, no front
    man = tm.merge_processes(
        [a, b], reference="ingest1")["disttrace"]["processes"]
    assert man[0]["process"] == "ingest1"
    with pytest.raises(ValueError, match="reference"):
        tm.merge_processes([a, b], reference="nope")


def test_load_rejects_traces_without_identity(tmp_path):
    tm = _load_tool("trace_merge")
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps([{"ph": "X", "name": "x"}]))
    with pytest.raises(ValueError, match="disttrace identity"):
        tm.load_processes(str(bare))
    old = tmp_path / "old.json"
    old.write_text(json.dumps({"traceEvents": []}))
    with pytest.raises(ValueError, match="disttrace metadata"):
        tm.load_processes(str(old))


def test_merge_cli_round_trip(tmp_path):
    tm = _load_tool("trace_merge")
    bundle = {"schema": "tfidf-trace/1", "pid": 1, "processes": [
        _proc_entry("front", 0, 0, [("route", 1, 0.0, 100.0, {})]),
        _proc_entry("r1", 0, 1_000, [("request", 1, 0.0, 50.0, {})]),
    ]}
    src = tmp_path / "bundle.json"
    src.write_text(json.dumps(bundle))
    out = tmp_path / "merged.json"
    assert tm.main([str(src), "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["schema"] == "tfidf-trace-merged/1"
    assert tm.main([str(tmp_path / "missing.json"),
                    "-o", str(out)]) == 2


# ---------------------------------------------------------------------
# fast: trace_check merged mode + doctor fleet timeline


def _merged_doc(route_ts=100.0, route_dur=500.0, req_ts=200.0,
                req_dur=300.0, samples=8):
    tm = _load_tool("trace_merge")
    tid = "t00000000000000aa"
    front = _proc_entry(
        "front", 0, 0,
        [("route", 1, route_ts, route_dur,
          {"trace": tid, "replica": 1, "rid": "rX-1"})])
    front["clock"] = {"offset_ns": 0, "uncertainty_ns": 0,
                      "rtt_ns": 0, "samples": 0}
    replica = _proc_entry(
        "r1", 0, 0,
        [("request", 1, req_ts, req_dur,
          {"rid": "rX-1", "trace": tid, "queries": 1, "k": 5,
           "outcome": "drained"}),
         ("queued", 1, req_ts, 10.0,
          {"rid": "rX-1", "outcome": "batched", "queries": 1,
           "k": 5})])
    replica["clock"]["samples"] = samples
    return tm.merge_processes([front, replica]), tid


def test_trace_check_merged_accepts_contained(tmp_path):
    tc = _load_tool("trace_check")
    doc, _ = _merged_doc()
    p = tmp_path / "merged.json"
    p.write_text(json.dumps(doc))
    errors, notes = tc.check_trace(str(p))      # auto-detects merged
    assert errors == [], (errors, notes)
    assert any("merged" in n for n in notes)
    assert any("1/1" in n for n in notes if "containment" in n)


def test_trace_check_merged_flags_broken_containment(tmp_path):
    tc = _load_tool("trace_check")
    # The replica's request ends 1ms after its route returned — a
    # bad offset would produce exactly this shape.
    doc, _ = _merged_doc(route_ts=100.0, route_dur=200.0,
                         req_ts=900.0, req_dur=800.0)
    p = tmp_path / "broken.json"
    p.write_text(json.dumps(doc))
    errors, _ = tc.check_trace(str(p))
    assert any("contain" in e for e in errors), errors


def test_trace_check_merged_flags_unmeasured_clock(tmp_path):
    tc = _load_tool("trace_check")
    doc, _ = _merged_doc(samples=0)
    p = tmp_path / "nosync.json"
    p.write_text(json.dumps(doc))
    errors, _ = tc.check_trace(str(p))
    assert any("samples" in e or "offset" in e for e in errors), errors


def test_doctor_fleet_timeline_joins_processes(tmp_path):
    doctor = _load_tool("doctor")
    assert doctor._is_trace_id("t00000000000000aa")
    assert not doctor._is_trace_id("rdeadbeef-1")
    doc, tid = _merged_doc()
    p = tmp_path / "merged.json"
    p.write_text(json.dumps(doc))
    rep = doctor.fleet_timeline(str(p), None, tid)
    assert rep is not None and rep["trace_id"] == tid
    assert rep["processes"] == ["front", "r1"]
    assert rep["rids"] == ["rX-1"]
    names = [r["span"] for r in rep["spans"]]
    assert names[0] == "route" and "request" in names
    assert "queued" in names            # rid-joined, not trace-stamped
    hops = rep["hops"]
    assert hops["route_ms"] >= hops["request_ms"]
    assert hops["wire_ms"] == pytest.approx(
        hops["route_ms"] - hops["request_ms"])
    assert doctor.render_fleet(rep).startswith(f"trace {tid}")
    assert doctor.fleet_timeline(str(p), None,
                                 "t00000000000000ff") is None


# ---------------------------------------------------------------------
# fast: ledger + gate wiring for the disttrace artifact columns


def _replica_artifact(tmp_path, parity_ok=1, overhead=3.0):
    art = {
        "metric": "replica_bench", "backend": "cpu", "docs": 256,
        "k": 10, "requests": 16, "concurrency": 4, "host_cores": 1,
        "cpu_bound": 1, "n_replicas": 2, "replica": {"sweep": []},
        "throughput_qps": 400.0, "qps_1": 410.0,
        "qps_scaling_x": 0.97, "scaling_efficiency": 0.49,
        "latency_ms": {"p50": 20.0, "p99": 50.0, "max": 50.0},
        "parity_checked": 48, "parity_mismatches": 0, "parity_ok": 1,
        "mixed_epoch_responses": 0, "recompiles_after_warmup": 0,
        "chaos": {"plan": "replica_prepare:fatal:n=1",
                  "swap_aborted": 1,
                  "old_epoch_everywhere_after_abort": 1,
                  "restarts": 1, "second_swap_epoch": 1,
                  "mixed_epoch_responses": 0, "parity_mismatches": 0},
        "disttrace": {"replicas": 2, "requests": 48,
                      "p50_off_ms": 20.0, "p50_on_ms": 20.6,
                      "overhead_pct": overhead,
                      "processes_merged": 3, "spans_merged": 120,
                      "max_clock_uncertainty_us": 25.0,
                      "parity_mismatches": 0 if parity_ok else 2,
                      "parity_ok": parity_ok,
                      "recompiles_after_warmup": 0},
    }
    p = tmp_path / f"REPLICA_p{parity_ok}_o{overhead}.json"
    p.write_text(json.dumps(art))
    return str(p)


def test_ledger_maps_disttrace_columns(tmp_path):
    ledger = _load_tool("perf_ledger")
    rec, reason = ledger.normalize(_replica_artifact(tmp_path))
    assert reason is None and rec["kind"] == "replica_serve"
    m = rec["metrics"]
    assert m["disttrace_parity_ok"] == 1
    assert m["disttrace_recompiles"] == 0
    assert m["disttrace_overhead_pct"] == 3.0
    assert m["disttrace_spans_merged"] == 120
    assert m["disttrace_max_clock_uncertainty_us"] == 25.0


def test_gate_zero_tolerates_disttrace_parity(tmp_path):
    ledger = _load_tool("perf_ledger")
    gate = _load_tool("perf_gate")
    clean, _ = ledger.normalize(_replica_artifact(tmp_path))
    broken, _ = ledger.normalize(
        _replica_artifact(tmp_path, parity_ok=0))
    verdict = gate.gate(broken, [clean])
    bad = {c["metric"] for c in verdict["checks"]
           if c["verdict"] == "REGRESSED"}
    assert "disttrace_parity_ok" in bad and not verdict["ok"]
    assert gate.gate(clean, [clean])["ok"]


def test_gate_bounds_propagation_overhead(tmp_path):
    ledger = _load_tool("perf_ledger")
    gate = _load_tool("perf_gate")
    clean, _ = ledger.normalize(_replica_artifact(tmp_path))
    bloated, _ = ledger.normalize(
        _replica_artifact(tmp_path, overhead=9.0))   # 3% -> 9%
    verdict = gate.gate(bloated, [clean])
    bad = {c["metric"] for c in verdict["checks"]
           if c["verdict"] == "REGRESSED"}
    assert "disttrace_overhead_pct" in bad and not verdict["ok"]


# ---------------------------------------------------------------------
# slow: the real tier — one clock-aligned timeline, fleet doctor, and
# the front's SIGTERM evidence parity


def _write_corpus(path, n_docs, seed, n_words=200, doc_len=30):
    rng = np.random.default_rng(seed)
    path.mkdir(parents=True, exist_ok=True)
    for i in range(1, n_docs + 1):
        words = [f"w{rng.integers(0, n_words)}"
                 for _ in range(doc_len)]
        (path / f"doc{i}").write_text(" ".join(words))
    return str(path)


def _cfg():
    return PipelineConfig(vocab_mode=VocabMode.HASHED,
                          vocab_size=4096, max_doc_len=64)


@pytest.mark.slow
def test_two_replica_merged_timeline_end_to_end(tmp_path):
    from tfidf_tpu import obs
    from tfidf_tpu.serve.front import ReplicatedFront
    tm = _load_tool("trace_merge")
    tc = _load_tool("trace_check")
    doctor = _load_tool("doctor")

    input_dir = _write_corpus(tmp_path / "input", 12, seed=7)
    disttrace.configure(True)
    prev_tracer = obs.get_tracer()
    obs.set_tracer(obs.Tracer(), None)
    obs.set_export_meta(process="front")
    serve_cfg = ServeConfig(
        max_batch=8, cache_entries=256,
        snapshot_dir=str(tmp_path / "snap"), replicas=2,
        replica_timeout_s=240.0)
    front = ReplicatedFront(input_dir, _cfg(), serve_cfg, k=5)
    try:
        front.start()
        # Traced load: every response echoes the front-minted id next
        # to the replica-local rid.
        tids = []
        for i in range(6):
            resp = front.query([f"w{i} w{i + 3}"], k=5,
                               use_cache=False)
            assert "error" not in resp
            assert disttrace.is_trace_id(resp.get("trace"))
            assert resp.get("rid")
            tids.append(resp["trace"])
        assert len(set(tids)) == 6

        # One tier-wide swap so the merged timeline carries the
        # two-phase txn tree.
        assert front.swap_index(input_dir) == 1

        bundle = front.trace_export()
        assert bundle["schema"] == "tfidf-trace/1"
        procs = {p["process"]: p for p in bundle["processes"]}
        assert set(procs) == {"front", "r1", "r2"}
        # The front IS the reference clock; each replica's entry must
        # carry a measured offset.
        for r in ("r1", "r2"):
            clock = procs[r]["clock"]
            assert clock["samples"] >= 1
            assert clock["uncertainty_ns"] > 0
        merged = tm.merge_processes(bundle["processes"])
    finally:
        front.close()
        obs.set_tracer(prev_tracer)

    mpath = tmp_path / "merged.json"
    mpath.write_text(json.dumps(merged))

    # The merged-mode audit: unique lanes, measured offsets, and —
    # for EVERY sampled query — route-contains-request after
    # alignment.
    errors, notes = tc.check_trace(str(mpath), min_threads=2)
    assert errors == [], (errors, notes)
    contain = [n for n in notes if "containment" in n]
    assert contain and "6/6" in contain[0], notes

    # Direct containment assertion for every sampled trace id (the
    # acceptance wording, independent of trace_check's implementation).
    xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    unc_by_pid = {p["pid"]: p["uncertainty_ns"] / 1e3
                  for p in merged["disttrace"]["processes"]}
    for tid in tids:
        route = [e for e in xs if e["name"] == "route"
                 and e.get("args", {}).get("trace") == tid]
        req = [e for e in xs if e["name"] == "request"
               and e.get("args", {}).get("trace") == tid]
        assert len(route) == 1 and len(req) == 1, tid
        r, q = route[0], req[0]
        slack = unc_by_pid[r["pid"]] + unc_by_pid[q["pid"]] + 250.0
        assert q["ts"] >= r["ts"] - slack
        assert q["ts"] + q["dur"] <= r["ts"] + r["dur"] + slack

    # The tier-wide swap is ONE txn tree: the front's epoch_swap span
    # mints the control-plane trace id; txn_phase spans from BOTH
    # replica processes and the front's drain gap all carry it.
    swaps = [e for e in xs if e["name"] == "epoch_swap"
             and e.get("args", {}).get("kind") == "swap"]
    assert len(swaps) == 1
    swap_tid = swaps[0]["args"]["trace"]
    assert disttrace.is_trace_id(swap_tid)
    phases = [e for e in xs if e["name"] == "txn_phase"
              and e.get("args", {}).get("trace") == swap_tid]
    by_pid = {e["pid"] for e in phases}
    assert len(by_pid) >= 3              # front + both replicas
    names = {e["args"]["phase"] for e in phases}
    assert {"prepare", "commit", "drain"} <= names
    drain = [e for e in phases if e["args"]["phase"] == "drain"]
    assert len(drain) == 1 and drain[0]["dur"] >= 0
    assert drain[0]["args"].get("outcome") == "drained"

    # Fleet-wide doctor: the front-minted trace id resolves to a
    # cross-process timeline with per-hop attribution, rc 0.
    rep = doctor.fleet_timeline(str(mpath), None, tids[0])
    assert rep is not None
    assert set(rep["processes"]) >= {"front"}
    assert len(rep["processes"]) == 2     # front + the owning replica
    assert rep["spans"][0]["span"] == "route"
    assert {"route_ms", "request_ms", "wire_ms"} <= set(rep["hops"])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "doctor.py"),
         str(mpath), "--request", tids[0]],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    assert tids[0] in out.stdout
    # The swap tree renders fleet-wide too.
    rep = doctor.fleet_timeline(str(mpath), None, swap_tid)
    assert rep is not None and len(rep["processes"]) == 3


@pytest.mark.slow
def test_disttrace_off_tier_degrades_to_local_rids(tmp_path):
    from tfidf_tpu import obs
    from tfidf_tpu.serve.front import ReplicatedFront

    input_dir = _write_corpus(tmp_path / "input", 8, seed=3)
    disttrace.configure(False)
    serve_cfg = ServeConfig(
        max_batch=8, snapshot_dir=str(tmp_path / "snap"), replicas=2,
        replica_timeout_s=240.0)
    front = ReplicatedFront(input_dir, _cfg(), serve_cfg, k=5)
    try:
        front.start()
        resp = front.query(["w1 w2"], k=5, use_cache=False)
        assert "error" not in resp
        assert "trace" not in resp          # degraded, not failed
        assert resp.get("rid")
        # The export path still answers — with no replica rings armed
        # the bundle is just thinner, never an error.
        bundle = front.trace_export()
        assert bundle["schema"] == "tfidf-trace/1"
        assert all(p["process"] == "front"
                   for p in bundle["processes"])
    finally:
        front.close()
        obs.set_tracer(None)


@pytest.mark.slow
def test_front_sigterm_leaves_flight_and_trace(tmp_path):
    """Satellite: front-process crash-forensics parity with the
    single-process serve CLI — SIGTERM to a REPLICATED front dumps
    its flight ring AND its trace atomically, exit 143."""
    input_dir = _write_corpus(tmp_path / "input", 8, seed=5)
    trace = str(tmp_path / "front_trace.json")
    flight = str(tmp_path / "front.flight.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tfidf_tpu.cli", "serve",
         "--input", input_dir, "--vocab-size", "512",
         "--replicas", "2", "--snapshot-dir",
         str(tmp_path / "snap"), "--max-wait-ms", "1",
         "--trace", trace, "--flight", flight],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=env, cwd=REPO, text=True)
    try:
        proc.stdin.write(json.dumps(
            {"id": 1, "queries": ["w1 w2"], "k": 3}) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        assert line, "front never answered before SIGTERM"
        resp = json.loads(line)
        assert resp["id"] == 1 and "results" in resp
        assert disttrace.is_trace_id(resp.get("trace"))
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == 143
    assert os.path.exists(flight) and os.path.exists(trace)
    tc = _load_tool("trace_check")
    errors, notes = tc.check_flight(flight)
    assert errors == [], (errors, notes)
    # The front's own ring: route spans, at least the main lane.
    errors, notes = tc.check_trace(trace, mode="auto", min_threads=1)
    assert errors == [], (errors, notes)
    doc = json.loads(open(trace).read())
    assert doc.get("disttrace", {}).get("process") == "front"
    assert any(e.get("name") == "route"
               for e in doc["traceEvents"] if e.get("ph") == "X")
