"""Multi-host bring-up: the single-host no-op branch and a real
2-process ``jax.distributed`` smoke over localhost.

The reference cannot run at all without an MPI runtime (``MPI_Init``,
``TFIDF.c:82``); ``multihost.initialize`` must instead be a safe no-op
on one host and a real DCN bring-up when a coordinator is configured.
"""

import os
import subprocess
import sys

from tfidf_tpu.parallel.multihost import HostTopology, initialize


class TestSingleHost:
    def test_noop_reports_local_topology(self):
        # No coordinator args, no cluster env: must not try to bring up
        # a distributed runtime, just report what jax already sees.
        assert not os.environ.get("JAX_COORDINATOR_ADDRESS")
        topo = initialize()
        assert isinstance(topo, HostTopology)
        assert topo.process_id == 0
        assert topo.num_processes == 1
        assert topo.local_devices == topo.global_devices
        assert topo.local_devices >= 1

    def test_idempotent(self):
        assert initialize() == initialize()


_WORKER = r"""
import sys
import jax
# CPU-backend stand-in for a TPU pod: gloo carries the cross-process
# collectives that ICI/DCN would on real hardware.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from tfidf_tpu.parallel.multihost import initialize
topo = initialize(coordinator_address=sys.argv[1],
                  num_processes=2, process_id=int(sys.argv[2]))
assert topo.num_processes == 2, topo
assert topo.process_id == int(sys.argv[2]), topo
assert topo.global_devices == 2 * topo.local_devices, topo
# One collective over DCN (gRPC on localhost here): psum of the
# process id across both processes must be 0 + 1 everywhere.
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from tfidf_tpu.parallel.compat import shard_map
devs = jax.devices()
mesh = Mesh(devs, ("d",))
got = jax.jit(
    shard_map(lambda x: jax.lax.psum(x, "d"), mesh=mesh,
              in_specs=P("d"), out_specs=P()),
)(jnp.arange(len(devs), dtype=jnp.float32))
assert float(got[0]) == sum(range(len(devs))), got

# THE PIPELINE across processes (round 4, VERDICT r3 item 6a): the
# docs-sharded TF-IDF forward runs over the process-spanning mesh —
# its DF psum and top-k all_gather ride the gloo transport — and must
# equal a single-device run of the same batch exactly.
import numpy as np
from jax import lax
from tfidf_tpu.parallel.collectives import make_sharded_forward
from tfidf_tpu.parallel.mesh import MeshPlan
from tfidf_tpu.ops.histogram import tf_counts_masked
from tfidf_tpu.ops.scoring import idf_from_df

plan = MeshPlan.create(docs=len(devs), devices=devs)
vocab, d, L, k = 256, 8, 16, 3
rng = np.random.default_rng(0)  # same batch in every process
toks = rng.integers(0, vocab, (d, L)).astype(np.int32)
lens = np.asarray(rng.integers(1, L + 1, (d,)), dtype=np.int32)
tok_g = jax.make_array_from_callback(
    (d, L), plan.sharding(plan.batch_spec()), lambda idx: toks[idx])
len_g = jax.make_array_from_callback(
    (d,), plan.sharding(plan.lengths_spec()), lambda idx: lens[idx])
fwd = make_sharded_forward(plan, vocab, jnp.float32, topk=k)
df, vals, ids = fwd(tok_g, len_g, jnp.int32(d))

@jax.jit
def ref_dense(tokens, lengths):
    live = (jnp.arange(tokens.shape[1])[None, :] < lengths[:, None])
    counts = tf_counts_masked(tokens, live, vocab, id_offset=0)
    rdf = (counts > 0).astype(jnp.int32).sum(axis=0)
    idf = idf_from_df(rdf, jnp.int32(d), jnp.float32)
    scores = counts.astype(jnp.float32) \
        / jnp.maximum(lengths, 1).astype(jnp.float32)[:, None] \
        * idf[None, :]
    rvals, rids = lax.top_k(scores, k)
    return rdf, rvals, rids

rdf, rvals, rids = ref_dense(toks, lens)
rdf, rvals, rids = np.asarray(rdf), np.asarray(rvals), np.asarray(rids)
# DF is replicated -> fully addressable everywhere; top-k rows are
# docs-sharded -> compare this process's addressable shards only.
np.testing.assert_array_equal(np.asarray(df.addressable_shards[0].data),
                              rdf)
for arr, ref in ((vals, rvals), (ids, rids)):
    for shard in arr.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data),
                                   ref[shard.index], rtol=1e-6)

# Streaming incremental DF (BASELINE config 5) across the same
# process-spanning mesh: the minibatch update's psum crosses the
# process boundary; the folded DF must equal the dense reference's.
from tfidf_tpu.streaming import _mesh_update_sparse_fn
upd = _mesh_update_sparse_fn(plan, vocab)
df_state = jnp.zeros((vocab,), jnp.int32)
for lo in range(0, d, d // 2):  # two minibatches
    bt = jax.make_array_from_callback(
        (d // 2, L), plan.sharding(plan.batch_spec()),
        lambda idx, lo=lo: toks[lo:lo + d // 2][idx])
    bl = jax.make_array_from_callback(
        (d // 2,), plan.sharding(plan.lengths_spec()),
        lambda idx, lo=lo: lens[lo:lo + d // 2][idx])
    df_state = upd(df_state, bt, bl)
np.testing.assert_array_equal(
    np.asarray(df_state.addressable_shards[0].data), rdf)
print("OK", topo.process_id)
"""


class TestTwoProcess:
    def test_distributed_smoke_localhost(self, tmp_path):
        """2-process jax.distributed bring-up + one cross-process psum."""
        import socket
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # 1 CPU device per process
        # Ask the kernel for a free port instead of hardcoding one: a
        # concurrent run (or a TIME_WAIT socket from the last one) on a
        # fixed port would flake.
        with socket.socket() as s:
            s.bind(("localhost", 0))
            addr = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, "-c", _WORKER, addr, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env) for pid in range(2)]
        try:
            outs = [p.communicate(timeout=120) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"rc={p.returncode}\n{out}\n{err}"
        # gloo prints connection chatter on stdout; the verdict is the
        # last line each worker prints.
        assert sorted(o.strip().splitlines()[-1]
                      for o, _ in outs) == ["OK 0", "OK 1"]


_INGEST_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from tfidf_tpu.parallel.multihost import initialize
topo = initialize(coordinator_address=sys.argv[1],
                  num_processes=2, process_id=int(sys.argv[2]))
input_dir, expect_npz = sys.argv[3], sys.argv[4]

# The FLAGSHIP ingest across real processes (VERDICT r4 item 4): the
# docs-sharded resident run_overlapped over a process-spanning mesh.
# Each process packs only its own shards' documents (per-process chunk
# ingest); the run's single DF psum and the result allgather cross the
# gloo transport. The expected arrays were produced by the SAME mesh
# shape on two single-process devices, so every float op is identical
# and the comparison is exact.
import numpy as np
from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.ingest import run_overlapped
from tfidf_tpu.parallel.mesh import MeshPlan

plan = MeshPlan.create(docs=2, devices=jax.devices())
assert jax.process_count() == 2
cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                     topk=4, engine="sparse")
r = run_overlapped(input_dir, cfg, chunk_docs=16, doc_len=32, plan=plan)
exp = np.load(expect_npz)
np.testing.assert_array_equal(r.topk_ids, exp["ids"])
np.testing.assert_array_equal(np.asarray(r.df), exp["df"])
np.testing.assert_array_equal(r.topk_vals, exp["vals"])
np.testing.assert_array_equal(r.lengths, exp["lengths"])
assert r.path == "resident-mesh", r.path
print("OK", topo.process_id)
"""


class TestTwoProcessIngest:
    def test_flagship_mesh_ingest_across_processes(self, tmp_path):
        """run_overlapped's mesh regime over 2 jax.distributed
        processes == the same mesh on one process, bit for bit."""
        import socket

        import numpy as np

        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.mesh import MeshPlan
        import jax

        d = tmp_path / "input"
        d.mkdir()
        rng = np.random.default_rng(9)
        for i in range(1, 25):
            (d / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 200)}"
                         for _ in range(rng.integers(1, 30))))
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                             topk=4, engine="sparse")
        plan1 = MeshPlan.create(docs=2, devices=jax.devices("cpu")[:2])
        ref = run_overlapped(str(d), cfg, chunk_docs=16, doc_len=32,
                             plan=plan1)
        expect = tmp_path / "expect.npz"
        np.savez(expect, ids=ref.topk_ids, vals=ref.topk_vals,
                 df=np.asarray(ref.df), lengths=ref.lengths)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # 1 CPU device per process
        with socket.socket() as s:
            s.bind(("localhost", 0))
            addr = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, "-c", _INGEST_WORKER, addr, str(pid),
             str(d), str(expect)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env) for pid in range(2)]
        try:
            outs = [p.communicate(timeout=180) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"rc={p.returncode}\n{out}\n{err}"
        assert sorted(o.strip().splitlines()[-1]
                      for o, _ in outs) == ["OK 0", "OK 1"]


_STREAM_WORKER = r"""
import os
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from tfidf_tpu.parallel.multihost import initialize
topo = initialize(coordinator_address=sys.argv[1],
                  num_processes=2, process_id=int(sys.argv[2]))
input_dir, expect_npz = sys.argv[3], sys.argv[4]

# The beyond-HBM regime across processes: force the streaming-mesh
# path (resident budget 0) and pin bit-parity against the same mesh
# shape on one process.
os.environ["TFIDF_TPU_RESIDENT_ELEMS"] = "0"
import numpy as np
from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.ingest import run_overlapped
from tfidf_tpu.parallel.mesh import MeshPlan

plan = MeshPlan.create(docs=2, devices=jax.devices())
cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                     topk=4, engine="sparse")
r = run_overlapped(input_dir, cfg, chunk_docs=16, doc_len=32, plan=plan)
assert r.path == "streaming-mesh", r.path
exp = np.load(expect_npz)
np.testing.assert_array_equal(r.topk_ids, exp["ids"])
np.testing.assert_array_equal(np.asarray(r.df), exp["df"])
np.testing.assert_array_equal(r.topk_vals, exp["vals"])
np.testing.assert_array_equal(r.lengths, exp["lengths"])
print("OK", topo.process_id)
"""


class TestTwoProcessStreamingMesh:
    def test_streaming_mesh_across_processes(self, tmp_path, monkeypatch):
        import socket

        import numpy as np

        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.mesh import MeshPlan
        import jax

        d = tmp_path / "input"
        d.mkdir()
        rng = np.random.default_rng(13)
        for i in range(1, 25):
            (d / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 200)}"
                         for _ in range(rng.integers(1, 30))))
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                             topk=4, engine="sparse")
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        plan1 = MeshPlan.create(docs=2, devices=jax.devices("cpu")[:2])
        ref = run_overlapped(str(d), cfg, chunk_docs=16, doc_len=32,
                             plan=plan1)
        assert ref.path == "streaming-mesh"
        expect = tmp_path / "expect.npz"
        np.savez(expect, ids=ref.topk_ids, vals=ref.topk_vals,
                 df=np.asarray(ref.df), lengths=ref.lengths)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        with socket.socket() as s:
            s.bind(("localhost", 0))
            addr = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, "-c", _STREAM_WORKER, addr, str(pid),
             str(d), str(expect)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env) for pid in range(2)]
        try:
            outs = [p.communicate(timeout=180) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"rc={p.returncode}\n{out}\n{err}"
        assert sorted(o.strip().splitlines()[-1]
                      for o, _ in outs) == ["OK 0", "OK 1"]
