"""Multi-host bring-up: the single-host no-op branch and a real
2-process ``jax.distributed`` smoke over localhost.

The reference cannot run at all without an MPI runtime (``MPI_Init``,
``TFIDF.c:82``); ``multihost.initialize`` must instead be a safe no-op
on one host and a real DCN bring-up when a coordinator is configured.
"""

import json
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from tfidf_tpu.parallel.multihost import (HostTopology, MpiLiteComm,
                                          MpiLiteError, initialize,
                                          shard_bounds)


def _make_comms(n):
    """A size-n mpi_lite world over in-process socketpairs (one comm
    per 'rank', driven from threads) — the launcher's fd topology
    without the subprocesses."""
    pair = [[-1] * n for _ in range(n)]
    for i in range(n):
        for j in range(i + 1, n):
            a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
            pair[i][j] = a.detach()
            pair[j][i] = b.detach()
    return [MpiLiteComm(r, n, [pair[r][j] for j in range(n)])
            for r in range(n)]


def _run_ranks(comms, fn):
    """Run fn(comm) on every rank concurrently; returns rank-ordered
    results, re-raising the first rank failure."""
    results = [None] * len(comms)
    errors = []

    def body(r):
        try:
            results[r] = fn(comms[r])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append((r, e))

    threads = [threading.Thread(target=body, args=(r,))
               for r in range(len(comms))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for comm in comms:
        comm.close()
    if errors:
        raise errors[0][1]
    return results


class TestMpiLiteComm:
    """The Python mpi_lite runtime: frame protocol + root-sequenced
    collectives, the rendezvous under the sharded ingest."""

    @pytest.mark.parametrize("n", [2, 3, 4])
    def test_allreduce_sum_is_exact_and_replicated(self, n):
        rng = np.random.default_rng(n)
        parts = [rng.integers(0, 1000, 64).astype(np.int32)
                 for _ in range(n)]
        want = np.sum(parts, axis=0, dtype=np.int32)
        got = _run_ranks(_make_comms(n),
                         lambda c: c.allreduce_sum(parts[c.rank]))
        for g in got:
            np.testing.assert_array_equal(g, want)

    def test_barrier_and_bcast(self):
        def body(comm):
            comm.barrier()
            return comm.bcast_bytes(b"payload" if comm.rank == 0
                                    else None)
        assert _run_ranks(_make_comms(3), body) == [b"payload"] * 3

    def test_tag_mismatch_aborts_loudly(self):
        def body(comm):
            if comm.rank == 0:
                comm.send(1, 7, b"x")
                return None
            with pytest.raises(MpiLiteError, match="tag mismatch"):
                comm.recv(0, 8)
            return True
        _run_ranks(_make_comms(2), body)

    def test_from_env_requires_launcher(self, monkeypatch):
        for var in ("MPILITE_RANK", "MPILITE_SIZE", "MPILITE_FDS"):
            monkeypatch.delenv(var, raising=False)
        with pytest.raises(MpiLiteError, match="launcher"):
            MpiLiteComm.from_env()

    def test_from_env_rejects_malformed_fds(self, monkeypatch):
        monkeypatch.setenv("MPILITE_RANK", "0")
        monkeypatch.setenv("MPILITE_SIZE", "2")
        monkeypatch.setenv("MPILITE_FDS", "-1,notanint")
        with pytest.raises(MpiLiteError, match="malformed"):
            MpiLiteComm.from_env()

    def test_shard_bounds_cover_contiguously(self):
        for docs, workers in ((26, 4), (5, 2), (8, 8), (3, 7), (0, 2)):
            bounds = shard_bounds(docs, workers)
            assert bounds[0][0] == 0 and bounds[-1][1] == docs
            for (_, a_hi), (b_lo, _) in zip(bounds, bounds[1:]):
                assert a_hi == b_lo
            # Never more shards than documents (empty shards would
            # make run_overlapped raise in a worker).
            assert all(hi > lo for lo, hi in bounds) or docs == 0


class TestSingleHost:
    def test_noop_reports_local_topology(self):
        # No coordinator args, no cluster env: must not try to bring up
        # a distributed runtime, just report what jax already sees.
        assert not os.environ.get("JAX_COORDINATOR_ADDRESS")
        topo = initialize()
        assert isinstance(topo, HostTopology)
        assert topo.process_id == 0
        assert topo.num_processes == 1
        assert topo.local_devices == topo.global_devices
        assert topo.local_devices >= 1

    def test_idempotent(self):
        assert initialize() == initialize()


_WORKER = r"""
import sys
import jax
# CPU-backend stand-in for a TPU pod: gloo carries the cross-process
# collectives that ICI/DCN would on real hardware.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from tfidf_tpu.parallel.multihost import initialize
topo = initialize(coordinator_address=sys.argv[1],
                  num_processes=2, process_id=int(sys.argv[2]))
assert topo.num_processes == 2, topo
assert topo.process_id == int(sys.argv[2]), topo
assert topo.global_devices == 2 * topo.local_devices, topo
# One collective over DCN (gRPC on localhost here): psum of the
# process id across both processes must be 0 + 1 everywhere.
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from tfidf_tpu.parallel.compat import shard_map
devs = jax.devices()
mesh = Mesh(devs, ("d",))
got = jax.jit(
    shard_map(lambda x: jax.lax.psum(x, "d"), mesh=mesh,
              in_specs=P("d"), out_specs=P()),
)(jnp.arange(len(devs), dtype=jnp.float32))
assert float(got[0]) == sum(range(len(devs))), got

# THE PIPELINE across processes (round 4, VERDICT r3 item 6a): the
# docs-sharded TF-IDF forward runs over the process-spanning mesh —
# its DF psum and top-k all_gather ride the gloo transport — and must
# equal a single-device run of the same batch exactly.
import numpy as np
from jax import lax
from tfidf_tpu.parallel.collectives import make_sharded_forward
from tfidf_tpu.parallel.mesh import MeshPlan
from tfidf_tpu.ops.histogram import tf_counts_masked
from tfidf_tpu.ops.scoring import idf_from_df

plan = MeshPlan.create(docs=len(devs), devices=devs)
vocab, d, L, k = 256, 8, 16, 3
rng = np.random.default_rng(0)  # same batch in every process
toks = rng.integers(0, vocab, (d, L)).astype(np.int32)
lens = np.asarray(rng.integers(1, L + 1, (d,)), dtype=np.int32)
tok_g = jax.make_array_from_callback(
    (d, L), plan.sharding(plan.batch_spec()), lambda idx: toks[idx])
len_g = jax.make_array_from_callback(
    (d,), plan.sharding(plan.lengths_spec()), lambda idx: lens[idx])
fwd = make_sharded_forward(plan, vocab, jnp.float32, topk=k)
df, vals, ids = fwd(tok_g, len_g, jnp.int32(d))

@jax.jit
def ref_dense(tokens, lengths):
    live = (jnp.arange(tokens.shape[1])[None, :] < lengths[:, None])
    counts = tf_counts_masked(tokens, live, vocab, id_offset=0)
    rdf = (counts > 0).astype(jnp.int32).sum(axis=0)
    idf = idf_from_df(rdf, jnp.int32(d), jnp.float32)
    scores = counts.astype(jnp.float32) \
        / jnp.maximum(lengths, 1).astype(jnp.float32)[:, None] \
        * idf[None, :]
    rvals, rids = lax.top_k(scores, k)
    return rdf, rvals, rids

rdf, rvals, rids = ref_dense(toks, lens)
rdf, rvals, rids = np.asarray(rdf), np.asarray(rvals), np.asarray(rids)
# DF is replicated -> fully addressable everywhere; top-k rows are
# docs-sharded -> compare this process's addressable shards only.
np.testing.assert_array_equal(np.asarray(df.addressable_shards[0].data),
                              rdf)
for arr, ref in ((vals, rvals), (ids, rids)):
    for shard in arr.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data),
                                   ref[shard.index], rtol=1e-6)

# Streaming incremental DF (BASELINE config 5) across the same
# process-spanning mesh: the minibatch update's psum crosses the
# process boundary; the folded DF must equal the dense reference's.
from tfidf_tpu.streaming import _mesh_update_sparse_fn
upd = _mesh_update_sparse_fn(plan, vocab)
df_state = jnp.zeros((vocab,), jnp.int32)
for lo in range(0, d, d // 2):  # two minibatches
    bt = jax.make_array_from_callback(
        (d // 2, L), plan.sharding(plan.batch_spec()),
        lambda idx, lo=lo: toks[lo:lo + d // 2][idx])
    bl = jax.make_array_from_callback(
        (d // 2,), plan.sharding(plan.lengths_spec()),
        lambda idx, lo=lo: lens[lo:lo + d // 2][idx])
    df_state = upd(df_state, bt, bl)
np.testing.assert_array_equal(
    np.asarray(df_state.addressable_shards[0].data), rdf)
print("OK", topo.process_id)
"""


class TestTwoProcess:
    def test_distributed_smoke_localhost(self, tmp_path):
        """2-process jax.distributed bring-up + one cross-process psum."""
        import socket
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # 1 CPU device per process
        # Ask the kernel for a free port instead of hardcoding one: a
        # concurrent run (or a TIME_WAIT socket from the last one) on a
        # fixed port would flake.
        with socket.socket() as s:
            s.bind(("localhost", 0))
            addr = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, "-c", _WORKER, addr, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env) for pid in range(2)]
        try:
            outs = [p.communicate(timeout=120) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"rc={p.returncode}\n{out}\n{err}"
        # gloo prints connection chatter on stdout; the verdict is the
        # last line each worker prints.
        assert sorted(o.strip().splitlines()[-1]
                      for o, _ in outs) == ["OK 0", "OK 1"]


_INGEST_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from tfidf_tpu.parallel.multihost import initialize
topo = initialize(coordinator_address=sys.argv[1],
                  num_processes=2, process_id=int(sys.argv[2]))
input_dir, expect_npz = sys.argv[3], sys.argv[4]

# The FLAGSHIP ingest across real processes (VERDICT r4 item 4): the
# docs-sharded resident run_overlapped over a process-spanning mesh.
# Each process packs only its own shards' documents (per-process chunk
# ingest); the run's single DF psum and the result allgather cross the
# gloo transport. The expected arrays were produced by the SAME mesh
# shape on two single-process devices, so every float op is identical
# and the comparison is exact.
import numpy as np
from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.ingest import run_overlapped
from tfidf_tpu.parallel.mesh import MeshPlan

plan = MeshPlan.create(docs=2, devices=jax.devices())
assert jax.process_count() == 2
cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                     topk=4, engine="sparse")
r = run_overlapped(input_dir, cfg, chunk_docs=16, doc_len=32, plan=plan)
exp = np.load(expect_npz)
np.testing.assert_array_equal(r.topk_ids, exp["ids"])
np.testing.assert_array_equal(np.asarray(r.df), exp["df"])
np.testing.assert_array_equal(r.topk_vals, exp["vals"])
np.testing.assert_array_equal(r.lengths, exp["lengths"])
assert r.path == "resident-mesh", r.path
print("OK", topo.process_id)
"""


class TestTwoProcessIngest:
    def test_flagship_mesh_ingest_across_processes(self, tmp_path):
        """run_overlapped's mesh regime over 2 jax.distributed
        processes == the same mesh on one process, bit for bit."""
        import socket

        import numpy as np

        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.mesh import MeshPlan
        import jax

        d = tmp_path / "input"
        d.mkdir()
        rng = np.random.default_rng(9)
        for i in range(1, 25):
            (d / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 200)}"
                         for _ in range(rng.integers(1, 30))))
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                             topk=4, engine="sparse")
        plan1 = MeshPlan.create(docs=2, devices=jax.devices("cpu")[:2])
        ref = run_overlapped(str(d), cfg, chunk_docs=16, doc_len=32,
                             plan=plan1)
        expect = tmp_path / "expect.npz"
        np.savez(expect, ids=ref.topk_ids, vals=ref.topk_vals,
                 df=np.asarray(ref.df), lengths=ref.lengths)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)  # 1 CPU device per process
        with socket.socket() as s:
            s.bind(("localhost", 0))
            addr = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, "-c", _INGEST_WORKER, addr, str(pid),
             str(d), str(expect)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env) for pid in range(2)]
        try:
            outs = [p.communicate(timeout=180) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"rc={p.returncode}\n{out}\n{err}"
        assert sorted(o.strip().splitlines()[-1]
                      for o, _ in outs) == ["OK 0", "OK 1"]


_STREAM_WORKER = r"""
import os
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from tfidf_tpu.parallel.multihost import initialize
topo = initialize(coordinator_address=sys.argv[1],
                  num_processes=2, process_id=int(sys.argv[2]))
input_dir, expect_npz = sys.argv[3], sys.argv[4]

# The beyond-HBM regime across processes: force the streaming-mesh
# path (resident budget 0) and pin bit-parity against the same mesh
# shape on one process.
os.environ["TFIDF_TPU_RESIDENT_ELEMS"] = "0"
import numpy as np
from tfidf_tpu.config import PipelineConfig, VocabMode
from tfidf_tpu.ingest import run_overlapped
from tfidf_tpu.parallel.mesh import MeshPlan

plan = MeshPlan.create(docs=2, devices=jax.devices())
cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                     topk=4, engine="sparse")
r = run_overlapped(input_dir, cfg, chunk_docs=16, doc_len=32, plan=plan)
assert r.path == "streaming-mesh", r.path
exp = np.load(expect_npz)
np.testing.assert_array_equal(r.topk_ids, exp["ids"])
np.testing.assert_array_equal(np.asarray(r.df), exp["df"])
np.testing.assert_array_equal(r.topk_vals, exp["vals"])
np.testing.assert_array_equal(r.lengths, exp["lengths"])
print("OK", topo.process_id)
"""


class TestTwoProcessStreamingMesh:
    def test_streaming_mesh_across_processes(self, tmp_path, monkeypatch):
        import socket

        import numpy as np

        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.mesh import MeshPlan
        import jax

        d = tmp_path / "input"
        d.mkdir()
        rng = np.random.default_rng(13)
        for i in range(1, 25):
            (d / f"doc{i}").write_text(
                " ".join(f"w{rng.integers(0, 200)}"
                         for _ in range(rng.integers(1, 30))))
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=2048,
                             topk=4, engine="sparse")
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        plan1 = MeshPlan.create(docs=2, devices=jax.devices("cpu")[:2])
        ref = run_overlapped(str(d), cfg, chunk_docs=16, doc_len=32,
                             plan=plan1)
        assert ref.path == "streaming-mesh"
        expect = tmp_path / "expect.npz"
        np.savez(expect, ids=ref.topk_ids, vals=ref.topk_vals,
                 df=np.asarray(ref.df), lengths=ref.lengths)

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        with socket.socket() as s:
            s.bind(("localhost", 0))
            addr = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, "-c", _STREAM_WORKER, addr, str(pid),
             str(d), str(expect)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env) for pid in range(2)]
        try:
            outs = [p.communicate(timeout=180) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, (out, err) in zip(procs, outs):
            assert p.returncode == 0, f"rc={p.returncode}\n{out}\n{err}"
        assert sorted(o.strip().splitlines()[-1]
                      for o, _ in outs) == ["OK 0", "OK 1"]


def _write_corpus(path, n_docs, seed, n_words=300, max_len=40):
    rng = np.random.default_rng(seed)
    path.mkdir()
    for i in range(1, n_docs + 1):
        (path / f"doc{i}").write_text(
            " ".join(f"w{rng.integers(0, n_words)}"
                     for _ in range(rng.integers(1, max_len))))
    return str(path)


def _assert_bit_identical(ref, got):
    """The full round-19 parity contract: DF, scores (IDF-weighted),
    ids (tie order rides in them — lax.top_k per row), lengths, names."""
    np.testing.assert_array_equal(np.asarray(ref.df), np.asarray(got.df))
    np.testing.assert_array_equal(ref.topk_vals, got.topk_vals)
    np.testing.assert_array_equal(ref.topk_ids, got.topk_ids)
    np.testing.assert_array_equal(ref.lengths, got.lengths)
    assert ref.names == got.names
    assert ref.df_occupied == got.df_occupied


class TestShardedIngest:
    """Multi-process sharded ingest (round 19): N OS-process workers
    over mpi_lite-style channels must merge to a BIT-identical index.
    Per-doc rows depend only on the doc's own tokens + the global
    DF/IDF, so the property must hold across worker counts, chunk
    boundaries, and a ragged last shard."""

    def _cfg(self):
        from tfidf_tpu.config import PipelineConfig, VocabMode
        return PipelineConfig(vocab_mode=VocabMode.HASHED,
                              vocab_size=2048, topk=4, engine="sparse")

    def test_two_worker_bit_parity(self, tmp_path):
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.multihost import run_sharded_ingest
        d = _write_corpus(tmp_path / "input", 25, seed=11)  # ragged
        cfg = self._cfg()
        ref = run_overlapped(d, cfg, chunk_docs=8, doc_len=32)
        got, info = run_sharded_ingest(d, cfg, n_workers=2,
                                       chunk_docs=8, doc_len=32)
        _assert_bit_identical(ref, got)
        assert info.n_workers == 2
        assert info.shards == [(0, 12), (12, 25)]
        assert len(info.link_utilization) == 2
        assert got.path.startswith("sharded-2proc")

    @pytest.mark.slow
    @pytest.mark.parametrize("n_workers,n_docs,seed", [
        (2, 30, 21), (4, 26, 22), (3, 17, 23)])
    def test_sharded_parity_property(self, tmp_path, n_workers, n_docs,
                                     seed):
        """Random corpora x worker counts, every last shard ragged —
        the CI smoke stage (tools/ci_check.sh) runs exactly this."""
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.multihost import run_sharded_ingest
        d = _write_corpus(tmp_path / "input", n_docs, seed=seed)
        cfg = self._cfg()
        ref = run_overlapped(d, cfg, chunk_docs=8, doc_len=32)
        got, info = run_sharded_ingest(d, cfg, n_workers=n_workers,
                                       chunk_docs=8, doc_len=32)
        _assert_bit_identical(ref, got)
        assert [lo for lo, _ in info.shards][0] == 0
        assert info.shards[-1][1] == n_docs

    @pytest.mark.slow
    def test_sharded_parity_streaming_regime(self, tmp_path,
                                             monkeypatch):
        """Workers forced past the resident budget: the DF allreduce
        slots into the streaming pass-A/B boundary instead."""
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.multihost import run_sharded_ingest
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        d = _write_corpus(tmp_path / "input", 24, seed=31)
        cfg = self._cfg()
        ref = run_overlapped(d, cfg, chunk_docs=8, doc_len=32)
        assert ref.path == "streaming"
        got, _ = run_sharded_ingest(d, cfg, n_workers=2,
                                    chunk_docs=8, doc_len=32)
        assert got.path == "sharded-2proc:streaming"
        _assert_bit_identical(ref, got)

    @pytest.mark.slow
    def test_sharded_parity_pair_result_wire(self, tmp_path):
        """The pair-wire fused finish must route the merged DF through
        the gather join (sort-join's per-slot DF is local-triples-only
        — the mesh rule); parity pins it."""
        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.multihost import run_sharded_ingest
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=2048, topk=4, engine="sparse",
                             result_wire="pair")
        d = _write_corpus(tmp_path / "input", 20, seed=41)
        ref = run_overlapped(d, cfg, chunk_docs=8, doc_len=32)
        assert ref.result_wire == "pair"
        got, _ = run_sharded_ingest(d, cfg, n_workers=2,
                                    chunk_docs=8, doc_len=32)
        _assert_bit_identical(ref, got)

    def test_mesh_plan_excludes_process_hooks(self, tmp_path):
        import jax

        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.parallel.mesh import MeshPlan
        d = _write_corpus(tmp_path / "input", 4, seed=51)
        plan = MeshPlan.create(docs=1, devices=jax.devices("cpu")[:1])
        with pytest.raises(ValueError, match="multi-PROCESS"):
            run_overlapped(d, self._cfg(), chunk_docs=4, doc_len=16,
                           plan=plan, shard=(0, 2))

    def test_shard_slice_validates(self, tmp_path):
        from tfidf_tpu.ingest import run_overlapped
        d = _write_corpus(tmp_path / "input", 4, seed=52)
        with pytest.raises(ValueError, match="shard"):
            run_overlapped(d, self._cfg(), chunk_docs=4, doc_len=16,
                           shard=(2, 99))

    @pytest.mark.slow
    def test_ingest_mh_bench_artifact_and_ledger(self, tmp_path):
        """The tool end-to-end on a tiny corpus: artifact schema,
        parity verdict, and the ledger files it as kind=ingest_mh."""
        out = tmp_path / "INGEST_MH_test.json"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "ingest_mh_bench.py"),
             "--docs", "96", "--doc-len", "32", "--workers", "2",
             "--repeat", "1", "--out", str(out)],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, proc.stderr[-2000:]
        art = json.loads(out.read_text())
        assert art["metric"] == "ingest_mh"
        assert art["parity_ok"] == 1
        assert art["n_workers"] == 2
        assert len(art["link_utilization"]) == 2
        assert art["upload_s"] > 0 and art["upload_s_1p"] > 0
        sys.path.insert(0, os.path.join(repo, "tools"))
        try:
            import perf_ledger
            rec, reason = perf_ledger.normalize(str(out))
        finally:
            sys.path.pop(0)
        assert reason is None and rec["kind"] == "ingest_mh"
        assert rec["metrics"]["parity_ok"] == 1
        assert rec["context"]["n_workers"] == 2
