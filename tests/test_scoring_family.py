"""Scoring-family subsystem (round 23): BM25, field weights and
filtered queries through the same tiled kernel.

The contracts pinned here, in order of how expensive they are to lose:

* **Default bit-identity by construction**: the tfidf scorer with no
  filter runs EXACTLY the pre-round-23 code path — ``scorer="tfidf"``
  and no-arg ``search`` must be bit-equal on every tier.
* **Oracle bit-parity per scorer**: doc IDS and TIE ORDER match the
  pure-numpy oracle (``scoring.oracle``) exactly; scores allclose
  (L-slot accumulation order is float32's one degree of freedom).
* **Tiled == untiled per scorer**: ``--score-tiling=off`` stays an
  exact fallback for every family member, not just tfidf.
* **Filters are visibility, composed by AND**: filter ∘ tombstone over
  the segmented index behaves as a boolean AND of allow-masks; corpus
  statistics stay global.
* **The family rides every tier**: segmented views, the mesh-sharded
  retriever, the serve batcher (mixed-scorer batches never share a
  dispatch or a cache row), snapshots, and the canary.
* **Zero recompiles after warm**: k1/b are traced scalars and every
  scorer face shares one tiled jit — a scorer/parameter switch never
  mints a program.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.io.corpus import Corpus
from tfidf_tpu.models import TfidfRetriever
from tfidf_tpu.models.retrieval import query_matrix
from tfidf_tpu.ops.sparse import (score_topk_tiled,
                                  score_topk_tiled_cache_size)
from tfidf_tpu.recall import retrieval_recall_at_k, scorer_overlap_at_k
from tfidf_tpu.scoring import oracle
from tfidf_tpu.scoring.family import (DEFAULT_B, DEFAULT_K1, ScorerSpec,
                                      parse_scorer, resolve_scorer,
                                      scorer_key, spec_from_parts)
from tfidf_tpu.scoring.filters import (FilterSpec, filter_key,
                                       filter_mask, parse_filter)
from tfidf_tpu.serve import TfidfServer

CFG = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=512,
                     max_doc_len=32, doc_chunk=32)

WORDS = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
         "lam mu nu xi omicron pi").split()

# Oracle-parity corpora draw from a WIDE vocabulary: ids + tie order
# are pinned bit-identical vs the numpy oracle, which requires score
# gaps above float32 fusion noise (~1 ulp) between distinct docs — a
# 16-word pool makes sub-ulp near-ties common, 64 words does not.
# Exact ties (duplicate docs) stay covered by the dedicated tie tests.
WIDE_WORDS = [f"term{i:02d}" for i in range(64)]


def make_corpus(n_docs, seed=0, vocab=WORDS, prefix="doc"):
    rng = random.Random(seed)
    names = [f"{prefix}{i}" for i in range(n_docs)]
    docs = [" ".join(rng.choice(vocab)
                     for _ in range(rng.randint(3, 20))).encode()
            for _ in range(n_docs)]
    return Corpus(names=names, docs=docs)


def make_queries(n, seed=0, vocab=WORDS):
    rng = random.Random(1000 + seed)
    return [" ".join(rng.choice(vocab)
                     for _ in range(rng.randint(1, 4)))
            for _ in range(n)]


SCORERS = ["tfidf", "bm25", "bm25:k1=1.5,b=0.6", "bm25:k1=0.0,b=0.0"]


def oracle_search(r, queries, k, scorer=None, filter=None):
    """The NumPy reference every device path is pinned against: the
    retriever's own derived host face + the same query columns, ranked
    by the oracle's lexsort (score desc, row asc — lax.top_k's
    discipline), trimmed to the device result width."""
    spec = r.scorer if scorer is None else parse_scorer(scorer)
    data, cols = r.scorer_face(spec)
    rows = data.shape[0]
    live = np.zeros((rows,), bool)
    live[:r._num_docs] = True
    fspec = parse_filter(filter)
    if fspec is not None:
        live[:r._num_docs] &= filter_mask(fspec, r._num_docs,
                                          names=r.names)
    qmat = query_matrix(
        queries, r.config, np.asarray(r._idf),
        mode="counts" if spec.kind == "bm25" else "cosine")
    vals, ids = oracle.oracle_topk(data, cols, live, qmat, k)
    width = min(k, r._num_docs)
    return vals[:, :width], ids[:, :width]


def assert_matches_oracle(got, want, ctx=""):
    gv, gi = got
    wv, wi = want
    np.testing.assert_array_equal(np.asarray(gi), wi, err_msg=ctx)
    np.testing.assert_allclose(np.asarray(gv), wv, rtol=1e-5,
                               atol=1e-6, err_msg=ctx)


class TestSpecParsing:
    """Host-side spec layer: canonical keys, every input form, and
    loud failure on malformed requests."""

    def test_canonical_keys_round_trip(self):
        for raw in SCORERS:
            spec = parse_scorer(raw)
            assert parse_scorer(spec.key()) == spec
        assert scorer_key(None) == "tfidf"
        assert scorer_key("bm25") == f"bm25:b={DEFAULT_B:g},k1={DEFAULT_K1:g}"
        assert scorer_key({"kind": "bm25", "k1": 1.5, "b": 0.6}) == \
            scorer_key("bm25:k1=1.5,b=0.6") == "bm25:b=0.6,k1=1.5"

    def test_tfidf_normalizes_params(self):
        # Spec equality == scoring equality: tfidf ignores k1/b, so
        # the spec forgets them too.
        assert parse_scorer({"kind": "tfidf", "k1": 9.0}) == ScorerSpec()
        assert scorer_key("tfidf") == "tfidf"

    @pytest.mark.parametrize("bad", [
        "cosine", "bm25:k1=", "bm25:q=3", "bm25:k1=-1",
        "bm25:b=1.5", 42, {"kind": "bm25", "alpha": 1},
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises((ValueError, TypeError)):
            parse_scorer(bad)

    def test_spec_from_parts(self):
        # Bare kind + standalone knobs compose; inline params win.
        assert spec_from_parts(None, None, None) == ScorerSpec()
        assert spec_from_parts("bm25", 1.5, None) == \
            ScorerSpec("bm25", k1=1.5, b=DEFAULT_B)
        assert spec_from_parts("bm25:k1=2.0,b=0.5", 1.5, 0.9) == \
            ScorerSpec("bm25", k1=2.0, b=0.5)

    def test_resolve_scorer_env(self, monkeypatch):
        monkeypatch.delenv("TFIDF_TPU_SCORER", raising=False)
        assert resolve_scorer() == ScorerSpec()
        monkeypatch.setenv("TFIDF_TPU_SCORER", "bm25")
        monkeypatch.setenv("TFIDF_TPU_BM25_K1", "1.7")
        monkeypatch.setenv("TFIDF_TPU_BM25_B", "0.4")
        assert resolve_scorer() == ScorerSpec("bm25", k1=1.7, b=0.4)
        # An inline spec ignores the standalone knobs...
        monkeypatch.setenv("TFIDF_TPU_SCORER", "bm25:k1=2.5")
        assert resolve_scorer() == ScorerSpec("bm25", k1=2.5)
        # ...and an explicit argument beats the env outright.
        assert resolve_scorer("tfidf") == ScorerSpec()

    def test_filter_forms_and_keys(self):
        assert parse_filter(None) is None and filter_key(None) == ""
        f = parse_filter({"ids": [7, 3, 3]})
        assert f.key() == '{"ids":[3,7]}'
        assert parse_filter(f.key()).key() == f.key()  # round-trips
        assert filter_key({"id_range": [2, 9]}) == '{"id_range":[2,9]}'
        assert filter_key({"prefix": "a/"}) == '{"prefix":"a/"}'

    @pytest.mark.parametrize("bad", [
        {"ids": [1], "prefix": "x"}, {"tenant": "a"}, {"ids": "1,2"},
        {"ids": [True]}, {"id_range": [3]}, {"id_range": [5, 1]},
        {"prefix": 7}, "not json", [1, 2],
    ])
    def test_malformed_filters_raise(self, bad):
        with pytest.raises((ValueError, TypeError)):
            parse_filter(bad)

    def test_filter_mask_semantics(self):
        names = ["a/1", "a/2", "b/1", None]
        m = filter_mask(FilterSpec(kind="ids", ids=(0, 2, 99)), 4)
        assert m.tolist() == [True, False, True, False]  # 99 ignored
        m = filter_mask(FilterSpec(kind="id_range", lo=-5, hi=2), 4)
        assert m.tolist() == [True, True, False, False]  # clamped
        m = filter_mask(FilterSpec(kind="prefix", prefix="a/"), 4,
                        names=names)
        assert m.tolist() == [True, True, False, False]  # None: never
        with pytest.raises(ValueError):
            filter_mask(FilterSpec(kind="prefix", prefix="a"), 4)


class TestFaceParity:
    """The derived device faces vs their pure-numpy mirrors: the
    elementwise weight math is IEEE on both sides, so the arrays
    themselves compare BIT-equal, not just allclose."""

    @pytest.mark.parametrize("spec", SCORERS)
    def test_device_face_equals_oracle_face(self, spec):
        r = TfidfRetriever(CFG).index(make_corpus(23, seed=3))
        s = parse_scorer(spec)
        got_d, got_c = r.scorer_face(s)
        tol = dict(rtol=3e-7, atol=1e-7)  # XLA FMA fusion: 1 ulp
        ids = np.asarray(r._ids)
        head = np.asarray(r._head)
        counts, lengths = oracle.counts_from_sorted(ids, head)
        df = oracle.df_from_sorted(ids, head, CFG.vocab_size)
        n = r._num_docs
        if s.kind == "tfidf":
            want_d, want_c = oracle.tfidf_face(ids, counts, head,
                                               lengths, df, n)
        else:
            avgdl = np.float32(np.float32(int(lengths[:n].sum()))
                               / np.float32(n))
            want_d, want_c = oracle.bm25_face(ids, counts, head,
                                              lengths, df, n, avgdl,
                                              s.k1, s.b)
        np.testing.assert_allclose(got_d, want_d, **tol)
        np.testing.assert_array_equal(got_c, want_c)

    def test_bm25_idf_stays_positive(self):
        # Lucene idf > 0 even at df == N — the repo-wide ``vals > 0``
        # real-result mask survives ubiquitous terms (raw Robertson
        # idf would go negative past df > N/2 and mask real hits).
        df = np.array([0, 1, 50, 99, 100])
        idf = oracle.bm25_idf(df, 100)
        assert idf[0] == 0.0
        assert (idf[1:] > 0).all()


class TestFlatParity:
    """TfidfRetriever.search: every (scorer, filter) bit-identical to
    the oracle and to the untiled fallback, default path untouched."""

    @pytest.mark.parametrize("spec", SCORERS)
    @pytest.mark.parametrize("q", [1, 7, 65])
    def test_oracle_parity_across_widths(self, spec, q):
        r = TfidfRetriever(CFG).index(
            make_corpus(31, seed=q, vocab=WIDE_WORDS))
        queries = make_queries(q, seed=q, vocab=WIDE_WORDS)
        got = r.search(queries, k=5, scorer=spec)
        want = oracle_search(r, queries, 5, scorer=spec)
        assert_matches_oracle(got, want, ctx=f"{spec} q={q}")

    @pytest.mark.parametrize("spec", SCORERS)
    def test_tiled_equals_untiled(self, spec, monkeypatch):
        r = TfidfRetriever(CFG).index(make_corpus(29, seed=11))
        queries = make_queries(9, seed=11)
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "on")
        on = r.search(queries, k=6, scorer=spec)
        monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "off")
        off = r.search(queries, k=6, scorer=spec)
        np.testing.assert_array_equal(on[0], off[0])
        np.testing.assert_array_equal(on[1], off[1])

    def test_default_scorer_is_the_legacy_path_bitwise(self):
        r = TfidfRetriever(CFG).index(
            make_corpus(17, seed=2, vocab=WIDE_WORDS))
        queries = make_queries(8, seed=2, vocab=WIDE_WORDS)
        plain = r.search(queries, k=4)
        explicit = r.search(queries, k=4, scorer="tfidf")
        np.testing.assert_array_equal(plain[0], explicit[0])
        np.testing.assert_array_equal(plain[1], explicit[1])
        assert_matches_oracle(plain, oracle_search(r, queries, 4))

    def test_index_level_default_scorer(self):
        # A retriever CONSTRUCTED bm25-default serves bm25 with no
        # per-call argument — and a per-call tfidf still overrides.
        corpus = make_corpus(19, seed=4)
        queries = make_queries(7, seed=4)
        base = TfidfRetriever(CFG).index(corpus)
        bm = TfidfRetriever(CFG, scorer="bm25").index(corpus)
        dv, di = bm.search(queries, k=5)
        wv, wi = base.search(queries, k=5, scorer="bm25")
        np.testing.assert_array_equal(di, wi)
        np.testing.assert_array_equal(dv, wv)
        tv, ti = bm.search(queries, k=5, scorer="tfidf")
        bv, bi = base.search(queries, k=5)
        np.testing.assert_array_equal(ti, bi)
        np.testing.assert_array_equal(tv, bv)

    def test_bm25_actually_ranks_differently(self):
        # Guard against the subsystem degenerating into a renamed
        # tfidf: on a seeded corpus the two top-k sets must differ.
        r = TfidfRetriever(CFG).index(make_corpus(60, seed=5))
        queries = make_queries(32, seed=5)
        _, ti = r.search(queries, k=10)
        _, bi = r.search(queries, k=10, scorer="bm25")
        assert scorer_overlap_at_k(ti, bi, 10) < 1.0

    def test_bm25_k1_zero_ignores_tf(self):
        # k1=0 collapses the saturation to 1: a doc repeating the
        # query term scores exactly like one mentioning it once, so
        # ties resolve by row — observable, parameter-level semantics.
        corpus = Corpus(names=["d0", "d1", "d2"],
                        docs=[b"alpha beta", b"alpha alpha alpha beta",
                              b"gamma delta"])
        r = TfidfRetriever(CFG).index(corpus)
        vals, ids = r.search(["alpha"], k=3, scorer="bm25:k1=0,b=0")
        assert ids[0, 0] == 0 and ids[0, 1] == 1
        assert vals[0, 0] == vals[0, 1]

    def test_pallas_scope_extends_to_bm25(self):
        # The fused gather-accumulate kernel runs the bm25 face with
        # the same contract as phase B: ids bit-identical to the XLA
        # lowering, scores allclose.
        r = TfidfRetriever(CFG).index(make_corpus(37, seed=6))
        data, cols = r._scorer_face(parse_scorer("bm25"))
        qmat = jnp.asarray(query_matrix(
            make_queries(9, seed=6), CFG, np.asarray(r._idf),
            mode="counts"))
        want_v, want_i = score_topk_tiled(data, cols, None, qmat, 5,
                                          tile=16, method="xla")
        got_v, got_i = score_topk_tiled(data, cols, None, qmat, 5,
                                        tile=16, method="pallas")
        np.testing.assert_array_equal(np.asarray(got_i),
                                      np.asarray(want_i))
        np.testing.assert_allclose(np.asarray(got_v),
                                   np.asarray(want_v), rtol=1e-6)


class TestFilteredQueries:
    """Query-time visibility: results come only from the allowed set,
    statistics stay global, tombstones compose by AND."""

    @pytest.mark.parametrize("spec", ["tfidf", "bm25"])
    @pytest.mark.parametrize("filt", [
        {"ids": [0, 3, 5, 8, 12]},
        {"id_range": [4, 15]},
        {"prefix": "doc1"},            # doc1, doc10..doc19
    ])
    def test_filter_oracle_parity(self, spec, filt):
        r = TfidfRetriever(CFG).index(
            make_corpus(25, seed=7, vocab=WIDE_WORDS))
        queries = make_queries(11, seed=7, vocab=WIDE_WORDS)
        got = r.search(queries, k=6, scorer=spec, filter=filt)
        want = oracle_search(r, queries, 6, scorer=spec, filter=filt)
        assert_matches_oracle(got, want, ctx=f"{spec} {filt}")
        allow = filter_mask(parse_filter(filt), r._num_docs,
                            names=r.names)
        ids = np.asarray(got[1])
        real = ids[ids >= 0]
        assert allow[real].all(), "a filtered-out doc surfaced"

    def test_filter_keeps_global_statistics(self):
        # The SAME doc retrieved through two different filters scores
        # the SAME value — filters restrict candidates, they never
        # reweigh terms (tenant isolation without score skew).
        r = TfidfRetriever(CFG).index(make_corpus(20, seed=8))
        queries = make_queries(12, seed=8)
        gv, gi = r.search(queries, k=20)
        fv, fi = r.search(queries, k=20, filter={"id_range": [0, 10]})
        glob = {(q, int(d)): gv[q, c] for q in range(len(queries))
                for c, d in enumerate(gi[q]) if d >= 0}
        seen = 0
        for q in range(len(queries)):
            for c, d in enumerate(fi[q]):
                if d >= 0:
                    assert fv[q, c] == glob[(q, int(d))]
                    seen += 1
        assert seen > 0

    def test_empty_filter_result_masks_clean(self):
        r = TfidfRetriever(CFG).index(make_corpus(10, seed=9))
        vals, ids = r.search(make_queries(3, seed=9), k=4,
                             filter={"ids": []})
        assert (ids == -1).all() and (vals == 0.0).all()

    def test_filter_composes_with_tombstones(self):
        # Segmented index: delete doc A, filter allows {A, B} — only B
        # can surface. The boolean AND, observed end to end.
        from tfidf_tpu.index.segmented import SegmentedIndex
        idx = SegmentedIndex(CFG, delta_docs=4, compact_at=64)
        rng = random.Random(10)
        for i in range(12):
            idx.add_docs([f"d{i}"],
                         [" ".join(rng.choice(WORDS) for _ in range(8))])
        idx.delete_docs(["d2", "d5"])
        view = idx.view()
        allow = {"ids": [2, 3, 5, 7]}
        vals, ids = view.search(make_queries(9, seed=10), k=12,
                                filter=allow)
        surfaced = {int(d) for d in ids[ids >= 0]}
        assert surfaced <= {3, 7}, surfaced
        # Parity against the flat rebuild of the LIVE corpus under the
        # equivalent name-set filter (rows renumber after rebuild).
        oracle_r = idx.rebuild_retriever()
        want_names = {"d3", "d7"}
        rows = [i for i, nm in enumerate(oracle_r.names)
                if nm in want_names]
        wv, wi = oracle_r.search(make_queries(9, seed=10), k=12,
                                 filter={"ids": rows})
        got = [[None if d < 0 else view.names[d] for d in row]
               for row in ids]
        want = [[None if d < 0 else oracle_r.names[d] for d in row]
                for row in wi]
        assert got == want
        np.testing.assert_array_equal(vals, wv)


class TestFieldedIndex:
    """Per-field weights: stacked sub-indexes sharing one vocab, the
    weighted sum over fields IS the single row's dot."""

    def _fielded(self, w_title=3.0, w_body=1.0):
        names = [f"d{i}" for i in range(8)]
        rng = random.Random(20)
        titles = Corpus(names=names, docs=[
            b"alpha beta", b"gamma delta", b"epsilon zeta",
            b"eta theta", b"iota kappa", b"lam mu",
            b"nu xi", b"omicron pi"])
        bodies = Corpus(names=names, docs=[
            (" ".join(rng.choice(WORDS) for _ in range(12))).encode()
            for _ in range(8)])
        r = TfidfRetriever(CFG).index_fields(
            [("title", titles, w_title), ("body", bodies, w_body)])
        return r, titles, bodies

    def test_fielded_oracle_parity_both_scorers(self):
        r, _, _ = self._fielded()
        queries = make_queries(9, seed=20)
        for spec in ("tfidf", "bm25"):
            got = r.search(queries, k=5, scorer=spec)
            want = oracle_search(r, queries, 5, scorer=spec)
            assert_matches_oracle(got, want, ctx=spec)

    def test_title_weight_dominates(self):
        # "gamma delta" is d1's TITLE and appears nowhere else's
        # title; with a heavy title weight d1 must rank first even
        # though body text competes.
        r, _, _ = self._fielded(w_title=5.0, w_body=0.5)
        _, ids = r.search(["gamma delta"], k=3)
        assert ids[0, 0] == 1

    def test_field_weights_scale_stored_face(self):
        # Doubling every field weight scales scores but cannot change
        # the ranking — the weighted-sum factorization, observed.
        r1, _, _ = self._fielded(w_title=1.0, w_body=1.0)
        r2, _, _ = self._fielded(w_title=2.0, w_body=2.0)
        queries = make_queries(7, seed=21)
        _, i1 = r1.search(queries, k=4)
        _, i2 = r2.search(queries, k=4)
        np.testing.assert_array_equal(i1, i2)

    def test_misaligned_fields_raise(self):
        names = ["a", "b"]
        t = Corpus(names=names, docs=[b"x", b"y"])
        bad = Corpus(names=["a", "c"], docs=[b"x", b"y"])
        with pytest.raises(ValueError):
            TfidfRetriever(CFG).index_fields(
                [("title", t, 1.0), ("body", bad, 1.0)])
        with pytest.raises(ValueError):
            TfidfRetriever(CFG).index_fields([])


class TestSegmentedParity:
    """Segmented views serve the family with flat-rebuild bit-parity —
    the stacked face derivation is the same traced math."""

    def _index(self, n=14, seed=30, deletes=("d3", "d8")):
        from tfidf_tpu.index.segmented import SegmentedIndex
        idx = SegmentedIndex(CFG, delta_docs=4, compact_at=64)
        rng = random.Random(seed)
        for i in range(n):
            idx.add_docs([f"d{i}"],
                         [" ".join(rng.choice(WORDS) for _ in range(9))])
        idx.delete_docs(list(deletes))
        return idx

    @pytest.mark.parametrize("spec", SCORERS)
    def test_view_matches_flat_rebuild(self, spec):
        idx = self._index()
        view = idx.view()
        oracle_r = idx.rebuild_retriever()
        queries = make_queries(13, seed=30)
        vv, vi = view.search(queries, k=5, scorer=spec)
        wv, wi = oracle_r.search(queries, k=5, scorer=spec)
        got = [[None if d < 0 else view.names[d] for d in row]
               for row in vi]
        want = [[None if d < 0 else oracle_r.names[d] for d in row]
                for row in wi]
        assert got == want, spec
        np.testing.assert_array_equal(vv, wv)

    def test_view_tiled_equals_untiled(self, monkeypatch):
        idx = self._index(seed=31)
        view = idx.view()
        queries = make_queries(8, seed=31)
        for spec in ("bm25", "tfidf"):
            monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "on")
            on = view.search(queries, k=4, scorer=spec,
                             filter={"prefix": "d1"})
            monkeypatch.setenv("TFIDF_TPU_SCORE_TILING", "off")
            off = view.search(queries, k=4, scorer=spec,
                              filter={"prefix": "d1"})
            np.testing.assert_array_equal(on[0], off[0])
            np.testing.assert_array_equal(on[1], off[1])


def needs_devices(n):
    return pytest.mark.skipif(len(jax.devices()) < n,
                              reason=f"needs {n} virtual devices")


@needs_devices(2)
class TestMeshParity:
    """The sharded retriever serves the family bit-identically to its
    single-device source — the mesh program is scorer-agnostic."""

    @pytest.mark.parametrize("spec", ["tfidf", "bm25",
                                      "bm25:k1=1.5,b=0.6"])
    def test_sharded_matches_single(self, spec):
        from tfidf_tpu.parallel.serving import (make_serving_plan,
                                                shard_index)
        single = TfidfRetriever(CFG).index(make_corpus(13, seed=40))
        sharded = shard_index(single, make_serving_plan(2))
        queries = make_queries(9, seed=40)
        for filt in (None, {"id_range": [0, 7]}, {"prefix": "doc1"}):
            v1, i1 = single.search(queries, 5, scorer=spec,
                                   filter=filt)
            v2, i2 = sharded.search(queries, 5, scorer=spec,
                                    filter=filt)
            np.testing.assert_array_equal(i1, i2,
                                          err_msg=f"{spec} {filt}")
            np.testing.assert_array_equal(v1, v2)


class TestSnapshotRoundTrip:
    """The scorer rides snapshots; the default writes NOTHING — a
    round-22 snapshot and a round-23 default snapshot stay
    byte-identical."""

    def test_default_meta_is_unchanged(self, tmp_path):
        r = TfidfRetriever(CFG).index(make_corpus(9, seed=50))
        r.snapshot(str(tmp_path), epoch=1)
        r2, meta = TfidfRetriever.restore(str(tmp_path), CFG)
        assert "scorer" not in meta and "fields" not in meta
        assert r2.scorer == ScorerSpec()

    def test_bm25_scorer_round_trips(self, tmp_path):
        corpus = make_corpus(15, seed=51)
        r = TfidfRetriever(CFG, scorer="bm25:k1=1.5,b=0.6").index(corpus)
        r.snapshot(str(tmp_path), epoch=2)
        r2, meta = TfidfRetriever.restore(str(tmp_path), CFG)
        assert meta["scorer"] == "bm25:b=0.6,k1=1.5"
        assert r2.scorer == r.scorer
        queries = make_queries(8, seed=51)
        a = r.search(queries, k=5)
        b = r2.search(queries, k=5)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])

    def test_fielded_index_round_trips(self, tmp_path):
        names = [f"d{i}" for i in range(6)]
        titles = Corpus(names=names,
                        docs=[f"{WORDS[i]} {WORDS[i + 1]}".encode()
                              for i in range(6)])
        bodies = Corpus(names=names,
                        docs=[" ".join(WORDS[i:i + 5]).encode()
                              for i in range(6)])
        r = TfidfRetriever(CFG).index_fields(
            [("title", titles, 2.0), ("body", bodies, 1.0)])
        r.snapshot(str(tmp_path), epoch=3)
        r2, meta = TfidfRetriever.restore(str(tmp_path), CFG)
        assert r2._fields == r._fields
        queries = make_queries(6, seed=52)
        for spec in ("tfidf", "bm25"):
            a = r.search(queries, k=4, scorer=spec)
            b = r2.search(queries, k=4, scorer=spec)
            np.testing.assert_array_equal(a[0], b[0], err_msg=spec)
            np.testing.assert_array_equal(a[1], b[1], err_msg=spec)


class TestServeFamily:
    """The serve tier: per-request scorer/filter, group keys, cache
    isolation, the live default change, and the canary's scorer-aware
    golden capture."""

    CORPUS = None  # built once per class below

    @pytest.fixture()
    def retriever(self):
        if TestServeFamily.CORPUS is None:
            TestServeFamily.CORPUS = make_corpus(30, seed=60)
        return TfidfRetriever(CFG).index(TestServeFamily.CORPUS)

    def _cfg(self, **kw):
        kw.setdefault("max_batch", 8)
        kw.setdefault("max_wait_ms", 5)
        kw.setdefault("queue_depth", 64)
        kw.setdefault("cache_entries", 64)
        return ServeConfig(**kw)

    def test_served_parity_per_scorer_and_filter(self, retriever):
        queries = make_queries(10, seed=60)
        with TfidfServer(retriever, self._cfg()) as srv:
            for spec in SCORERS:
                for filt in (None, {"id_range": [0, 15]}):
                    sv, si = srv.search(queries, k=5, scorer=spec,
                                        filter=filt)
                    dv, di = retriever.search(queries, k=5,
                                              scorer=spec, filter=filt)
                    np.testing.assert_array_equal(
                        si, di, err_msg=f"{spec} {filt}")
                    np.testing.assert_array_equal(sv, dv)

    def test_cache_never_aliases_across_scorers(self, retriever):
        # Warm the cache under tfidf, then ask the SAME bytes under
        # bm25 (and vice versa, twice each): every answer must match a
        # direct search of its own scorer — a shared row would leak
        # the other family member's ranking.
        queries = make_queries(6, seed=61)
        with TfidfServer(retriever, self._cfg()) as srv:
            want = {s: retriever.search(queries, k=5, scorer=s)
                    for s in ("tfidf", "bm25")}
            for _round in range(2):
                for s in ("tfidf", "bm25"):
                    sv, si = srv.search(queries, k=5, scorer=s)
                    np.testing.assert_array_equal(si, want[s][1])
                    np.testing.assert_array_equal(sv, want[s][0])
            hits = srv.metrics_snapshot()["cache"]["hits"]
            assert hits >= len(queries) * 2   # second round all-hit

    def test_mixed_scorer_batch_isolation(self, retriever):
        # Concurrent submits alternating scorer: coalescing groups by
        # (epoch, retriever, scorer, filter), so each future resolves
        # to ITS scorer's bytes even when admitted together.
        queries = make_queries(12, seed=62)
        specs = [SCORERS[i % len(SCORERS)] for i in range(12)]
        with TfidfServer(retriever, self._cfg(max_wait_ms=20,
                                              cache_entries=0)) as srv:
            futs = [srv.submit([q], k=4, scorer=s)
                    for q, s in zip(queries, specs)]
            for q, s, f in zip(queries, specs, futs):
                sv, si = f.result(timeout=30)
                dv, di = retriever.search([q], k=4, scorer=s)
                np.testing.assert_array_equal(si, di, err_msg=s)
                np.testing.assert_array_equal(sv, dv)

    def test_malformed_request_fails_loud_not_wide(self, retriever):
        with TfidfServer(retriever, self._cfg()) as srv:
            with pytest.raises(ValueError):
                srv.submit(["alpha"], k=3, scorer="bogus")
            with pytest.raises(ValueError):
                srv.submit(["alpha"], k=3, filter={"tenant": "x"})
            # The server is still healthy after the rejects.
            sv, si = srv.search(["alpha"], k=3)
            dv, di = retriever.search(["alpha"], k=3)
            np.testing.assert_array_equal(si, di)

    def test_default_scorer_from_config(self, retriever):
        queries = make_queries(5, seed=63)
        cfg = self._cfg(scorer="bm25", bm25_k1=1.5, bm25_b=0.6)
        with TfidfServer(retriever, cfg) as srv:
            assert srv.default_scorer_key() == "bm25:b=0.6,k1=1.5"
            sv, si = srv.search(queries, k=4)
            dv, di = retriever.search(queries, k=4,
                                      scorer="bm25:k1=1.5,b=0.6")
            np.testing.assert_array_equal(si, di)
            np.testing.assert_array_equal(sv, dv)

    def test_set_scorer_bumps_epoch_and_recaptures_canary(self,
                                                          retriever):
        from tfidf_tpu.serve.canary import CanaryProber
        queries = make_queries(6, seed=64)
        with TfidfServer(retriever,
                         self._cfg(scorer="bm25")) as srv:
            canary = CanaryProber(srv, queries[:4], k=3)
            assert canary.probe() == 1.0      # golden captured bm25
            e0 = srv.epoch
            e1 = srv.set_scorer("tfidf")
            assert e1 == e0 + 1
            assert srv.default_scorer_key() == "tfidf"
            # The golden re-captured under the NEW default: parity
            # holds, and served bytes are now the tfidf bytes.
            assert canary.probe() == 1.0
            sv, si = srv.search(queries, k=4)
            dv, di = retriever.search(queries, k=4)
            np.testing.assert_array_equal(si, di)
            np.testing.assert_array_equal(sv, dv)
            canary.close()

    def test_config_validates_scorer_knobs(self):
        with pytest.raises(ValueError):
            ServeConfig(scorer="bogus")
        with pytest.raises(ValueError):
            ServeConfig(bm25_k1=-1.0)
        with pytest.raises(ValueError):
            ServeConfig(bm25_b=1.5)


class TestRecompileDiscipline:
    """Scorer switching after warm mints NOTHING: k1/b/N/avgdl are
    traced scalars and every derived face shares one tiled jit."""

    def test_zero_programs_across_scorer_and_param_switches(self):
        from tfidf_tpu.models.retrieval import _search_tiled
        r = TfidfRetriever(CFG).index(make_corpus(21, seed=70))
        queries = make_queries(8, seed=70)

        def total():
            return (_search_tiled._cache_size()
                    + score_topk_tiled_cache_size())

        # Warm: the default path, the scored unfiltered path, and the
        # scored filtered path (the live-mask arg changes the jit
        # signature once) at this (bucket, k).
        r.search(queries, k=5)
        r.search(queries, k=5, scorer="bm25")
        r.search(queries, k=5, filter={"id_range": [0, 10]})
        warm = total()
        for spec in ("bm25:k1=0.5,b=0.2", "bm25:k1=2.0,b=1.0",
                     "bm25", "tfidf"):
            r.search(queries, k=5, scorer=spec)
        for filt in ({"ids": [1, 5, 9]}, {"prefix": "doc2"},
                     {"id_range": [3, 18]}):
            r.search(queries, k=5, scorer="bm25", filter=filt)
            r.search(queries, k=5, filter=filt)
        # Same pow2 bucket at a different query count: still warm.
        r.search(queries[:5], k=5, scorer="bm25:k1=1.7,b=0.3")
        assert total() == warm, (
            f"scorer/parameter switching compiled "
            f"{total() - warm} new program(s)")

    def test_faces_cache_per_key_until_install(self):
        r = TfidfRetriever(CFG).index(make_corpus(11, seed=71))
        f1 = r._scorer_face(parse_scorer("bm25"))
        f2 = r._scorer_face(parse_scorer("bm25:k1=1.2,b=0.75"))
        assert f1 is f2                       # same canonical key
        f3 = r._scorer_face(parse_scorer("bm25:k1=2.0"))
        assert f3 is not f1
        r.index(make_corpus(11, seed=72))     # install invalidates
        assert r._scorer_face(parse_scorer("bm25")) is not f1


class TestRecallHelpers:
    """The satellite metrics the scoring artifact embeds."""

    def test_recall_at_k(self):
        got = np.array([[1, 2, 3], [4, -1, -1]])
        ora = np.array([[3, 2, 9], [4, 5, -1]])
        # q0: {1,2,3} vs {3,2,9} -> 2/3; q1: {4} vs {4,5} -> 1/2
        assert retrieval_recall_at_k(got, ora, 3) == \
            pytest.approx((2 / 3 + 1 / 2) / 2)
        assert retrieval_recall_at_k(ora, ora, 3) == 1.0
        # Empty-oracle queries drop out of the mean...
        ora2 = np.array([[3, 2, 9], [-1, -1, -1]])
        assert retrieval_recall_at_k(got, ora2, 3) == \
            pytest.approx(2 / 3)
        # ...and no defined queries at all is an error, not a 0.0.
        with pytest.raises(ValueError):
            retrieval_recall_at_k(got, np.full((2, 3), -1), 3)
        with pytest.raises(ValueError):
            retrieval_recall_at_k(got, ora[:1], 3)

    def test_scorer_overlap(self):
        a = np.array([[1, 2, 3], [7, 8, -1]])
        b = np.array([[3, 2, 1], [9, -1, -1]])
        # q0 jaccard 1.0; q1: {7,8} vs {9} -> 0
        assert scorer_overlap_at_k(a, b, 3) == pytest.approx(0.5)
        assert scorer_overlap_at_k(a, a, 3) == 1.0
        empty = np.full((2, 3), -1)
        with pytest.raises(ValueError):
            scorer_overlap_at_k(empty, empty, 3)
