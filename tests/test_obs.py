"""Observability layer: span tracer, trace export, metrics registry.

Pins (ISSUE 5): span nesting, cross-thread begin/end pairing, Chrome
trace-event JSON schema (valid ``ph``/``ts``/``dur``, distinct tids
for packer/drainer), Prometheus exposition format, the disabled-tracer
overhead guard (< 150 ns/span, slow-marked), the serve request-span
chain parity (every submitted query appears exactly once as drained /
cache_hit / a shed), and the queue-peak reset the registry gauge
fixes.
"""

import importlib.util
import json
import os
import threading
import time

import pytest

from tfidf_tpu import obs
from tfidf_tpu.config import PipelineConfig, ServeConfig, VocabMode
from tfidf_tpu.obs.registry import MetricsRegistry
from tfidf_tpu.serve.metrics import ServeMetrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts and ends with the global tracer disarmed —
    tracing must never leak into the rest of the suite."""
    obs.set_tracer(None)
    yield
    obs.set_tracer(None)


@pytest.fixture
def tracer(tmp_path):
    t = obs.Tracer()
    obs.set_tracer(t, str(tmp_path / "trace.json"))
    return t


def _load_trace_check():
    import sys
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:  # the script dir `python tools/x.py` has
        sys.path.append(tools)
    spec = importlib.util.spec_from_file_location(
        "trace_check", os.path.join(tools, "trace_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTracer:
    def test_span_nesting_records_both(self, tracer):
        with obs.span("outer", depth=0):
            time.sleep(0.002)
            with obs.span("inner", depth=1):
                time.sleep(0.001)
        evs = {name: (t0, dur, args)
               for name, _tid, t0, dur, args in tracer.events()}
        assert set(evs) == {"outer", "inner"}
        o_t0, o_dur, o_args = evs["outer"]
        i_t0, i_dur, _ = evs["inner"]
        # The child's interval nests inside the parent's.
        assert o_t0 <= i_t0 and i_t0 + i_dur <= o_t0 + o_dur
        assert o_args == {"depth": 0}

    def test_cross_thread_begin_end_pairs(self, tracer):
        h = obs.begin("request", n=3)
        done = threading.Event()

        def worker():
            obs.name_thread("resolver")
            with obs.span("work"):
                pass
            obs.end(h, outcome="drained")
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(5)
        by_name = {e[0]: e for e in tracer.events()}
        req = by_name["request"]
        work = by_name["work"]
        # The request span landed on the BEGINNING thread's lane and
        # carries both the begin-time and end-time args.
        assert req[1] != work[1]
        assert tracer.thread_label(req[1]) == "main"
        assert tracer.thread_label(work[1]) == "resolver"
        assert req[4] == {"n": 3, "outcome": "drained"}

    def test_end_merges_without_mutating_begin_args(self, tracer):
        base = {"n": 1}
        h = obs.begin("r", **base)
        obs.end(h, outcome="x")
        obs.set_tracer(None)
        assert base == {"n": 1}

    def test_ring_buffer_keeps_newest(self):
        t = obs.Tracer(capacity=4)
        obs.set_tracer(t)
        for i in range(10):
            with obs.span(f"s{i}"):
                pass
        names = [e[0] for e in t.events()]
        assert names == ["s6", "s7", "s8", "s9"]

    def test_disabled_calls_are_noops(self):
        assert not obs.enabled()
        with obs.span("x", a=1):
            pass
        obs.end(obs.begin("y"))
        obs.instant("z")
        obs.name_thread("w")
        assert obs.span_totals() == {}
        assert obs.export() is None

    def test_configure_from_env_and_idempotence(self, tmp_path,
                                                monkeypatch):
        path = str(tmp_path / "env_trace.json")
        monkeypatch.setenv("TFIDF_TPU_TRACE", path)
        assert obs.configure() == path
        t = obs.get_tracer()
        with obs.span("alive"):
            pass
        # Re-arming with no/same path keeps the tracer and its spans.
        assert obs.configure() == path
        assert obs.configure(path) == path
        assert obs.get_tracer() is t
        assert obs.export() == path
        assert any(e["name"] == "alive"
                   for e in obs.load_chrome_trace(path))


class TestChromeExport:
    def test_schema_and_distinct_worker_tids(self, tmp_path,
                                             toy_corpus_dir):
        """An overlapped ingest under the tracer emits valid trace-
        event JSON whose pack and drain spans sit on distinct non-main
        tids (the packer/drainer lanes)."""
        from tfidf_tpu.ingest import run_overlapped
        obs.set_tracer(obs.Tracer())
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        run_overlapped(toy_corpus_dir, cfg, doc_len=16, chunk_docs=4)
        path = str(tmp_path / "ingest_trace.json")
        obs.export(path)
        events = obs.load_chrome_trace(path)
        xs = [e for e in events if e.get("ph") == "X"]
        assert xs, "no complete events exported"
        for e in xs:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert isinstance(e["name"], str) and e["name"]
            assert e["pid"] == 1
        lanes = obs.spans_by_thread(events)
        assert {"main", "packer", "drainer"} <= set(lanes)
        pack_tids = {e["tid"] for e in lanes["packer"]}
        drain_tids = {e["tid"] for e in lanes["drainer"]}
        main_tids = {e["tid"] for e in lanes["main"]}
        assert not (pack_tids & drain_tids)
        assert not (pack_tids & main_tids)
        assert {e["name"] for e in lanes["packer"]} == {"pack"}
        assert "drain" in {e["name"] for e in lanes["drainer"]}
        # json round-trips (valid JSON document, not just loadable).
        json.dumps(events)

    def test_trace_check_passes_on_ingest_trace(self, tmp_path,
                                                toy_corpus_dir):
        from tfidf_tpu.ingest import run_overlapped
        obs.set_tracer(obs.Tracer())
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=4,
                             vocab_size=1 << 12)
        run_overlapped(toy_corpus_dir, cfg, doc_len=16, chunk_docs=2)
        path = str(tmp_path / "t.json")
        obs.export(path)
        tc = _load_trace_check()
        errors, notes = tc.check_trace(path, mode="ingest",
                                       min_threads=3)
        assert errors == [], (errors, notes)

    def test_cli_trace_flag_writes_trace(self, tmp_path,
                                         toy_corpus_dir):
        from tfidf_tpu.cli import main
        path = str(tmp_path / "cli_trace.json")
        rc = main(["run", "--input", toy_corpus_dir,
                   "--output", str(tmp_path / "out.txt"),
                   "--vocab-mode", "hashed", "--topk", "4",
                   "--doc-len", "16", "--chunk-docs", "4",
                   "--trace", path])
        assert rc == 0
        lanes = obs.spans_by_thread(obs.load_chrome_trace(path))
        assert {"main", "packer", "drainer"} <= set(lanes)

    def test_phase_timer_and_spans_agree(self, tracer):
        """The combined _TimedSpan feeds PhaseTimer and the tracer
        from ONE interval — identical to float precision."""
        from tfidf_tpu.utils.timing import PhaseTimer, phase_or_null
        timer = PhaseTimer()
        with phase_or_null(timer, "work"):
            time.sleep(0.003)
        totals = obs.span_totals()
        assert totals.keys() == {"work"}
        assert abs(totals["work"] - timer.seconds("work")) < 2e-3

    def test_device_span_records_host_span(self, tracer):
        with obs.device_span("phase_b", chunk=0):
            pass
        (name, _tid, _t0, _dur, args), = tracer.events()
        assert name == "phase_b" and args == {"chunk": 0}


class TestRegistry:
    def test_counter_gauge_histogram_snapshot(self):
        r = MetricsRegistry()
        r.counter("a_total").inc(2)
        g = r.gauge("depth")
        g.set(7)
        g.set(3)
        r.histogram("lat_seconds").observe(0.01)
        snap = r.snapshot()
        assert snap["a_total"] == 2
        assert snap["depth"] == {"value": 3, "peak": 7}
        assert snap["lat_seconds"]["count"] == 1
        json.dumps(snap)

    def test_get_or_create_and_kind_clash(self):
        r = MetricsRegistry()
        c = r.counter("x")
        assert r.counter("x") is c
        with pytest.raises(ValueError):
            r.gauge("x")

    def test_counter_rejects_decrease(self):
        with pytest.raises(ValueError):
            MetricsRegistry().counter("c").inc(-1)

    def test_snapshot_reset_peaks(self):
        r = MetricsRegistry()
        g = r.gauge("depth")
        g.set(9)
        g.set(2)
        assert r.snapshot(reset_peaks=True)["depth"]["peak"] == 9
        assert r.snapshot()["depth"]["peak"] == 2  # restarted at value
        g.set(4)
        assert r.snapshot()["depth"]["peak"] == 4

    def test_prometheus_exposition_format(self):
        r = MetricsRegistry()
        r.counter("tfidf_requests_total", "served requests").inc(5)
        g = r.gauge("tfidf_queue_depth")
        g.set(3)
        h = r.histogram("tfidf_latency_seconds", "latency")
        h.observe(0.004)
        h.observe(0.2)
        text = r.render_prom()
        assert text.endswith("\n")
        assert "# TYPE tfidf_requests_total counter\n" in text
        assert "tfidf_requests_total 5\n" in text
        assert "# TYPE tfidf_queue_depth gauge\n" in text
        assert "tfidf_queue_depth 3\n" in text
        assert "# TYPE tfidf_latency_seconds histogram\n" in text
        assert 'tfidf_latency_seconds_bucket{le="+Inf"} 2\n' in text
        assert "tfidf_latency_seconds_count 2\n" in text
        assert "tfidf_latency_seconds_sum" in text
        # Bucket counts are cumulative (monotone in le).
        counts = [int(line.rsplit(" ", 1)[1])
                  for line in text.splitlines()
                  if line.startswith("tfidf_latency_seconds_bucket")]
        assert counts == sorted(counts)
        assert counts[-1] == 2

    def test_serve_metrics_prom_has_latency_buckets(self):
        m = ServeMetrics()
        m.observe_request(0.005, 2)
        m.observe_batch(2, 2)
        m.set_queue_depth(1)
        text = m.render_prom()
        assert "serve_request_latency_seconds_bucket{le=" in text
        assert "serve_requests_total 1" in text
        assert "serve_queue_depth_peak 1" in text

    def test_serve_metrics_queue_peak_resets(self):
        m = ServeMetrics()
        m.set_queue_depth(5)
        m.set_queue_depth(1)
        assert m.snapshot()["queue"]["peak"] == 5
        assert m.snapshot(reset_peaks=True)["queue"]["peak"] == 5
        assert m.snapshot()["queue"]["peak"] == 1


class TestMerge:
    """Satellite (ISSUE 6): LatencyHistogram.merge + registry-level
    merge — the per-replica aggregation primitive ROADMAP item 5
    needs, and what lets perf_gate pool multi-run samples."""

    def test_latency_histogram_merge_exact_and_percentiles(self):
        from tfidf_tpu.utils.timing import LatencyHistogram
        a, b, ref = (LatencyHistogram() for _ in range(3))
        for v in (0.001, 0.002, 0.005, 0.5):
            a.record(v)
            ref.record(v)
        for v in (0.010, 0.020, 0.100):
            b.record(v)
            ref.record(v)
        a.merge(b)
        assert a.count == ref.count == 7
        assert a.sum_seconds == pytest.approx(ref.sum_seconds)
        assert a.min == ref.min and a.max == ref.max
        for p in (50, 95, 99):
            assert a.percentile(p) == ref.percentile(p)

    def test_merge_empty_sides(self):
        from tfidf_tpu.utils.timing import LatencyHistogram
        a, b = LatencyHistogram(), LatencyHistogram()
        b.record(0.25)
        a.merge(b)                       # empty <- data
        assert a.count == 1 and a.min == 0.25 and a.max == 0.25
        a.merge(LatencyHistogram())      # data <- empty
        assert a.count == 1 and a.min == 0.25

    def test_merge_rejects_geometry_mismatch(self):
        from tfidf_tpu.utils.timing import LatencyHistogram
        with pytest.raises(ValueError, match="geometry"):
            LatencyHistogram().merge(LatencyHistogram(lo=1e-3))
        with pytest.raises(ValueError, match="geometry"):
            LatencyHistogram().merge(LatencyHistogram(resolution=0.05))

    def test_registry_merge_aggregates_replicas(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("reqs").inc(10)
        b.counter("reqs").inc(5)
        b.counter("only_b").inc(2)       # missing in a: created
        ga, gb = a.gauge("depth"), b.gauge("depth")
        ga.set(3)
        gb.set(9)
        gb.set(4)
        a.histogram("lat").observe(0.01)
        b.histogram("lat").observe(0.10)
        a.merge(b)
        snap = a.snapshot()
        assert snap["reqs"] == 15
        assert snap["only_b"] == 2
        # Gauges sum values and peaks (fleet depth; peak upper bound).
        assert snap["depth"] == {"value": 7, "peak": 12}
        assert snap["lat"]["count"] == 2
        # b is untouched.
        assert b.snapshot()["reqs"] == 5

    def test_registry_merge_kind_clash_raises(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("x")
        b.gauge("x").set(1)
        with pytest.raises(ValueError, match="already registered"):
            a.merge(b)

    def test_serve_metrics_merge_via_registry(self):
        a, b = ServeMetrics(), ServeMetrics()
        a.observe_request(0.01, 1)
        b.observe_request(0.02, 3)
        b.count("shed_overload")
        a.registry.merge(b.registry)
        snap = a.snapshot()
        assert snap["requests"] == 2 and snap["queries"] == 4
        assert snap["shed"]["overload"] == 1
        assert snap["latency_s"]["count"] == 2


class TestPromUnderConcurrentMutation:
    """Satellite (ISSUE 6): Prometheus exposition while 8 threads
    hammer the registry — no tearing, no exceptions, parseable text
    on every render."""

    def test_render_prom_while_8_threads_mutate(self):
        r = MetricsRegistry()
        stop = threading.Event()
        errors = []

        def writer(tid):
            try:
                c = r.counter("hot_total")
                g = r.gauge("depth")
                h = r.histogram("lat_seconds")
                i = 0
                while not stop.is_set():
                    c.inc()
                    g.set(i % 32)
                    h.observe(0.001 * (1 + i % 100))
                    if i % 50 == 0:  # registry map churns too
                        r.counter(f"t{tid}_{i // 50}_total").inc()
                    i += 1
            except Exception as e:  # noqa: BLE001 — surface in main
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        try:
            deadline = time.monotonic() + 0.5
            renders = 0
            while time.monotonic() < deadline:
                text = r.render_prom()
                snap = r.snapshot(reset_peaks=True)
                json.dumps(snap)
                assert text.endswith("\n")
                for line in text.splitlines():
                    if line.startswith("#") or not line:
                        continue
                    name, value = line.rsplit(" ", 1)
                    assert name
                    float(value)          # every sample parses
                renders += 1
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors
        assert renders >= 3
        # Quiesced totals are exact: no lost increments under load.
        text = r.render_prom()
        hot = next(l for l in text.splitlines()
                   if l.startswith("hot_total "))
        assert int(hot.split()[1]) == r.get("hot_total").value
        counts = [int(l.rsplit(" ", 1)[1]) for l in text.splitlines()
                  if l.startswith("lat_seconds_bucket")]
        assert counts == sorted(counts)  # le-buckets stay cumulative


class TestServeSpanParity:
    def _retriever(self, corpus_dir):
        from tfidf_tpu.models import TfidfRetriever
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=1 << 12)
        return TfidfRetriever(cfg).index_dir(corpus_dir, strict=True)

    def test_every_request_appears_exactly_once(self, toy_corpus_dir):
        """Span-chain parity: N submits -> N request spans, each with
        exactly one terminal outcome (drained / cache_hit / shed)."""
        from tfidf_tpu.serve import Overloaded, TfidfServer
        tracer = obs.Tracer()
        obs.set_tracer(tracer)
        srv = TfidfServer(self._retriever(toy_corpus_dir),
                          ServeConfig(max_batch=8, max_wait_ms=1,
                                      queue_depth=4, cache_entries=64))
        submitted = 0
        try:
            srv.search(["quick fox"], k=2)
            submitted += 1
            srv.search(["quick fox"], k=2)  # cache hit
            submitted += 1
            srv.search(["lazy dog", "brown fox"], k=2)
            submitted += 1
            # Overload shed: 5 queries > queue_depth=4 at admission.
            with pytest.raises(Overloaded):
                srv.submit(["a", "b", "c", "d", "e"], k=2)
            submitted += 1
        finally:
            srv.close(drain=True)
        reqs = [e for e in tracer.events() if e[0] == "request"]
        assert len(reqs) == submitted
        outcomes = sorted((e[4] or {}).get("outcome") for e in reqs)
        assert outcomes == sorted(["drained", "cache_hit", "drained",
                                   "shed_overload"])
        # Lifecycle stages exist and the batcher lane is labeled.
        names = {e[0] for e in tracer.events()}
        assert {"queued", "batched", "device"} <= names
        labels = {tracer.thread_label(e[1]) for e in tracer.events()
                  if e[0] == "batched"}
        assert labels == {"batcher"}
        # Batch-id attribution: every batched queued-span names its
        # batch, and batch ids are consistent with batched spans.
        qb = [(e[4] or {}) for e in tracer.events() if e[0] == "queued"]
        for args in qb:
            if args.get("outcome") == "batched":
                assert isinstance(args.get("batch"), int)

    def test_deadline_shed_outcome(self, toy_corpus_dir):
        from tfidf_tpu.serve import DeadlineExceeded, TfidfServer
        tracer = obs.Tracer()
        obs.set_tracer(tracer)
        srv = TfidfServer(self._retriever(toy_corpus_dir),
                          ServeConfig(max_batch=64, max_wait_ms=30,
                                      queue_depth=64, cache_entries=0))
        try:
            fut = srv.submit(["quick fox"], k=2, deadline_ms=0.0)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=5)
        finally:
            srv.close(drain=True)
        reqs = [e for e in tracer.events() if e[0] == "request"]
        assert [(e[4] or {}).get("outcome") for e in reqs] \
            == ["shed_deadline"]
        sheds = [(e[4] or {}) for e in tracer.events()
                 if e[0] == "queued"]
        assert any(a.get("outcome") == "shed_deadline" for a in sheds)

    def test_trace_check_passes_on_serve_trace(self, tmp_path,
                                               toy_corpus_dir):
        from tfidf_tpu.serve import TfidfServer
        obs.set_tracer(obs.Tracer())
        srv = TfidfServer(self._retriever(toy_corpus_dir),
                          ServeConfig(max_batch=8, max_wait_ms=1))
        try:
            srv.search(["quick fox", "lazy dog"], k=2)
        finally:
            srv.close(drain=True)
        path = str(tmp_path / "serve.json")
        obs.export(path)
        tc = _load_trace_check()
        errors, notes = tc.check_trace(path, mode="serve",
                                       min_threads=2)
        assert errors == [], (errors, notes)


@pytest.mark.slow
class TestDisabledOverhead:
    """The hot paths call the tracer unconditionally; with no tracer
    armed a span must be nearly free. Marginal cost is measured over
    an empty loop (the loop itself is timed and subtracted); best of
    several rounds rides out scheduler noise. Local name binding
    matches how a per-item hot loop would hold the functions."""

    def test_disabled_begin_end_pair_under_150ns(self):
        """The per-ITEM hot path — one begin/end pair per served
        request (server.submit/resolve) — must cost < 150 ns per span
        disabled (ISSUE 5 guard)."""
        assert not obs.enabled()
        n, r = 300_000, range(300_000)
        begin, end = obs.begin, obs.end

        def spin_pair():
            t0 = time.perf_counter_ns()
            for _ in r:
                end(begin("x"))
            return time.perf_counter_ns() - t0

        def spin_empty():
            t0 = time.perf_counter_ns()
            for _ in r:
                pass
            return time.perf_counter_ns() - t0

        per = min((spin_pair() - spin_empty()) / n for _ in range(5))
        assert per < 150, f"disabled begin/end pair costs {per:.0f} ns"

    def test_disabled_with_span_stays_cheap(self):
        """The ``with`` form runs at per-chunk/per-batch granularity
        (a handful per run); its disabled floor is the CPython
        ``with``-protocol itself (~150 ns on a slow container), so the
        sanity bound is looser — it guards against the disabled path
        ever growing real work (locks, allocation, string formatting),
        not against interpreter-level costs."""
        assert not obs.enabled()
        n, r = 300_000, range(300_000)
        span = obs.span

        def spin_span():
            t0 = time.perf_counter_ns()
            for _ in r:
                with span("x"):
                    pass
            return time.perf_counter_ns() - t0

        def spin_empty():
            t0 = time.perf_counter_ns()
            for _ in r:
                pass
            return time.perf_counter_ns() - t0

        per = min((spin_span() - spin_empty()) / n for _ in range(5))
        assert per < 500, f"disabled with-span costs {per:.0f} ns"
