"""The one-dispatch scanned finish and the fused Pallas score/top-k
(round 8): scan-vs-chunked value parity across regimes, wires, and
uplink formats; the fused Mosaic kernel pinned against the XLA
score+select lowering (tie and all-invalid-slot cases included); drain
ordering under --finish=scan; finish resolution/fallback; the
dispatch-count accounting the bench artifact reports; and the
persistent compile cache (slow-marked subprocess smoke)."""

import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from tfidf_tpu import PipelineConfig
from tfidf_tpu import ingest as ing
from tfidf_tpu.cli import main
from tfidf_tpu.config import VocabMode, apply_compile_cache
from tfidf_tpu.ops.pallas_kernels import fused_score_topk_pallas
from tfidf_tpu.ops.scoring import idf_from_df
from tfidf_tpu.ops.sparse import (score_method, score_topk,
                                  sorted_term_counts, sparse_scores,
                                  sparse_topk)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fp16 carries 11 significand bits: relative rounding error <= 2^-11
# (the packed result wire's score precision, tests/test_downlink.py).
FP16_RTOL = 1e-3


def _cfg(**kw):
    base = dict(vocab_mode=VocabMode.HASHED, vocab_size=1 << 10,
                max_doc_len=64, doc_chunk=64, topk=5, engine="sparse")
    base.update(kw)
    return PipelineConfig(**base)


@pytest.fixture
def corpus_dir(tmp_path):
    rng = np.random.default_rng(23)
    for i in range(1, 41):
        words = [f"w{rng.integers(0, 60)}"
                 for _ in range(int(rng.integers(0, 40)))]
        (tmp_path / f"doc{i}").write_text(" ".join(words))
    return str(tmp_path)


class TestFinishResolution:
    def test_config_validates(self):
        with pytest.raises(ValueError, match="finish"):
            _cfg(finish="loop")

    def test_default_is_scan(self):
        assert ing.resolve_finish(_cfg()) == "scan"
        assert ing.use_scan_finish(_cfg(), packed_wire=True)

    def test_pair_wire_never_scans(self):
        # the pair wire's fused finish is already one dispatch — the
        # scan only ever applies to the packed word wire
        assert not ing.use_scan_finish(_cfg(result_wire="pair"),
                                       packed_wire=False)

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_FINISH", "chunked")
        assert ing.resolve_finish(_cfg()) == "chunked"
        monkeypatch.setenv("TFIDF_TPU_FINISH", "recursive")
        with pytest.raises(ValueError, match="TFIDF_TPU_FINISH"):
            ing.resolve_finish(_cfg())


class TestScanChunkedParity:
    """--finish=scan is bit-identical on ids (and allclose on scores)
    to the round-7 chunked finish, on every regime/wire combination
    the scan can reach."""

    @pytest.mark.parametrize("regime", ["resident", "streaming",
                                        "streaming-nocache"])
    @pytest.mark.parametrize("wire", ["ragged", "padded"])
    def test_regime_wire_matrix(self, corpus_dir, regime, wire,
                                monkeypatch):
        if regime.startswith("streaming"):
            monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        if regime == "streaming-nocache":
            monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        r_s = ing.run_overlapped(corpus_dir, _cfg(wire=wire),
                                 chunk_docs=10, doc_len=64)
        r_c = ing.run_overlapped(corpus_dir,
                                 _cfg(wire=wire, finish="chunked"),
                                 chunk_docs=10, doc_len=64)
        assert r_c.finish == "chunked"
        if regime == "streaming-nocache":
            # nothing cached = nothing for one program to see: the
            # scan ask resolves to the pure chunked flow, honestly
            # reported
            assert r_s.finish == "chunked"
        else:
            assert r_s.finish == "scan"
            assert r_s.n_finish_dispatches < r_c.n_finish_dispatches
        np.testing.assert_array_equal(r_s.topk_ids, r_c.topk_ids)
        np.testing.assert_allclose(r_s.topk_vals, r_c.topk_vals,
                                   rtol=FP16_RTOL, atol=1e-7)
        np.testing.assert_array_equal(np.asarray(r_s.df),
                                      np.asarray(r_c.df))
        assert r_s.bytes_off_wire == r_c.bytes_off_wire

    def test_pair_wire_ignores_scan_ask(self, corpus_dir):
        # both finishes on the pair wire take the fused single-dispatch
        # program — bit-identical results, finish reported as "fused"
        r_s = ing.run_overlapped(corpus_dir, _cfg(result_wire="pair"),
                                 chunk_docs=10, doc_len=64)
        r_c = ing.run_overlapped(corpus_dir,
                                 _cfg(result_wire="pair",
                                      finish="chunked"),
                                 chunk_docs=10, doc_len=64)
        assert r_s.finish == r_c.finish == "fused"
        assert r_s.n_finish_dispatches == 1
        np.testing.assert_array_equal(r_s.topk_ids, r_c.topk_ids)
        np.testing.assert_array_equal(r_s.topk_vals, r_c.topk_vals)

    def test_streaming_partial_cache_prefix(self, corpus_dir,
                                            monkeypatch):
        # budget for ONE cached chunk: the scan covers the cached
        # prefix, the remaining chunks keep per-chunk dispatches, and
        # results stay chunk-major (equality against the resident run
        # pins the ordering end to end)
        ref = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=10,
                                 doc_len=64)
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES",
                           str(10 * 64 * 9 + 10 * 4 + 1))
        r = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=10,
                               doc_len=64)
        assert r.finish == "scan"
        assert r.phases["triple_cached_chunks"] == 1.0
        assert r.n_finish_dispatches == 4  # 1 scan + 3 per-chunk
        np.testing.assert_array_equal(r.topk_ids, ref.topk_ids)
        np.testing.assert_allclose(r.topk_vals, ref.topk_vals,
                                   rtol=FP16_RTOL, atol=1e-7)

    def test_profiler_mirrors_finish(self, corpus_dir, monkeypatch):
        # cache-sharing doctrine: the fenced profiler dispatches the
        # same finish structure production resolved
        ph_s = ing.profile_resident(corpus_dir, _cfg(), chunk_docs=10,
                                    doc_len=64)
        assert ph_s["n_phase_b_dispatches"] == 1.0
        monkeypatch.setenv("TFIDF_TPU_FINISH", "chunked")
        ph_c = ing.profile_resident(corpus_dir, _cfg(), chunk_docs=10,
                                    doc_len=64)
        assert ph_c["n_phase_b_dispatches"] == 4.0
        assert ph_s["bytes_off_wire"] == ph_c["bytes_off_wire"]


class TestScanDrainOrdering:
    """Under --finish=scan the resident drain is ONE submit whose
    worker unpacks the whole scanned buffer chunk-major, and it still
    precedes the terminal fetch stall."""

    def test_single_drain_chunk_major(self, corpus_dir):
        events = []
        ing._overlap_trace = events.append
        try:
            r = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=10,
                                   doc_len=64)
        finally:
            ing._overlap_trace = None
        assert r.finish == "scan"
        submits = [i for i, e in enumerate(events)
                   if e[0] == "drain_submit"]
        assert len(submits) == 1  # the whole finish is one buffer
        fetch_start = events.index(("fetch_start", -1))
        assert submits[0] < fetch_start
        # every chunk upload/dispatch preceded the finish submit
        dispatches = [i for i, e in enumerate(events)
                      if e[0] == "dispatch"]
        assert len(dispatches) == 4
        assert all(d < submits[0] for d in dispatches)
        # chunk-major content: equality against the chunked finish
        r_c = ing.run_overlapped(corpus_dir, _cfg(finish="chunked"),
                                 chunk_docs=10, doc_len=64)
        np.testing.assert_array_equal(r.topk_ids, r_c.topk_ids)


def _triples(rng, d, length, vocab):
    toks = rng.integers(0, vocab, (d, length)).astype(np.int32)
    lens = rng.integers(0, length + 1, d).astype(np.int32)
    ids, cnt, head = sorted_term_counts(jnp.asarray(toks),
                                        jnp.asarray(lens))
    df = rng.integers(0, d + 1, vocab).astype(np.int32)
    idf = idf_from_df(jnp.asarray(df), jnp.int32(max(d, 1)),
                      jnp.float32)
    return ids, cnt, head, jnp.asarray(lens), idf


class TestFusedScoreTopkPallas:
    """The fused Mosaic score/top-k kernel against the XLA lowering:
    ids bit-identical (same selection, same lax.top_k tie order),
    scores allclose."""

    def test_property_random(self):
        rng = np.random.default_rng(3)
        for _ in range(8):
            d = int(rng.integers(1, 40))
            length = int(rng.integers(4, 80))
            k = int(rng.integers(1, 9))
            ids, cnt, head, lens, idf = _triples(rng, d, length, 311)
            sc = sparse_scores(ids, cnt, head, lens, idf)
            v0, t0 = sparse_topk(sc, ids, head, k)
            v1, t1 = fused_score_topk_pallas(ids, cnt, head, lens, idf,
                                             k=min(k, length),
                                             interpret=True)
            np.testing.assert_array_equal(np.asarray(t0),
                                          np.asarray(t1))
            np.testing.assert_allclose(np.asarray(v0), np.asarray(v1),
                                       rtol=1e-6, atol=1e-7)

    def test_tie_breaks_toward_lower_slot(self):
        # two distinct terms with identical counts and identical DF
        # score EQUAL: lax.top_k picks the lower sorted-slot index
        # first, and the kernel must agree exactly
        toks = np.array([[5, 5, 9, 9, 3]], np.int32)
        lens = np.array([4], np.int32)  # the trailing 3 is dead
        ids, cnt, head = sorted_term_counts(jnp.asarray(toks),
                                            jnp.asarray(lens))
        idf = idf_from_df(jnp.asarray(np.ones(16, np.int32)),
                          jnp.int32(4), jnp.float32)
        sc = sparse_scores(ids, cnt, head, jnp.asarray(lens), idf)
        v0, t0 = sparse_topk(sc, ids, head, 3)
        v1, t1 = fused_score_topk_pallas(ids, cnt, head,
                                         jnp.asarray(lens), idf, k=3,
                                         interpret=True)
        np.testing.assert_array_equal(np.asarray(t0), np.asarray(t1))
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))

    def test_all_invalid_slots(self):
        # zero-length docs have NO head slots: every selection decodes
        # to the (0, -1) contract on both lowerings
        toks = np.array([[7, 7, 7], [1, 2, 3]], np.int32)
        lens = np.array([0, 0], np.int32)
        ids, cnt, head = sorted_term_counts(jnp.asarray(toks),
                                            jnp.asarray(lens))
        idf = idf_from_df(jnp.asarray(np.ones(8, np.int32)),
                          jnp.int32(2), jnp.float32)
        v1, t1 = fused_score_topk_pallas(ids, cnt, head,
                                         jnp.asarray(lens), idf, k=2,
                                         interpret=True)
        np.testing.assert_array_equal(np.asarray(t1), -1)
        np.testing.assert_array_equal(np.asarray(v1), 0)

    def test_score_method_resolution(self, monkeypatch):
        assert score_method() == "xla"
        monkeypatch.setenv("TFIDF_TPU_SCORE", "pallas")
        assert score_method() == "pallas"
        monkeypatch.setenv("TFIDF_TPU_SCORE", "cuda")
        with pytest.raises(ValueError, match="TFIDF_TPU_SCORE"):
            score_method()

    def test_score_topk_routes(self, monkeypatch):
        rng = np.random.default_rng(9)
        ids, cnt, head, lens, idf = _triples(rng, 12, 32, 101)
        v0, t0 = score_topk(ids, cnt, head, lens, idf, 4)
        monkeypatch.setenv("TFIDF_TPU_SCORE", "pallas")
        v1, t1 = score_topk(ids, cnt, head, lens, idf, 4)
        np.testing.assert_array_equal(np.asarray(t0), np.asarray(t1))
        np.testing.assert_allclose(np.asarray(v0), np.asarray(v1),
                                   rtol=1e-6, atol=1e-7)

    def test_ingest_with_pallas_score(self, corpus_dir, monkeypatch):
        ref = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=10,
                                 doc_len=64)
        monkeypatch.setenv("TFIDF_TPU_SCORE", "pallas")
        r = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=10,
                               doc_len=64)
        np.testing.assert_array_equal(r.topk_ids, ref.topk_ids)
        np.testing.assert_allclose(r.topk_vals, ref.topk_vals,
                                   rtol=FP16_RTOL, atol=1e-7)


class TestCliFinish:
    def test_finish_flag_round_trip(self, toy_corpus_dir, tmp_path):
        a, b = tmp_path / "a.txt", tmp_path / "b.txt"
        common = ["run", "--input", toy_corpus_dir, "--backend", "tpu",
                  "--vocab-mode", "hashed", "--topk", "2",
                  "--doc-len", "32"]
        assert main(common + ["--output", str(a),
                              "--finish", "scan"]) == 0
        assert main(common + ["--output", str(b),
                              "--finish", "chunked"]) == 0
        assert a.read_bytes() == b.read_bytes()

    def test_explicit_scan_on_pair_wire_warns(self, toy_corpus_dir,
                                              tmp_path, capsys):
        rc = main(["run", "--input", toy_corpus_dir, "--backend", "tpu",
                   "--vocab-mode", "hashed", "--topk", "2",
                   "--doc-len", "32", "--result-wire", "pair",
                   "--finish", "scan",
                   "--output", str(tmp_path / "o.txt")])
        assert rc == 0
        assert "finish=scan" in capsys.readouterr().err

    def test_default_pair_wire_does_not_warn(self, toy_corpus_dir,
                                             tmp_path, capsys):
        # the scan DEFAULT quietly rides the fused finish; only an
        # explicit --finish=scan ask earns the fallback warning
        rc = main(["run", "--input", toy_corpus_dir, "--backend", "tpu",
                   "--vocab-mode", "hashed", "--topk", "2",
                   "--doc-len", "32", "--result-wire", "pair",
                   "--output", str(tmp_path / "o.txt")])
        assert rc == 0
        assert "finish=scan" not in capsys.readouterr().err

    def test_help_epilog_documents_knobs(self, capsys):
        with pytest.raises(SystemExit):
            main(["run", "--help"])
        out = capsys.readouterr().out
        assert "--finish" in out and "--compile-cache" in out
        assert "TFIDF_TPU_FINISH" in out
        assert "TFIDF_TPU_COMPILE_CACHE" in out
        assert "TFIDF_TPU_SCORE" in out


class TestCompileCache:
    def test_apply_is_noop_without_path(self, monkeypatch):
        monkeypatch.delenv("TFIDF_TPU_COMPILE_CACHE", raising=False)
        assert apply_compile_cache(None) is None

    def test_apply_resolves_env(self, tmp_path, monkeypatch):
        import jax
        monkeypatch.setenv("TFIDF_TPU_COMPILE_CACHE",
                           str(tmp_path / "cc"))
        try:
            assert apply_compile_cache(None) == str(tmp_path / "cc")
            assert os.path.isdir(tmp_path / "cc")
        finally:
            # never leave the process-global cache pointed at a tmp
            # dir the fixture is about to delete
            jax.config.update("jax_compilation_cache_dir", None)

    @pytest.mark.slow
    def test_cache_persists_across_processes(self, tmp_path):
        """Subprocess smoke: a cold process fills the cache directory;
        a second fresh process compiles the same program measurably
        using the persisted entries (asserted on the cache being read,
        not on wall-clock — CI-safe)."""
        cache = str(tmp_path / "cc")
        prog = (
            "import sys; sys.path.insert(0, %r)\n"
            "from tfidf_tpu.config import apply_compile_cache\n"
            "apply_compile_cache(%r)\n"
            "import jax, jax.numpy as jnp\n"
            "import numpy as np\n"
            "x = np.zeros((64, 32), np.int32)\n"
            "jax.jit(lambda a: jnp.sort(a, axis=1).sum())(x)\n"
            "print('done')\n" % (REPO, cache))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for _ in range(2):
            out = subprocess.run([sys.executable, "-c", prog],
                                 capture_output=True, text=True,
                                 timeout=300, env=env)
            assert out.returncode == 0, out.stderr[-2000:]
            assert "done" in out.stdout
        entries = os.listdir(cache)
        assert entries, "persistent cache directory stayed empty"
