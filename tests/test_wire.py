"""The ragged (CSR-style) chunk wire and the double-buffered upload
pipeline (ingest.py round 6): ragged<->padded round-trip equality,
device-rebuild vs host-pad parity on both engines, the overlap-loop
ordering contract, and the --wire knob's fallback selection."""

import numpy as np
import pytest

from tfidf_tpu import PipelineConfig
from tfidf_tpu import ingest as ing
from tfidf_tpu.config import VocabMode
from tfidf_tpu.io.corpus import (Corpus, pack_corpus, pack_ragged,
                                 ragged_to_padded_host)
from tfidf_tpu.pipeline import TfidfPipeline


def _cfg(**kw):
    base = dict(vocab_mode=VocabMode.HASHED, vocab_size=1 << 10,
                max_doc_len=64, doc_chunk=64, topk=5, engine="sparse")
    base.update(kw)
    return PipelineConfig(**base)


@pytest.fixture
def corpus_dir(tmp_path):
    rng = np.random.default_rng(7)
    for i in range(1, 41):
        words = [f"w{rng.integers(0, 60)}"
                 for _ in range(int(rng.integers(0, 40)))]
        (tmp_path / f"doc{i}").write_text(" ".join(words))
    return str(tmp_path)


class TestRoundTrip:
    """flatten_aligned -> rebuild is the identity on live slots, for
    every granule, including empty and full-length docs."""

    @pytest.mark.parametrize("align", [1, 4, 16])
    def test_property_random_lengths(self, align):
        rng = np.random.default_rng(3)
        length = 24
        for case in range(20):
            d = int(rng.integers(1, 9))
            lens = rng.integers(0, length + 1, d).astype(np.int32)
            # force the edge cases into every draw
            lens[rng.integers(0, d)] = 0          # empty doc
            lens[rng.integers(0, d)] = length     # L-length doc
            ids = np.zeros((d, length), np.int32)
            mask = np.arange(length)[None, :] < lens[:, None]
            ids[mask] = rng.integers(1, 60000, int(mask.sum()))
            flat, total = ing.flatten_aligned(ids, lens, align)
            assert flat.size % ing._FLAT_BUCKET == 0
            aligned = (-(-np.maximum(lens, 0) // align) * align).sum()
            assert total == aligned
            # Host rebuild: bit-identical to the zero-padded batch.
            np.testing.assert_array_equal(
                ragged_to_padded_host(flat, lens, length, align), ids)
            # Device rebuild: value-identical at live slots (padding
            # slots carry clamp garbage that every consumer masks).
            tok = np.asarray(ing._ragged_to_padded(flat, lens, length,
                                                   align))
            np.testing.assert_array_equal(np.where(mask, tok, 0), ids)

    def test_all_empty_batch(self):
        lens = np.zeros((4,), np.int32)
        ids = np.zeros((4, 16), np.int32)
        flat, total = ing.flatten_aligned(ids, lens, 8)
        assert total == 0 and flat.size == ing._FLAT_BUCKET
        np.testing.assert_array_equal(
            ragged_to_padded_host(flat, lens, 16, 8), ids)


class TestEngineParity:
    """A RaggedBatch through the minibatch layers equals the padded
    batch bit for bit — the device rebuild vs host-pad contract."""

    @pytest.mark.parametrize("engine", ["sparse", "dense"])
    def test_pipeline_run_packed(self, engine):
        docs = [b"apple banana apple", b"", b"cherry date fig " * 8,
                b"kiwi"]
        corpus = Corpus(names=[f"doc{i}" for i in range(1, 5)], docs=docs)
        cfg = _cfg(engine=engine, vocab_size=1 << 12, topk=4)
        pipe = TfidfPipeline(cfg)
        r_pad = pipe.run_packed(pack_corpus(corpus, cfg))
        r_rag = pipe.run_packed(pack_ragged(corpus, cfg))
        np.testing.assert_array_equal(r_pad.df, r_rag.df)
        np.testing.assert_array_equal(r_pad.topk_ids, r_rag.topk_ids)
        np.testing.assert_allclose(r_pad.topk_vals, r_rag.topk_vals)

    def test_streaming_update_score(self):
        from tfidf_tpu.streaming import StreamingTfidf
        docs = [b"alpha beta alpha gamma", b"", b"delta " * 30]
        corpus = Corpus(names=["doc1", "doc2", "doc3"], docs=docs)
        cfg = _cfg(vocab_size=1 << 12, topk=3)
        s_pad, s_rag = StreamingTfidf(cfg), StreamingTfidf(cfg)
        b_pad = s_pad.pack(corpus, fixed_len=32)
        b_rag = s_rag.pack_ragged(corpus, fixed_len=32)
        s_pad.update(b_pad)
        s_rag.update(b_rag)
        np.testing.assert_array_equal(s_pad.df(), s_rag.df())
        v1, i1 = s_pad.score(b_pad)
        v2, i2 = s_rag.score(b_rag)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2))

    @pytest.mark.parametrize("regime", ["resident", "streaming"])
    def test_run_overlapped_wire_parity(self, corpus_dir, regime,
                                        monkeypatch):
        if regime == "streaming":
            monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
            monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        r_rag = ing.run_overlapped(corpus_dir, _cfg(wire="ragged"),
                                   chunk_docs=16, doc_len=64)
        r_pad = ing.run_overlapped(corpus_dir, _cfg(wire="padded"),
                                   chunk_docs=16, doc_len=64)
        assert r_rag.wire == "ragged" and r_pad.wire == "padded"
        np.testing.assert_array_equal(r_rag.df, r_pad.df)
        np.testing.assert_allclose(r_rag.topk_vals, r_pad.topk_vals,
                                   rtol=1e-6)
        # bytes accounting: the padded run's actual wire IS the padded
        # format; both runs report the same padded-format denominator.
        assert r_pad.bytes_on_wire == r_pad.bytes_on_wire_padded
        assert r_rag.bytes_on_wire_padded == r_pad.bytes_on_wire_padded
        assert r_rag.bytes_on_wire > 0

    def test_pallas_rebuild_matches_xla(self, corpus_dir, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_REBUILD", "pallas")
        monkeypatch.setenv("TFIDF_TPU_WIRE_ALIGN", "16")
        r_p = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        monkeypatch.setenv("TFIDF_TPU_REBUILD", "xla")
        r_x = ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                                 doc_len=64)
        np.testing.assert_array_equal(r_p.df, r_x.df)
        np.testing.assert_allclose(r_p.topk_vals, r_x.topk_vals)


class TestOverlapLoop:
    """Ordering contract of the double-buffered upload pipeline: the
    packer thread runs ahead of dispatch, every chunk's upload is
    issued before the (single, terminal) result fetch completes."""

    def _trace_run(self, corpus_dir, **kw):
        events = []
        ing._overlap_trace = events.append
        try:
            ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=10,
                               doc_len=64, **kw)
        finally:
            ing._overlap_trace = None
        return events

    def test_uploads_precede_fetch(self, corpus_dir):
        events = self._trace_run(corpus_dir)
        uploads = [i for i, e in enumerate(events) if e[0] == "upload"]
        fetch_done = events.index(("fetch_done", -1))
        assert len(uploads) == 4  # 40 docs / 10-doc chunks
        # chunk i+1's upload is issued before chunk i's fetch completes
        # (there is one terminal fetch; every upload precedes it).
        assert all(u < fetch_done for u in uploads)
        fetch_start = events.index(("fetch_start", -1))
        assert all(u < fetch_start for u in uploads)

    def test_pack_rides_ahead_of_dispatch(self, corpus_dir):
        events = self._trace_run(corpus_dir)

        def idx(ev):
            return events.index(ev)

        # Double buffer: chunk i+1's pack is submitted (in flight on
        # the worker thread) before chunk i's dispatch returns.
        n = 4
        for i in range(n - 1):
            assert idx(("pack_submit", i + 1)) < idx(("dispatch", i))
        # and the packer retires chunks in submission order.
        dones = [e[1] for e in events if e[0] == "pack_done"]
        assert dones == sorted(dones)

    def test_streaming_loop_traces_too(self, corpus_dir, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_RESIDENT_ELEMS", "0")
        monkeypatch.setenv("TFIDF_TPU_TRIPLE_CACHE_BYTES", "0")
        events = self._trace_run(corpus_dir)
        uploads = [i for i, e in enumerate(events) if e[0] == "upload"]
        fetch_start = events.index(("fetch_start", -1))
        assert len(uploads) == 4
        assert all(u < fetch_start for u in uploads)


class TestWireSelection:
    """config.wire resolution: ragged by default, padded forced or
    degraded-to automatically when ragged cannot carry the run."""

    def test_config_validates_wire(self):
        with pytest.raises(ValueError, match="wire"):
            _cfg(wire="csr")

    def test_forced_padded(self):
        assert not ing.use_ragged_wire(_cfg(wire="padded"), 16, 64)

    def test_wide_vocab_degrades(self):
        cfg = _cfg(vocab_size=(1 << 16) + 1)
        assert not ing.use_ragged_wire(cfg, 16, 64)

    def test_over_bucket_chunk_degrades(self):
        # aligned flat capacity past the int32 bucket bound -> padded
        assert not ing.use_ragged_wire(_cfg(), 1 << 26, 64)
        assert ing.use_ragged_wire(_cfg(), 1 << 20, 64)

    def test_wide_vocab_run_reports_padded(self, corpus_dir):
        r = ing.run_overlapped(corpus_dir,
                               _cfg(vocab_size=(1 << 16) + 8),
                               chunk_docs=16, doc_len=64)
        assert r.wire == "padded"


class TestWireAlignGuard:
    """The _WIRE_ALIGN env knob is validated at the packer/rebuild
    entry points, by name — not at module import (ADVICE round 5)."""

    def test_non_power_of_two_raises(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_WIRE_ALIGN", "12")
        with pytest.raises(ValueError, match="TFIDF_TPU_WIRE_ALIGN"):
            ing._wire_align()

    def test_over_bucket_raises(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_WIRE_ALIGN",
                           str(ing._FLAT_BUCKET * 2))
        with pytest.raises(ValueError, match="TFIDF_TPU_WIRE_ALIGN"):
            ing._wire_align()

    def test_entry_point_names_the_knob(self, corpus_dir, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_WIRE_ALIGN", "3")
        with pytest.raises(ValueError, match="TFIDF_TPU_WIRE_ALIGN"):
            ing.run_overlapped(corpus_dir, _cfg(), chunk_docs=16,
                               doc_len=64)

    def test_valid_align_passes(self, monkeypatch):
        monkeypatch.setenv("TFIDF_TPU_WIRE_ALIGN", "8")
        assert ing._wire_align() == 8


class TestTotalSlotsGuard:
    """Total-resident-slots int32 bound for the finish-program
    sort-join (ADVICE round 5): raised by name at the ingest entry
    points and re-asserted inside df_slot_sorted at trace time."""

    def test_entry_point_guard(self):
        with pytest.raises(ValueError, match="int32"):
            ing._check_total_slots_fit_int32(1 << 26, 64)
        ing._check_total_slots_fit_int32(1 << 20, 64)  # fits

    def test_df_slot_sorted_reasserts(self):
        import jax
        import jax.numpy as jnp

        from tfidf_tpu.ops.sparse import df_slot_sorted
        big = jax.ShapeDtypeStruct((1 << 26, 64), jnp.int32)
        head = jax.ShapeDtypeStruct((1 << 26, 64), jnp.bool_)
        with pytest.raises(ValueError, match="int32"):
            jax.eval_shape(df_slot_sorted, big, head)
