"""Contract-drift gates (C-family): cross-artifact consistency.

The repo's conventions live in four places at once — code, docs, CLI,
and the stdlib tools that read the artifacts the code writes. Each
gate below holds one n-way correspondence together:

* **C001/C002 knobs** — every ``TFIDF_TPU_*`` env var referenced in
  code has a ``docs/CONFIG.md`` table row, and every row names a var
  the code still reads (the stale-doc direction).
* **C003 ServeConfig.from_env** — every ``(field, env)`` pair in the
  resolver names a real ``ServeConfig`` dataclass field.
* **C004 CLI mirrors** — every env knob declared CLI-mirrored in
  ``vocab.ENV_CLI_FLAGS`` has its flag as an ``add_argument`` literal
  in ``tfidf_tpu/cli.py``.
* **C005/C006/C007 spans** — every literal span label emitted through
  ``obs.span``/``device_span``/``begin``/``instant`` is declared in
  ``vocab.SPANS``/``INSTANTS``; every span name the trace tools
  consume (``tools/doctor.py`` ``_MAIN_SPANS``/``_WORKER_SPANS``) is
  actually emitted somewhere; a *dynamic* span label is flagged so it
  is either justified in the baseline or made literal.
* **C008 outcomes** — every literal ``outcome=`` label ends up in
  ``tools/trace_check.py``'s ``_OUTCOMES`` vocabulary (or the
  queued-span extras).
* **C009/C010 fault seams** — every seam declared in
  ``tfidf_tpu/faults.py`` ``SEAMS`` is consulted by a real
  ``faults.fire(...)`` call site, and no call site names an
  undeclared seam.
* **C011 metrics** — every literal registry metric name is mentioned
  in ``docs/OBSERVABILITY.md`` (dynamic families match by declared
  prefix).
* **C012/C013 flight events** — every literal ``log_event`` kind is
  declared in ``vocab.FLIGHT_EVENTS``, and every kind the doctor /
  trace_check consume is emitted by some call site.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from . import vocab
from .core import (Finding, Tree, call_name, const_str, kwarg,
                   str_consts_in)

_ENV_RE = re.compile(r"TFIDF_TPU_[A-Z0-9_]+")
_DOC_ROW_RE = re.compile(r"^\|\s*`(TFIDF_TPU_[A-Z0-9_]+)`\s*\|",
                         re.MULTILINE)

# implementation modules whose internal plumbing would self-match
_SPAN_IMPL = ("tfidf_tpu/obs/tracer.py", "tfidf_tpu/obs/__init__.py")
_LOG_IMPL = ("tfidf_tpu/obs/log.py",)
_METRIC_IMPL = ("tfidf_tpu/obs/registry.py", "tfidf_tpu/obs/__init__.py")


def _norm(rel: str) -> str:
    return rel.replace("\\", "/")


# --- knobs -----------------------------------------------------------

def _code_env_refs(tree: Tree) -> Dict[str, Tuple[str, int]]:
    """env var -> (first file, line) across the contract scope."""
    refs: Dict[str, Tuple[str, int]] = {}
    for rel in tree.contract_files():
        for i, line in enumerate(tree.text(rel).splitlines(), 1):
            for m in _ENV_RE.finditer(line):
                refs.setdefault(m.group(0), (rel, i))
    return refs


def check_knobs(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    if not tree.exists("docs/CONFIG.md"):
        return [Finding("C001", "docs/CONFIG.md", 1, "CONFIG.md",
                        "docs/CONFIG.md is missing — the knob table "
                        "is the contract surface")]
    doc_text = tree.text("docs/CONFIG.md")
    rows = {m.group(1) for m in _DOC_ROW_RE.finditer(doc_text)}
    refs = _code_env_refs(tree)
    for var, (rel, line) in sorted(refs.items()):
        if var not in rows:
            findings.append(Finding(
                "C001", rel, line, var,
                f"env knob {var} is read in code but has no "
                f"docs/CONFIG.md table row"))
    row_lines = {m.group(1): doc_text[:m.start()].count("\n") + 1
                 for m in _DOC_ROW_RE.finditer(doc_text)}
    for var in sorted(rows - set(refs)):
        findings.append(Finding(
            "C002", "docs/CONFIG.md", row_lines[var], var,
            f"docs/CONFIG.md documents {var} but no code reads it "
            f"(stale row, or the reader was renamed)"))
    return findings


def check_serve_config(tree: Tree) -> List[Finding]:
    rel = "tfidf_tpu/config.py"
    if not tree.exists(rel):
        return []
    mod = tree.tree(rel)
    findings: List[Finding] = []
    for cls in ast.walk(mod):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name == "ServeConfig"):
            continue
        fields = {s.target.id for s in cls.body
                  if isinstance(s, ast.AnnAssign)
                  and isinstance(s.target, ast.Name)}
        for node in ast.walk(cls):
            if not isinstance(node, ast.Tuple) \
                    or len(node.elts) not in (2, 3):
                continue
            field = const_str(node.elts[0])
            env = const_str(node.elts[1])
            if field is None or env is None \
                    or not env.startswith("TFIDF_TPU_"):
                continue
            if field not in fields:
                findings.append(Finding(
                    "C003", rel, node.lineno, field,
                    f"ServeConfig.from_env maps {env} onto "
                    f"'{field}', which is not a ServeConfig field"))
    return findings


def check_cli_flags(tree: Tree) -> List[Finding]:
    rel = "tfidf_tpu/cli.py"
    if not tree.exists(rel):
        return []
    flags: Set[str] = set()
    for node in ast.walk(tree.tree(rel)):
        if isinstance(node, ast.Call) \
                and call_name(node).endswith("add_argument"):
            for a in node.args:
                s = const_str(a)
                if s and s.startswith("--"):
                    flags.add(s)
    findings: List[Finding] = []
    for env, flag in sorted(vocab.ENV_CLI_FLAGS.items()):
        if flag not in flags:
            findings.append(Finding(
                "C004", rel, 1, env,
                f"{env} is declared CLI-mirrored as '{flag}' "
                f"(vocab.ENV_CLI_FLAGS) but cli.py defines no such "
                f"flag"))
    return findings


# --- spans -----------------------------------------------------------

def _emitted_spans(tree: Tree) -> Tuple[Dict[str, Tuple[str, int]],
                                        Dict[str, Tuple[str, int]],
                                        List[Finding]]:
    """-> (span name -> first site, instant name -> first site,
    dynamic-label findings)."""
    spans: Dict[str, Tuple[str, int]] = {}
    instants: Dict[str, Tuple[str, int]] = {}
    dynamic: List[Finding] = []
    for rel in tree.product_files():
        if _norm(rel) in _SPAN_IMPL:
            continue
        for node in ast.walk(tree.tree(rel)):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            last = name.rsplit(".", 1)[-1]
            if last in ("span", "device_span", "begin"):
                if not name.startswith("obs") and "." in name:
                    # foo.span()/x.begin() on a non-obs object (e.g.
                    # a Future/Condition API) is not the tracer
                    if not name.startswith(("obs.", "self.obs")):
                        continue
                if not node.args:
                    continue
                label = const_str(node.args[0])
                if label is None:
                    dynamic.append(Finding(
                        "C007", rel, node.lineno,
                        f"dynamic:{name}",
                        f"span label passed to {name}() is not a "
                        f"string literal — the trace tools cannot "
                        f"know this name"))
                else:
                    spans.setdefault(label, (rel, node.lineno))
            elif last == "instant" and node.args \
                    and name.startswith("obs"):
                label = const_str(node.args[0])
                if label is not None:
                    instants.setdefault(label, (rel, node.lineno))
    return spans, instants, dynamic


def _doctor_consumed_spans(tree: Tree) -> Set[str]:
    rel = "tools/doctor.py"
    if not tree.exists(rel):
        return set()
    out: Set[str] = set()
    for node in ast.walk(tree.tree(rel)):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id in ("_MAIN_SPANS", "_WORKER_SPANS")
                        for t in node.targets):
            out.update(s for s in str_consts_in(node.value))
    return out


def check_spans(tree: Tree) -> List[Finding]:
    spans, instants, findings = _emitted_spans(tree)
    for label, (rel, line) in sorted(spans.items()):
        if label not in vocab.SPANS:
            findings.append(Finding(
                "C005", rel, line, label,
                f"span '{label}' is emitted but not declared in "
                f"tools/analyze/vocab.py SPANS — the trace tools "
                f"don't know it"))
    for label, (rel, line) in sorted(instants.items()):
        if label not in vocab.INSTANTS:
            findings.append(Finding(
                "C005", rel, line, label,
                f"trace instant '{label}' is emitted but not declared "
                f"in tools/analyze/vocab.py INSTANTS"))
    for label in sorted(_doctor_consumed_spans(tree)):
        if label not in spans:
            findings.append(Finding(
                "C006", "tools/doctor.py", 1, label,
                f"tools/doctor.py attributes the span '{label}' but "
                f"no code emits it (renamed emission site?)"))
    return findings


# --- outcomes --------------------------------------------------------

def _trace_check_outcomes(tree: Tree) -> Set[str]:
    rel = "tools/trace_check.py"
    if not tree.exists(rel):
        return set()
    for node in ast.walk(tree.tree(rel)):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "_OUTCOMES"
                        for t in node.targets):
            return set(str_consts_in(node.value))
    return set()


def check_outcomes(tree: Tree) -> List[Finding]:
    known = _trace_check_outcomes(tree) | vocab.QUEUED_OUTCOMES
    if not known:
        return [Finding("C008", "tools/trace_check.py", 1, "_OUTCOMES",
                        "tools/trace_check.py no longer declares the "
                        "_OUTCOMES vocabulary")]
    findings: List[Finding] = []
    for rel in tree.product_files():
        if _norm(rel) in _SPAN_IMPL:
            continue
        for node in ast.walk(tree.tree(rel)):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name.rsplit(".", 1)[-1] not in ("end", "span", "begin"):
                continue
            if not name.startswith("obs"):
                continue
            val = kwarg(node, "outcome")
            label = const_str(val) if val is not None else None
            if label is not None and label not in known:
                findings.append(Finding(
                    "C008", rel, node.lineno, label,
                    f"span outcome '{label}' is emitted but "
                    f"tools/trace_check.py's _OUTCOMES vocabulary "
                    f"does not know it"))
    return findings


# --- fault seams -----------------------------------------------------

def _declared_seams(tree: Tree) -> Set[str]:
    rel = "tfidf_tpu/faults.py"
    if not tree.exists(rel):
        return set()
    for node in ast.walk(tree.tree(rel)):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "SEAMS"
                        for t in node.targets):
            return set(str_consts_in(node.value))
    return set()


def _seam_literals(node: ast.expr) -> List[str]:
    """Seam names a ``fire()`` first argument can evaluate to: a
    literal, or either branch of a conditional expression. A string
    inside an IfExp's *test* is never the seam itself."""
    s = const_str(node)
    if s is not None:
        return [s]
    if isinstance(node, ast.IfExp):
        return _seam_literals(node.body) + _seam_literals(node.orelse)
    return []


def check_seams(tree: Tree) -> List[Finding]:
    declared = _declared_seams(tree)
    consulted: Dict[str, Tuple[str, int]] = {}
    findings: List[Finding] = []
    for rel in tree.product_files():
        if _norm(rel) == "tfidf_tpu/faults.py":
            continue
        for node in ast.walk(tree.tree(rel)):
            if not (isinstance(node, ast.Call)
                    and call_name(node) == "faults.fire"
                    and node.args):
                continue
            names = _seam_literals(node.args[0])
            if not names:
                findings.append(Finding(
                    "C010", rel, node.lineno, f"dynamic:{rel}",
                    "faults.fire() with a fully dynamic seam name — "
                    "the seam gate cannot prove it is declared"))
            for seam in names:
                consulted.setdefault(seam, (rel, node.lineno))
                if seam not in declared:
                    findings.append(Finding(
                        "C010", rel, node.lineno, seam,
                        f"faults.fire('{seam}') names a seam not "
                        f"declared in faults.SEAMS"))
    for seam in sorted(declared - set(consulted)):
        findings.append(Finding(
            "C009", "tfidf_tpu/faults.py", 1, seam,
            f"fault seam '{seam}' is declared in faults.SEAMS but no "
            f"hot path consults it — chaos plans naming it silently "
            f"never fire"))
    return findings


# --- metrics ---------------------------------------------------------

def check_metrics(tree: Tree) -> List[Finding]:
    if not tree.exists("docs/OBSERVABILITY.md"):
        return []
    doc = tree.text("docs/OBSERVABILITY.md")
    findings: List[Finding] = []
    for rel in tree.product_files():
        if _norm(rel) in _METRIC_IMPL:
            continue
        for node in ast.walk(tree.tree(rel)):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name.rsplit(".", 1)[-1] not in ("counter", "gauge",
                                               "histogram"):
                continue
            if not node.args:
                continue
            metric = const_str(node.args[0])
            if metric is None:
                continue
            documented = metric in doc or any(
                metric.startswith(p) and p in doc
                for p in vocab.METRIC_DYNAMIC_PREFIXES)
            if not documented:
                findings.append(Finding(
                    "C011", rel, node.lineno, metric,
                    f"registry metric '{metric}' is not mentioned in "
                    f"docs/OBSERVABILITY.md"))
    return findings


# --- flight events ---------------------------------------------------

def check_flight_events(tree: Tree) -> List[Finding]:
    emitted: Dict[str, Tuple[str, int]] = {}
    findings: List[Finding] = []
    # contract scope, not just product scope: bench.py and the tools
    # ride the same flight ring as the library
    for rel in tree.contract_files():
        if _norm(rel) in _LOG_IMPL:
            continue
        for node in ast.walk(tree.tree(rel)):
            if not (isinstance(node, ast.Call)
                    and call_name(node).rsplit(".", 1)[-1]
                    == "log_event"
                    and len(node.args) >= 2):
                continue
            kind = const_str(node.args[1])
            if kind is None:
                continue
            emitted.setdefault(kind, (rel, node.lineno))
            if kind not in vocab.FLIGHT_EVENTS:
                findings.append(Finding(
                    "C012", rel, node.lineno, kind,
                    f"flight event '{kind}' is emitted but not "
                    f"declared in tools/analyze/vocab.py "
                    f"FLIGHT_EVENTS"))
    consumed: Set[str] = set()
    for rel in ("tools/doctor.py", "tools/trace_check.py"):
        if not tree.exists(rel):
            continue
        for node in ast.walk(tree.tree(rel)):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in vocab.FLIGHT_EVENTS:
                consumed.add(node.value)
    for kind in sorted(consumed - set(emitted)):
        findings.append(Finding(
            "C013", "tools/doctor.py", 1, kind,
            f"the flight event '{kind}' is consumed by the trace "
            f"tools but no code emits it (renamed emission site?)"))
    return findings


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    findings += check_knobs(tree)
    findings += check_serve_config(tree)
    findings += check_cli_flags(tree)
    findings += check_spans(tree)
    findings += check_outcomes(tree)
    findings += check_seams(tree)
    findings += check_metrics(tree)
    findings += check_flight_events(tree)
    return findings
