"""The declared vocabularies the contract gates check code against.

This file is the *extension point* (docs/ANALYSIS.md): when a PR adds
a span name, a flight event kind, a fault seam consumer, or an env
knob with a CLI mirror, it must extend the matching set here — and the
gate then holds every other artifact (docs row, tool vocabulary, CLI
flag) to the same name. A rename that touches only one side fails the
run; that is the point.
"""

from __future__ import annotations

#: Every span name the tracer may emit with a literal label
#: (``obs.span`` / ``obs.device_span`` / ``obs.begin``). The trace
#: tooling (tools/trace_check.py, tools/doctor.py) reads exactly these
#: names; an undeclared label means the timeline grew a lane the tools
#: cannot attribute.
SPANS = {
    # ingest main lane
    "pack_wait", "dispatch", "device_tokenize", "phase_b",
    "fetch_wait", "fetch",
    # ingest worker lanes
    "pack", "slab", "drain",
    # streaming device phases
    "stream_update", "stream_score",
    # serve request lifecycle
    "request", "queued", "batched", "device", "dispatch_retry",
    # segmented index (round 17): the compaction merge pass
    "compact",
    # link tax (round 19): the query slab's single byte-stamped H2D
    # copy per batch, and the sharded ingest's cross-worker DF
    # allreduce at the pass-A/B boundary
    "h2d", "link_sync",
    # replicated tier (round 20): the front's routing decision and a
    # two-phase epoch transaction end to end (prepare..commit/abort)
    "route", "epoch_swap",
    # tiled scoring (round 21): one lax.scan dispatch folding a
    # streaming top-k across document tiles — carries tiles/rows/
    # queries (and segments on the stacked segmented path)
    "score_tile",
    # fleet tracing (round 23): each two-phase participant's slice of
    # a tier-wide transaction (phase=prepare/ping/commit/abort on the
    # replica's ctrl lane, phase=drain for the front's drain-to-zero
    # gap) — carries the txn and, when disttrace is on, the trace id
    # that joins the whole swap into one tree in the merged timeline
    "txn_phase",
}

#: Trace instants (``obs.instant``) — point events, not spans.
#: ``serve_pipeline_bubble`` (round 22): the pipelined batcher
#: dispatched onto an EMPTY in-flight window mid-burst — the device
#: idled between dispatches, exactly the gap depth-D execution exists
#: to close (serve_bench --ab-pipeline reports the bubble fraction).
INSTANTS = {"worker_restart", "recompile_in_batch",
            "serve_pipeline_bubble"}

#: Spans that cover *device work in flight* (dispatch staging, jitted
#: calls, TraceAnnotation scopes). A host materialization inside one —
#: ``np.asarray`` / ``.item()`` / ``float()`` on a device value —
#: silently serializes the overlap machinery the span exists to prove;
#: the J002 lint flags it. Host-side spans (``fetch``, ``drain``,
#: ``pack``, ``slab``, ``batched``...) sync by design and are not
#: listed.
DEVICE_HOT_SPANS = {
    "dispatch", "phase_b", "device", "stream_update", "stream_score",
    "device_tokenize",
}

#: Outcome labels legal on NON-request spans in addition to the
#: request-outcome vocabulary trace_check enforces: a ``queued`` span
#: that reached a batch ends ``batched``; a front ``txn_phase`` drain
#: span ends ``stalled`` when in-flight never reached zero inside the
#: two-phase timeout (the drained case reuses the request vocabulary's
#: ``drained``). Requests never end with either.
QUEUED_OUTCOMES = {"batched", "stalled"}

#: Every flight-recorder event kind ``obs.log.log_event`` may emit
#: with a literal name. tools/doctor.py folds a subset into its fault
#: section and tools/trace_check.py cross-checks ``query_quarantined``
#: — the C013 gate proves those consumers never go dark.
FLIGHT_EVENTS = {
    # recovery story (round 13)
    "dispatch_retry", "worker_restart", "breaker_trip", "breaker_close",
    "query_quarantined", "poison_isolated", "fault_injected",
    # device truth (round 12)
    "hbm_watermark", "hbm_watermark_clear", "hbm_census",
    "devmon_error", "xla_recompile", "xla_compile", "compile_warm",
    # per-request forensics (round 16): the slow-query log — emitted
    # at request resolution with the phase breakdown + rid, consumed
    # by tools/doctor.py --request
    "slow_query",
    # serving lifecycle + self-watching (round 11)
    "index_swap", "index_snapshot", "index_restored",
    "health_state_change", "canary_parity_failure",
    "canary_probe_error",
    # live mutation (round 17): segment lifecycle + visibility bumps —
    # segment_seal / compaction carry the lifecycle receipts (docs,
    # tombstones dropped, pause_s — tools/doctor.py budgets the
    # pauses); index_mutation marks every non-swap epoch bump
    "segment_seal", "compaction", "index_mutation",
    # mesh-sharded serving (round 18): edge-triggered per-shard index
    # bytes + imbalance ratio on every install — tools/doctor.py's
    # shards section and --shard-imbalance budget read it
    "shard_balance",
    # engine/bench diagnostics (round 11 structured-logger migration)
    "exact_engine_fallback", "margin_pressure", "bench_progress",
    # replicated tier (round 20): replica lifecycle + the two-phase
    # epoch protocol's receipts — tools/doctor.py's replicas section
    # reads liveness/routed-share/restarts/commits from exactly these
    "replica_up", "replica_down",
    "epoch_prepare", "epoch_commit", "epoch_abort",
    # fleet tracing (round 23): one clock-offset handshake receipt per
    # replica boot/restart (offset/uncertainty/rtt/samples) — the
    # estimate tools/trace_merge.py applies and trace_check's merged
    # mode audits
    "clock_sync",
}

#: ``TFIDF_TPU_*`` env knobs mirrored by a CLI flag: the C004 gate
#: requires each flag string to appear as an ``add_argument`` literal
#: in tfidf_tpu/cli.py. Knobs without a CLI mirror (pure env tuning)
#: are simply absent here.
ENV_CLI_FLAGS = {
    "TFIDF_TPU_WIRE": "--wire",
    "TFIDF_TPU_PACK_THREADS": "--pack-threads",
    "TFIDF_TPU_RESULT_WIRE": "--result-wire",
    "TFIDF_TPU_FINISH": "--finish",
    "TFIDF_TPU_COMPILE_CACHE": "--compile-cache",
    "TFIDF_TPU_TRACE": "--trace",
    "TFIDF_TPU_FLIGHT": "--flight",
    "TFIDF_TPU_MAX_BATCH": "--max-batch",
    "TFIDF_TPU_MAX_WAIT_MS": "--max-wait-ms",
    "TFIDF_TPU_QUEUE_DEPTH": "--queue-depth",
    "TFIDF_TPU_CACHE_ENTRIES": "--cache-entries",
    "TFIDF_TPU_HEALTH_PERIOD_MS": "--health-period-ms",
    "TFIDF_TPU_DEVMON_PERIOD_MS": "--devmon-period-ms",
    "TFIDF_TPU_SNAPSHOT_DIR": "--snapshot-dir",
    "TFIDF_TPU_FAULTS": "--faults",
    "TFIDF_TPU_FAULT_SEED": "--fault-seed",
    "TFIDF_TPU_SLOW_MS": "--slow-ms",
    "TFIDF_TPU_SLO_MS": "--slo-ms",
    "TFIDF_TPU_SLO_TARGET": "--slo-target",
    "TFIDF_TPU_DELTA_DOCS": "--delta-docs",
    "TFIDF_TPU_COMPACT_AT": "--compact-at",
    "TFIDF_TPU_MESH_SHARDS": "--mesh-shards",
    "TFIDF_TPU_INGEST_WORKERS": "--ingest-workers",
    "TFIDF_TPU_QUERY_SLAB": "--query-slab",
    "TFIDF_TPU_SCORE_TILING": "--score-tiling",
    "TFIDF_TPU_SERVE_PIPELINE": "--serve-pipeline-depth",
    "TFIDF_TPU_REPLICAS": "--replicas",
    "TFIDF_TPU_REPLICA_TIMEOUT_S": "--replica-timeout-s",
    "TFIDF_TPU_SCORER": "--scorer",
    "TFIDF_TPU_BM25_K1": "--bm25-k1",
    "TFIDF_TPU_BM25_B": "--bm25-b",
    "TFIDF_TPU_DISTTRACE": "--disttrace",
}

#: Shared attributes the T001 thread lint tolerates without a lock,
#: as ``(path-suffix, Class, attr)`` — ``"*"`` matches every attr.
#: Each entry is an intentional design decision, not an oversight;
#: keep the justification next to it.
THREAD_ALLOWLIST = (
    # The tracer's span ring is deliberately lock-free: one atomic
    # index bump per record (docs/OBSERVABILITY.md "overhead"); a
    # lock here would cost more than the spans it records.
    ("obs/tracer.py", "*", "*"),
    # The flight recorder's event ring follows the same discipline —
    # bounded, append-mostly, torn reads tolerated by the dump
    # protocol's completeness header.
    ("obs/log.py", "*", "*"),
)

#: Metric-name prefixes built dynamically (f-strings / loops) that the
#: C011 docs gate matches by prefix instead of the full literal.
METRIC_DYNAMIC_PREFIXES = (
    "hbm_bytes_in_use_d", "hbm_peak_bytes_d", "hbm_bytes_limit_d",
    "serve_", "shard_bytes_d",
)
