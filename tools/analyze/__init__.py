"""Repo-invariant static analysis: ``python -m tools.analyze``.

One runner, three checker families (docs/ANALYSIS.md):

* ``jax`` — donated-buffer use-after-donate, host syncs inside
  device-hot spans, Python control flow on traced values (J-family);
* ``threads`` — unlocked ``self.*`` writes reachable from two or more
  thread entry domains (T-family);
* ``contracts`` — knob/doc/CLI drift, span and flight-event
  vocabulary drift, unconsulted fault seams, undocumented metrics
  (C-family).

Exit 0 when every finding is baselined (``baseline.json``), 1 when a
new finding fires, 2 on unreadable input. ``--json`` for machines,
``--write-baseline`` to grandfather the current findings (each new
entry gets a TODO justification a human must replace).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from . import contracts, jax_lints, threads
from .core import Baseline, Finding, Tree

CHECKERS = {
    "jax": jax_lints.check,
    "threads": threads.check,
    "contracts": contracts.check,
}

_TODO = "TODO: justify or fix (added by --write-baseline)"


def default_baseline_path(root: Optional[str] = None) -> str:
    tree = Tree(root)
    return os.path.join(tree.root, "tools", "analyze", "baseline.json")


def run(root: Optional[str] = None,
        checkers: Optional[List[str]] = None,
        baseline_path: Optional[str] = None) -> Dict:
    """Run the selected checkers; returns the report dict the CLI
    prints (``ok`` is the gate verdict)."""
    tree = Tree(root)
    names = checkers or sorted(CHECKERS)
    findings: List[Finding] = []
    for name in names:
        findings += CHECKERS[name](tree)
    findings.sort(key=lambda f: (f.code, f.path, f.line))
    if baseline_path is None:
        baseline_path = os.path.join(tree.root, "tools", "analyze",
                                     "baseline.json")
    baseline = Baseline.load(baseline_path)
    new, suppressed, stale = baseline.split(findings)
    return {
        "root": tree.root,
        "checkers": names,
        "findings": [f.to_json() for f in new],
        "suppressed": [dict(f.to_json(),
                            justification=baseline.entries[f.key])
                       for f in suppressed],
        "stale_baseline": stale,
        "ok": not new,
    }


def _render(report: Dict) -> str:
    lines: List[str] = []
    for f in report["findings"]:
        lines.append(f"{f['code']} {f['path']}:{f['line']} "
                     f"[{f['symbol']}] {f['message']}")
    if report["suppressed"]:
        lines.append(f"  {len(report['suppressed'])} baselined "
                     f"finding(s) suppressed:")
        for f in report["suppressed"]:
            lines.append(f"    {f['key']} — {f['justification']}")
    for key in report["stale_baseline"]:
        lines.append(f"  STALE baseline entry (no longer fires — "
                     f"delete it): {key}")
    n = len(report["findings"])
    lines.append(
        f"analyze: {n} new finding(s), "
        f"{len(report['suppressed'])} baselined, "
        f"checkers: {', '.join(report['checkers'])}"
        + (" — FAIL" if n else " — OK"))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description=__doc__.split("\n")[0],
        epilog="exit 0 = clean vs baseline, 1 = new findings, "
               "2 = unreadable input")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this repo)")
    ap.add_argument("--checker", action="append", dest="checkers",
                    choices=sorted(CHECKERS), default=None,
                    help="run only this family (repeatable)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         "tools/analyze/baseline.json under --root)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather current findings into the "
                         "baseline (new entries get a TODO "
                         "justification)")
    args = ap.parse_args(argv)
    try:
        report = run(args.root, args.checkers, args.baseline)
    except (OSError, SyntaxError, ValueError) as e:
        print(f"analyze: cannot analyze: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        path = args.baseline or default_baseline_path(args.root)
        baseline = Baseline.load(path)
        for k in report["stale_baseline"]:
            baseline.entries.pop(k, None)
        for f in report["findings"]:
            baseline.entries.setdefault(f["key"], _TODO)
        baseline.save(path)
        print(f"analyze: baseline written to {path} "
              f"({len(baseline.entries)} entries)")
        return 0
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(_render(report))
    return 0 if report["ok"] else 1
