"""Shared machinery of the static-analysis suite: findings, the
baseline protocol, and the repo file walk.

Every checker returns :class:`Finding` records keyed on *stable*
identity (checker code + file + symbol — never a line number), so a
baseline entry survives unrelated edits to the file above it. The
baseline file (``tools/analyze/baseline.json``) grandfathers findings
for incremental adoption: a finding whose key appears there is
reported as suppressed and does not fail the run; every entry must
carry a one-line justification (the review surface for "why is this
allowed to stay").
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

#: Directories (relative to the repo root) whose Python files the
#: code-facing checkers walk. Tests are deliberately out of scope:
#: they monkeypatch, fake workers and plant hazards on purpose.
PRODUCT_DIRS = ("tfidf_tpu",)
#: Additional scope for the contract gates (knob references, tool
#: vocabularies). tools/analyze itself is excluded — its vocabulary
#: files *name* every knob and span and would self-match everything.
CONTRACT_DIRS = ("tfidf_tpu", "tools")
CONTRACT_FILES = ("bench.py",)
EXCLUDE_DIRS = (os.path.join("tools", "analyze"), ".git",
                "__pycache__", ".pytest_cache")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violated invariant.

    Attributes:
      code: checker id (``J001`` .. ``C0xx``), grouping for humans.
      path: repo-relative file the finding anchors to.
      line: 1-based line (display only — NOT part of the identity).
      symbol: the stable subject (env var, span name, ``Class.attr``,
        function name) the finding is about.
      message: one human sentence.
    """

    code: str
    path: str
    line: int
    symbol: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.code}:{self.path}:{self.symbol}"

    def render(self) -> str:
        return (f"{self.code} {self.path}:{self.line} [{self.symbol}] "
                f"{self.message}")

    def to_json(self) -> dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "key": self.key}


class Baseline:
    """The grandfather file: ``{"version": 1, "entries": [{"key": ...,
    "justification": ...}]}``. Unknown keys in the file are *stale*
    (the finding they suppressed no longer fires) and are reported so
    the file shrinks over time instead of rotting."""

    def __init__(self, entries: Dict[str, str]):
        self.entries = entries

    @staticmethod
    def load(path: Optional[str]) -> "Baseline":
        if not path or not os.path.exists(path):
            return Baseline({})
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != 1:
            raise ValueError(
                f"baseline {path}: unknown version {data.get('version')!r}")
        entries = {}
        for e in data.get("entries", []):
            if not e.get("key") or not e.get("justification"):
                raise ValueError(
                    f"baseline {path}: entry missing key/justification: "
                    f"{e!r}")
            entries[e["key"]] = e["justification"]
        return Baseline(entries)

    def save(self, path: str) -> None:
        data = {"version": 1, "entries": [
            {"key": k, "justification": v}
            for k, v in sorted(self.entries.items())]}
        with open(path, "w") as f:
            json.dump(data, f, indent=2)
            f.write("\n")

    def split(self, findings: Iterable[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[str]]:
        """-> (new, suppressed, stale_keys)."""
        new, suppressed = [], []
        seen = set()
        for f in findings:
            seen.add(f.key)
            (suppressed if f.key in self.entries else new).append(f)
        stale = sorted(k for k in self.entries if k not in seen)
        return new, suppressed, stale


class Tree:
    """One analysis run's view of the repo: file lists + a parse cache
    (every checker shares one ``ast.parse`` per file)."""

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(root or repo_root())
        self._asts: Dict[str, ast.Module] = {}
        self._texts: Dict[str, str] = {}

    def _walk(self, dirs: Iterable[str], files: Iterable[str] = ()
              ) -> List[str]:
        out = []
        for d in dirs:
            top = os.path.join(self.root, d)
            for dirpath, dirnames, filenames in os.walk(top):
                rel_dir = os.path.relpath(dirpath, self.root)
                if any(rel_dir == e or rel_dir.startswith(e + os.sep)
                       for e in EXCLUDE_DIRS):
                    dirnames[:] = []
                    continue
                dirnames[:] = [n for n in dirnames
                               if n not in ("__pycache__", ".git")]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.normpath(
                            os.path.join(rel_dir, name)))
        for f in files:
            if os.path.exists(os.path.join(self.root, f)):
                out.append(f)
        return sorted(set(out))

    def product_files(self) -> List[str]:
        return self._walk(PRODUCT_DIRS)

    def contract_files(self) -> List[str]:
        return self._walk(CONTRACT_DIRS, CONTRACT_FILES)

    def text(self, rel: str) -> str:
        if rel not in self._texts:
            with open(os.path.join(self.root, rel),
                      encoding="utf-8") as f:
                self._texts[rel] = f.read()
        return self._texts[rel]

    def tree(self, rel: str) -> ast.Module:
        if rel not in self._asts:
            self._asts[rel] = ast.parse(self.text(rel), filename=rel)
        return self._asts[rel]

    def exists(self, rel: str) -> bool:
        return os.path.exists(os.path.join(self.root, rel))


# --- small AST helpers shared by the checkers ------------------------

def call_name(call: ast.Call) -> str:
    """Dotted name of a call target: ``obs.span`` / ``span`` / ``''``
    for anything not a plain name/attribute chain."""
    parts: List[str] = []
    node = call.func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        # a call like `foo().bar(...)`: keep the attribute tail so the
        # last component is still matchable
        parts.append("")
    return ".".join(reversed(parts))


def const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_consts_in(node) -> List[str]:
    """Every string literal anywhere inside ``node`` — how the seam
    gate reads ``fire("a" if cond else "b")``."""
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None
