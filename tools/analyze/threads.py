"""Thread-discipline lint (T001): unlocked cross-thread attribute
writes.

Nine rounds built a small thread zoo — the ingest ``_PackAhead`` /
``_DrainAhead`` jobs, the serve batcher loop, the health watchdog, the
canary prober, the device monitor, supervisor restarts — and the
convention holding it together is "shared ``self.*`` state is written
under the object's lock/condition". Nothing checked that. This lint
rebuilds the thread-entry graph per class and flags every attribute
*mutated* from two or more entry domains where at least one mutation
site holds no lock.

Model (intra-class, heuristic — the envelope is in docs/ANALYSIS.md):

* **Thread roots** — methods passed as ``threading.Thread(target=...)``
  and local functions handed to an executor's ``.submit(...)`` (the
  worker-job idiom of ``ingest.py``). Each root opens one *thread
  domain* containing every method reachable from it through
  ``self.m()`` calls.
* **Main domain** — every public method (and every dunder except
  ``__init__``) that is not itself a thread root, plus its reachable
  helpers. ``__init__`` is excluded entirely: writes before
  ``Thread.start()`` are ordered by the start's happens-before edge.
* **Locked** — a write lexically inside ``with self.<lockish>:`` (attr
  name matching lock/cond/mutex/mu), inside a method that calls
  ``self.<lockish>.acquire()``, or inside a *private* method whose
  every intra-class call site is itself lock-held (the
  ``_pop_batch``-under-``_take_batch`` idiom).
* **Mutation** — ``self.x = ...``, ``self.x op= ...``, and subscript
  stores ``self.x[i] = ...``. Container *method* calls (``.append``)
  are deliberately out of scope: too noisy, and the bounded deques in
  this codebase pair them with condition waits.

``vocab.THREAD_ALLOWLIST`` seeds the intentional exceptions (the
lock-free rings in ``obs/tracer.py`` / ``obs/log.py``), each with its
justification next to it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import vocab
from .core import Finding, Tree, call_name

_LOCKISH = re.compile(r"(lock|cond|mutex|mu)$|^(lock|cond|mutex)",
                      re.IGNORECASE)


def _is_lockish_attr(node: ast.expr) -> bool:
    """``self._lock`` / ``self._cond`` / ``self._lock.acquire`` ..."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                     ast.Attribute):
        # self._lock.acquire -> look at the middle attribute
        if _LOCKISH.search(node.attr) or _is_lockish_attr(node.value):
            return True
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return bool(_LOCKISH.search(node.attr))
    return False


class _Site:
    __slots__ = ("attr", "line", "locked", "owner")

    def __init__(self, attr: str, line: int, locked: bool, owner: str):
        self.attr = attr
        self.line = line
        self.locked = locked
        self.owner = owner          # (virtual) method name


class _Method:
    """One method body, or one nested worker function promoted to a
    virtual method (``method.inner``)."""

    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        self.writes: List[_Site] = []
        self.calls: Set[str] = set()          # self.m() targets
        self.local_calls: Set[str] = set()    # bare-name calls
        self.locked_calls: Set[str] = set()   # self.m() made under lock
        self.acquires_lock = False
        self.thread_root = False


def _attr_store_target(node: ast.expr) -> Optional[str]:
    """``self.x`` or ``self.x[...]`` store target -> ``x``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _scan_body(m: _Method, body: List[ast.stmt], locked: bool,
               nested: Dict[str, ast.AST]) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested[stmt.name] = stmt
            continue
        lock_here = locked
        if isinstance(stmt, ast.With):
            if any(_is_lockish_attr(item.context_expr)
                   for item in stmt.items):
                lock_here = True
            _scan_stmt_exprs(m, stmt, locked)
            _scan_body(m, stmt.body, lock_here, nested)
            continue
        if isinstance(stmt, (ast.If, ast.While)):
            _scan_stmt_exprs(m, stmt, locked)
            _scan_body(m, stmt.body, locked, nested)
            _scan_body(m, stmt.orelse, locked, nested)
            continue
        if isinstance(stmt, ast.For):
            _scan_stmt_exprs(m, stmt, locked)
            _scan_body(m, stmt.body, locked, nested)
            _scan_body(m, stmt.orelse, locked, nested)
            continue
        if isinstance(stmt, ast.Try):
            _scan_body(m, stmt.body, locked, nested)
            for h in stmt.handlers:
                _scan_body(m, h.body, locked, nested)
            _scan_body(m, stmt.orelse, locked, nested)
            _scan_body(m, stmt.finalbody, locked, nested)
            continue
        _scan_stmt_exprs(m, stmt, locked)


def _scan_stmt_exprs(m: _Method, stmt: ast.stmt, locked: bool) -> None:
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.For):
        targets = [stmt.target]
    for t in targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            attr = _attr_store_target(e)
            if attr is not None:
                m.writes.append(_Site(attr, e.lineno, locked, m.name))
    # calls (for the graph + lock inference + acquire detection)
    for node in ast.walk(stmt):
        if not isinstance(node, ast.Call):
            continue
        if _is_lockish_attr(node) and call_name(node).endswith("acquire"):
            m.acquires_lock = True
        if isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            m.calls.add(node.func.attr)
            if locked:
                m.locked_calls.add(node.func.attr)
        elif isinstance(node.func, ast.Name):
            m.local_calls.add(node.func.id)


def _thread_roots(methods: Dict[str, _Method]) -> Set[str]:
    roots: Set[str] = set()
    for m in methods.values():
        for node in ast.walk(m.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            target = None
            if name.endswith("Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif name.endswith(".submit") and node.args:
                target = node.args[0]
            if target is None:
                continue
            if isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" \
                    and target.attr in methods:
                roots.add(target.attr)
            elif isinstance(target, ast.Name):
                qual = f"{m.name.split('.')[0]}.{target.id}"
                if qual in methods:
                    roots.add(qual)
    return roots


def _build_methods(cls: ast.ClassDef) -> Dict[str, _Method]:
    methods: Dict[str, _Method] = {}

    def add(name: str, node) -> None:
        m = _Method(name, node)
        nested: Dict[str, ast.AST] = {}
        _scan_body(m, node.body, locked=False, nested=nested)
        methods[name] = m
        for nname, nnode in nested.items():
            add(f"{name.split('.')[0]}.{nname}", nnode)

    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add(stmt.name, stmt)
    # resolve bare-name calls to sibling virtual methods (a nested
    # `job` calling nested `body`), and nested closures calling self.m
    for m in methods.values():
        base = m.name.split(".")[0]
        for ln in m.local_calls:
            if f"{base}.{ln}" in methods:
                m.calls.add(f"{base}.{ln}")
    return methods


def _closure(methods: Dict[str, _Method], seeds: Set[str]) -> Set[str]:
    seen: Set[str] = set()
    work = [s for s in seeds if s in methods]
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for callee in methods[name].calls:
            if callee in methods and callee not in seen:
                work.append(callee)
            base = name.split(".")[0]
            if f"{base}.{callee}" in methods:
                work.append(f"{base}.{callee}")
    return seen


def _always_locked_methods(methods: Dict[str, _Method],
                           roots: Set[str]) -> Set[str]:
    """Private helpers whose every intra-class call site is lock-held
    (single level — the ``_pop_batch`` idiom)."""
    callers: Dict[str, List[Tuple[str, bool]]] = {}
    for m in methods.values():
        for callee in m.calls:
            callers.setdefault(callee, []).append(
                (m.name, callee in m.locked_calls))
    out: Set[str] = set()
    for name, m in methods.items():
        short = name.split(".")[-1]
        if not short.startswith("_") or short.startswith("__"):
            continue
        if name in roots:
            continue
        sites = callers.get(short, []) + callers.get(name, [])
        if sites and all(locked for _, locked in sites):
            out.add(name)
    return out


def _allowlisted(rel: str, cls: str, attr: str) -> bool:
    for suffix, c, a in vocab.THREAD_ALLOWLIST:
        if rel.endswith(suffix) and c in ("*", cls) \
                and a in ("*", attr):
            return True
    return False


def _check_class(rel: str, cls: ast.ClassDef) -> List[Finding]:
    methods = _build_methods(cls)
    if not methods:
        return []
    roots = _thread_roots(methods)
    if not roots:
        return []                     # no worker thread, no hazard
    always_locked = _always_locked_methods(methods, roots)

    domains: Dict[str, Set[str]] = {}
    for r in roots:
        domains[f"thread:{r}"] = _closure(methods, {r})
    main_entries = {
        name for name in methods
        if name not in roots and "." not in name
        and (not name.startswith("_") or
             (name.startswith("__") and name != "__init__"))}
    domains["main"] = _closure(methods, main_entries)

    # attr -> {domain}, plus the unlocked write sites for the report
    attr_domains: Dict[str, Set[str]] = {}
    unlocked_sites: Dict[str, List[_Site]] = {}
    for dom, members in domains.items():
        for mname in members:
            m = methods[mname]
            held = m.acquires_lock or mname in always_locked
            for w in m.writes:
                attr_domains.setdefault(w.attr, set()).add(dom)
                if not (w.locked or held):
                    unlocked_sites.setdefault(w.attr, []).append(w)

    findings: List[Finding] = []
    for attr, doms in sorted(attr_domains.items()):
        if len(doms) < 2 or attr not in unlocked_sites:
            continue
        if _allowlisted(rel, cls.name, attr):
            continue
        site = min(unlocked_sites[attr], key=lambda s: s.line)
        findings.append(Finding(
            "T001", rel, site.line, f"{cls.name}.{attr}",
            f"'self.{attr}' is written from {len(doms)} thread entry "
            f"domains ({', '.join(sorted(doms))}) and the write in "
            f"{site.owner}() holds no lock"))
    return findings


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    for rel in tree.product_files():
        for node in ast.walk(tree.tree(rel)):
            if isinstance(node, ast.ClassDef):
                findings += _check_class(rel, node)
    return findings
