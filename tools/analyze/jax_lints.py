"""JAX hazard lints (J-family).

Three bug classes nine rounds of dispatch machinery made possible and
nothing checked statically:

* **J001 use-after-donate** — a buffer named in ``donate_argnums`` /
  ``donate_argnames`` of a jitted callable is read again after the
  call. XLA may already have aliased its memory into the output; on
  CPU the read *works*, on a real device it is garbage or a crash —
  exactly the class of silent platform-dependent drift this repo
  cannot afford (every donated wire buffer rides the ingest hot path).
* **J002 host sync inside a device-hot span** — ``np.asarray`` /
  ``.item()`` / ``float()`` on a device value between the enter/exit
  of a span that claims to cover in-flight device work
  (``vocab.DEVICE_HOT_SPANS``). The sync silently serializes the
  overlap the span exists to prove, and the trace then *lies*.
* **J003 Python control flow on a traced value** — ``if``/``while``
  on a non-static parameter inside a ``@jit`` body. This raises
  ``TracerBoolConversionError`` at trace time, but only on the first
  call of that code path — a rarely-taken branch ships broken.

All heuristics are intra-module and line-ordered: a use *textually*
before the donating call but executed after it (loop carry) is out of
scope — docs/ANALYSIS.md spells out the envelope.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import vocab
from .core import Finding, Tree, call_name, const_str, kwarg

_SYNC_CALLS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "jax.device_get"}


# --- decorator / binding classification ------------------------------

def _jit_call_info(call: ast.Call) -> Optional[dict]:
    """``jax.jit(...)`` or ``functools.partial(jax.jit, ...)`` ->
    {static_names, static_nums, donate_nums, donate_names} or None."""
    name = call_name(call)
    inner = None
    if name.endswith("jit"):
        inner = call
    elif name.endswith("partial") and call.args:
        first = call.args[0]
        if (isinstance(first, (ast.Name, ast.Attribute))
                and call_name(ast.Call(func=first, args=[],
                                       keywords=[])).endswith("jit")):
            inner = call
    if inner is None:
        return None
    info = {"static_names": set(), "static_nums": set(),
            "donate_nums": set(), "donate_names": set()}
    for key, out, want in (("static_argnames", "static_names", str),
                           ("donate_argnames", "donate_names", str),
                           ("static_argnums", "static_nums", int),
                           ("donate_argnums", "donate_nums", int)):
        val = kwarg(call, key)
        if val is None:
            continue
        elts = val.elts if isinstance(val, (ast.Tuple, ast.List)) \
            else [val]
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, want):
                info[out].add(e.value)
    return info


def _decorated_jit(fn: ast.FunctionDef) -> Optional[dict]:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            info = _jit_call_info(dec)
            if info is not None:
                return info
        elif isinstance(dec, (ast.Name, ast.Attribute)):
            if call_name(ast.Call(func=dec, args=[],
                                  keywords=[])).endswith("jit"):
                return {"static_names": set(), "static_nums": set(),
                        "donate_nums": set(), "donate_names": set()}
    return None


def _donating_callables(mod: ast.Module) -> Dict[str, dict]:
    """Module-level names bound to a donating jitted callable: both
    ``@partial(jax.jit, donate_argnums=...)`` defs and
    ``name = jax.jit(f, donate_argnums=...)`` assignments."""
    out: Dict[str, dict] = {}
    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _decorated_jit(node)
            if info and (info["donate_nums"] or info["donate_names"]):
                out[node.name] = info
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            info = _jit_call_info(node.value)
            if info and (info["donate_nums"] or info["donate_names"]):
                out[node.targets[0].id] = info
    return out


# --- J001 ------------------------------------------------------------

def _scope_walk(fn):
    """Walk a function body WITHOUT descending into nested function /
    lambda scopes — a closure's parameters shadow the outer names, so
    its loads are not uses of the outer binding."""
    work = list(ast.iter_child_nodes(fn))
    while work:
        node = work.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        work.extend(ast.iter_child_nodes(node))


def _check_use_after_donate(rel: str, mod: ast.Module
                            ) -> List[Finding]:
    donors = _donating_callables(mod)
    if not donors:
        return []
    findings: List[Finding] = []
    funcs = [n for n in ast.walk(mod)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        calls: List[Tuple[int, str, str]] = []  # (line, var, callee)
        loads: Dict[str, List[int]] = {}
        stores: Dict[str, List[int]] = {}
        # donating calls whose value immediately leaves the function
        # (`return f(buf, ...)`) end the scope — nothing after them
        # runs, so they open no hazard window
        returned_calls = {
            id(c) for n in _scope_walk(fn)
            if isinstance(n, ast.Return) and n.value is not None
            for c in ast.walk(n.value) if isinstance(c, ast.Call)}
        for node in _scope_walk(fn):
            if isinstance(node, ast.Name):
                book = loads if isinstance(node.ctx, ast.Load) else stores
                book.setdefault(node.id, []).append(node.lineno)
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in donors
                    and id(node) not in returned_calls):
                continue
            info = donors[node.func.id]
            donated: List[str] = []
            for idx in info["donate_nums"]:
                if idx < len(node.args) \
                        and isinstance(node.args[idx], ast.Name):
                    donated.append(node.args[idx].id)
            for kw in node.keywords:
                if kw.arg in info["donate_names"] \
                        and isinstance(kw.value, ast.Name):
                    donated.append(kw.value.id)
            for var in donated:
                calls.append((node.lineno, var, node.func.id))
        for line, var, callee in calls:
            # the first rebind at/after the call line ends the hazard
            # window (a store on the call line is the result binding
            # `buf = f(buf, ...)` itself)
            rebinds = [ln for ln in stores.get(var, []) if ln >= line]
            horizon = min(rebinds) if rebinds else None
            for use in loads.get(var, []):
                if use > line and (horizon is None or use < horizon):
                    findings.append(Finding(
                        "J001", rel, use, f"{fn.name}:{var}",
                        f"'{var}' is read after being donated to "
                        f"{callee}() at line {line} — XLA may have "
                        f"aliased its buffer into the output"))
                    break
    return findings


# --- J002 ------------------------------------------------------------

def _span_name_of(call: ast.Call) -> Optional[str]:
    name = call_name(call)
    if not (name.endswith("span") or name.endswith("device_span")):
        return None
    if not call.args:
        return None
    return const_str(call.args[0])


def _sync_calls_in(body: List[ast.stmt]) -> List[Tuple[int, str]]:
    hits: List[Tuple[int, str]] = []
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in _SYNC_CALLS:
                hits.append((node.lineno, name))
            elif name.endswith(".item") and not node.args:
                hits.append((node.lineno, ".item()"))
            elif isinstance(node.func, ast.Name) \
                    and node.func.id == "float" and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                hits.append((node.lineno, "float()"))
    return hits


def _check_host_sync_in_span(rel: str, mod: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(mod):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                if not isinstance(item.context_expr, ast.Call):
                    continue
                span = _span_name_of(item.context_expr)
                if span is None or span not in vocab.DEVICE_HOT_SPANS:
                    continue
                for line, what in _sync_calls_in(node.body):
                    findings.append(Finding(
                        "J002", rel, line,
                        f"{fn.name}:{span}:{what}",
                        f"{what} inside the device-hot span "
                        f"'{span}' forces a host sync — the overlap "
                        f"the span claims is silently serialized"))
    return findings


# --- J003 ------------------------------------------------------------

def _traced_params(fn: ast.FunctionDef, info: dict) -> Set[str]:
    names = [a.arg for a in fn.args.args]
    traced = set()
    for i, n in enumerate(names):
        if n == "self":
            continue
        if n in info["static_names"] or i in info["static_nums"]:
            continue
        traced.add(n)
    return traced


_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "nbytes"}


def _suspect_names(test: ast.expr, traced: Set[str]) -> List[str]:
    """Traced parameter names the branch condition genuinely depends
    on — attribute reads of static metadata (``x.shape``...) and
    ``is None`` identity tests are trace-safe and excluded."""
    if isinstance(test, ast.Compare) \
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
        return []
    shape_bases = {n.value.id for n in ast.walk(test)
                   if isinstance(n, ast.Attribute)
                   and n.attr in _SHAPE_ATTRS
                   and isinstance(n.value, ast.Name)}
    call_fn_names = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            cn = call_name(n)
            if cn in ("isinstance", "len", "hasattr", "getattr"):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Name):
                        call_fn_names.add(sub.id)
    return sorted({n.id for n in ast.walk(test)
                   if isinstance(n, ast.Name)
                   and isinstance(n.ctx, ast.Load)
                   and n.id in traced
                   and n.id not in shape_bases
                   and n.id not in call_fn_names})


def _check_traced_control_flow(rel: str, mod: ast.Module
                               ) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(mod):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = _decorated_jit(fn)
        if info is None:
            continue
        traced = _traced_params(fn, info)
        if not traced:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for name in _suspect_names(node.test, traced):
                findings.append(Finding(
                    "J003", rel, node.lineno, f"{fn.name}:{name}",
                    f"Python {'if' if isinstance(node, ast.If) else 'while'}"
                    f" on traced parameter '{name}' inside the @jit "
                    f"body of {fn.name}() — TracerBoolConversionError "
                    f"on the first call that reaches it"))
    return findings


def check(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    for rel in tree.product_files():
        mod = tree.tree(rel)
        findings += _check_use_after_donate(rel, mod)
        findings += _check_host_sync_in_span(rel, mod)
        findings += _check_traced_control_flow(rel, mod)
    return findings
