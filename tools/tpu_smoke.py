"""Real-hardware smoke check (run OUTSIDE pytest: the test suite pins
JAX to a virtual CPU mesh, and the axon tunnel admits a single client).

Usage:  python tools/tpu_smoke.py

Validates the paths that interpret/CPU tests cannot: Mosaic compilation
of the Pallas TF+DF kernel and the jitted dense/sparse forwards on the
actual TPU backend, checking exact agreement between all engines.
"""

from __future__ import annotations

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np  # noqa: E402


def main() -> int:
    import jax
    import jax.numpy as jnp

    from tfidf_tpu.ops.histogram import df_from_counts, tf_counts
    from tfidf_tpu.ops.pallas_kernels import tf_df_pallas
    from tfidf_tpu.pipeline import _forward_jit, _sparse_forward_jit

    backend = jax.default_backend()
    print(f"backend: {backend} ({len(jax.devices())} device(s))")

    rng = np.random.default_rng(7)
    v, d, length, k = 1 << 10, 64, 256, 8
    tokens = jnp.asarray(rng.integers(0, v, (d, length), dtype=np.int32))
    lengths = jnp.asarray(rng.integers(1, length + 1, d).astype(np.int32))

    ref_counts = tf_counts(tokens, lengths, v)
    ref_df = df_from_counts(ref_counts)

    pc, pdf = tf_df_pallas(tokens, lengths, vocab_size=v,
                           interpret=backend != "tpu")
    assert (np.asarray(pc) == np.asarray(ref_counts)).all(), "pallas counts"
    assert (np.asarray(pdf) == np.asarray(ref_df)).all(), "pallas df"
    print("pallas tf+df kernel: exact match")

    df1, tv1, ti1 = _forward_jit(
        tokens, lengths, jnp.int32(d), vocab_size=v, chunk=length,
        score_dtype=jnp.dtype("float32"), topk=k, use_pallas=False,
        pallas_interpret=False)
    df2, tv2, ti2 = _sparse_forward_jit(
        tokens, lengths, jnp.int32(d), vocab_size=v,
        score_dtype=jnp.dtype("float32"), topk=k)
    assert (np.asarray(df1) == np.asarray(df2)).all(), "df dense vs sparse"
    np.testing.assert_allclose(np.asarray(tv1), np.asarray(tv2), rtol=1e-6)
    print("dense vs sparse engines: top-k agree")
    print("smoke: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
