"""A/B/C probe of the [V] DF-vector lowering at the bench shape.

  a) sort + searchsorted edges   (sparse_df "sort", current default —
     the trace showed the vmapped binary search costs ~10.6 ms/call)
  b) sort + RLE run lengths + unique-index scatter at run starts
  c) masked scatter-add          (sparse_df "scatter")

All three produce identical counts (asserted). Pipelined-marginal
timing (8x chain, fence once) — the methodology of tools/roofline.py.

Usage: python tools/df_probe.py [--docs 32768] [--len 256]
"""

from __future__ import annotations

import argparse
import sys
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from tfidf_tpu.obs.costmodel import (achieved_gbps,  # noqa: E402
                                     stage_bytes)
from tfidf_tpu.ops.sparse import sorted_term_counts  # noqa: E402

VOCAB = 1 << 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=32768)
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()
    d, length = args.docs, args.length

    print(f"backend={jax.default_backend()}", file=sys.stderr)
    rng = np.random.default_rng(0)
    ids_np = ((np.clip(rng.zipf(1.3, (d, length)), 1, 8192) - 1)
              % VOCAB).astype(np.int32)
    lens_np = rng.integers(length // 2, length + 1, d).astype(np.int32)

    ids, counts, head = jax.jit(sorted_term_counts)(
        jnp.asarray(ids_np), jnp.asarray(lens_np))
    jax.device_get(jnp.sum(head))

    n = d * length
    sentinel = jnp.iinfo(jnp.int32).max

    @jax.jit
    def df_searchsorted(ids, head):
        masked = jnp.where(head, ids, sentinel).reshape(-1)
        srt = jnp.sort(masked)
        edges = jnp.arange(VOCAB + 1, dtype=jnp.int32)
        pos = jnp.searchsorted(srt, edges)
        return (pos[1:] - pos[:-1]).astype(jnp.int32)

    @jax.jit
    def df_rle_scatter(ids, head):
        masked = jnp.where(head, ids, sentinel).reshape(-1)
        srt = jnp.sort(masked)
        slot = jnp.arange(n, dtype=jnp.int32)
        start = srt != jnp.concatenate(
            [jnp.full((1,), -1, srt.dtype), srt[:-1]])
        nstart = jnp.where(start, slot, n)
        smin = lax.cummin(nstart[::-1])[::-1]
        next_start = jnp.concatenate(
            [smin[1:], jnp.full((1,), n, jnp.int32)])
        run_len = jnp.where(start, next_start - slot, 0)
        tgt = jnp.where(start & (srt != sentinel), srt, VOCAB)
        df = jnp.zeros((VOCAB + 1,), jnp.int32)
        df = df.at[tgt].add(run_len, mode="drop", unique_indices=False)
        return df[:VOCAB]

    @jax.jit
    def df_scatter(ids, head):
        safe = jnp.where(head, ids, VOCAB)
        df = jnp.zeros((VOCAB + 1,), jnp.int32)
        df = df.at[safe.reshape(-1)].add(
            head.reshape(-1).astype(jnp.int32))
        return df[:VOCAB]

    fns = {"searchsorted": df_searchsorted,
           "rle_scatter": df_rle_scatter,
           "scatter_add": df_scatter}
    ref = None
    for name, fn in fns.items():
        out = np.asarray(fn(ids, head))
        if ref is None:
            ref = out
        else:
            np.testing.assert_array_equal(out, ref, err_msg=name)
        one = None
        t0 = time.perf_counter()
        jax.device_get(fn(ids, head).sum())
        one = time.perf_counter() - t0
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            last = None
            for _ in range(8):
                last = fn(ids, head)
            jax.device_get(last.sum())
            best = min(best, time.perf_counter() - t0)
        marginal = max((best - one) / 7, 1e-9)
        # Model bytes for the DF lowering from the SHARED analytic
        # model (obs/costmodel.py): the marginal GB/s says how close
        # each variant runs to the chip's sort roofline.
        model_b = stage_bytes(d, length)["df_global_sort"]
        gbps = achieved_gbps(model_b, marginal) or 0.0
        print(f"{name:13s} one-shot {one * 1e3:7.1f} ms  "
              f"marginal {marginal * 1e3:7.1f} ms  "
              f"({gbps:6.1f} GB/s of {model_b / 1e9:.2f} GB model)",
              flush=True)


if __name__ == "__main__":
    main()
