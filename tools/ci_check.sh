#!/usr/bin/env bash
# One gate for builders and CI: static analysis, lint, tier-1 tests,
# perf gate — every stage runs (no fail-fast), one summary at the end,
# exit non-zero if any stage failed. docs/ANALYSIS.md has the story.
#
# Usage: bash tools/ci_check.sh        (from anywhere; cd's to the repo)

set -u
cd "$(dirname "$0")/.."

declare -a NAMES VERDICTS
fail=0

stage() {   # stage NAME CMD...
    local name="$1"; shift
    echo "=== ${name} ==="
    if "$@"; then
        VERDICTS+=("PASS")
    else
        VERDICTS+=("FAIL")
        fail=1
    fi
    NAMES+=("${name}")
    echo
}

skip() {    # skip NAME REASON
    echo "=== $1 === SKIP: $2"
    NAMES+=("$1"); VERDICTS+=("SKIP")
    echo
}

# 1. repo-invariant static analysis (tools/analyze, baseline-gated)
stage "analyze" python -m tools.analyze

# 2. ruff (rule set in pyproject.toml) — skip cleanly where the image
#    lacks it; the analyze stage above always runs
if command -v ruff >/dev/null 2>&1; then
    stage "ruff" ruff check .
elif python -c "import ruff" >/dev/null 2>&1; then
    stage "ruff" python -m ruff check .
else
    skip "ruff" "ruff not installed"
fi

# 3. tier-1 tests (the ROADMAP.md command, minus the log plumbing)
stage "tier1" env JAX_PLATFORMS=cpu timeout -k 10 870 \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider

# 4. multi-process ingest smoke (slow-marked, round 19): a real
#    2-process mpi_lite-rendezvous sharded ingest must stay
#    bit-identical to single-process — this path went dark the way the
#    mesh paths did before PR 13 exactly once; never again.
stage "multihost_ingest_smoke" env JAX_PLATFORMS=cpu timeout -k 10 600 \
    python -m pytest tests/test_multihost.py -q -m slow -k sharded \
    -p no:cacheprovider

# 5. replicated-tier smoke (slow-marked, round 20): a real 2-replica
#    front — routed queries parity-checked against direct search, a
#    hot swap, and the chaos rehearsal: SIGKILL a replica between its
#    prepare-ack and the commit; the swap must abort with EVERY
#    surviving replica still on the OLD epoch (zero mixed-epoch
#    responses), then the supervised restart + retried swap commit.
stage "replica_front_smoke" env JAX_PLATFORMS=cpu timeout -k 10 600 \
    python -m pytest tests/test_replica.py -q -m slow \
    -p no:cacheprovider

# 5b. fleet-tracing smoke (slow-marked, round 23): a real 2-replica
#    front under traced load — trace_export pull, clock-aligned merge
#    (tools/trace_merge.py), the merged-mode trace_check audit
#    (route-contains-request after alignment for EVERY sampled query,
#    one txn tree per tier-wide swap), fleet-wide doctor --request,
#    and the front's SIGTERM crash-forensics parity.
stage "disttrace_fleet_smoke" env JAX_PLATFORMS=cpu timeout -k 10 600 \
    python -m pytest tests/test_disttrace.py -q -m slow \
    -p no:cacheprovider

# 6. perf gate: re-gate the committed newest artifacts against the
#    ledger (unchanged artifacts must pass; a refreshed artifact that
#    regressed fails here)
for artifact in BENCH_r05.json SERVE_r01.json SERVE_r02.json \
                SERVE_r03.json SERVE_r04.json SERVE_r05.json \
                REPLICA_r01.json REPLICA_r02.json \
                INGEST_MH_r01.json RETR_r01.json \
                SCORING_r01.json; do
    if [ -f "${artifact}" ]; then
        stage "perf_gate:${artifact}" \
            python tools/perf_gate.py "${artifact}"
    else
        skip "perf_gate:${artifact}" "artifact not present"
    fi
done

echo "=== summary ==="
for i in "${!NAMES[@]}"; do
    printf '%-28s %s\n' "${NAMES[$i]}" "${VERDICTS[$i]}"
done
exit "${fail}"
