"""Capture + analyze a real-chip jax.profiler trace (VERDICT r4 #2).

Runs the resident device program (sparse_forward and the production
chunked structure) at the bench shape under ``jax.profiler.trace``,
then parses the emitted ``*.trace.json.gz`` — via the shared
Chrome-trace helpers in ``tfidf_tpu.obs.tracer`` — and aggregates
device-lane op durations: which XLA ops actually dominate the compute
the bench charges to the chip (sort vs DF vs score vs top-k vs
gather/pack).

``--host-trace`` additionally arms the host span tracer for the timed
section and writes ``<out>/host_trace.json`` into the SAME output dir,
so one Perfetto session can hold the device capture and the host
timeline side by side (the ``device_span`` TraceAnnotations carry the
same names on both).

Usage: python tools/trace_capture.py [--docs 32768] [--len 256]
       [--out /tmp/tfidf_trace] [--host-trace]
Prints a per-op table to stdout; the raw trace dir is left for
inspection (point TensorBoard or Perfetto at it).
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tfidf_tpu import obs  # noqa: E402
from tfidf_tpu.config import PipelineConfig, VocabMode  # noqa: E402
from tfidf_tpu.ingest import (_chunk_step, _finish_wire,  # noqa: E402
                              _resident_df_mode, flatten_aligned)
from tfidf_tpu.ops.sparse import sparse_forward  # noqa: E402

VOCAB = 1 << 16
TOPK = 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=32768)
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--out", default="/tmp/tfidf_trace")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--host-trace", action="store_true",
                    help="also record the host span timeline and "
                         "write <out>/host_trace.json next to the "
                         "device capture")
    args = ap.parse_args()
    d, length = args.docs, args.length
    if args.host_trace:
        obs.configure(os.path.join(args.out, "host_trace.json"))

    print(f"backend={jax.default_backend()}", file=sys.stderr)
    rng = np.random.default_rng(0)
    ids_np = (np.clip(rng.zipf(1.3, (d, length)), 1, 8192) - 1) % VOCAB
    lens_np = rng.integers(length // 2, length + 1, d).astype(np.int32)
    mask = np.arange(length)[None, :] < lens_np[:, None]
    ids_np = np.where(mask, ids_np, 0).astype(np.int32)

    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                         max_doc_len=length, doc_chunk=length, topk=TOPK,
                         engine="sparse")
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))

    tok_dev = jax.device_put(ids_np)
    len_dev = jax.device_put(lens_np)
    # The packers' aligned layout — _chunk_step decodes with
    # _WIRE_ALIGN, so the traced program must consume the real wire.
    flat_dev = jax.device_put(flatten_aligned(ids_np, lens_np)[0])

    @jax.jit
    def fwd(t, l):
        df, vals, out_ids = sparse_forward(
            t, l, jnp.int32(d), vocab_size=VOCAB,
            score_dtype=score_dtype, topk=TOPK)
        return (df.sum() + out_ids.sum() + vals.sum().astype(jnp.int32))

    k = min(TOPK, length)

    def prod():
        df_acc = jnp.zeros((VOCAB,), jnp.int32)
        i_, c_, h_, df_acc = _chunk_step(
            flat_dev, len_dev, df_acc, cfg, length, ragged=True,
            fold_df=not _resident_df_mode()[1])
        _, wire = _finish_wire(([i_], [c_], [h_]), [len_dev], df_acc, d,
                               k, score_dtype, cfg, wire_vals=True)
        return jnp.asarray(wire).astype(jnp.int32).sum()

    # Warm everything (compiles + lazy input transfers) OUTSIDE the trace.
    jax.device_get(fwd(tok_dev, len_dev))
    jax.device_get(prod())

    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        for i in range(args.iters):
            with obs.span("fwd", iter=i):
                jax.device_get(fwd(tok_dev, len_dev))
        for i in range(args.iters):
            with obs.device_span("prod", iter=i):
                jax.device_get(prod())
    host_path = obs.export()
    if host_path:
        print(f"host trace: {host_path}", file=sys.stderr)

    traces = sorted(glob.glob(os.path.join(
        args.out, "**", "*.trace.json.gz"), recursive=True))
    if not traces:
        everything = glob.glob(os.path.join(args.out, "**", "*"),
                               recursive=True)
        print("no trace.json.gz found; artifacts:", file=sys.stderr)
        for p in everything:
            print("  " + p, file=sys.stderr)
        sys.exit(1)
    path = traces[-1]
    # The shared Chrome-trace reader/aggregator (tfidf_tpu.obs.tracer)
    # — one definition of "device lane" and one table shape for this
    # tool, trace_check and the tests.
    events = obs.load_chrome_trace(path)
    rows, total = obs.device_op_table(events, top=25)
    print(f"trace: {path}")
    print(f"\n| op | total ms | calls | % of device time |")
    print("|---|---|---|---|")
    for name, us, calls in rows:
        print(f"| {name[:60]} | {us / 1e3:9.2f} | {calls:5d} | "
              f"{100 * us / max(total, 1e-9):5.1f}% |")
    print(f"\ntotal device-lane time: {total / 1e3:.1f} ms over "
          f"{2 * args.iters} timed calls")


if __name__ == "__main__":
    main()
