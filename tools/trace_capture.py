"""Capture + analyze a real-chip jax.profiler trace (VERDICT r4 #2).

Runs the resident device program (sparse_forward and the production
chunked structure) at the bench shape under ``jax.profiler.trace``,
then parses the emitted ``*.trace.json.gz`` and aggregates device-lane
op durations — which XLA ops actually dominate the compute the bench
charges to the chip (sort vs DF vs score vs top-k vs gather/pack).

Usage: python tools/trace_capture.py [--docs 32768] [--len 256]
       [--out /tmp/tfidf_trace]
Prints a per-op table to stdout; the raw trace dir is left for
inspection (point TensorBoard or Perfetto at it).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tfidf_tpu.config import PipelineConfig, VocabMode  # noqa: E402
from tfidf_tpu.ingest import (_chunk_step, _finish_wire,  # noqa: E402
                              _resident_df_mode, flatten_aligned)
from tfidf_tpu.ops.sparse import sparse_forward  # noqa: E402

VOCAB = 1 << 16
TOPK = 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=32768)
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--out", default="/tmp/tfidf_trace")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()
    d, length = args.docs, args.length

    print(f"backend={jax.default_backend()}", file=sys.stderr)
    rng = np.random.default_rng(0)
    ids_np = (np.clip(rng.zipf(1.3, (d, length)), 1, 8192) - 1) % VOCAB
    lens_np = rng.integers(length // 2, length + 1, d).astype(np.int32)
    mask = np.arange(length)[None, :] < lens_np[:, None]
    ids_np = np.where(mask, ids_np, 0).astype(np.int32)

    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                         max_doc_len=length, doc_chunk=length, topk=TOPK,
                         engine="sparse")
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))

    tok_dev = jax.device_put(ids_np)
    len_dev = jax.device_put(lens_np)
    # The packers' aligned layout — _chunk_step decodes with
    # _WIRE_ALIGN, so the traced program must consume the real wire.
    flat_dev = jax.device_put(flatten_aligned(ids_np, lens_np)[0])

    @jax.jit
    def fwd(t, l):
        df, vals, out_ids = sparse_forward(
            t, l, jnp.int32(d), vocab_size=VOCAB,
            score_dtype=score_dtype, topk=TOPK)
        return (df.sum() + out_ids.sum() + vals.sum().astype(jnp.int32))

    k = min(TOPK, length)

    def prod():
        df_acc = jnp.zeros((VOCAB,), jnp.int32)
        i_, c_, h_, df_acc = _chunk_step(
            flat_dev, len_dev, df_acc, cfg, length, ragged=True,
            fold_df=not _resident_df_mode()[1])
        _, wire = _finish_wire(([i_], [c_], [h_]), [len_dev], df_acc, d,
                               k, score_dtype, cfg, wire_vals=True)
        return jnp.asarray(wire).astype(jnp.int32).sum()

    # Warm everything (compiles + lazy input transfers) OUTSIDE the trace.
    jax.device_get(fwd(tok_dev, len_dev))
    jax.device_get(prod())

    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        for _ in range(args.iters):
            jax.device_get(fwd(tok_dev, len_dev))
        for _ in range(args.iters):
            jax.device_get(prod())

    traces = sorted(glob.glob(os.path.join(
        args.out, "**", "*.trace.json.gz"), recursive=True))
    if not traces:
        everything = glob.glob(os.path.join(args.out, "**", "*"),
                               recursive=True)
        print("no trace.json.gz found; artifacts:", file=sys.stderr)
        for p in everything:
            print("  " + p, file=sys.stderr)
        sys.exit(1)
    path = traces[-1]
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])

    # Device lanes: pid/tid whose process name mentions the accelerator.
    proc_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"].get("name", "")
    dev_pids = {p for p, n in proc_names.items()
                if "TPU" in n or "/device" in n.lower() or "Device" in n}
    agg: dict = collections.defaultdict(float)
    cnt: dict = collections.defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0))  # microseconds
        agg[name] += dur
        cnt[name] += 1
        total += dur
    print(f"trace: {path}")
    print(f"device pids: "
          f"{ {p: proc_names[p] for p in dev_pids} }", file=sys.stderr)
    print(f"\n| op | total ms | calls | % of device time |")
    print("|---|---|---|---|")
    for name, us in sorted(agg.items(), key=lambda kv: -kv[1])[:25]:
        print(f"| {name[:60]} | {us / 1e3:9.2f} | {cnt[name]:5d} | "
              f"{100 * us / max(total, 1e-9):5.1f}% |")
    print(f"\ntotal device-lane time: {total / 1e3:.1f} ms over "
          f"{2 * args.iters} timed calls")


if __name__ == "__main__":
    main()
