"""Retrieval throughput artifact (VERDICT r4 item 9).

Indexes a Zipf corpus with models/retrieval.TfidfRetriever (the
overlapped chunked ingest) and measures batched-query search QPS on
the live backend — the config-3 BCOO north-star use. Prints one JSON
line per query-batch size plus an index-build row; paste into
BASELINE.md.

Usage: python tools/retrieval_bench.py [--docs 100000] [--batches 16,64,256]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=100000)
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--batches", default="16,64,256")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import bench as benchmod
    benchmod.N_DOCS = args.docs
    benchmod.DOC_LEN = args.length

    import jax
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.models.retrieval import TfidfRetriever

    print(f"backend={jax.default_backend()}", file=sys.stderr)
    tmp = tempfile.mkdtemp(prefix="retr_bench_")
    try:
        print(f"generating {args.docs}-doc corpus...", file=sys.stderr)
        input_dir = benchmod.make_corpus(tmp)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=benchmod.VOCAB,
                             max_doc_len=args.length, topk=None,
                             engine="sparse")
        r = TfidfRetriever(cfg)
        t0 = time.perf_counter()
        r.index_dir(input_dir, doc_len=args.length)
        jax.block_until_ready((r._ids, r._weights))
        t_index = time.perf_counter() - t0
        print(json.dumps({"metric": "retrieval_index_docs_per_sec",
                          "docs": args.docs,
                          "index_s": round(t_index, 3),
                          "value": round(args.docs / t_index, 1)}))

        rng = np.random.default_rng(7)
        for q in (int(b) for b in args.batches.split(",")):
            queries = [" ".join(f"w{rng.integers(0, benchmod.N_WORDS)}"
                                for _ in range(5)) for _ in range(q)]
            r.search(queries[:2], k=args.k)  # warm/compile
            best = float("inf")
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                vals, idx = r.search(queries, k=args.k)
                best = min(best, time.perf_counter() - t0)
            assert vals.shape[0] == q
            print(json.dumps({
                "metric": "retrieval_qps", "batch": q,
                "k": args.k, "search_s": round(best, 4),
                "value": round(q / best, 1),
                "docs": args.docs}), flush=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
