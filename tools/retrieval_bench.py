"""Retrieval throughput artifact: the batch-scaling receipt.

Indexes a Zipf corpus with models/retrieval.TfidfRetriever (the
overlapped chunked ingest) and measures batched-query search QPS over
a QUERY-COUNT SWEEP on the live backend. Round 21 made this the tiled
scorer's artifact of record (``RETR_r01.json``): the legacy untiled
path's QPS went DOWN as Q grew (the serial 64-query block split —
VERDICT weak-5); the tiled scan's one-dispatch-at-any-width claim is
only real if this sweep shows it, so the artifact carries:

* ``sweep``: per-Q QPS rows, Q = 16 .. 512 by powers of two;
* ``qps_monotonic_through_256``: 1 iff QPS is non-decreasing from
  Q=64 through Q=256 (the exact regression weak-5 documents, within
  a small timing-noise band);
* ``parity_ok``: tiled results bit-identical (scores, ids, tie
  order) to the ``TFIDF_TPU_SCORE_TILING=off`` fallback at probe
  widths on BOTH sides of the legacy 64 split;
* ``recompiles_after_warmup``: compiled-program delta across every
  measured repeat AFTER each bucket's warm pass — must be 0.

``tools/perf_ledger.py`` ingests the artifact as kind ``retrieval``;
``tools/perf_gate.py`` zero-tolerates parity/monotonic/recompiles and
gates the QPS columns directionally. Exit 1 when parity or the
recompile pin fails — the bench IS the regression test.

``--scorers`` (round 23) switches to the SCORING-FAMILY artifact
(``SCORING_r01.json``, ledger kind ``scoring``): per scorer variant
(tfidf, bm25, bm25+filter) it measures QPS at Q=64/256 through the
same tiled kernel, pins bit-parity three ways (tiled vs the
``TFIDF_TPU_SCORE_TILING=off`` fallback; device ids vs the pure-NumPy
oracle of ``tfidf_tpu.scoring.oracle``, tie order included), embeds
per-scorer retrieval recall@10 vs that oracle plus the bm25-vs-tfidf
top-10 overlap (proof the family members actually rank differently),
and re-pins zero recompiles after warm-up across every variant —
scorer switching must never mint new search programs.

Usage::

    python tools/retrieval_bench.py [--docs 100000] [--out RETR_r01.json]
    python tools/retrieval_bench.py --scorers [--docs 20000] \\
        [--out SCORING_r01.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np  # noqa: E402


def _measure(r, queries, k, repeats, **search_kw):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        vals, idx = r.search(queries, k=k, **search_kw)
        best = min(best, time.perf_counter() - t0)
    return best, vals, idx


def _scoring_main(args) -> int:
    """--scorers: the scoring-family artifact (module docstring)."""
    import bench as benchmod
    benchmod.N_DOCS = args.docs
    benchmod.DOC_LEN = args.length

    import jax
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.models.retrieval import (TfidfRetriever, _search_tiled,
                                            query_matrix)
    from tfidf_tpu.ops.sparse import score_topk_tiled_cache_size
    from tfidf_tpu.recall import retrieval_recall_at_k, scorer_overlap_at_k
    from tfidf_tpu.scoring import parse_filter, parse_scorer
    from tfidf_tpu.scoring.filters import filter_mask
    from tfidf_tpu.scoring.oracle import oracle_topk

    backend = jax.default_backend()
    print(f"backend={backend}", file=sys.stderr)
    tmp = tempfile.mkdtemp(prefix="scoring_bench_")
    try:
        print(f"generating {args.docs}-doc corpus...", file=sys.stderr)
        input_dir = benchmod.make_corpus(tmp)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=benchmod.VOCAB,
                             max_doc_len=args.length, topk=None,
                             engine="sparse")
        r = TfidfRetriever(cfg)
        r.index_dir(input_dir, doc_len=args.length)
        jax.block_until_ready((r._ids, r._weights))

        rng = np.random.default_rng(7)
        pool = [" ".join(f"w{rng.integers(0, benchmod.N_WORDS)}"
                         for _ in range(5)) for _ in range(256)]
        half_filter = {"id_range": [0, args.docs // 2]}
        variants = [("tfidf", "tfidf", None),
                    ("bm25", "bm25", None),
                    ("bm25_filter", "bm25", half_filter)]

        def cache_size():
            return _search_tiled._cache_size() + score_topk_tiled_cache_size()

        def oracle(queries, spec, fspec, k):
            data, cols = r.scorer_face(spec)
            live = np.zeros((data.shape[0],), bool)
            live[:r._num_docs] = (True if fspec is None else filter_mask(
                fspec, r._num_docs, names=r.names))
            qmat = query_matrix(
                queries, r.config, np.asarray(r._idf),
                mode="counts" if spec.kind == "bm25" else "cosine")
            return oracle_topk(data, cols, live, qmat, k)

        artifact = {"metric": "scoring_bench", "backend": backend,
                    "docs": args.docs, "doc_len": args.length,
                    "k": args.k}
        recompiles = 0
        parity_ok = True
        ids_by_variant = {}
        for name, skey, flt in variants:
            spec = parse_scorer(skey)
            fspec = parse_filter(flt)
            kw = {"scorer": spec}
            if flt is not None:
                kw["filter"] = flt
            for q in (64, 256):
                queries = pool[:q]
                r.search(queries, k=args.k, **kw)    # warm this bucket
                warm = cache_size()
                best, vals, idx = _measure(r, queries, args.k,
                                           args.repeats, **kw)
                recompiles += cache_size() - warm
                assert vals.shape[0] == q
                artifact[f"qps_q{q}_{name}"] = round(q / best, 1)
                print(json.dumps({"metric": "scoring_qps",
                                  "scorer": name, "batch": q,
                                  "k": args.k,
                                  "value": round(q / best, 1)}),
                      flush=True)
            # --- parity: tiled vs untiled, device vs NumPy oracle ---
            queries = pool[:64]
            on_v, on_i = r.search(queries, k=args.k, **kw)
            os.environ["TFIDF_TPU_SCORE_TILING"] = "off"
            try:
                off_v, off_i = r.search(queries, k=args.k, **kw)
            finally:
                os.environ["TFIDF_TPU_SCORE_TILING"] = "on"
            tiled_same = (np.array_equal(on_v, off_v)
                          and np.array_equal(on_i, off_i))
            ov, oi = oracle(queries, spec, fspec, args.k)
            oracle_same = (np.array_equal(np.asarray(on_i), oi[:, :args.k])
                           and np.allclose(np.asarray(on_v),
                                           ov[:, :args.k], rtol=1e-5,
                                           atol=1e-6))
            parity_ok &= tiled_same and oracle_same
            artifact[f"parity_{name}"] = int(tiled_same and oracle_same)
            artifact[f"recall_at_10_{name}"] = round(
                retrieval_recall_at_k(np.asarray(on_i), oi, 10), 4)
            ids_by_variant[name] = np.asarray(on_i)
            print(f"parity {name}: tiled_vs_untiled="
                  f"{'ok' if tiled_same else 'MISMATCH'} vs_oracle="
                  f"{'ok' if oracle_same else 'MISMATCH'}",
                  file=sys.stderr)

        artifact["bm25_vs_tfidf_overlap_at_10"] = round(
            scorer_overlap_at_k(ids_by_variant["tfidf"],
                                ids_by_variant["bm25"], 10), 4)
        artifact["parity_ok"] = int(parity_ok)
        artifact["recompiles_after_warmup"] = int(recompiles)
        print(json.dumps(artifact, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(artifact, f, indent=1)
            print(f"wrote {args.out}", file=sys.stderr)
        if not parity_ok or recompiles:
            print("scoring_bench: FAIL (parity or recompile pin)",
                  file=sys.stderr)
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=100000)
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--batches", default="16,32,64,128,256,512",
                    help="query-count sweep (pow2 keeps one bucket "
                         "per width)")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--parity-batches", default="16,64,256",
                    help="widths A/B'd against --score-tiling=off "
                         "(either side of the legacy 64 split)")
    ap.add_argument("--out", default=None,
                    help="write the JSON artifact here (RETR_r0X.json, "
                         "or SCORING_r0X.json with --scorers)")
    ap.add_argument("--scorers", action="store_true",
                    help="scoring-family mode: per-scorer QPS + "
                         "three-way parity + recall artifact "
                         "(module docstring)")
    args = ap.parse_args()
    if args.scorers:
        return _scoring_main(args)

    import bench as benchmod
    benchmod.N_DOCS = args.docs
    benchmod.DOC_LEN = args.length

    import jax
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.models.retrieval import TfidfRetriever, _search_tiled
    from tfidf_tpu.ops.sparse import score_tile_rows, score_tiling

    backend = jax.default_backend()
    print(f"backend={backend}", file=sys.stderr)
    tmp = tempfile.mkdtemp(prefix="retr_bench_")
    try:
        print(f"generating {args.docs}-doc corpus...", file=sys.stderr)
        input_dir = benchmod.make_corpus(tmp)
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=benchmod.VOCAB,
                             max_doc_len=args.length, topk=None,
                             engine="sparse")
        r = TfidfRetriever(cfg)
        t0 = time.perf_counter()
        r.index_dir(input_dir, doc_len=args.length)
        jax.block_until_ready((r._ids, r._weights))
        t_index = time.perf_counter() - t0
        print(json.dumps({"metric": "retrieval_index_docs_per_sec",
                          "docs": args.docs,
                          "index_s": round(t_index, 3),
                          "value": round(args.docs / t_index, 1)}))

        rng = np.random.default_rng(7)
        widths = [int(b) for b in args.batches.split(",")]
        pool = [" ".join(f"w{rng.integers(0, benchmod.N_WORDS)}"
                         for _ in range(5)) for _ in range(max(widths))]

        # --- QPS sweep (tiled path, the default) --------------------
        sweep = []
        recompiles = 0
        for q in widths:
            queries = pool[:q]
            r.search(queries, k=args.k)        # warm this bucket
            warm = _search_tiled._cache_size()
            best, vals, idx = _measure(r, queries, args.k,
                                       args.repeats)
            recompiles += _search_tiled._cache_size() - warm
            assert vals.shape[0] == q
            row = {"q": q, "search_s": round(best, 4),
                   "qps": round(q / best, 1)}
            sweep.append(row)
            print(json.dumps({"metric": "retrieval_qps", "batch": q,
                              "k": args.k, "search_s": row["search_s"],
                              "value": row["qps"],
                              "docs": args.docs}), flush=True)

        qps = {row["q"]: row["qps"] for row in sweep}
        # Non-decreasing through Q=256 within a 5% timing-noise band:
        # the weak-5 regression was -18% over that range, an order of
        # magnitude outside it.
        mono_widths = [q for q in widths if 64 <= q <= 256]
        monotonic = all(
            qps[b] >= qps[a] * 0.95
            for a, b in zip(mono_widths, mono_widths[1:]))

        # --- bit-parity A/B vs --score-tiling=off -------------------
        parity_ok = True
        for q in (int(b) for b in args.parity_batches.split(",")):
            queries = pool[:q]
            os.environ["TFIDF_TPU_SCORE_TILING"] = "off"
            try:
                off_v, off_i = r.search(queries, k=args.k)
            finally:
                os.environ["TFIDF_TPU_SCORE_TILING"] = "on"
            on_v, on_i = r.search(queries, k=args.k)
            same = (np.array_equal(np.asarray(on_v), np.asarray(off_v))
                    and np.array_equal(np.asarray(on_i),
                                       np.asarray(off_i)))
            parity_ok &= same
            print(f"parity q={q}: {'ok' if same else 'MISMATCH'}",
                  file=sys.stderr)

        artifact = {
            "metric": "retrieval_bench",
            "backend": backend,
            "docs": args.docs, "doc_len": args.length, "k": args.k,
            "tiling": "on" if score_tiling() else "off",
            "tile_rows": score_tile_rows(args.docs),
            "index_s": round(t_index, 3),
            "index_docs_per_sec": round(args.docs / t_index, 1),
            "sweep": sweep,
            "qps_q64": qps.get(64),
            "qps_q256": qps.get(256),
            "qps_q512": qps.get(512),
            "qps_monotonic_through_256": int(monotonic),
            "parity_ok": int(parity_ok),
            "recompiles_after_warmup": int(recompiles),
        }
        print(json.dumps(artifact, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(artifact, f, indent=1)
            print(f"wrote {args.out}", file=sys.stderr)
        if not parity_ok or recompiles:
            print("retrieval_bench: FAIL (parity or recompile pin)",
                  file=sys.stderr)
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
