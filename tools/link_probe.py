"""Probe the host<->device link: throughput vs transfer granularity.

Measures device_put/device_get wall time for the bench's wire shapes at
several chunkings, so upload/fetch optimization targets measured tunnel
behavior instead of guesses; plus (round 14) a three-way A/B of the
chunk wire FORMATS — padded [D, L] ids, ragged flat uint16 ids, and the
raw-byte slab — on a bench-shaped Zipf corpus: bytes on the wire, pack
wall (the host cost of producing each format), and staged upload time.
Run standalone on the real chip:
    python tools/link_probe.py
"""

import os
import sys
import tempfile
import time

import numpy as np

import jax
import jax.numpy as jnp

import _common  # noqa: E402,F401  repo-root sys.path bootstrap


def timed(fn, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def wire_format_ab(n_docs: int = 8192, doc_len: int = 256) -> None:
    """Three-way ragged/padded/bytes wire A/B on one bench-shaped
    chunk: what each format costs the HOST to produce (pack wall —
    tokenize+hash for the id wires, read+memcpy for the byte slab),
    what it puts ON the wire, and the staged upload wall. The byte
    receipt is corpus-dependent: the slab carries mean-token-bytes+1
    per token where the ragged wire carries a flat 2 — raw UTF-8 only
    wins the byte count below ~2 B/token (docs/SCALING.md round 14
    has the honest arithmetic)."""
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import (make_bytes_packer, make_chunk_packer,
                                  make_flat_packer)

    rng = np.random.default_rng(0)
    words = np.array([f"w{i}".encode() for i in range(8192)],
                     dtype=object)
    tmp = tempfile.mkdtemp(prefix="wire_ab_")
    lens = np.maximum(
        doc_len // np.clip(rng.zipf(1.3, n_docs), 1, doc_len), 1)
    for i in range(1, n_docs + 1):
        n = int(lens[i - 1])
        doc = b" ".join(words[np.clip(rng.zipf(1.3, n), 1, 8192) - 1])
        with open(os.path.join(tmp, f"doc{i}"), "wb") as f:
            f.write(doc)
    names = [f"doc{i}" for i in range(1, n_docs + 1)]
    n_tokens = int(lens.sum())
    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, topk=16,
                         max_doc_len=doc_len, doc_chunk=doc_len,
                         engine="sparse")
    packers = {
        "padded": make_chunk_packer(tmp, cfg, n_docs, doc_len),
        "ragged": make_flat_packer(tmp, cfg, n_docs, doc_len),
        "bytes": make_bytes_packer(tmp, cfg, n_docs, doc_len),
    }
    print(f"\nwire-format A/B ({n_docs} docs x {doc_len} cap, "
          f"{n_tokens} live tokens):")
    for name, pack in packers.items():
        pack_wall = timed(lambda pack=pack: pack(names))
        out = pack(names)
        wire, plens = out[0], out[1]
        nbytes = wire.nbytes + plens.nbytes

        def put(wire=wire, plens=plens):
            jax.block_until_ready([jax.device_put(wire),
                                   jax.device_put(plens)])

        up = timed(put)
        print(f"  {name:>6}: {nbytes / 1e6:7.2f} MB "
              f"({nbytes / max(n_tokens, 1):5.2f} B/token)  "
              f"pack {pack_wall * 1e3:7.1f} ms  "
              f"put {up * 1e3:7.1f} ms")


def main():
    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    D, L = 32768, 256
    rng = np.random.default_rng(0)
    full = rng.integers(0, 1 << 16, (D, L), dtype=np.uint16)

    for chunk in (32768, 8192, 2048, 512):
        parts = [full[i:i + chunk] for i in range(0, D, chunk)]

        def put_all(parts=parts):
            jax.block_until_ready([jax.device_put(p) for p in parts])

        mb = full.nbytes / 1e6
        s = timed(put_all)
        print(f"put  chunk={chunk:6d} ({len(parts):3d} xfers): "
              f"{s:.3f}s  {mb / s:6.1f} MB/s")

    # Fetch: three result arrays separately vs one packed byte buffer.
    df = jnp.zeros((1 << 16,), jnp.int32)
    vals = jnp.zeros((D, 16), jnp.bfloat16)
    ids = jnp.zeros((D, 16), jnp.uint16)
    jax.block_until_ready((df, vals, ids))
    s3 = timed(lambda: jax.device_get((df, vals, ids)))

    @jax.jit
    def pack(df, vals, ids):
        return jnp.concatenate([
            jax.lax.bitcast_convert_type(df, jnp.uint8).reshape(-1),
            jax.lax.bitcast_convert_type(vals, jnp.uint8).reshape(-1),
            jax.lax.bitcast_convert_type(ids, jnp.uint8).reshape(-1)])

    packed = pack(df, vals, ids)
    jax.block_until_ready(packed)
    s1 = timed(lambda: jax.device_get(packed))
    mb = (df.nbytes + vals.nbytes + ids.nbytes) / 1e6
    print(f"get  3 arrays ({mb:.1f} MB): {s3:.3f}s  {mb / s3:6.1f} MB/s")
    print(f"get  1 packed ({packed.nbytes / 1e6:.1f} MB): {s1:.3f}s  "
          f"{packed.nbytes / 1e6 / s1:6.1f} MB/s")

    # Tiny-transfer round-trip latency (upper bound on per-xfer overhead).
    one = np.zeros((8,), np.int32)
    s = timed(lambda: np.asarray(jax.device_put(one)))
    print(f"roundtrip 32B: {s * 1000:.1f} ms")

    wire_format_ab()


if __name__ == "__main__":
    main()
