"""Probe the host<->device link: throughput vs transfer granularity.

Measures device_put/device_get wall time for the bench's wire shapes at
several chunkings, so upload/fetch optimization targets measured tunnel
behavior instead of guesses. Run standalone on the real chip:
    python tools/link_probe.py
"""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def timed(fn, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    D, L = 32768, 256
    rng = np.random.default_rng(0)
    full = rng.integers(0, 1 << 16, (D, L), dtype=np.uint16)

    for chunk in (32768, 8192, 2048, 512):
        parts = [full[i:i + chunk] for i in range(0, D, chunk)]

        def put_all(parts=parts):
            jax.block_until_ready([jax.device_put(p) for p in parts])

        mb = full.nbytes / 1e6
        s = timed(put_all)
        print(f"put  chunk={chunk:6d} ({len(parts):3d} xfers): "
              f"{s:.3f}s  {mb / s:6.1f} MB/s")

    # Fetch: three result arrays separately vs one packed byte buffer.
    df = jnp.zeros((1 << 16,), jnp.int32)
    vals = jnp.zeros((D, 16), jnp.bfloat16)
    ids = jnp.zeros((D, 16), jnp.uint16)
    jax.block_until_ready((df, vals, ids))
    s3 = timed(lambda: jax.device_get((df, vals, ids)))

    @jax.jit
    def pack(df, vals, ids):
        return jnp.concatenate([
            jax.lax.bitcast_convert_type(df, jnp.uint8).reshape(-1),
            jax.lax.bitcast_convert_type(vals, jnp.uint8).reshape(-1),
            jax.lax.bitcast_convert_type(ids, jnp.uint8).reshape(-1)])

    packed = pack(df, vals, ids)
    jax.block_until_ready(packed)
    s1 = timed(lambda: jax.device_get(packed))
    mb = (df.nbytes + vals.nbytes + ids.nbytes) / 1e6
    print(f"get  3 arrays ({mb:.1f} MB): {s3:.3f}s  {mb / s3:6.1f} MB/s")
    print(f"get  1 packed ({packed.nbytes / 1e6:.1f} MB): {s1:.3f}s  "
          f"{packed.nbytes / 1e6 / s1:6.1f} MB/s")

    # Tiny-transfer round-trip latency (upper bound on per-xfer overhead).
    one = np.zeros((8,), np.int32)
    s = timed(lambda: np.asarray(jax.device_put(one)))
    print(f"roundtrip 32B: {s * 1000:.1f} ms")


if __name__ == "__main__":
    main()
