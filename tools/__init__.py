# Namespace package marker so `python -m tools.analyze` resolves from
# the repo root. The standalone scripts in this directory still run as
# scripts (`python tools/doctor.py`) via their `import _common` bootstrap.
