"""Multi-process sharded ingest A/B + INGEST_MH_r0x.json artifact.

BENCH_r05 reduced the end-to-end story to one number: ``link_tax_s``
~1.05 s against ~0.94 s of everything else. This tool measures the fix
— the reference's rank-partitioned document loop (``TFIDF.c:130``)
done over N OS processes each owning its own link
(``tfidf_tpu/parallel/multihost.run_sharded_ingest``) — as a PAIRED
A/B against the identical single-process protocol, and emits the
ledger artifact ``tools/perf_ledger.py`` files as ``kind=ingest_mh``.

Protocol fairness: BOTH sides run through the same worker machinery
(fresh OS processes, mpi_lite-style rendezvous, barrier-aligned timed
windows, ``--repeat`` in-process repeats with the LAST — warm — run
reported), so interpreter start and XLA compile cold-starts cancel
out. The verdict fields:

* ``parity_ok`` — the N-worker merged index bit-identical to the
  1-process result (DF, IDF-scored top-k values, ids, lengths, names
  — zero-tolerance in the perf gate);
* ``upload_s`` vs ``upload_s_1p`` — wall of the slowest worker's
  link-driving phase (``put``), THE attacked column;
* ``speedup_vs_1p`` = ``upload_s_1p / upload_s``;
* per-worker ``link_utilization`` — fraction of each worker's wall
  spent driving its link.

Usage::

    python tools/ingest_mh_bench.py --docs 32768 --workers 2 \
        --out INGEST_MH_r01.json

Exit codes: 0 = parity holds (and ratio bound met when given),
1 = parity/bound failure, 2 = setup error.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="artifact keys: upload_s[_1p], wall_s[_1p], "
               "speedup_vs_1p, parity_ok, link_utilization")
    ap.add_argument("--docs", type=int, default=32768,
                    help="synthetic corpus size (ignored with --input)")
    ap.add_argument("--doc-len", type=int, default=256)
    ap.add_argument("--workers", type=int, default=2,
                    help="ingest worker processes for the sharded side")
    ap.add_argument("--chunk-docs", type=int, default=8192)
    ap.add_argument("--repeat", type=int, default=2,
                    help="in-process timed repeats per worker; the "
                         "LAST (warm) run is reported — compile "
                         "cold-start excluded on both sides alike")
    ap.add_argument("--input", default=None,
                    help="ingest an existing corpus dir instead")
    ap.add_argument("--max-upload-ratio", type=float, default=None,
                    help="fail (exit 1) when upload_s exceeds this "
                         "fraction of upload_s_1p (the round-19 "
                         "acceptance bound is 0.6)")
    ap.add_argument("--out", default="INGEST_MH_r01.json")
    args = ap.parse_args()

    import bench as benchmod
    benchmod.N_DOCS = args.docs
    benchmod.DOC_LEN = args.doc_len

    import jax

    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.obs import log as obs_log
    from tfidf_tpu.parallel.multihost import run_sharded_ingest

    log = obs_log.get_log()
    tmp = None
    if args.input is None:
        tmp = tempfile.mkdtemp(prefix="ingest_mh_")
        log.info("ingest_mh_bench",
                 msg=f"generating {args.docs}-doc corpus...")
        input_dir = benchmod.make_corpus(tmp)
    else:
        input_dir = args.input
    try:
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=benchmod.VOCAB,
                             max_doc_len=args.doc_len,
                             topk=benchmod.TOPK, engine="sparse")

        def run(n):
            t0 = time.perf_counter()
            result, info = run_sharded_ingest(
                input_dir, cfg, n_workers=n,
                chunk_docs=args.chunk_docs, doc_len=args.doc_len,
                strict=False, repeat=args.repeat)
            return result, info, time.perf_counter() - t0

        log.info("ingest_mh_bench", msg="1-process reference side...")
        ref, info1, e2e1 = run(1)
        log.info("ingest_mh_bench",
                 msg=f"{args.workers}-process sharded side...")
        mh, infoN, e2eN = run(args.workers)

        parity_ok = int(
            np.array_equal(np.asarray(ref.df), np.asarray(mh.df))
            and np.array_equal(ref.topk_vals, mh.topk_vals)
            and np.array_equal(ref.topk_ids, mh.topk_ids)
            and np.array_equal(ref.lengths, mh.lengths)
            and ref.names == mh.names)

        upload_ratio = (infoN.upload_s / info1.upload_s
                        if info1.upload_s > 0 else 0.0)
        artifact = {
            "metric": "ingest_mh",
            "backend": jax.default_backend(),
            "n_docs": ref.num_docs,
            "doc_len": args.doc_len,
            "chunk_docs": args.chunk_docs,
            "n_workers": infoN.n_workers,
            "repeat": args.repeat,
            "wire": infoN.wire,
            "ingest_path": infoN.path,
            "parity_ok": parity_ok,
            # The attacked column: wall of the slowest worker's
            # link-driving phase, measured in barrier-aligned windows.
            "upload_s": round(infoN.upload_s, 4),
            "upload_s_1p": round(info1.upload_s, 4),
            "upload_ratio": round(upload_ratio, 4),
            "speedup_vs_1p": round(1.0 / upload_ratio, 4)
            if upload_ratio > 0 else 0.0,
            "wall_s": round(infoN.wall_s, 4),
            "wall_s_1p": round(info1.wall_s, 4),
            "worker_walls_s": [round(w, 4)
                               for w in infoN.worker_walls_s],
            "worker_upload_s": [round(u, 4)
                                for u in infoN.worker_upload_s],
            "link_utilization": infoN.link_utilization,
            "shards": [list(s) for s in infoN.shards],
            # Driver-side end-to-end including process spawn/teardown:
            # context, not a gated column (interpreter+jax start is
            # ~constant per process, amortized at real corpus sizes).
            "e2e_s": round(e2eN, 4),
            "e2e_s_1p": round(e2e1, 4),
        }
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps(artifact, sort_keys=True))
        if not parity_ok:
            log.error("ingest_mh_parity",
                      msg="parity FAILED: the sharded merge diverged "
                          "from the single-process index")
            return 1
        if (args.max_upload_ratio is not None
                and upload_ratio > args.max_upload_ratio):
            log.error("ingest_mh_ratio",
                      msg=f"upload ratio {upload_ratio:.3f} exceeds "
                          f"bound {args.max_upload_ratio}")
            return 1
        return 0
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
