"""Exact-terms margin sweep: recall vs wall-clock per --exact-margin.

The exact-terms mode keeps margin*k candidate buckets on device so the
host re-rank can recover words whose bucket a collision partner pushed
below rank k (tfidf_tpu/rerank.py). Round 2 shipped margin=2 as an
unmeasured constant (VERDICT r2 weak #3); this sweep measures the
margin -> (exact recall, time) curve on the bench corpus so the default
is a decision, not a guess. Results land in docs/EXACT.md.

Run on the real chip:  python tools/margin_sweep.py [margins...]
"""

import os
import sys
import tempfile
import time

import numpy as np

import _common  # noqa: E402,F401  repo-root sys.path bootstrap
from _common import REPO  # noqa: E402

import importlib.util

spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


def main():
    margins = [int(a) for a in sys.argv[1:]] or [1, 2, 4, 8, 16]
    tmp = tempfile.mkdtemp(prefix="margin_sweep_")
    print(f"corpus: {bench.N_DOCS} docs...", file=sys.stderr)
    input_dir = bench.make_corpus(tmp)
    oracle_out = os.path.join(tmp, "ref.txt")
    bench.native_once(input_dir, oracle_out)

    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import run_overlapped
    from tfidf_tpu.recall import exact_doc_recall, parse_oracle_output
    from tfidf_tpu.rerank import exact_topk

    sample = [f"doc{i}"
              for i in range(1, min(bench.RECALL_DOCS, bench.N_DOCS) + 1)]
    per_doc = parse_oracle_output(oracle_out, docs=sample)

    k = bench.TOPK
    print("| margin | device k' | exact recall@16 | miss/512 docs | "
          "wall s | docs/sec |")
    print("|---|---|---|---|---|---|")
    for m in margins:
        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=bench.VOCAB,
                             max_doc_len=bench.DOC_LEN,
                             doc_chunk=bench.DOC_LEN,
                             topk=min(m * k, bench.DOC_LEN),
                             engine="sparse")
        chunk = max(2048, bench.N_DOCS // 4)
        run_overlapped(input_dir, cfg, chunk_docs=chunk,
                       doc_len=bench.DOC_LEN)  # warm compile
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            r = run_overlapped(input_dir, cfg, chunk_docs=chunk,
                               doc_len=bench.DOC_LEN)
            rr = exact_topk(input_dir, r.names, r.topk_ids, r.num_docs,
                            cfg, k=k, max_tokens=bench.DOC_LEN)
            best = min(best, time.perf_counter() - t0)
        scores, miss = [], 0
        for name, ref in per_doc.items():
            rec = exact_doc_recall(ref, [w for w, _ in rr[name]], k)
            if rec is not None:
                scores.append(rec)
                if rec < 1.0:
                    miss += 1
        recall = float(np.mean(scores))
        print(f"| {m} | {min(m * k, bench.DOC_LEN)} | {recall:.4f} | "
              f"{miss} | {best:.2f} | {bench.N_DOCS / best:.0f} |",
              flush=True)


if __name__ == "__main__":
    main()
