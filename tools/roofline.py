"""Roofline + stage split of the resident device program (VERDICT r4 #1).

Round 4 left a ~7-10x unexplained gap between the bench shape's fenced
"compute" (~30-47 Mtok/s for 32768x256) and the sort engine's own
measured marginal rate (~350 Mtok/s at L=4096, docs/ENGINES.md). This
tool decomposes that number on the real chip:

  floor     dispatch+fetch round trip of a trivial program
  h2d       cost of the FIRST program to consume freshly device_put
            wire data (the tunneled link stages uploads lazily, so this
            is where the real host->device transfer bill lands)
  sort      sorted_term_counts alone (pre-materialized inputs)
  sort+df   + sparse_df (the engine_bench unit)
  forward   + idf/score/topk (sparse_forward, the algorithmic whole)
  prod N=c  the production dispatch structure: c x _chunk_step +
            _finish_wire, inputs pre-materialized, fenced by a
            checksum fetch (compute only)
  wirefetch the [D, k] packed wire's device_get alone

plus an analytic bytes model per stage vs HBM peak. Every timing is
fenced by a device_get of a small dependent reduction —
block_until_ready under-reports on this backend (docs/ENGINES.md).

Usage: python tools/roofline.py [--docs 32768] [--len 256] [--repeats 5]
Writes a markdown table to stdout and one JSON line to stderr.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tfidf_tpu.config import PipelineConfig, VocabMode  # noqa: E402
from tfidf_tpu.ingest import (_chunk_step, _finish_wire,  # noqa: E402
                              _resident_df_mode, flatten_aligned)
# The analytic bytes model lives in obs/costmodel.py since round 12 —
# the tracer and tools/doctor.py quote the same arithmetic.
from tfidf_tpu.obs.costmodel import (HBM_PEAK_GBS_DEFAULT,  # noqa: E402
                                     bytes_model, hbm_peak_gbs)
from tfidf_tpu.ops.sparse import (sorted_term_counts, sparse_df,  # noqa: E402
                                  sparse_forward)

VOCAB = 1 << 16
TOPK = 16


def fence(x):
    """Force execution and completion via a real (tiny) fetch."""
    return jax.device_get(x)


def timeit(fn, repeats: int) -> float:
    fence(fn())  # warm (compile + any lazy input transfer)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fence(fn())
        best = min(best, time.perf_counter() - t0)
    return best


@functools.partial(jax.jit, static_argnames=())
def _checksum3(a, b, c):
    return (a.astype(jnp.int64).sum() + b.astype(jnp.int64).sum()
            + c.astype(jnp.int64).sum())


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=32768)
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--chunks", default="1,2,4,8",
                    help="chunk counts for the prod stage")
    ap.add_argument("--stages", default="all",
                    help="comma list of: floor,h2d,sort,df,fwd,pipe,"
                         "prod (or 'all'); each compile is ~20-40 s on "
                         "the tunnel, so pick what you need")
    args = ap.parse_args()
    stages = (set("floor,h2d,sort,df,fwd,pipe,prod".split(","))
              if args.stages == "all" else set(args.stages.split(",")))
    d, length = args.docs, args.length
    rep = args.repeats

    backend = jax.default_backend()
    print(f"backend={backend} device={jax.devices()[0].device_kind} "
          f"docs={d} len={length} best-of-{rep}", file=sys.stderr)

    rng = np.random.default_rng(0)
    ids_np = (np.clip(rng.zipf(1.3, (d, length)), 1, 8192) - 1) % VOCAB
    lens_np = rng.integers(length // 2, length + 1, d).astype(np.int32)
    mask = np.arange(length)[None, :] < lens_np[:, None]
    ids_np = np.where(mask, ids_np, 0).astype(np.int32)
    tokens = float(lens_np.sum())

    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                         max_doc_len=length, doc_chunk=length, topk=TOPK,
                         engine="sparse")
    score_dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(cfg.score_dtype))

    res: dict = {"docs": d, "len": length, "tokens": int(tokens),
                 "backend": backend}

    # -- floor: trivial program round trip --------------------------------
    if "floor" in stages:
        tiny = jnp.zeros((8,), jnp.int32)
        add1 = jax.jit(lambda x: x + 1)
        res["floor_s"] = timeit(lambda: add1(tiny), rep)

    # -- h2d: first consumption of freshly staged uploads ------------------
    # The ragged wire the production path ships: uint16 flat ids in the
    # packers' (granule-aligned) layout.
    flat_np, _ = flatten_aligned(ids_np, lens_np)
    consume = jax.jit(lambda t, l: (t.astype(jnp.int32).sum()
                                    + l.sum().astype(jnp.int32)))
    if "h2d" in stages:
        fence(consume(jnp.asarray(flat_np[:8]), jnp.asarray(lens_np[:8])))
        best = float("inf")
        for _ in range(rep):
            t0 = time.perf_counter()
            t_dev = jax.device_put(flat_np)
            l_dev = jax.device_put(lens_np)
            fence(consume(t_dev, l_dev))
            best = min(best, time.perf_counter() - t0)
        res["h2d_first_consume_s"] = best
        res["wire_mb"] = flat_np.nbytes / 1e6

    # Pre-materialized device inputs for all compute stages.
    tok_dev = jax.device_put(ids_np)
    len_dev = jax.device_put(lens_np)
    fence(consume(tok_dev, len_dev))

    # -- stage: sort -------------------------------------------------------
    if "sort" in stages:
        sort_fn = jax.jit(lambda t, l: _checksum3(*sorted_term_counts(t, l)))
        res["sort_s"] = timeit(lambda: sort_fn(tok_dev, len_dev), rep)

    # -- stage: sort + df --------------------------------------------------
    if "df" in stages:
        @jax.jit
        def sortdf(t, l):
            i, c, h = sorted_term_counts(t, l)
            return sparse_df(i, h, VOCAB).astype(jnp.int64).sum()
        res["sort_df_s"] = timeit(lambda: sortdf(tok_dev, len_dev), rep)

    # -- stage: full forward (sort+df+idf+score+topk) ----------------------
    @functools.partial(jax.jit, static_argnames=())
    def fwd(t, l):
        df, vals, out_ids = sparse_forward(
            t, l, jnp.int32(d), vocab_size=VOCAB,
            score_dtype=score_dtype, topk=TOPK)
        return (df.astype(jnp.int64).sum()
                + out_ids.astype(jnp.int64).sum()
                + vals.sum().astype(jnp.int64))
    if "fwd" in stages or "pipe" in stages:
        res["forward_s"] = timeit(lambda: fwd(tok_dev, len_dev), rep)

    # -- production dispatch structure at several chunk counts -------------
    k = min(TOPK, length)
    for n_chunks in (int(c) for c in args.chunks.split(",")):
        if "prod" not in stages or d % n_chunks:
            continue
        cd = d // n_chunks
        parts = []
        for s in range(0, d, cd):
            flat, _ = flatten_aligned(ids_np[s:s + cd],
                                      lens_np[s:s + cd])
            parts.append((jax.device_put(flat),
                          jax.device_put(lens_np[s:s + cd])))
        for t_, l_ in parts:
            fence(consume(t_, l_))

        def prod():
            df_acc = jnp.zeros((VOCAB,), jnp.int32)
            ti, tc, th, lp = [], [], [], []
            for t_, l_ in parts:
                i_, c_, h_, df_acc = _chunk_step(
                    t_, l_, df_acc, cfg, length, ragged=True,
                    fold_df=not _resident_df_mode()[1])
                ti.append(i_)
                tc.append(c_)
                th.append(h_)
                lp.append(l_)
            _, wire = _finish_wire((ti, tc, th), lp, df_acc, d, k,
                                   score_dtype, cfg, wire_vals=True)
            # checksum fence: compute cost without the wire's fetch
            return jnp.asarray(wire).astype(jnp.int32).sum()

        res[f"prod_c{n_chunks}_s"] = timeit(prod, rep)
        if n_chunks == 1:
            # Pipelined production marginal: the steady-state per-batch
            # cost of the full resident program pair (chunk + finish),
            # tunnel latency amortized (device executes in-order, so
            # fencing the last chain output proves all completed).
            def prod_chain():
                out = None
                for _ in range(8):
                    out = prod()
                return out

            fence(prod_chain())
            best = float("inf")
            for _ in range(rep):
                t0 = time.perf_counter()
                fence(prod_chain())
                best = min(best, time.perf_counter() - t0)
            res["prod_c1_x8_s"] = best
            res["prod_c1_marginal_s"] = max(
                (best - res["prod_c1_s"]) / 7, 1e-9)
        if n_chunks == 4:
            # the wire fetch alone, on top of warm compute
            def prod_wire():
                df_acc = jnp.zeros((VOCAB,), jnp.int32)
                ti, tc, th, lp = [], [], [], []
                for t_, l_ in parts:
                    i_, c_, h_, df_acc = _chunk_step(t_, l_, df_acc, cfg,
                                                     length, ragged=True)
                    ti.append(i_)
                    tc.append(c_)
                    th.append(h_)
                    lp.append(l_)
                _, wire = _finish_wire((ti, tc, th), lp, df_acc, d, k,
                                       score_dtype, cfg, wire_vals=True)
                return wire
            fence(prod_wire())
            best = float("inf")
            for _ in range(rep):
                t0 = time.perf_counter()
                fence(prod_wire())
                best = min(best, time.perf_counter() - t0)
            res["prod_c4_with_fetch_s"] = best

    # -- pipelined marginal device time -----------------------------------
    # Dispatch the full forward N times back-to-back and fence ONCE: the
    # tunnel's dispatch latency overlaps device compute, so the marginal
    # per-iteration time is the chip's true steady-state cost — what a
    # co-located host (or a pipelined production loop) would pay per
    # batch. This is the honest denominator for device_docs_per_sec:
    # the one-shot fenced number above charges the chip for ~100 ms of
    # link round trip it does not spend.
    # Device-side program execution is in-order, so fencing the LAST
    # chain output proves all n_pipe programs completed.
    n_pipe = 8 if "pipe" in stages else 0

    def fwd_chain():
        out = None
        for _ in range(n_pipe):
            out = fwd(tok_dev, len_dev)
        return out

    if n_pipe:
        fence(fwd_chain())
        best = float("inf")
        for _ in range(rep):
            t0 = time.perf_counter()
            fence(fwd_chain())
            best = min(best, time.perf_counter() - t0)
        res["forward_x8_s"] = best
        res["forward_marginal_s"] = max(
            (best - res["forward_s"]) / (n_pipe - 1), 1e-9)

    # -- analytic bytes model (obs/costmodel.py, shared) -------------------
    hbm_gbs = (hbm_peak_gbs(jax.devices()[0].device_kind)
               or HBM_PEAK_GBS_DEFAULT)
    model = bytes_model(d, length, topk=TOPK, hbm_gbs=hbm_gbs)
    res["bytes_model"] = {k2: round(v, 4) for k2, v in model.items()}

    # -- report ------------------------------------------------------------
    def row(name, s, note=""):
        mtoks = tokens / s / 1e6 if s else float("inf")
        print(f"| {name} | {s * 1e3:8.1f} ms | {mtoks:8.1f} | {note} |")

    print(f"\nStage | time | Mtok/s | note")
    print("|---|---|---|---|")
    if "floor_s" in res:
        row("floor", res["floor_s"])
    if "h2d_first_consume_s" in res:
        row("h2d first consume", res["h2d_first_consume_s"],
            f"{res['wire_mb']:.1f} MB wire")
    if "sort_s" in res:
        row("sort", res["sort_s"])
    if "sort_df_s" in res:
        row("sort+df", res["sort_df_s"])
    if "forward_s" in res:
        row("forward", res["forward_s"])
    if "forward_marginal_s" in res:
        row("forward marginal (x8 pipelined)", res["forward_marginal_s"],
            "true per-batch device cost")
    for c in (1, 2, 4, 8):
        key = f"prod_c{c}_s"
        if key in res:
            row(f"prod x{c} chunks", res[key])
    if "prod_c1_marginal_s" in res:
        row("prod marginal (x8 pipelined)", res["prod_c1_marginal_s"],
            "true per-batch production cost")
    if "prod_c4_with_fetch_s" in res:
        row("prod x4 + wire fetch", res["prod_c4_with_fetch_s"])
    print(f"\nbytes model: {json.dumps(res['bytes_model'])}")
    # the UNROUNDED floor: the artifact value rounds to 4 dp, which a
    # toy shape's microsecond-scale floor rounds to zero
    bound_s = model["hbm_bound_s"]
    print(f"HBM-bound floor at {hbm_gbs:.0f} GB/s: "
          f"{bound_s * 1e3:.1f} ms "
          f"({tokens / bound_s / 1e6:.0f} Mtok/s)")
    print(json.dumps({k2: (round(v, 5) if isinstance(v, float) else v)
                      for k2, v in res.items()}), file=sys.stderr)


if __name__ == "__main__":
    main()
