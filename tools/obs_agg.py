"""Cross-process obs federation: merge N serve processes into one view.

One ``TfidfServer`` renders its own metrics; a replicated tier
(ROADMAP item 3) needs the FRONT's view — one Prometheus page whose
counters are fleet totals and whose latency histogram is the merged
distribution. ``MetricsRegistry.merge`` (round 11) was built for
exactly this; this tool is the transport: it polls each serve
process's ``{"op": "obs_export"}`` JSONL op (a versioned bundle of
full instrument state — histogram buckets AND exemplars, so the merge
is lossless — plus the flight tail), rebuilds a registry per process
via ``MetricsRegistry.import_state``, merges them, and renders:

* the MERGED Prometheus exposition (counters add, gauges sum,
  histogram buckets add elementwise; request-id exemplars survive the
  merge, so a fleet p99 still links to one replayable trace);
* per-process labeled samples (``serve_requests_total{process="..."}``
  — which replica is hot, which is shedding);
* or ``--json``: the merged snapshot + per-process metadata.

Usage::

    python tools/obs_agg.py --endpoints 127.0.0.1:9101,127.0.0.1:9102
    python tools/obs_agg.py --endpoints ... --period 15   # poll loop
    python tools/obs_agg.py --bundles a.json b.json       # offline

Pure stdlib when the package is not already loaded — the registry and
histogram modules are loaded standalone (the doctor/trace_check
pattern), so this runs in a bare CI interpreter with no jax at all.
Exit 0 = rendered, 1 = some endpoint unreachable (partial render
still printed when at least one answered), 2 = nothing usable.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import socket
import sys
import time
import types
from typing import Dict, List, Optional, Tuple

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

OBS_SCHEMA = "tfidf-obs/1"

_REG_MOD = None   # cached standalone load (None until first use)


def _load_registry_module():
    """The shared registry/merge logic lives in
    ``tfidf_tpu/obs/registry.py``; importing it THROUGH the package
    would pull in jax. When the package is already imported (in-
    process tests) use it; otherwise load the two stdlib-only modules
    standalone with a transient package shim so registry's
    ``from tfidf_tpu.utils.timing import LatencyHistogram``
    resolves."""
    global _REG_MOD
    if "tfidf_tpu" in sys.modules:
        from tfidf_tpu.obs import registry
        return registry
    if _REG_MOD is not None:
        return _REG_MOD

    def load(rel: str, name: str):
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(_common.REPO, rel))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod

    loaded = []
    try:
        timing = load("tfidf_tpu/utils/timing.py",
                      "tfidf_tpu.utils.timing")
        loaded.append("tfidf_tpu.utils.timing")
        for name in ("tfidf_tpu", "tfidf_tpu.utils"):
            if name not in sys.modules:
                mod = types.ModuleType(name)
                mod.__path__ = []  # mark as package
                sys.modules[name] = mod
                loaded.append(name)
        sys.modules["tfidf_tpu.utils"].timing = timing
        registry = load("tfidf_tpu/obs/registry.py",
                        "tfidf_tpu.obs.registry")
        loaded.append("tfidf_tpu.obs.registry")
    finally:
        # The shims exist only to satisfy registry's import line —
        # drop every transient entry so a LATER real
        # `import tfidf_tpu` in the same process is unaffected.
        for name in loaded:
            sys.modules.pop(name, None)
    _REG_MOD = registry
    return registry


def fetch_bundle(host: str, port: int,
                 timeout_s: float = 5.0) -> dict:
    """One ``{"op": "obs_export"}`` round-trip over the serve TCP
    JSONL protocol."""
    with socket.create_connection((host, port),
                                  timeout=timeout_s) as sock:
        sock.sendall(b'{"op": "obs_export"}\n')
        buf = b""
        sock.settimeout(timeout_s)
        while not buf.endswith(b"\n"):
            chunk = sock.recv(1 << 16)
            if not chunk:
                break
            buf += chunk
    resp = json.loads(buf.decode())
    if "obs_export" not in resp:
        raise ValueError(f"endpoint answered without obs_export: "
                         f"{list(resp)}")
    return resp["obs_export"]


def validate_bundle(bundle: dict, label: str) -> None:
    if bundle.get("schema") != OBS_SCHEMA:
        raise ValueError(
            f"{label}: bundle schema {bundle.get('schema')!r} != "
            f"{OBS_SCHEMA!r} — mixed versions cannot merge safely")
    if not isinstance(bundle.get("registry"), dict):
        raise ValueError(f"{label}: bundle carries no registry state")


def merge_bundles(bundles: Dict[str, dict]):
    """label -> bundle mapping -> (merged registry, per-process
    registries). Counters add, gauges sum, histograms merge bucket-
    wise with exemplars surviving."""
    reg_mod = _load_registry_module()
    per = {label: reg_mod.MetricsRegistry.import_state(b["registry"])
           for label, b in bundles.items()}
    merged = reg_mod.MetricsRegistry()
    for reg in per.values():
        merged.merge(reg)
    return merged, per


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def render_prom(merged, per: Dict, bundles: Dict[str, dict]) -> str:
    """Merged exposition + per-process labeled samples. The merged
    half is the fleet view (histogram counts are the SUM of the
    per-process snapshots — pinned by tests); the labeled half says
    which replica contributed what."""
    lines = [f"# obs_agg: {len(per)} process(es) merged",
             f"obs_agg_processes {len(per)}"]
    lines.append(merged.render_prom().rstrip("\n"))
    for label, reg in sorted(per.items()):
        bundle = bundles[label]
        plabel = f'process="{_esc(label)}"'
        lines.append(f"# process {label}: pid={bundle.get('pid')} "
                     f"epoch={bundle.get('epoch')} "
                     f"uptime_s={bundle.get('uptime_s')}")
        snap = reg.snapshot()
        for name, value in sorted(snap.items()):
            if isinstance(value, (int, float)):
                lines.append(f"{name}{{{plabel}}} {value}")
            elif isinstance(value, dict) and "value" in value:
                lines.append(f"{name}{{{plabel}}} {value['value']}")
            elif isinstance(value, dict) and "count" in value:
                lines.append(f"{name}_count{{{plabel}}} "
                             f"{value['count']}")
    return "\n".join(lines) + "\n"


def render_json(merged, per: Dict, bundles: Dict[str, dict]) -> str:
    doc = {
        "schema": OBS_SCHEMA,
        "processes": {
            label: {"pid": b.get("pid"), "epoch": b.get("epoch"),
                    "uptime_s": b.get("uptime_s"),
                    "fingerprint": b.get("fingerprint"),
                    "registry": per[label].snapshot(),
                    "flight_events": len(b.get("flight_tail", []))}
            for label, b in bundles.items()},
        "merged": merged.snapshot(),
    }
    return json.dumps(doc, sort_keys=True)


def collect(endpoints: List[Tuple[str, int]],
            bundle_paths: List[str]) -> Tuple[Dict[str, dict],
                                              List[str]]:
    """-> (label -> validated bundle, per-source error strings)."""
    bundles: Dict[str, dict] = {}
    errors: List[str] = []
    for host, port in endpoints:
        label = f"{host}:{port}"
        try:
            b = fetch_bundle(host, port)
            validate_bundle(b, label)
            bundles[label] = b
        except (OSError, ValueError) as e:
            errors.append(f"{label}: {e}")
    for path in bundle_paths:
        label = os.path.basename(path)
        try:
            with open(path) as f:
                b = json.load(f)
            b = b.get("obs_export", b)  # raw bundle or full response
            validate_bundle(b, label)
            bundles[label] = b
        except (OSError, ValueError) as e:
            errors.append(f"{label}: {e}")
    return bundles, errors


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit 0 = rendered, 1 = endpoint errors (partial "
               "render when possible), 2 = nothing usable")
    ap.add_argument("--endpoints", default="",
                    help="comma-separated host:port list of serve "
                         "--port processes to poll via the "
                         "obs_export op")
    ap.add_argument("--bundles", nargs="*", default=[],
                    help="obs_export bundle JSON files to merge "
                         "offline (a saved op response or the bare "
                         "bundle)")
    ap.add_argument("--period", type=float, default=0.0,
                    help="poll every N seconds and re-render "
                         "(0 = once)")
    ap.add_argument("--json", action="store_true",
                    help="render merged JSON instead of Prometheus "
                         "text")
    args = ap.parse_args()

    endpoints: List[Tuple[str, int]] = []
    for spec in (s.strip() for s in args.endpoints.split(",")):
        if not spec:
            continue
        host, _, port = spec.rpartition(":")
        try:
            endpoints.append((host or "127.0.0.1", int(port)))
        except ValueError:
            print(f"obs_agg: bad endpoint {spec!r} (want host:port)",
                  file=sys.stderr)
            return 2
    if not endpoints and not args.bundles:
        print("obs_agg: nothing to aggregate (pass --endpoints or "
              "--bundles)", file=sys.stderr)
        return 2

    while True:
        bundles, errors = collect(endpoints, args.bundles)
        for err in errors:
            print(f"obs_agg: {err}", file=sys.stderr)
        if not bundles:
            print("obs_agg: no endpoint answered", file=sys.stderr)
            return 2
        merged, per = merge_bundles(bundles)
        out = (render_json(merged, per, bundles) if args.json
               else render_prom(merged, per, bundles))
        sys.stdout.write(out)
        sys.stdout.flush()
        if args.period <= 0:
            return 1 if errors else 0
        time.sleep(args.period)


if __name__ == "__main__":
    sys.exit(main())
