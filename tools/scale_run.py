"""Corpus-scale measurement: resident vs streaming regimes at size.

Generates an N-doc Zipf corpus on disk (one file per doc, the
reference's contract), then measures `run_overlapped` end-to-end:
  - the resident path (default) at its actual scale ceiling, and
  - the two-pass streaming path (forced via TFIDF_TPU_RESIDENT_ELEMS=0)
    under both spill policies.
Numbers land in docs/SCALING.md. Corpus generation is the slow part at
1M docs — the corpus dir is kept between runs unless --fresh.

    python tools/scale_run.py [n_docs] [--streaming-only]
"""

import json
import os
import resource
import shutil
import sys
import time

import numpy as np

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

N_DOCS = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() \
    else 1_000_000
DOC_LEN = 256
N_WORDS = 8192
CHUNK = 32768
ROOT = os.environ.get("SCALE_DIR", f"/tmp/tfidf_scale_{N_DOCS}")


def make_corpus(input_dir: str) -> None:
    if os.path.isdir(input_dir) and \
            len(os.listdir(input_dir)) == N_DOCS and \
            "--fresh" not in sys.argv:
        print(f"reusing corpus {input_dir}", file=sys.stderr)
        return
    shutil.rmtree(input_dir, ignore_errors=True)
    os.makedirs(input_dir)
    rng = np.random.default_rng(42)
    words = np.array([f"w{i}".encode() for i in range(N_WORDS)],
                     dtype=object)
    t0 = time.perf_counter()
    step = 65536
    for base in range(0, N_DOCS, step):
        n_here = min(step, N_DOCS - base)
        zipf = np.clip(rng.zipf(1.3, size=n_here * DOC_LEN), 1,
                       N_WORDS) - 1
        lens = rng.integers(DOC_LEN // 2, DOC_LEN + 1, n_here)
        off = 0
        for j in range(n_here):
            n = int(lens[j])
            doc = b" ".join(words[zipf[off:off + n]])
            off += n
            with open(os.path.join(input_dir, f"doc{base + j + 1}"),
                      "wb") as f:
                f.write(doc)
        print(f"  corpus {base + n_here}/{N_DOCS} "
              f"({time.perf_counter() - t0:.0f}s)", file=sys.stderr)


def run_once(input_dir, tag):
    from tfidf_tpu.config import PipelineConfig, VocabMode
    from tfidf_tpu.ingest import run_overlapped

    cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=1 << 16,
                         max_doc_len=DOC_LEN, doc_chunk=DOC_LEN, topk=16,
                         engine="sparse")
    t0 = time.perf_counter()
    r = run_overlapped(input_dir, cfg, chunk_docs=CHUNK, doc_len=DOC_LEN)
    wall = time.perf_counter() - t0
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    rec = {"tag": tag, "path": r.path, "n_docs": r.num_docs,
           "wall_s": round(wall, 1),
           "docs_per_sec": round(r.num_docs / wall, 0),
           "host_maxrss_gb": round(rss, 2),
           "phases": {k: round(v, 2) for k, v in (r.phases or {}).items()}}
    print(json.dumps(rec), flush=True)
    return rec


def main():
    input_dir = os.path.join(ROOT, "input")
    make_corpus(input_dir)
    if "--streaming-only" not in sys.argv:
        run_once(input_dir, "resident-warm0")  # includes compiles
        run_once(input_dir, "resident")
    os.environ["TFIDF_TPU_RESIDENT_ELEMS"] = "0"
    for spill in ("host", "reread"):
        os.environ["TFIDF_TPU_SPILL_BYTES"] = "0" if spill == "reread" \
            else str(1 << 62)
        run_once(input_dir, f"streaming-{spill}-warm0")
        run_once(input_dir, f"streaming-{spill}")


if __name__ == "__main__":
    main()
