"""BASELINE config 4 measured on a REAL source-code corpus.

Char n-gram (3..5) hashed TF-IDF over actual source files — this
repository's own tree plus the installed jax package's .py sources —
on the device chargram path (rolling-hash n-gram ids computed on chip,
no host n-gram materialization). Round 2 only ever measured synthetic
Zipf corpora (VERDICT r2 missing #3); this is the first non-synthetic
config on chip.

Prints a summary + one JSON line; numbers land in docs/SCALING.md.
    python tools/chargram_bench.py
"""

import glob
import json
import os
import sys
import time

import numpy as np

import _common  # noqa: E402,F401  repo-root sys.path bootstrap
from _common import REPO  # noqa: E402

MAX_BYTES = 4096       # per-file cap: keeps batches rectangular-ish
BATCH = 1024           # dense [BATCH, 2^16] int32 counts = 256 MB
VOCAB = 1 << 16
TOPK = 16
NGRAMS = (3, 5)


def collect_sources(limit=8192):
    pats = [os.path.join(REPO, "**", "*.py"),
            os.path.join(REPO, "**", "*.cc"),
            os.path.join(REPO, "**", "*.h"),
            os.path.join(REPO, "**", "*.md")]
    import jax
    jax_root = os.path.dirname(jax.__file__)
    pats.append(os.path.join(jax_root, "**", "*.py"))
    files = []
    for p in pats:
        files.extend(sorted(glob.glob(p, recursive=True)))
    docs = []
    for f in files:
        if len(docs) >= limit:
            break
        try:
            with open(f, "rb") as fh:
                data = fh.read(MAX_BYTES)
        except OSError:
            continue
        if data.strip():
            docs.append(data)
    return docs


def main():
    docs = collect_sources()
    total_bytes = sum(len(d) for d in docs)
    print(f"{len(docs)} source files, {total_bytes / 1e6:.1f} MB "
          f"(capped at {MAX_BYTES}B/file)", file=sys.stderr)

    from tfidf_tpu.config import PipelineConfig, TokenizerKind, VocabMode
    from tfidf_tpu.io.corpus import Corpus
    from tfidf_tpu.pipeline import TfidfPipeline

    cfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                         vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                         ngram_range=NGRAMS, topk=TOPK)
    pipe = TfidfPipeline(cfg)

    def run_all():
        outs = []
        for s in range(0, len(docs), BATCH):
            batch = docs[s:s + BATCH]
            corpus = Corpus(
                names=[f"doc{i}" for i in range(1, len(batch) + 1)],
                docs=batch)
            outs.append(pipe.run_bytes(corpus))
        return outs

    run_all()  # warm the compile caches (one per distinct batch shape)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        outs = run_all()
        best = min(best, time.perf_counter() - t0)

    # Sanity: device n-gram counts == the pure-Python rolling-hash
    # reference on a few real files (ids exact, the test_chargram pin,
    # here exercised on-chip with real source bytes).
    sample = Corpus(names=["doc1", "doc2"], docs=[docs[0], docs[len(docs) // 2]])
    scfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                          vocab_mode=VocabMode.HASHED, vocab_size=512,
                          ngram_range=NGRAMS)
    r = TfidfPipeline(scfg).run_bytes(sample)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_chargram import chargram_counts_ref
    for d, doc in enumerate(sample.docs):
        want = chargram_counts_ref(doc, NGRAMS[0], NGRAMS[1], 512, 0)
        assert (np.asarray(r.counts)[d] == want).all(), f"doc{d + 1} counts"
    parity = "device n-gram counts == python rolling-hash ref on real files"
    print(parity, file=sys.stderr)

    total_ids = sum(max(len(d) - n + 1, 0)
                    for d in docs
                    for n in range(NGRAMS[0], NGRAMS[1] + 1))
    dps = len(docs) / best
    rec = {"metric": "chargram(3..5) docs/sec, real source-code corpus "
                     "(repo + jax sources), hashed 2^16 vocab, top-16",
           "value": round(dps, 1), "unit": "docs/sec",
           "n_docs": len(docs), "corpus_mb": round(total_bytes / 1e6, 1),
           "wall_s": round(best, 3), "topk_sanity": "exact-id parity",
           "ngram_ids_per_sec": round(total_ids / best, 0)}
    print(json.dumps(rec), flush=True)

    # Wide-vocab stress (the POINT of config 4): 2^20 vocab on the
    # row-sparse device lowering — the dense [BATCH, V] histogram would
    # be 4 GB; the sparse engine touches only [BATCH, sum_L] triples
    # plus a [V] DF vector. Phase breakdown via PhaseTimer.
    from tfidf_tpu.utils.timing import PhaseTimer
    wide_timer = PhaseTimer()
    wcfg = PipelineConfig(tokenizer=TokenizerKind.CHARGRAM,
                          vocab_mode=VocabMode.HASHED,
                          vocab_size=1 << 20, ngram_range=NGRAMS,
                          engine="sparse", topk=TOPK)
    wpipe = TfidfPipeline(wcfg, timer=wide_timer)

    def run_wide():
        for s in range(0, len(docs), BATCH):
            batch = docs[s:s + BATCH]
            wpipe.run_bytes(Corpus(
                names=[f"doc{i}" for i in range(1, len(batch) + 1)],
                docs=batch))

    run_wide()  # warm
    wbest = float("inf")
    for _ in range(2):
        wide_timer.reset()
        t0 = time.perf_counter()
        run_wide()
        wbest = min(wbest, time.perf_counter() - t0)
    rec = {"metric": "chargram(3..5) docs/sec, real source-code corpus, "
                     "hashed 2^20 WIDE vocab (sparse lowering), top-16",
           "value": round(len(docs) / wbest, 1), "unit": "docs/sec",
           "n_docs": len(docs), "wall_s": round(wbest, 3),
           "ngram_ids_per_sec": round(total_ids / wbest, 0),
           "phases": wide_timer.as_dict()}
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
