"""Microbenchmark: the three TF(+DF) engines head-to-head on device.

VERDICT r1 item 3: the default engine must be chosen by measurement, not
docstring. Times, per (vocab, doc_len) cell:

  scatter — masked scatter-add dense histogram (ops/histogram.tf_counts,
            chunked scan for doc_len > chunk)
  sort    — sort+RLE row-sparse triples + dual-lowering DF
            (ops/sparse.sorted_term_counts + sparse_df)
  pallas  — fused compare-and-reduce TF+DF kernel
            (ops/pallas_kernels.tf_df_pallas) — O(L*V) work per doc,
            expected to lose at large vocab

Each engine's timed unit is "token ids on device -> (TF representation +
DF [V] on device)" — the common subproblem all three solve. Run on the
real TPU; writes a markdown table to stdout (paste into docs/ENGINES.md)
plus one JSON line per cell to stderr.

Usage: python tools/engine_bench.py [--docs 4096] [--repeats 5]
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tfidf_tpu.ops.histogram import (df_from_counts, tf_counts,  # noqa: E402
                                     tf_counts_chunked)
from tfidf_tpu.ops.pallas_kernels import tf_df_pallas  # noqa: E402
from tfidf_tpu.ops.sparse import sorted_term_counts, sparse_df  # noqa: E402

CHUNK = 512  # doc_len above this takes the chunked-scan scatter path


@functools.partial(jax.jit, static_argnames=("vocab_size", "chunk"))
def _scatter(token_ids, lengths, *, vocab_size, chunk):
    if token_ids.shape[1] > chunk:
        counts = tf_counts_chunked(token_ids, lengths, vocab_size, chunk)
    else:
        counts = tf_counts(token_ids, lengths, vocab_size)
    return counts, df_from_counts(counts)


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _sort(token_ids, lengths, *, vocab_size):
    ids, counts, head = sorted_term_counts(token_ids, lengths)
    return (ids, counts, head), sparse_df(ids, head, vocab_size)


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def _pallas(token_ids, lengths, *, vocab_size):
    return tf_df_pallas(token_ids, lengths, vocab_size=vocab_size)


def time_engine(fn, token_ids, lengths, repeats: int) -> float:
    """Best-of-N wall-clock of one engine call, fenced by a real fetch.

    block_until_ready alone under-reports on the tunneled axon backend
    (observed: "completion" in 33 us for 16M tokens); device_get of the
    [V] DF vector — identical across engines — forces actual execution.
    """
    out = fn(token_ids, lengths)  # compile + warmup
    jax.device_get(out[1])
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.device_get(fn(token_ids, lengths)[1])
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=4096)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--zipf", type=float, default=1.3)
    args = ap.parse_args()

    backend = jax.default_backend()
    dev = jax.devices()[0]
    print(f"backend={backend} device={dev.device_kind} docs={args.docs} "
          f"best-of-{args.repeats}", file=sys.stderr)

    engines = {
        "scatter": lambda v: (lambda t, l: _scatter(t, l, vocab_size=v,
                                                    chunk=CHUNK)),
        "sort": lambda v: (lambda t, l: _sort(t, l, vocab_size=v)),
        "pallas": lambda v: (lambda t, l: _pallas(t, l, vocab_size=v)),
    }
    cells = []
    rng = np.random.default_rng(0)
    for vocab in (1 << 10, 1 << 16):
        for doc_len in (256, 4096):
            # Zipf-distributed ids mirror bench.py's corpus shape; pad
            # tail tokens past each doc's length with zeros like the
            # packer does.
            ids = np.clip(rng.zipf(args.zipf, (args.docs, doc_len)),
                          1, vocab) - 1
            lens = rng.integers(doc_len // 2, doc_len + 1,
                                args.docs).astype(np.int32)
            mask = np.arange(doc_len)[None, :] < lens[:, None]
            ids = jnp.asarray(np.where(mask, ids, 0).astype(np.int32))
            lens = jnp.asarray(lens)
            row = {"vocab": vocab, "doc_len": doc_len}
            for name, make in engines.items():
                try:
                    s = time_engine(make(vocab), ids, lens, args.repeats)
                    row[name] = s
                except Exception as e:  # OOM / Mosaic limits: record it
                    row[name] = None
                    row[f"{name}_error"] = type(e).__name__
                    print(f"{name} v={vocab} L={doc_len}: "
                          f"{str(e)[:200]}", file=sys.stderr)
            print(json.dumps(row), file=sys.stderr)
            cells.append(row)

    def fmt(row, name):
        s = row.get(name)
        if s is None:
            return row.get(f"{name}_error", "fail")
        mtoks = args.docs * row["doc_len"] / s / 1e6
        return f"{s * 1e3:.2f} ms ({mtoks:.0f} Mtok/s)"

    print(f"\n| vocab | doc_len | scatter | sort+RLE | pallas | winner |")
    print("|---|---|---|---|---|---|")
    for row in cells:
        timed = {n: row[n] for n in engines if row.get(n) is not None}
        win = min(timed, key=timed.get) if timed else "-"
        print(f"| 2^{int(np.log2(row['vocab']))} | {row['doc_len']} "
              f"| {fmt(row, 'scatter')} | {fmt(row, 'sort')} "
              f"| {fmt(row, 'pallas')} | {win} |")


if __name__ == "__main__":
    main()
