"""Collective-overhead probe: docs-sharded step vs single-device step.

Runs the sparse forward on the SAME global batch twice on the virtual
8-device CPU mesh — once single-device, once docs-sharded through
shard_map (DF psum + partitioning) — and reports the wall ratio. Feeds
the multi-chip projection in docs/SCALING.md ("The 50x story"): the
measured ratio ~1.0 shows partitioning + the 256 KB DF psum add no
measurable cost beyond the per-shard work itself.

    python tools/mesh_overhead.py
"""

import functools
import os
import time

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", "cpu")

from tfidf_tpu.ops.sparse import sparse_forward
from tfidf_tpu.parallel.collectives import make_sparse_sharded_forward
from tfidf_tpu.parallel.mesh import MeshPlan

D, L, V, K = 8192, 256, 1 << 16, 16


def best_of(fn, n=5):
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    rng = np.random.default_rng(0)
    toks = rng.integers(0, V, (D, L)).astype(np.int32)
    lens = rng.integers(L // 2, L + 1, D).astype(np.int32)

    single = jax.jit(functools.partial(
        sparse_forward, vocab_size=V, score_dtype=jnp.float32, topk=K))
    a, b = jax.device_put(toks), jax.device_put(lens)
    jax.block_until_ready(single(a, b, jnp.int32(D)))  # compile
    t_single = best_of(lambda: single(a, b, jnp.int32(D)))

    plan = MeshPlan.create(docs=8)
    fwd = make_sparse_sharded_forward(plan, V, jnp.float32, K)
    sa = jax.device_put(toks, plan.sharding(plan.batch_spec()))
    sb = jax.device_put(lens, plan.sharding(plan.lengths_spec()))
    jax.block_until_ready(fwd(sa, sb, jnp.int32(D)))  # compile
    t_mesh = best_of(lambda: fwd(sa, sb, jnp.int32(D)))

    print(f"single-device sparse step ({D}x{L}, V=2^16, k={K}): "
          f"{t_single:.3f}s")
    print(f"8-shard docs-mesh step (same global batch):        "
          f"{t_mesh:.3f}s")
    print(f"mesh/single wall ratio: {t_mesh / t_single:.2f} "
          f"(one host core runs all 8 shards serially, so ratio ~1.0 "
          f"means partitioning + collectives are free at this payload)")
    print(f"DF psum payload: {V * 4 // 1024} KB per step; "
          f"top-k all_gather: none (docs axis only)")


if __name__ == "__main__":
    main()
