"""Perf gate: hold a fresh artifact against the ledger's baseline.

``tools/perf_ledger.py`` records the trajectory; this tool CONSUMES
it: given a fresh bench/serve artifact, find the comparable ledger
records (same kind, same backend, same corpus size), build a rolling
baseline (per-metric median over the last N), and fail — exit 1 —
when any gated metric regresses past its tolerance. This is the CI
tripwire the five BENCH rounds never had: a 2x latency regression or
a halved throughput now fails a command instead of waiting for a
human to eyeball two JSON files.

Noise-awareness, because a tripwire that cries wolf gets deleted:

* the baseline is a MEDIAN over up to ``--window`` runs, not the last
  run — one lucky/unlucky round does not move the bar;
* each metric has a direction (higher-is-better throughput vs
  lower-is-better latency) and a base relative tolerance sized to its
  observed round-to-round noise (latency percentiles on a loaded box
  jitter far more than docs/sec medians);
* when the window holds >= 3 samples the tolerance WIDENS to the
  observed relative spread of the baseline itself (half the min-max
  band, x ``--noise-mult``) if that is larger — a metric the ledger
  shows to be noisy cannot fail the gate inside its own noise band;
* a candidate identical to a ledger record passes by construction
  (zero delta <= any tolerance) — re-running the gate on an unchanged
  artifact is a no-op, the false-positive floor tests pin.

Usage::

    python tools/perf_gate.py FRESH.json [--ledger BENCH_LEDGER.jsonl]
    python tools/perf_gate.py SERVE_r01.json --json   # machine verdict

Exit codes: 0 = pass (or no comparable baseline — warned, unless
``--require-baseline``), 1 = regression, 2 = unusable input.
Stdlib-only; runnable with no jax at all.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
from typing import Dict, List

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import perf_ledger  # noqa: E402  (sibling tool: shared normalization)

# metric -> (direction, base relative tolerance). Directions: "higher"
# fails when the candidate drops below baseline*(1-tol); "lower" fails
# past baseline*(1+tol). Tolerances are the measured round-to-round
# noise bands (BENCH_r02-r05 docs/sec IQR ~8%, serve p99 on a shared
# CPU box swings ~40%), padded to stay quiet inside normal jitter.
_GATES = {
    "bench": {
        "docs_per_sec": ("higher", 0.25),
        "vs_baseline": ("higher", 0.25),
        "device_docs_per_sec": ("higher", 0.30),
        # Serialized one-pass host pack (artifact pack_serial_s, with
        # a pack_s fallback for pre-round-14 artifacts — the ledger
        # keeps ONE pack trajectory under this name; perf_ledger.py
        # has the rename story). A pack that re-serializes or loses
        # its threading regresses here and fails the gate.
        "pack_s": ("lower", 0.40),
        # Upload byte receipt (bytes_on_wire / padded denominator):
        # byte counts are deterministic at a fixed corpus shape, so
        # the band is tight — a packer change that silently re-fattens
        # the wire cannot hide inside run-to-run noise.
        "wire_ratio": ("lower", 0.05),
        "link_tax_s": ("lower", 0.40),
        # Round 19 attributed link columns (bench `link` object): the
        # exact columns the multi-process ingest and the query slab
        # attack, held separately so a regression in one cannot hide
        # inside the other's noise band.
        "upload_s": ("lower", 0.40),
        "sync_s": ("lower", 0.40),
        "recall_at_k": ("higher", 0.02),
        # Round 12: memory/compile regressions gate like latency ones.
        # Peak HBM at a fixed corpus shape is allocator-deterministic
        # to within fragmentation noise (~10%); compile counts should
        # be exactly reproducible, but the persistent cache can elide
        # a few, so allow a small band rather than absolute zero.
        "peak_hbm_bytes": ("lower", 0.10),
        "xla_compiles": ("lower", 0.15),
    },
    "serve_bench": {
        "throughput_qps": ("higher", 0.30),
        "throughput_rps": ("higher", 0.30),
        "p50_ms": ("lower", 0.60),
        "p99_ms": ("lower", 0.60),
        "cache_hit_rate": ("higher", 0.10),
        "recompiles_after_warmup": ("lower", 0.0),
        "peak_hbm_bytes": ("lower", 0.10),
        "xla_compiles": ("lower", 0.15),
        # Round 16: the latency objective is a gated direction — a PR
        # whose serving quietly blows the SLO (compliance drops past
        # the band vs the rolling baseline) fails CI even when raw
        # p50/p99 stay inside their (wide) noise tolerances.
        "slo_compliance": ("higher", 0.10),
        # Round 19 query-slab receipts (--ab-slab): parity vs the
        # slab-off pass is the contract (zero-tolerance), and the
        # structural invariants gate absolutely — steady state must
        # allocate NOTHING (0 allocs/batch) and copy ONCE (the
        # absolute zero-baseline rule fires on any nonzero allocs;
        # h2d/batch above 1 fails the 1.0 baseline's 0% band).
        "slab_parity_ok": ("higher", 0.0),
        "slab_allocs_per_batch": ("lower", 0.0),
        "slab_h2d_per_batch": ("lower", 0.0),
        # Round 20 bench honesty: the cache-bypassed latency columns.
        # Wider bands than the warm ones — every request pays the full
        # device path — but gated, so the headline p50/p99 can never
        # again improve purely by riding a fatter cache.
        "p50_ms_cache_off": ("lower", 0.60),
        "p99_ms_cache_off": ("lower", 0.80),
        # Round 21 tiled-scoring A/B (--ab-tiled): parity vs the
        # tiling-off pass is the contract — any byte divergence at
        # any probed width fails absolutely.
        "tiled_parity_ok": ("higher", 0.0),
        # Round 22 pipelined-execution A/B (--ab-pipeline): parity
        # across depths AND vs direct search is zero-tolerance, as
        # are per-depth steady-state recompiles (the absolute
        # zero-baseline rule fires on any nonzero count). The qps
        # columns gate directionally: the depth-2 window must keep
        # beating the depth-1 baseline (the gain column heading to
        # zero is the overlap rotting back into lockstep execution).
        "pipeline_parity_ok": ("higher", 0.0),
        "pipeline_recompiles_depth2": ("lower", 0.0),
        "pipeline_recompiles_depth4": ("lower", 0.0),
        "pipeline_qps_depth2": ("higher", 0.30),
        "pipeline_qps_gain_depth2": ("higher", 0.50),
    },
    # Multi-process sharded ingest (tools/ingest_mh_bench.py): parity
    # is zero-tolerance — the N-worker merged index must stay
    # bit-identical to single-process (DF, IDF, scores, names, tie
    # order); upload_s is THE attacked column (wall of the slowest
    # link-owning worker, lower); speedup_vs_1p gates higher so the
    # protocol cannot quietly decay back toward serial ingest.
    "ingest_mh": {
        "parity_ok": ("higher", 0.0),
        "upload_s": ("lower", 0.40),
        "wall_s": ("lower", 0.40),
        "speedup_vs_1p": ("higher", 0.25),
    },
    # Mutation workloads (serve_bench --mutate): parity under a live
    # add/update/delete stream is zero-tolerance (served bytes must
    # equal the from-scratch rebuild oracle's), as are steady-state
    # recompiles and a dead compactor; visibility lag and compaction
    # pauses gate directionally with wide bands (shared-box timing of
    # sub-ms installs jitters hard), so only a real slowdown fails.
    "mutate": {
        "mutation_qps": ("higher", 0.50),
        "throughput_qps": ("higher", 0.50),
        "visibility_lag_p50_ms": ("lower", 0.60),
        "visibility_lag_p99_ms": ("lower", 0.80),
        "compaction_pause_max_ms": ("lower", 1.00),
        "recompiles_after_warmup": ("lower", 0.0),
        "parity_ok": ("higher", 0.0),
        "compactor_dead": ("lower", 0.0),
    },
    # Mesh-sharded serving (serve_bench --mesh-shards): the ISSUE's
    # directional gates. Parity is the contract — sharded serve bytes
    # must equal the single-device source's (zero-tolerance), as must
    # steady-state recompiles; throughput gates higher and p99 lower
    # so a fatter collective or a slower merge fails CI; the shard
    # imbalance ratio is allocator-deterministic at a fixed corpus
    # shape, so its band is tight.
    "mesh_serve": {
        "throughput_qps": ("higher", 0.30),
        "throughput_rps": ("higher", 0.30),
        "p50_ms": ("lower", 0.60),
        "p99_ms": ("lower", 0.60),
        "parity_ok": ("higher", 0.0),
        "recompiles_after_warmup": ("lower", 0.0),
        "shard_imbalance": ("lower", 0.10),
        "slo_compliance": ("higher", 0.10),
    },
    # Replicated serving tier (serve_bench --replicas): the pins are
    # zero-tolerance — parity_ok must stay 1 (front-routed responses
    # float32-identical to direct search at every sweep width AND
    # under the chaos plan), mixed_epoch_responses must stay 0 (no
    # client observes an epoch the front has not committed; the
    # absolute zero-baseline rule fires on any nonzero candidate),
    # recompiles_after_warmup must stay 0 per replica, and the chaos
    # rehearsal receipts must stay 1 (kill-mid-swap aborted AND left
    # every replica on the old epoch). Throughput gates directionally
    # with a wide band: host_cores is a match key, but even at a fixed
    # core count a 1-core box times scheduler fairness, not replicas.
    "replica_serve": {
        "throughput_qps": ("higher", 0.50),
        "qps_1": ("higher", 0.50),
        "qps_scaling_x": ("higher", 0.30),
        "p99_ms": ("lower", 0.80),
        "parity_ok": ("higher", 0.0),
        "mixed_epoch_responses": ("lower", 0.0),
        "recompiles_after_warmup": ("lower", 0.0),
        "chaos_swap_aborted": ("higher", 0.0),
        "chaos_old_epoch_everywhere": ("higher", 0.0),
        # Fleet tracing (round 23): parity and recompiles WITH the
        # trace context on every hop are zero-tolerance — tracing may
        # never change an answer or mint a program. The propagation
        # overhead gates directionally with a very wide band (a
        # cache-off p50 delta on a shared box is noisy) alongside the
        # raw on-leg p50, so a hop that starts serializing on the
        # trace plumbing fails CI instead of hiding in the average.
        "disttrace_parity_ok": ("higher", 0.0),
        "disttrace_recompiles": ("lower", 0.0),
        "disttrace_overhead_pct": ("lower", 1.00),
        "disttrace_p50_on_ms": ("lower", 0.80),
    },
    # Retrieval batch-scaling sweep (tools/retrieval_bench.py): the
    # round-21 tiled-scorer receipts. parity_ok must stay 1 (tiled
    # bit-identical to --score-tiling=off — scores, ids, tie order),
    # qps_monotonic_through_256 must stay 1 (the weak-5 "throughput
    # goes DOWN with batch size" regression can never return), and
    # recompiles_after_warmup must stay 0 (one program per pow2
    # bucket, full stop). The QPS columns gate directionally so the
    # scan lowering cannot quietly slow down.
    "retrieval": {
        "parity_ok": ("higher", 0.0),
        "qps_monotonic_through_256": ("higher", 0.0),
        "recompiles_after_warmup": ("lower", 0.0),
        "qps_q64": ("higher", 0.30),
        "qps_q256": ("higher", 0.30),
        "qps_q512": ("higher", 0.30),
        "index_docs_per_sec": ("higher", 0.30),
    },
    # Scoring-family sweep (tools/retrieval_bench.py --scorers, round
    # 23): parity_ok must stay 1 (every scorer variant bit-identical
    # to the untiled fallback AND to the pure-NumPy oracle — ids and
    # tie order, not just scores), recompiles_after_warmup must stay 0
    # (tfidf and bm25 faces share the same compiled search programs —
    # scorer switching may never mint a new one), and the per-scorer
    # recall@10 columns must stay 1.0 with a hair of band (they are
    # device-vs-oracle receipts, deterministic at a fixed corpus).
    # The per-scorer QPS columns gate directionally.
    "scoring": {
        "parity_ok": ("higher", 0.0),
        "recompiles_after_warmup": ("lower", 0.0),
        "recall_at_10_tfidf": ("higher", 0.0),
        "recall_at_10_bm25": ("higher", 0.0),
        "qps_q64_tfidf": ("higher", 0.30),
        "qps_q256_tfidf": ("higher", 0.30),
        "qps_q64_bm25": ("higher", 0.30),
        "qps_q256_bm25": ("higher", 0.30),
        "qps_q64_bm25_filter": ("higher", 0.30),
        "qps_q256_bm25_filter": ("higher", 0.30),
    },
    # The mesh dryrun verdict: ok must STAY 1 (zero-tolerance, the
    # absolute zero-baseline rule below never fires because ok is the
    # higher-is-better direction with a nonzero baseline).
    "multichip": {
        "ok": ("higher", 0.0),
    },
    # Chaos runs (serve_bench --chaos): parity under faults is the
    # whole point — zero-tolerance both ways. parity_ok must stay 1
    # (any served-vs-direct byte divergence fails), and
    # breaker_open_at_exit must stay 0 (a run that ends with the
    # breaker open did not recover — the absolute zero-baseline rule
    # fires on any nonzero candidate). Fault counts are context, not
    # gates: they move with the plan, which _MATCH_KEYS pins anyway.
    "chaos": {
        "parity_ok": ("higher", 0.0),
        "breaker_open_at_exit": ("lower", 0.0),
        "throughput_qps": ("higher", 0.50),
    },
}
# Context keys that must MATCH for two records to be comparable.
_MATCH_KEYS = {"bench": ("backend", "n_docs", "wire"),
               "serve_bench": ("backend", "docs", "k", "max_batch",
                               "pipeline_depth"),
               "chaos": ("backend", "docs", "k", "max_batch", "plan",
                         "seed"),
               "mutate": ("backend", "k", "max_batch", "rate",
                          "delta_docs", "compact_at", "chaos_plan"),
               "mesh_serve": ("backend", "docs", "k", "max_batch",
                              "n_shards"),
               "ingest_mh": ("backend", "n_docs", "doc_len",
                             "n_workers", "wire"),
               "replica_serve": ("backend", "docs", "k",
                                 "n_replicas", "host_cores"),
               "retrieval": ("backend", "docs", "doc_len", "k",
                             "tiling"),
               "scoring": ("backend", "docs", "doc_len", "k"),
               "multichip": ("n_devices",)}
# Defaults applied to BOTH sides of a match when the key is absent —
# how records that predate a context key stay comparable to their
# successors (pre-round-14 bench records carry no "wire"; they were
# all ragged-wire runs by construction).
_MATCH_DEFAULTS = {"wire": "ragged",
                   # Pre-round-22 serve records carry no
                   # pipeline_depth; the serving default (2) keeps
                   # them comparable to their successors so the
                   # pipelined runs are gated against the unpipelined
                   # history they must beat.
                   "pipeline_depth": 2}


def comparable(rec: dict, cand: dict) -> bool:
    if rec["kind"] != cand["kind"]:
        return False
    for key in _MATCH_KEYS[cand["kind"]]:
        default = _MATCH_DEFAULTS.get(key)
        if (rec["context"].get(key) or default) \
                != (cand["context"].get(key) or default):
            return False
    return True


def gate(cand: dict, ledger: List[dict], window: int = 5,
         noise_mult: float = 1.5) -> Dict:
    """Compare one normalized candidate record against the ledger.
    Returns the verdict dict (``ok``, ``baseline_runs``, ``checks``)."""
    base_recs = [r for r in ledger if comparable(r, cand)][-window:]
    checks = []
    ok = True
    for name, (direction, base_tol) in _GATES[cand["kind"]].items():
        value = cand["metrics"].get(name)
        samples = [r["metrics"][name] for r in base_recs
                   if name in r["metrics"]]
        if value is None or not samples:
            checks.append({"metric": name, "verdict": "skipped",
                           "reason": ("missing in candidate"
                                      if value is None
                                      else "missing in baseline")})
            continue
        baseline = statistics.median(samples)
        tol = base_tol
        if len(samples) >= 3 and baseline:
            spread = (max(samples) - min(samples)) / 2 / abs(baseline)
            tol = max(tol, noise_mult * spread)
        if baseline == 0:
            # Zero baselines gate absolutely (e.g. recompiles must
            # stay 0 for lower-is-better; a zero throughput baseline
            # could never fail anything relative).
            regressed = (value > 0 if direction == "lower" else False)
            delta = value
        elif direction == "lower":
            delta = value / baseline - 1.0
            regressed = delta > tol
        else:
            delta = 1.0 - value / baseline
            regressed = delta > tol
        ok &= not regressed
        checks.append({
            "metric": name, "direction": direction,
            "baseline": baseline, "value": value,
            "delta": round(delta, 4), "tolerance": round(tol, 4),
            "samples": len(samples),
            "verdict": "REGRESSED" if regressed else "ok",
        })
    return {"ok": ok, "kind": cand["kind"],
            "baseline_runs": len(base_recs),
            "window": window, "checks": checks}


def render(verdict: Dict, source: str) -> str:
    lines = [f"perf_gate: {source} [{verdict['kind']}] vs "
             f"{verdict['baseline_runs']} baseline run(s)"]
    for c in verdict["checks"]:
        if c["verdict"] == "skipped":
            lines.append(f"  {c['metric']:<24} skipped "
                         f"({c['reason']})")
            continue
        arrow = "v" if c["direction"] == "lower" else "^"
        lines.append(
            f"  {c['metric']:<24}{arrow} {c['value']:>12.4g} vs "
            f"{c['baseline']:>12.4g} (delta {c['delta']:+.1%}, tol "
            f"{c['tolerance']:.0%}, n={c['samples']}) {c['verdict']}")
    lines.append("PASS" if verdict["ok"] else "FAIL: regression past "
                 "tolerance — see REGRESSED rows")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit 0 = pass, 1 = regression, 2 = unusable input")
    ap.add_argument("artifact", help="fresh bench/serve_bench JSON")
    ap.add_argument("--ledger", default=perf_ledger.DEFAULT_LEDGER)
    ap.add_argument("--window", type=int, default=5,
                    help="baseline = median over the last N "
                         "comparable ledger runs (default 5)")
    ap.add_argument("--noise-mult", type=float, default=1.5,
                    help="multiplier on the observed baseline spread "
                         "when widening tolerances (>=3 samples)")
    ap.add_argument("--require-baseline", action="store_true",
                    help="fail (exit 1) when the ledger holds no "
                         "comparable runs instead of warning")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable verdict")
    args = ap.parse_args()

    cand, reason = perf_ledger.normalize(args.artifact)
    if cand is None:
        print(f"perf_gate: cannot read {args.artifact}: {reason}",
              file=sys.stderr)
        return 2
    try:
        ledger = perf_ledger.load_ledger(args.ledger)
    except ValueError as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 2
    verdict = gate(cand, ledger, window=args.window,
                   noise_mult=args.noise_mult)
    if args.json:
        print(json.dumps(verdict, sort_keys=True))
    else:
        print(render(verdict, cand["source"]))
    if verdict["baseline_runs"] == 0:
        print("perf_gate: no comparable baseline in the ledger "
              f"({args.ledger}) — run tools/perf_ledger.py first",
              file=sys.stderr)
        return 1 if args.require_baseline else 0
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
