"""Perf ledger: append bench/serve artifacts to BENCH_LEDGER.jsonl.

The repo accumulates one-shot artifacts (``BENCH_r0X.json``,
``SERVE_r0X.json``) but had no TRAJECTORY — five rounds of numbers sat
on disk with nothing relating them, so a perf regression between
rounds would ship unnoticed. The ledger is the append-only record:
one normalized JSONL line per artifact, schema-versioned, deduped by
content, with enough context (backend, corpus size, config) that
``tools/perf_gate.py`` can decide which records are comparable and
hold a fresh run against the rolling baseline.

Record shape (``schema: 1``)::

    {"schema": 1, "kind": "bench" | "serve_bench",
     "source": "BENCH_r05.json", "captured_at": "...Z",
     "context": {"backend": ..., "n_docs": ..., ...},
     "metrics": {"docs_per_sec": ..., "vs_baseline": ..., ...}}

``kind`` is detected from the artifact itself (``bench``,
``serve_bench``, or ``multichip`` for the MULTICHIP_r0X dryrun
verdicts — ``ok`` gated as a 0/1 metric, ``n_devices`` as
comparability context); wrapped driver artifacts
(``{"n", "cmd", "rc", "tail", "parsed"}``) unwrap to their
``parsed`` payload, so both the raw ``bench.py`` stdout JSON and the
archived round files append identically. Artifacts that carry no
parsed metrics (a failed run, e.g. ``BENCH_r01.json``'s rc=1 crash)
are skipped with a note — the ledger records measurements, not stack
traces.

Usage::

    python tools/perf_ledger.py ARTIFACT.json [...]
    python tools/perf_ledger.py --backfill        # BENCH_r*/SERVE_r*
    python tools/perf_ledger.py --list            # print the ledger

Stdlib-only; runnable with no jax at all.
"""

from __future__ import annotations

import argparse
import datetime
import glob
import json
import os
import sys
from typing import List, Optional, Tuple

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

SCHEMA = 1
DEFAULT_LEDGER = os.path.join(_common.REPO, "BENCH_LEDGER.jsonl")

# metric name -> path into the artifact (dots descend into objects; a
# tuple = fallback chain, first present wins — how renamed artifact
# fields keep one trajectory under one metric name).
_BENCH_METRICS = {
    "docs_per_sec": "value",
    "vs_baseline": "vs_baseline",
    "device_docs_per_sec": "device_docs_per_sec",
    # THE serialized one-pass host pack measure. Round 14 renamed the
    # artifact field pack_serial_s (the old top-level pack_s collided
    # in name with phases.pack — the overlapped run's packer-thread
    # stall, a different span); the METRIC name stays pack_s so the
    # pre-rename ledger records remain one comparable trajectory.
    # This, not phases.pack, is what tools/perf_gate.py gates.
    "pack_s": ("pack_serial_s", "pack_s"),
    # Upload byte receipt (lower = leaner wire): actual bytes shipped
    # over the padded-format denominator. Gated so a packer regression
    # that silently re-fattens the wire fails the gate (round 14).
    "wire_ratio": "wire_ratio",
    "link_tax_s": "link_tax_s",
    # Round 19 attributed link columns: the aggregate splits into the
    # H2D staging wall and the synchronizing D2H round trip, so the
    # gate can hold the exact column the multi-process sharded ingest
    # attacks. Absent on pre-round-19 records (gate skips them there).
    "upload_s": "link.upload_s",
    "sync_s": "link.sync_s",
    "tpu_s": "tpu_s",
    "cpu_s": "cpu_s",
    "recall_at_k": "recall_at_k",
    "peak_hbm_bytes": "peak_hbm_bytes",
    "xla_compiles": "xla_compiles",
}
_SERVE_METRICS = {
    "throughput_qps": "throughput_qps",
    "throughput_rps": "throughput_rps",
    "p50_ms": "latency_ms.p50",
    "p95_ms": "latency_ms.p95",
    "p99_ms": "latency_ms.p99",
    "mean_occupancy": "batch.mean_occupancy",
    "cache_hit_rate": "cache.hit_rate",
    "shed_rate": "shed.rate",
    "recompiles_after_warmup": "recompiles_after_warmup",
    "peak_hbm_bytes": "peak_hbm_bytes",
    "xla_compiles": "xla_compiles",
    # Round 16 forensics receipts: windowed SLO compliance (gated
    # directionally — a PR that quietly blows the latency objective
    # fails perf_gate), the slow-query count (trend context), and the
    # measured request-identity overhead (--ab-reqtrace runs).
    "slo_compliance": "slo.compliance",
    "slow_queries": "slow_queries",
    "reqtrace_p50_regression": "reqtrace.p50_regression",
    # Round 19 query-slab receipts (--ab-slab runs): steady-state
    # allocations and H2D copies per batch are structural invariants
    # (0 and 1), parity is the bit-identity verdict vs the slab-off
    # pass; p50 delta is trend context at device-bound latencies.
    "slab_parity_ok": "slab.parity_ok",
    "slab_allocs_per_batch": "slab.allocs_per_batch",
    "slab_h2d_per_batch": "slab.h2d_copies_per_batch",
    # Round 20 bench honesty: the closed-loop p50/p99 columns above
    # are cache-warm — these two are the same pinned queries re-served
    # with the cache bypassed, so the trajectory can't quietly ride a
    # growing hit rate. Gated directionally by perf_gate.
    "p50_ms_cache_off": "cache_off.p50_ms",
    "p99_ms_cache_off": "cache_off.p99_ms",
    # Round 21 tiled-scoring receipts (--ab-tiled runs): parity is
    # the bit-identity verdict vs the tiling-off pass at every probed
    # width (zero-tolerance); the speedup column is the measured
    # tiled-over-block-split ratio at the widest width (trend).
    "tiled_parity_ok": "tiling.parity_ok",
    "tiled_speedup_widest": "tiling.speedup_widest",
    # Round 22 pipelined-execution receipts (--ab-pipeline runs):
    # parity is the bit-identity verdict across depths 1/2/4 AND vs
    # direct search (zero-tolerance); the per-depth recompile counts
    # are structural zeros; the depth-2/depth-1 cache-off qps columns
    # carry the win itself, gated directionally so the overlap can't
    # quietly rot back into lockstep execution.
    "pipeline_parity_ok": "pipeline.parity_ok",
    "pipeline_qps_depth1": "pipeline.qps.1",
    "pipeline_qps_depth2": "pipeline.qps.2",
    "pipeline_qps_gain_depth2": "pipeline.qps_gain_depth2",
    "pipeline_recompiles_depth2": "pipeline.recompiles.2",
    "pipeline_recompiles_depth4": "pipeline.recompiles.4",
}
# Chaos artifacts (serve_bench --chaos): the fault-plan receipts. The
# gated metric is parity_ok — every non-shed non-poisoned response
# bit-identical to direct search DESPITE the injected faults (1 must
# stay 1; perf_gate zero-tolerates it) — with breaker_open_at_exit
# its zero-must-stay-zero twin. The counts are recorded for trend
# reading, not gated: a different plan legitimately moves them.
_CHAOS_METRICS = {
    "parity_ok": "chaos.parity_ok",
    "breaker_open_at_exit": "chaos.breaker_open_at_exit",
    "retries": "chaos.retries",
    "worker_restarts": "chaos.worker_restarts",
    "breaker_trips": "chaos.breaker_trips",
    "quarantined": "chaos.quarantined",
    "poisoned_requests": "chaos.poisoned_requests",
    "shed_requests": "chaos.shed_requests",
    "throughput_qps": "throughput_qps",
}
_CHAOS_CONTEXT = {"backend": "backend", "docs": "docs", "k": "k",
                  "requests": "requests", "max_batch": "max_batch",
                  "plan": "chaos.plan", "seed": "chaos.seed"}
# Mutation workloads (serve_bench --mutate): the live-index receipts.
# parity_ok (served == from-scratch rebuild, byte for byte, under a
# mutation stream) and the zero-recompile pin gate absolutely; the
# lag/pause percentiles gate directionally so a PR that makes
# visibility or compaction quietly slower fails CI.
_MUTATE_METRICS = {
    "throughput_qps": "throughput_qps",
    "p99_ms": "latency_ms.p99",
    "mutation_qps": "mutate.mutation_qps",
    "visibility_lag_p50_ms": "mutate.visibility_lag_ms.p50",
    "visibility_lag_p99_ms": "mutate.visibility_lag_ms.p99",
    "compactions": "mutate.compaction.count",
    "compaction_pause_max_ms": "mutate.compaction.pause_ms.max",
    "recompiles_after_warmup": "recompiles_after_warmup",
    "parity_ok": "mutate.parity_ok",
    "compactor_dead": "mutate.compaction.compactor_dead",
}
_MUTATE_CONTEXT = {"backend": "backend", "docs": "docs", "k": "k",
                   "requests": "requests", "max_batch": "max_batch",
                   "rate": "mutate.rate",
                   "delta_docs": "mutate.delta_docs",
                   "compact_at": "mutate.compact_at",
                   "chaos_plan": "mutate.chaos_plan"}
# Mesh-sharded serving (serve_bench --mesh-shards): one logical index
# doc-sharded across the chip mesh. parity_ok (sharded serve responses
# bit-identical to the single-device source's direct search) and the
# zero-recompile pin gate absolutely; qps/p99 gate directionally so
# the collective's cost cannot quietly grow; shard_imbalance is the
# HBM-balance receipt. n_shards is comparability context — a 2-shard
# and a 4-shard run are different protocols.
_MESH_SERVE_METRICS = {
    "throughput_qps": "throughput_qps",
    "throughput_rps": "throughput_rps",
    "p50_ms": "latency_ms.p50",
    "p99_ms": "latency_ms.p99",
    "cache_hit_rate": "cache.hit_rate",
    "recompiles_after_warmup": "recompiles_after_warmup",
    "parity_ok": "mesh.parity_ok",
    "shard_imbalance": "mesh.shard_imbalance",
    "slo_compliance": "slo.compliance",
}
_MESH_SERVE_CONTEXT = {"backend": "backend", "docs": "docs", "k": "k",
                       "requests": "requests", "max_batch": "max_batch",
                       "concurrency": "concurrency", "mode": "mode",
                       "n_shards": "mesh.n_shards"}
# Multi-process sharded ingest (tools/ingest_mh_bench.py): the link
# receipts. parity_ok is zero-tolerance (the N-worker merge must stay
# bit-identical to single-process); upload_s gates lower-is-better —
# the wall-clock of the slowest link-owning worker, THE column this
# protocol divides; speedup_vs_1p gates higher so a regression back
# toward serial ingest fails CI. n_workers is comparability context —
# a 2-worker and a 4-worker run are different protocols.
_INGEST_MH_METRICS = {
    "parity_ok": "parity_ok",
    "upload_s": "upload_s",
    "upload_s_1p": "upload_s_1p",
    "wall_s": "wall_s",
    "wall_s_1p": "wall_s_1p",
    "speedup_vs_1p": "speedup_vs_1p",
}
_INGEST_MH_CONTEXT = {"backend": "backend", "n_docs": "n_docs",
                      "doc_len": "doc_len", "chunk_docs": "chunk_docs",
                      "n_workers": "n_workers", "wire": "wire"}
# Replicated serving tier (serve_bench --replicas): N full replica
# processes behind one front. parity_ok (front-routed responses
# float32-identical to direct search) and mixed_epoch_responses (no
# client ever observes an epoch the front has not committed — the
# two-phase pin, rehearsed under a kill-mid-swap fault plan) are
# zero-tolerance; recompiles_after_warmup pins 0 per replica;
# qps/scaling gate directionally. host_cores is comparability
# context — on a 1-core host the sweep is CPU-bound and the scaling
# column measures scheduler fairness, not replica parallelism
# (docs/SERVING.md "Replicated tier").
_REPLICA_METRICS = {
    "throughput_qps": "throughput_qps",
    "qps_1": "qps_1",
    "qps_scaling_x": "qps_scaling_x",
    "scaling_efficiency": "scaling_efficiency",
    "p50_ms": "latency_ms.p50",
    "p99_ms": "latency_ms.p99",
    "parity_ok": "parity_ok",
    "mixed_epoch_responses": "mixed_epoch_responses",
    "recompiles_after_warmup": "recompiles_after_warmup",
    "chaos_swap_aborted": "chaos.swap_aborted",
    "chaos_old_epoch_everywhere":
        "chaos.old_epoch_everywhere_after_abort",
    "chaos_restarts": "chaos.restarts",
    # Fleet tracing (round 23): the propagation-overhead A/B — same
    # 2-replica tier served cache-off with disttrace off then on.
    # disttrace_parity_ok and disttrace_recompiles are zero-tolerance
    # (tracing must not change answers or mint programs); the on-leg
    # p50 and the overhead percentage gate directionally; the merge
    # receipts (spans joined, worst clock-offset uncertainty) are the
    # evidence the trace_export -> trace_merge pull really aligned.
    "disttrace_parity_ok": "disttrace.parity_ok",
    "disttrace_recompiles": "disttrace.recompiles_after_warmup",
    "disttrace_overhead_pct": "disttrace.overhead_pct",
    "disttrace_p50_on_ms": "disttrace.p50_on_ms",
    "disttrace_spans_merged": "disttrace.spans_merged",
    "disttrace_max_clock_uncertainty_us":
        "disttrace.max_clock_uncertainty_us",
}
_REPLICA_CONTEXT = {"backend": "backend", "docs": "docs", "k": "k",
                    "requests": "requests",
                    "concurrency": "concurrency",
                    "n_replicas": "n_replicas",
                    "host_cores": "host_cores",
                    "cpu_bound": "cpu_bound",
                    "chaos_plan": "chaos.plan"}
# Retrieval batch-scaling sweep (tools/retrieval_bench.py, round 21):
# the tiled scorer's artifact of record. parity_ok (tiled results
# bit-identical to --score-tiling=off at probe widths) and
# qps_monotonic_through_256 (QPS non-decreasing Q=64 -> 256 — the
# exact weak-5 regression) are zero-tolerance 0/1 pins;
# recompiles_after_warmup pins 0; the per-width QPS columns and the
# index build rate gate directionally.
_RETRIEVAL_METRICS = {
    "parity_ok": "parity_ok",
    "qps_monotonic_through_256": "qps_monotonic_through_256",
    "recompiles_after_warmup": "recompiles_after_warmup",
    "qps_q64": "qps_q64",
    "qps_q256": "qps_q256",
    "qps_q512": "qps_q512",
    "index_docs_per_sec": "index_docs_per_sec",
}
_RETRIEVAL_CONTEXT = {"backend": "backend", "docs": "docs",
                      "doc_len": "doc_len", "k": "k",
                      "tiling": "tiling", "tile_rows": "tile_rows"}
# Scoring-family sweep (tools/retrieval_bench.py --scorers, round 23):
# per-scorer QPS through the same tiled kernel. parity_ok (every
# variant bit-identical to the untiled fallback AND to the NumPy
# oracle, tie order included) and recompiles_after_warmup (scorer
# switching mints zero new search programs) are zero-tolerance; the
# per-scorer QPS columns gate directionally; the recall/overlap
# columns are embedded receipts that the family members rank
# correctly and differently.
_SCORING_METRICS = {
    "parity_ok": "parity_ok",
    "recompiles_after_warmup": "recompiles_after_warmup",
    "qps_q64_tfidf": "qps_q64_tfidf",
    "qps_q256_tfidf": "qps_q256_tfidf",
    "qps_q64_bm25": "qps_q64_bm25",
    "qps_q256_bm25": "qps_q256_bm25",
    "qps_q64_bm25_filter": "qps_q64_bm25_filter",
    "qps_q256_bm25_filter": "qps_q256_bm25_filter",
    "recall_at_10_tfidf": "recall_at_10_tfidf",
    "recall_at_10_bm25": "recall_at_10_bm25",
    "bm25_vs_tfidf_overlap_at_10": "bm25_vs_tfidf_overlap_at_10",
}
_SCORING_CONTEXT = {"backend": "backend", "docs": "docs",
                    "doc_len": "doc_len", "k": "k"}
# Multi-chip dryrun artifacts (MULTICHIP_r0X.json): a driver wrapper
# with no parsed payload — just the mesh smoke's verdict. "ok" is the
# gated metric (1 must stay 1); n_devices is comparability context.
_MULTICHIP_METRICS = {"ok": "ok", "n_devices": "n_devices"}
_MULTICHIP_CONTEXT = {"n_devices": "n_devices"}
_BENCH_CONTEXT = {"backend": "backend", "n_docs": "n_docs",
                  "engine": "engine", "ingest_path": "ingest_path",
                  "repeats": "repeats",
                  # Chunk wire format (round 14): a bytes-wire bench
                  # and a ragged-wire bench are different protocols —
                  # comparability-matched by perf_gate with "ragged"
                  # defaulted for pre-wire records (_MATCH_DEFAULTS).
                  "wire": "wire"}
_SERVE_CONTEXT = {"backend": "backend", "docs": "docs", "k": "k",
                  "requests": "requests", "mode": "mode",
                  "concurrency": "concurrency",
                  "max_batch": "max_batch",
                  # Pipelined execution (round 22): runs at
                  # different in-flight depths are different
                  # experiments — matched by perf_gate with the
                  # pre-pipeline default (2) backfilled for older
                  # records (_MATCH_DEFAULTS).
                  "pipeline_depth": "pipeline_depth",
                  "fingerprint": "fingerprint.config_sha"}


def _dig(doc: dict, path):
    if isinstance(path, tuple):  # fallback chain: first present wins
        for p in path:
            v = _dig(doc, p)
            if v is not None:
                return v
        return None
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def unwrap(doc: dict) -> Optional[dict]:
    """Driver-wrapped artifacts ({n, cmd, rc, tail, parsed}) yield
    their parsed payload; bare artifacts pass through. None when the
    artifact carries no measurements (failed run)."""
    if "parsed" in doc and "cmd" in doc:
        return doc["parsed"]  # may be None: rc != 0 rounds
    return doc


def classify(payload: dict) -> Optional[str]:
    if payload.get("metric") == "ingest_mh":
        return "ingest_mh"
    if payload.get("metric") == "retrieval_bench":
        return "retrieval"
    if payload.get("metric") == "scoring_bench":
        return "scoring"
    if payload.get("metric") == "replica_bench":
        # Checked before the serve_bench branches: a replica artifact
        # also carries a "chaos" rehearsal block, which must not
        # misfile it as a single-process chaos run.
        return "replica_serve"
    if payload.get("metric") == "serve_bench":
        # A serve_bench run under an armed fault plan (or a mutation
        # stream) is its own kind: chaos/mutate runs are only
        # comparable to runs of the same shape (context below), never
        # to clean serving baselines.
        if "mutate" in payload:
            return "mutate"
        if "chaos" in payload:
            return "chaos"
        return "mesh_serve" if "mesh" in payload else "serve_bench"
    if payload.get("unit") == "docs/sec" or "vs_baseline" in payload:
        return "bench"
    if "n_devices" in payload and "ok" in payload:
        return "multichip"
    return None


def normalize(path: str) -> Tuple[Optional[dict], Optional[str]]:
    """Artifact file -> (ledger record, skip_reason). Exactly one of
    the pair is None."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        return None, "artifact is not a JSON object"
    payload = unwrap(doc)
    if not isinstance(payload, dict):
        return None, ("no parsed metrics payload (failed run, rc="
                      f"{doc.get('rc')!r})")
    kind = classify(payload)
    if kind is None:
        return None, "unrecognized artifact shape (not bench/serve)"
    metric_paths = {"serve_bench": _SERVE_METRICS,
                    "bench": _BENCH_METRICS,
                    "chaos": _CHAOS_METRICS,
                    "mutate": _MUTATE_METRICS,
                    "mesh_serve": _MESH_SERVE_METRICS,
                    "ingest_mh": _INGEST_MH_METRICS,
                    "replica_serve": _REPLICA_METRICS,
                    "retrieval": _RETRIEVAL_METRICS,
                    "scoring": _SCORING_METRICS,
                    "multichip": _MULTICHIP_METRICS}[kind]
    ctx_paths = {"serve_bench": _SERVE_CONTEXT,
                 "bench": _BENCH_CONTEXT,
                 "chaos": _CHAOS_CONTEXT,
                 "mutate": _MUTATE_CONTEXT,
                 "mesh_serve": _MESH_SERVE_CONTEXT,
                 "ingest_mh": _INGEST_MH_CONTEXT,
                 "replica_serve": _REPLICA_CONTEXT,
                 "retrieval": _RETRIEVAL_CONTEXT,
                 "scoring": _SCORING_CONTEXT,
                 "multichip": _MULTICHIP_CONTEXT}[kind]
    metrics = {name: (int(v) if isinstance(v, bool) else v)
               for name, p in metric_paths.items()
               if (v := _dig(payload, p)) is not None}
    if not metrics:
        return None, "artifact carries none of the known metrics"
    context = {name: v for name, p in ctx_paths.items()
               if (v := _dig(payload, p)) is not None}
    captured = datetime.datetime.fromtimestamp(
        os.path.getmtime(path), tz=datetime.timezone.utc)
    return {
        "schema": SCHEMA,
        "kind": kind,
        "source": os.path.basename(path),
        "captured_at": captured.isoformat(timespec="seconds"),
        "context": context,
        "metrics": metrics,
    }, None


def load_ledger(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    records = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: bad ledger line: {e}")
            if rec.get("schema") != SCHEMA:
                raise ValueError(
                    f"{path}:{i}: ledger schema "
                    f"{rec.get('schema')!r} != {SCHEMA} — migrate or "
                    f"regenerate (--backfill onto a fresh file)")
            records.append(rec)
    return records


def _identity(rec: dict) -> str:
    """Content identity for dedup: same source + same numbers is the
    same measurement, regardless of when it was appended."""
    return json.dumps([rec["kind"], rec["source"], rec["metrics"]],
                      sort_keys=True)


def append(paths: List[str], ledger_path: str,
           quiet: bool = False) -> Tuple[int, int]:
    """Normalize + append each artifact; returns (appended, skipped).
    Re-appending an unchanged artifact is a dedup no-op, so the
    backfill is idempotent."""
    existing = {_identity(r) for r in load_ledger(ledger_path)}
    appended = skipped = 0
    with open(ledger_path, "a") as f:
        for path in paths:
            rec, reason = normalize(path)
            if rec is None:
                skipped += 1
                if not quiet:
                    print(f"skip {os.path.basename(path)}: {reason}",
                          file=sys.stderr)
                continue
            ident = _identity(rec)
            if ident in existing:
                skipped += 1
                if not quiet:
                    print(f"skip {rec['source']}: already in ledger",
                          file=sys.stderr)
                continue
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            existing.add(ident)
            appended += 1
            if not quiet:
                print(f"append {rec['source']} [{rec['kind']}] "
                      f"{sorted(rec['metrics'])}", file=sys.stderr)
    return appended, skipped


def backfill_paths() -> List[str]:
    """The repo's archived round artifacts, oldest first."""
    return (sorted(glob.glob(os.path.join(_common.REPO, "BENCH_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "MULTICHIP_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "SERVE_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "MUTATE_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "MESH_SERVE_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "INGEST_MH_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "REPLICA_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "RETR_r*.json")))
            + sorted(glob.glob(os.path.join(_common.REPO,
                                            "SCORING_r*.json"))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit 0 = appended/deduped, 2 = nothing usable")
    ap.add_argument("artifacts", nargs="*",
                    help="bench/serve_bench artifact JSON files")
    ap.add_argument("--ledger", default=DEFAULT_LEDGER,
                    help=f"ledger path (default {DEFAULT_LEDGER})")
    ap.add_argument("--backfill", action="store_true",
                    help="append every BENCH_r*.json / SERVE_r*.json "
                         "in the repo root (idempotent)")
    ap.add_argument("--list", action="store_true",
                    help="print the ledger records and exit")
    args = ap.parse_args()
    if args.list:
        for rec in load_ledger(args.ledger):
            print(json.dumps(rec, sort_keys=True))
        return 0
    paths = list(args.artifacts)
    if args.backfill:
        paths += backfill_paths()
    if not paths:
        print("nothing to append (pass artifacts or --backfill)",
              file=sys.stderr)
        return 2
    appended, skipped = append(paths, args.ledger)
    print(f"{appended} appended, {skipped} skipped -> {args.ledger}",
          file=sys.stderr)
    return 0 if (appended or skipped) else 2


if __name__ == "__main__":
    sys.exit(main())
