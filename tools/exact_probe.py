"""Phase probe of the exact-terms mode (VERDICT r4 item 5 groundwork).

Times the device-exact engine's serial tail — wire fetch, native
exact_emit (rescore + format + global sort), boundary-tie re-reads —
separately from the ingest, on a bench-shaped corpus. What to overlap
or parallelize is decided from THIS split, not guessed.

Usage: python tools/exact_probe.py [--docs 8192] [--len 256]
"""

from __future__ import annotations

import argparse
import shutil
import tempfile
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=8192)
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import bench as benchmod
    benchmod.N_DOCS = args.docs
    benchmod.DOC_LEN = args.length

    tmp = tempfile.mkdtemp(prefix="exact_probe_")
    try:
        input_dir = benchmod.make_corpus(tmp)
        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.io import fast_tokenizer as ft
        from tfidf_tpu.ingest import run_overlapped_exact
        from tfidf_tpu.rerank import _device_cfg

        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                             vocab_size=benchmod.VOCAB,
                             max_doc_len=args.length,
                             doc_chunk=args.length,
                             topk=benchmod.MARGIN, engine="sparse")
        k = benchmod.TOPK
        chunk = max(2048, args.docs // 4)

        for it in range(args.repeats):
            with ft.InternSession(cfg.vocab_size) as sess:
                t0 = time.perf_counter()
                exact = run_overlapped_exact(input_dir, _device_cfg(cfg, k),
                                             chunk_docs=chunk,
                                             doc_len=args.length,
                                             strict=True, session=sess)
                t_ingest = time.perf_counter() - t0
                t0 = time.perf_counter()
                lines, per_doc, offs, lens_, scores, wblob = sess.emit(
                    input_dir, exact.names, exact.topk_ids,
                    exact.topk_counts, exact.df, exact.lengths,
                    exact.num_docs, k, cfg.truncate_tokens_at,
                    args.length, seed=cfg.hash_seed)
                t_emit = time.perf_counter() - t0
            ing_ph = dict(exact.phases or {})
            print(f"run {it}: ingest {t_ingest:.3f}s "
                  f"(phases {ing_ph}) emit {t_emit:.3f}s "
                  f"lines {len(lines)} bytes", flush=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
