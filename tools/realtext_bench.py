"""Real-TEXT corpus for the whitespace pipeline (BASELINE configs 1–2).

The headline bench runs synthetic Zipf corpora; config 4 already runs
real source CODE (tools/chargram_bench.py). This tool measures the
whitespace word pipeline on real English-ish TEXT the image ships:
installed-package METADATA descriptions, .md/.rst/.txt docs from the
Python environment, and /usr/share/doc files — a non-synthetic word
distribution (true hapax tails, real punctuation-glued tokens) the
Zipf generator cannot fake.

Measures, on the real chip:
  1. resident overlapped ingest docs/sec (hashed 2^16, top-16), and
  2. the exact-terms mode end-to-end (engine reported: the intern
     table overflows iff the corpus has > 2^16 distinct words) with
     exact recall vs the native bit-reference on a doc sample.

Prints one JSON line per measurement; numbers land in BASELINE.md.
    python tools/realtext_bench.py
"""

import glob
import gzip
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

import _common  # noqa: E402,F401  repo-root sys.path bootstrap
from _common import REPO  # noqa: E402

MAX_BYTES = 4096
TOPK = 16
VOCAB = 1 << 16
DOC_LEN = 2048  # > max tokens at MAX_BYTES (>=2 bytes/token incl. separator): truncation never bites, so recall vs the oracle is pure engine signal
RECALL_DOCS = 256


def collect_text(limit=4096):
    pats = ["/opt/venv/**/METADATA", "/opt/venv/**/*.md",
            "/opt/venv/**/*.rst", "/opt/venv/**/*.txt",
            "/usr/share/doc/**/*"]
    docs = []
    for p in pats:
        for f in sorted(glob.glob(p, recursive=True)):
            if len(docs) >= limit:
                return docs
            if not os.path.isfile(f):
                continue
            try:
                if f.endswith(".gz"):
                    with gzip.open(f, "rb") as fh:
                        data = fh.read(MAX_BYTES)
                else:
                    with open(f, "rb") as fh:
                        data = fh.read(MAX_BYTES)
            except OSError:
                continue
            if data.strip():
                docs.append(data)
    return docs


def main():
    docs = collect_text()
    total = sum(len(d) for d in docs)
    print(f"{len(docs)} real text docs, {total / 1e6:.1f} MB",
          file=sys.stderr)
    root = tempfile.mkdtemp(prefix="tfidf_realtext_")
    try:
        input_dir = os.path.join(root, "input")
        os.makedirs(input_dir)
        for i, d in enumerate(docs, 1):
            with open(os.path.join(input_dir, f"doc{i}"), "wb") as f:
                f.write(d)

        from tfidf_tpu.config import PipelineConfig, VocabMode
        from tfidf_tpu.ingest import run_overlapped
        from tfidf_tpu.recall import exact_doc_recall, parse_oracle_output
        from tfidf_tpu.rerank import exact_terms_lines

        cfg = PipelineConfig(vocab_mode=VocabMode.HASHED, vocab_size=VOCAB,
                             max_doc_len=DOC_LEN, doc_chunk=DOC_LEN,
                             topk=TOPK, engine="sparse")
        chunk = max(512, len(docs) // 4)
        run_overlapped(input_dir, cfg, chunk_docs=chunk, doc_len=DOC_LEN)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            r = run_overlapped(input_dir, cfg, chunk_docs=chunk,
                               doc_len=DOC_LEN)
            best = min(best, time.perf_counter() - t0)
        print(json.dumps({
            "metric": "docs/sec, real-text corpus (package docs/"
                      "metadata/changelogs), hashed 2^16, top-16",
            "value": round(len(docs) / best, 1), "unit": "docs/sec",
            "n_docs": len(docs), "corpus_mb": round(total / 1e6, 1),
            "wall_s": round(best, 3), "ingest_path": r.path,
            "df_occupied": r.df_occupied}), flush=True)

        # Exact-terms on real text: engine choice is data-driven (the
        # intern table overflows iff distinct words > 2^16).
        ecfg = PipelineConfig(vocab_mode=VocabMode.HASHED,
                              vocab_size=VOCAB, max_doc_len=DOC_LEN,
                              doc_chunk=DOC_LEN, topk=4 * TOPK,
                              engine="sparse")
        exact_terms_lines(input_dir, ecfg, k=TOPK, doc_len=DOC_LEN,
                          chunk_docs=chunk)  # warm
        ebest, engine, sample_fn = float("inf"), "?", None
        for _ in range(3):
            t0 = time.perf_counter()
            _, engine, sample_fn = exact_terms_lines(
                input_dir, ecfg, k=TOPK, doc_len=DOC_LEN,
                chunk_docs=chunk)
            ebest = min(ebest, time.perf_counter() - t0)

        # Recall vs the native bit-reference on a sample.
        binary = os.path.join(REPO, "native", "tfidf_ref")
        if not os.path.exists(binary):
            subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                           check=True, capture_output=True)
        oracle_out = os.path.join(root, "oracle.txt")
        subprocess.run([binary, input_dir, oracle_out, "9"], check=True,
                       stdout=subprocess.DEVNULL)
        # The doc_len cap must clear every doc or recall conflates
        # truncation with engine error — assert, don't assume.
        import tfidf_tpu.ops.tokenize as tok
        assert max(len(tok.whitespace_tokenize(d, None)) for d in docs) \
            <= DOC_LEN, "raise DOC_LEN: a doc exceeds the token cap"
        sample = [f"doc{i}" for i in
                  range(1, min(RECALL_DOCS, len(docs)) + 1)]
        per_doc = parse_oracle_output(oracle_out, docs=sample)
        got = sample_fn(sample)
        scores = []
        for name, ref in per_doc.items():
            rr = exact_doc_recall(ref, [w for w, _ in got[name]], TOPK)
            if rr is not None:
                scores.append(rr)
                if rr < 1.0:
                    print(f"recall<1 on {name}: {rr}", file=sys.stderr)
        print(json.dumps({
            "metric": "exact-terms on real text",
            "exact_docs_per_sec": round(len(docs) / ebest, 1),
            "exact_engine": engine,
            "recall_vs_oracle_sample": round(float(np.mean(scores)), 4),
            "recall_note": "doc_len exceeds every doc's token count, "
                           "so recall is pure engine signal",
            "n_sampled": len(scores)}), flush=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
