"""A/B probe of the phase-B dispatch tax on the current backend.

Times the SAME resident finish work two ways, warm, fenced once per
protocol (the pipelined-chain methodology of tools/roofline.py):

  a) chunked — n identical per-chunk scoring dispatches
     (``ingest._phase_b_cached_packed``, the round-7 structure)
  b) scan    — ONE donated ``lax.scan`` dispatch over the stacked
     chunk triples (``ingest._phase_b_scan_packed``, round 8)

Identical packed words out of both (asserted), so the wall delta is
pure dispatch structure: per-program launch/re-entry cost × (n − 1),
plus whatever fusion headroom the single program buys. On the tunneled
backend each dispatch costs ~8 ms (docs/SCALING.md) — the fixed cost
this probe makes visible; on CPU it measures the XLA callback floor.

Usage: python tools/dispatch_probe.py [--docs 8192] [--len 256]
       [--chunks 4] [--repeats 5] [--topk 16]
Prints one JSON line, like the other tools.
"""

from __future__ import annotations

import argparse
import json
import time

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tfidf_tpu.ingest import (_phase_b_cached_packed,  # noqa: E402
                              _phase_b_scan_packed)
from tfidf_tpu.obs.costmodel import (achieved_gbps,  # noqa: E402
                                     stage_bytes)
from tfidf_tpu.ops.scoring import idf_from_df  # noqa: E402
from tfidf_tpu.ops.sparse import sorted_term_counts  # noqa: E402

VOCAB = 1 << 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=8192,
                    help="docs per chunk")
    ap.add_argument("--len", type=int, dest="length", default=256)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--topk", type=int, default=16)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()
    d, length, n, k = args.docs, args.length, args.chunks, args.topk

    rng = np.random.default_rng(0)
    trips, lens = [], []
    df = np.zeros((VOCAB,), np.int64)
    for _ in range(n):
        toks = np.minimum(rng.zipf(1.3, (d, length)), VOCAB) - 1
        ll = rng.integers(1, length + 1, d).astype(np.int32)
        i_, c_, h_ = sorted_term_counts(jnp.asarray(toks, jnp.int32),
                                        jnp.asarray(ll))
        trips.append((i_, c_, h_))
        lens.append(jnp.asarray(ll))
    # any plausible DF serves — the probe times structure, not values
    df = jnp.asarray(rng.integers(0, n * d, VOCAB).astype(np.int32))
    idf = idf_from_df(df, jnp.int32(n * d), jnp.float32)
    jax.block_until_ready((trips, lens, idf))

    def chunked_once():
        return [_phase_b_cached_packed(i_, c_, h_, ll, idf, topk=k)
                for (i_, c_, h_), ll in zip(trips, lens)]

    def fresh_trips():
        # the scan donates its triple inputs, so every timed call gets
        # pre-staged copies — copied and FENCED outside the timer, the
        # way production triples already sit resident when the finish
        # dispatches
        f = [tuple(jnp.copy(t) for t in tr) for tr in trips]
        jax.block_until_ready(f)
        return f

    def scan_once(fresh):
        return _phase_b_scan_packed(
            tuple(t[0] for t in fresh), tuple(t[1] for t in fresh),
            tuple(t[2] for t in fresh), tuple(lens), idf, topk=k)

    # warm both programs and pin value parity
    words_c = jax.block_until_ready(chunked_once())
    words_s = jax.block_until_ready(scan_once(fresh_trips()))
    np.testing.assert_array_equal(
        np.stack([np.asarray(w) for w in words_c]), np.asarray(words_s))

    def best_of(fn, staged):
        best = float("inf")
        for _ in range(args.repeats):
            arg = staged() if staged else None
            t0 = time.perf_counter()
            jax.block_until_ready(fn(arg) if staged else fn())
            best = min(best, time.perf_counter() - t0)
        return best

    chunked_s = best_of(chunked_once, None)
    scan_s = best_of(scan_once, fresh_trips)
    # Model bytes for the timed work — n chunks of score+top-k — from
    # the SHARED analytic model (obs/costmodel.py): the achieved GB/s
    # say how far each finish structure sits from the roofline, not
    # just which one wins.
    model_bytes = n * stage_bytes(d, length, topk=k)["score_topk"]
    print(json.dumps({
        "backend": jax.default_backend(),
        "chunks": n, "docs_per_chunk": d, "len": length, "topk": k,
        "chunked_s": round(chunked_s, 4),
        "scan_s": round(scan_s, 4),
        "dispatch_tax_s": round(chunked_s - scan_s, 4),
        "per_dispatch_s": round((chunked_s - scan_s) / max(n - 1, 1), 5),
        "score_topk_model_gb": round(model_bytes / 1e9, 4),
        "chunked_gbps": round(achieved_gbps(model_bytes, chunked_s)
                              or 0.0, 2),
        "scan_gbps": round(achieved_gbps(model_bytes, scan_s)
                           or 0.0, 2),
    }))


if __name__ == "__main__":
    main()
