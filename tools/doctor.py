"""One-shot diagnosis: where every millisecond and megabyte went.

Reads the evidence one run leaves behind — the span trace
(``--trace`` / ``TFIDF_TPU_TRACE``), the flight-recorder dump
(``--flight`` / ``<trace>.flight.jsonl``) and the perf ledger
(``BENCH_LEDGER.jsonl``) — and prints one report:

* **phase attribution** — total seconds per span name (pack vs
  dispatch vs compute vs fetch vs drain), the wall-clock extent, the
  serialized sum and the overlap efficiency (how much of the phase
  wall the double-buffered pipeline hid). Span totals reconcile with
  ``PhaseTimer`` because the instrumentation records ONE interval for
  both (tests/test_devmon.py pins the 5% bound);
* **bandwidth** — per-phase MB moved and achieved GB/s from the
  byte-stamped spans (``obs/costmodel.py`` arithmetic — the same
  numbers the Perfetto timeline shows on each span);
* **HBM** — top owners from the newest ``hbm_census`` flight event
  and every ``hbm_watermark`` breach;
* **recompiles** — every ``xla_recompile`` flight event (program
  fingerprint included) plus ``recompile_in_batch`` trace instants;
* **faults** — the recovery story's receipts (round 13): dispatch
  retries, worker restarts (by worker), breaker trips/closes,
  quarantines and injected faults from the flight events, plus
  whether the run ENDED with the breaker open;
* **ledger** — the trailing BENCH_LEDGER.jsonl records for context.

Budgets make it a CI gate: the doctor exits non-zero when the run
recompiled after warm-up (``--allow-recompiles``, default 0), crossed
an HBM watermark (``--allow-watermarks``, default 0), ended with the
dispatch circuit breaker open (``--allow-breaker-open`` to tolerate)
or blew an explicit per-phase time budget (``--budget pack=0.5``,
repeatable).

Pure stdlib — runnable under ``JAX_PLATFORMS=cpu`` or no jax at all.
Exit 0 = healthy, 1 = a budget violation, 2 = unreadable input.

Usage::

    python tools/doctor.py TRACE.json [--flight DUMP.jsonl]
        [--ledger BENCH_LEDGER.jsonl] [--allow-recompiles 0]
        [--allow-watermarks 0] [--budget PHASE=SECONDS ...] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

import _common  # noqa: E402,F401  repo-root sys.path bootstrap

# Standalone tracer + costmodel loads (no package import -> no jax),
# the trace_check.py pattern.
import importlib.util as _ilu  # noqa: E402


def _load(mod: str):
    spec = _ilu.spec_from_file_location(
        f"_obs_{mod}", os.path.join(_common.REPO, "tfidf_tpu", "obs",
                                    f"{mod}.py"))
    m = _ilu.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


_tracer = _load("tracer")
_costmodel = _load("costmodel")

# The ingest pipeline's span vocabulary, grouped by what the time IS:
# main-lane stalls + dispatches + device waits, worker-lane busy time.
# device_tokenize (round 14, bytes wire) nests inside dispatch on the
# main lane; slab nests inside pack on the packer lane — both carry
# byte stamps, so the generic per-name attribution below prices the
# moved host pack the same way it prices the wire transfers.
_MAIN_SPANS = ("pack_wait", "dispatch", "device_tokenize", "phase_b",
               "fetch_wait", "fetch")
_WORKER_SPANS = ("pack", "slab", "drain")
_INGEST_SPANS = _MAIN_SPANS + _WORKER_SPANS


def load_flight(path: str) -> Tuple[dict, List[dict], List[dict]]:
    """Flight dump -> (header, events, digests). Raises ValueError on
    a malformed file (trace_check validates; the doctor just reads)."""
    with open(path) as f:
        lines = [l for l in (ln.strip() for ln in f) if l]
    if not lines:
        raise ValueError("flight dump is empty")
    header = json.loads(lines[0])
    events, digests = [], []
    for line in lines[1:]:
        rec = json.loads(line)
        (events if rec.get("kind") == "event" else digests).append(rec)
    return header, events, digests


def analyze_trace(path: str) -> dict:
    """Span totals, wall extent, byte/bandwidth attribution, serve
    outcome mix — everything the trace alone can say."""
    events = _tracer.load_chrome_trace(path)
    xs = [e for e in events if e.get("ph") == "X"]
    if not xs:
        raise ValueError("trace contains no complete (ph=X) spans")
    lanes = _tracer.spans_by_thread(events)

    phases: Dict[str, dict] = {}
    t_lo = float("inf")
    t_hi = 0.0
    for e in xs:
        name = e["name"]
        dur_s = e.get("dur", 0.0) / 1e6
        t_lo = min(t_lo, e["ts"])
        t_hi = max(t_hi, e["ts"] + e.get("dur", 0.0))
        rec = phases.setdefault(
            name, {"spans": 0, "total_s": 0.0, "bytes": 0})
        rec["spans"] += 1
        rec["total_s"] += dur_s
        b = (e.get("args") or {}).get("bytes")
        if isinstance(b, (int, float)):
            rec["bytes"] += int(b)
    for rec in phases.values():
        gbps = _costmodel.achieved_gbps(rec["bytes"], rec["total_s"])
        if rec["bytes"] and gbps is not None:
            rec["gb_s"] = round(gbps, 3)
        rec["total_s"] = round(rec["total_s"], 6)

    wall_s = max(0.0, (t_hi - t_lo) / 1e6)
    out: dict = {"phases": phases, "wall_s": round(wall_s, 6),
                 "lanes": sorted(lanes)}

    ingest_sum = sum(phases[n]["total_s"] for n in _INGEST_SPANS
                     if n in phases)
    if ingest_sum > 0:
        out["serialized_sum_s"] = round(ingest_sum, 6)
        # The bench's overlap formula: how much of the summed phase
        # wall the pipelining hid. A fully serial run scores ~0.
        out["overlap_efficiency"] = round(
            max(0.0, 1.0 - wall_s / ingest_sum), 3)

    requests = phases.get("request")
    if requests:
        from collections import Counter
        outcomes = Counter(
            (e.get("args") or {}).get("outcome")
            for e in xs if e["name"] == "request")
        out["serve"] = {
            "requests": requests["spans"],
            "outcomes": dict(outcomes),
            "batches": phases.get("batched", {}).get("spans", 0),
        }
        # The slowest requests by span duration, rid-joined (round
        # 16): the default report's entry point into per-request
        # forensics — feed any rid to ``--request`` for the full
        # causal timeline.
        req_spans = sorted(
            (e for e in xs if e["name"] == "request"),
            key=lambda e: -e.get("dur", 0.0))[:5]
        out["slowest_requests"] = [
            {"rid": (e.get("args") or {}).get("rid"),
             "ms": round(e.get("dur", 0.0) / 1e3, 3),
             "outcome": (e.get("args") or {}).get("outcome"),
             "queries": (e.get("args") or {}).get("queries")}
            for e in req_spans]
    out["recompile_instants"] = sum(
        1 for e in events
        if e.get("ph") == "i" and e.get("name") == "recompile_in_batch")
    return out


def analyze_flight(path: str) -> dict:
    header, events, digests = load_flight(path)
    recompiles = [e for e in events if e.get("event") == "xla_recompile"]
    watermarks = [e for e in events if e.get("event") == "hbm_watermark"]
    censuses = [e for e in events if e.get("event") == "hbm_census"]
    # The recovery story's receipts (round 13): every retry, worker
    # restart, breaker transition, quarantine and injected fault is a
    # flight event — the doctor folds them into one "faults" section
    # and flags a run that ENDED with the breaker open (the last
    # breaker event is a trip with no close after it: the server
    # never recovered before exit).
    from collections import Counter as _Counter
    _FAULT_EVENTS = ("dispatch_retry", "worker_restart", "breaker_trip",
                     "breaker_close", "query_quarantined",
                     "poison_isolated", "fault_injected")
    fault_counts = _Counter(e["event"] for e in events
                            if e.get("event") in _FAULT_EVENTS)
    breaker_tail = [e["event"] for e in events
                    if e.get("event") in ("breaker_trip",
                                          "breaker_close")]
    faults_out = {name: fault_counts.get(name, 0)
                  for name in _FAULT_EVENTS}
    faults_out["breaker_open_at_exit"] = bool(
        breaker_tail and breaker_tail[-1] == "breaker_trip")
    restarts_by_worker = _Counter(
        e.get("worker", "?") for e in events
        if e.get("event") == "worker_restart")
    if restarts_by_worker:
        faults_out["restarts_by_worker"] = dict(restarts_by_worker)
    # Live-mutation receipts (round 17): seals and compactions are
    # flight events carrying their lifecycle numbers; the doctor folds
    # them into one section and (via --compaction-budget-ms) gates the
    # total mutation pause a run is allowed to spend compacting.
    seals = [e for e in events if e.get("event") == "segment_seal"]
    compactions = [e for e in events if e.get("event") == "compaction"]
    pause_ms = [e.get("pause_s", 0.0) * 1e3 for e in compactions]
    segments_out = {
        "seals": len(seals),
        "compactions": len(compactions),
        "total_pause_ms": round(sum(pause_ms), 3),
        "max_pause_ms": round(max(pause_ms), 3) if pause_ms else 0.0,
        "tombstones_dropped": sum(
            e.get("dropped_tombstones", 0) for e in compactions),
        "mutations": sum(1 for e in events
                         if e.get("event") == "index_mutation"),
    }
    # Mesh-sharded serving receipts (round 18): the DeviceMonitor logs
    # an edge-triggered shard_balance event whenever the per-shard
    # index bytes change (i.e. on index installs); the NEWEST one is
    # the serving layout the run ended with — per-shard bytes plus the
    # max/mean imbalance ratio --shard-imbalance budgets.
    shard_events = [e for e in events
                    if e.get("event") == "shard_balance"]
    shards_out = None
    if shard_events:
        latest = shard_events[-1]
        shards_out = {
            "n_shards": latest.get("n_shards"),
            "shard_bytes": latest.get("shard_bytes"),
            "imbalance": latest.get("imbalance"),
            "installs_seen": len(shard_events),
        }
    # Replicated-tier receipts (round 20): the front logs replica
    # lifecycle (replica_up / replica_down with reason + routed
    # counts) and the two-phase epoch protocol's receipts
    # (epoch_prepare / epoch_commit / epoch_abort). The doctor folds
    # them into per-rank liveness + routed share plus the tier's
    # commit/abort tally — the first thing to read when a replicated
    # run misbehaves is whether an abort left the tier on the old
    # epoch (by design) or a rank burned its restart budget.
    rep_up = [e for e in events if e.get("event") == "replica_up"]
    rep_down = [e for e in events if e.get("event") == "replica_down"]
    prepares = [e for e in events if e.get("event") == "epoch_prepare"]
    commits = [e for e in events if e.get("event") == "epoch_commit"]
    aborts = [e for e in events if e.get("event") == "epoch_abort"]
    replicas_out = None
    if rep_up or rep_down:
        per_rank: dict = {}
        for e in rep_up + rep_down:
            r = per_rank.setdefault(str(e.get("replica", "?")), {
                "state": "down", "boot": 0, "routed": 0,
                "deaths": 0, "budget_exhausted": False})
            r["boot"] = max(r["boot"], e.get("boot", 0) or 0)
            if e.get("event") == "replica_up":
                r["state"] = "up"
            else:
                r["state"] = "down"
                r["routed"] = max(r["routed"], e.get("routed", 0) or 0)
                if e.get("reason") == "died":
                    r["deaths"] += 1
                elif e.get("reason") == "budget_exhausted":
                    r["budget_exhausted"] = True
        total_routed = sum(r["routed"] for r in per_rank.values()) or 1
        for r in per_rank.values():
            r["routed_share"] = round(r["routed"] / total_routed, 4)
        replicas_out = {
            "ranks": dict(sorted(per_rank.items())),
            "epoch_prepares": len(prepares),
            "epoch_commits": len(commits),
            "epoch_aborts": len(aborts),
            "last_epoch": (commits[-1].get("epoch")
                           if commits else None),
            "partial_commits": sum(1 for e in commits
                                   if e.get("partial")),
        }
    out = {
        "events": len(events),
        "digests": len(digests),
        "suppressed": header.get("suppressed", {}),
        "faults": faults_out,
        "segments": segments_out,
        "shards": shards_out,
        "replicas": replicas_out,
        "recompiles": [
            {k: v for k, v in e.items()
             if k not in ("t", "kind", "level", "msg")}
            for e in recompiles],
        "watermarks": [
            {"level": e.get("level"), "pressure": e.get("pressure"),
             "watermark": e.get("watermark")} for e in watermarks],
    }
    if censuses:
        latest = censuses[-1]
        owners = latest.get("owners") or {}
        out["hbm_owners"] = dict(sorted(
            owners.items(),
            key=lambda kv: -(kv[1] or {}).get("bytes", 0)))
        out["hbm_total_bytes"] = latest.get("total_bytes")
    if digests:
        from collections import Counter
        out["digest_outcomes"] = dict(Counter(
            d.get("outcome") for d in digests))
    return out


def _span_has_rid(e: dict, rid: str) -> bool:
    a = e.get("args") or {}
    return a.get("rid") == rid or rid in (a.get("rids") or ())


def _is_trace_id(s: str) -> bool:
    """Fleet trace-id shape (``t`` + 16 hex): what the front mints per
    admitted request (tfidf_tpu/obs/disttrace.py). ``--request``
    dispatches on this — a ``r...`` rid keeps the single-process
    timeline, a trace id joins across every process in the trace."""
    if not (isinstance(s, str) and len(s) == 17 and s[0] == "t"):
        return False
    try:
        int(s[1:], 16)
    except ValueError:
        return False
    return True


def _span_has_trace(e: dict, tid: str) -> bool:
    a = e.get("args") or {}
    return a.get("trace") == tid or tid in (a.get("traces") or ())


def fleet_timeline(trace: str, flight: Optional[str],
                   tid: str) -> Optional[dict]:
    """The cross-process causal timeline of ONE front-minted trace id
    (round 23), read from a ``tools/trace_merge.py`` output (or any
    trace whose spans carry ``trace``/``traces`` args): the front's
    ``route`` span, the owning replica's ``request``/``queued``/
    ``batched``/``device``/``drain`` spans (joined through the rids
    the direct spans carry) and the two-phase ``txn_phase`` spans,
    time-ordered on the ALIGNED clock with ``process:lane`` labels,
    plus per-hop latency attribution: ``wire_ms`` is the route wall
    minus the replica's request wall (protocol + socket + queue-to-
    submit), ``queued_ms``/``device_ms`` read straight off the
    replica's spans. None when the id appears nowhere."""
    events = _tracer.load_chrome_trace(trace)
    thread_names: Dict[tuple, str] = {}
    proc_names: Dict[object, str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
        elif e.get("name") == "process_name":
            proc_names[e.get("pid")] = \
                e.get("args", {}).get("name", "")
    multi = len(proc_names) > 1

    def lane(e: dict) -> str:
        th = thread_names.get((e.get("pid"), e.get("tid")),
                              f"{e.get('pid')}/{e.get('tid')}")
        if multi:
            return f"{proc_names.get(e.get('pid'), e.get('pid'))}:{th}"
        return th

    xs = [e for e in events if e.get("ph") == "X"]
    direct = [e for e in xs if _span_has_trace(e, tid)]
    rids = sorted({(e.get("args") or {}).get("rid")
                   for e in direct
                   if (e.get("args") or {}).get("rid")})
    spans = [e for e in xs
             if _span_has_trace(e, tid)
             or any(_span_has_rid(e, r) for r in rids)]
    if not spans:
        return None
    spans.sort(key=lambda e: e.get("ts", 0.0))
    t_base = spans[0]["ts"]
    rows = []
    for e in spans:
        args = dict(e.get("args") or {})
        args.pop("rids", None)    # batch-mate lists: noise in one
        args.pop("traces", None)  # trace's view
        rows.append({"span": e["name"], "lane": lane(e),
                     "at_ms": round((e["ts"] - t_base) / 1e3, 3),
                     "dur_ms": round(e.get("dur", 0.0) / 1e3, 3),
                     "args": args})

    def _total(name: str) -> float:
        return sum(e.get("dur", 0.0) for e in spans
                   if e["name"] == name) / 1e3

    hops = None
    routes = [e for e in spans if e["name"] == "route"]
    requests = [e for e in spans if e["name"] == "request"]
    if routes and requests:
        route_ms = routes[0].get("dur", 0.0) / 1e3
        request_ms = requests[0].get("dur", 0.0) / 1e3
        hops = {"route_ms": round(route_ms, 3),
                "request_ms": round(request_ms, 3),
                # Everything the front saw that the replica's server
                # didn't: JSONL encode/decode, the socketpair both
                # ways, and the replica's stdin loop.
                "wire_ms": round(max(0.0, route_ms - request_ms), 3),
                "queued_ms": round(_total("queued"), 3),
                "device_ms": round(_total("device"), 3),
                "drain_ms": round(_total("drain"), 3)}

    flight_events: List[dict] = []
    digests: List[dict] = []
    if flight and os.path.exists(flight):
        _header, fevents, fdigests = load_flight(flight)
        flight_events = [
            e for e in fevents
            if e.get("trace") == tid or e.get("rid") in rids
            or any(r in (e.get("rids") or ()) for r in rids)]
        digests = [d for d in fdigests
                   if d.get("rid") in rids or d.get("trace") == tid]
    return {"trace_id": tid, "rids": rids,
            "processes": sorted({r["lane"].split(":")[0]
                                 for r in rows}) if multi else [],
            "spans": rows, "hops": hops,
            "flight_events": [
                {k: v for k, v in e.items() if k != "kind"}
                for e in flight_events],
            "digests": digests}


def render_fleet(rep: dict) -> str:
    lines = [f"trace {rep['trace_id']}: {len(rep['spans'])} span(s) "
             f"across {len(rep['processes']) or 1} process(es)"
             + (f" {rep['processes']}" if rep["processes"] else "")
             + (f", rids {rep['rids']}" if rep["rids"] else "")]
    lines.append(f"  {'at ms':>9} {'dur ms':>9} {'lane':<18} "
                 f"{'span':<16} args")
    for r in rep["spans"]:
        lines.append(
            f"  {r['at_ms']:>9.3f} {r['dur_ms']:>9.3f} "
            f"{r['lane']:<18} {r['span']:<16} {r['args']}")
    if rep["hops"]:
        parts = ", ".join(f"{k}={v}" for k, v in rep["hops"].items())
        lines.append(f"  per-hop (ms): {parts}")
    for e in rep["flight_events"]:
        lines.append(f"  flight [{e.get('level')}] {e.get('event')}: "
                     f"{e.get('msg', '')}")
    for d in rep["digests"]:
        lines.append(f"  digest: {d}")
    return "\n".join(lines)


def request_timeline(trace: str, flight: Optional[str],
                     rid: str) -> Optional[dict]:
    """The full causal timeline of ONE request (round 16): every span
    stamped with its rid (directly, or via a batch's ``rids`` list),
    time-ordered with lane labels, plus the flight events and digests
    carrying the same key — trace, flight and response joined on the
    one id the serve layer minted at admission. None when the rid
    appears nowhere."""
    events = _tracer.load_chrome_trace(trace)
    lane_names: Dict[tuple, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            lane_names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    spans = [e for e in events if e.get("ph") == "X"
             and _span_has_rid(e, rid)]
    spans.sort(key=lambda e: e.get("ts", 0.0))
    flight_events: List[dict] = []
    digests: List[dict] = []
    if flight and os.path.exists(flight):
        _header, fevents, fdigests = load_flight(flight)
        flight_events = [e for e in fevents
                         if e.get("rid") == rid
                         or rid in (e.get("rids") or ())]
        digests = [d for d in fdigests if d.get("rid") == rid]
    if not spans and not flight_events and not digests:
        return None
    t_base = spans[0]["ts"] if spans else 0.0
    rows = []
    for e in spans:
        lane = lane_names.get((e.get("pid"), e.get("tid")),
                              f"{e.get('pid')}/{e.get('tid')}")
        args = dict(e.get("args") or {})
        args.pop("rids", None)   # batch-mate list: noise in one
        rows.append({                            # request's view
            "span": e["name"], "lane": lane,
            "at_ms": round((e["ts"] - t_base) / 1e3, 3),
            "dur_ms": round(e.get("dur", 0.0) / 1e3, 3),
            "args": args})
    slow = [e for e in flight_events if e.get("event") == "slow_query"]
    return {
        "rid": rid,
        "spans": rows,
        "flight_events": [
            {k: v for k, v in e.items() if k not in ("kind",)}
            for e in flight_events],
        "digests": digests,
        "breakdown": (slow[-1].get("breakdown") if slow else None),
    }


def render_request(rep: dict) -> str:
    lines = [f"request {rep['rid']}: {len(rep['spans'])} span(s), "
             f"{len(rep['flight_events'])} flight event(s), "
             f"{len(rep['digests'])} digest(s)"]
    if rep["spans"]:
        lines.append(f"  {'at ms':>9} {'dur ms':>9} {'lane':<10} "
                     f"{'span':<16} args")
        for r in rep["spans"]:
            lines.append(
                f"  {r['at_ms']:>9.3f} {r['dur_ms']:>9.3f} "
                f"{r['lane']:<10} {r['span']:<16} {r['args']}")
    if rep["breakdown"]:
        parts = ", ".join(f"{k}={v}" for k, v in
                          rep["breakdown"].items())
        lines.append(f"  breakdown (ms): {parts}")
    for e in rep["flight_events"]:
        lines.append(f"  flight [{e.get('level')}] {e.get('event')}: "
                     f"{e.get('msg', '')}")
    for d in rep["digests"]:
        lines.append(f"  digest: {d}")
    return "\n".join(lines)


def tail_ledger(path: str, n: int = 5) -> List[dict]:
    if not os.path.exists(path):
        return []
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records[-n:]


def diagnose(trace: str, flight: Optional[str], ledger: str,
             allow_recompiles: int = 0, allow_watermarks: int = 0,
             allow_breaker_open: bool = False,
             budgets: Optional[Dict[str, float]] = None,
             compaction_budget_ms: Optional[float] = None,
             shard_imbalance: Optional[float] = None) -> dict:
    report: dict = {"trace": trace}
    report.update(analyze_trace(trace))
    recompile_count = report["recompile_instants"]
    watermark_count = 0
    breaker_open = False
    compaction_pause_ms = 0.0
    shards = None
    if flight and os.path.exists(flight):
        report["flight"] = analyze_flight(flight)
        recompile_count = max(recompile_count,
                              len(report["flight"]["recompiles"]))
        watermark_count = len(report["flight"]["watermarks"])
        breaker_open = report["flight"]["faults"][
            "breaker_open_at_exit"]
        compaction_pause_ms = report["flight"]["segments"][
            "total_pause_ms"]
        shards = report["flight"].get("shards")
    report["ledger_tail"] = tail_ledger(ledger)

    violations: List[str] = []
    if recompile_count > allow_recompiles:
        violations.append(
            f"{recompile_count} XLA recompile(s) after warm-up "
            f"(allowed {allow_recompiles})")
    if watermark_count > allow_watermarks:
        violations.append(
            f"{watermark_count} HBM watermark breach(es) "
            f"(allowed {allow_watermarks})")
    if breaker_open and not allow_breaker_open:
        violations.append(
            "circuit breaker OPEN at exit (last breaker event is a "
            "trip with no close after it — the server never "
            "recovered; --allow-breaker-open to tolerate)")
    if compaction_budget_ms is not None \
            and compaction_pause_ms > compaction_budget_ms:
        violations.append(
            f"compaction paused mutation for "
            f"{compaction_pause_ms:.1f} ms total > budget "
            f"{compaction_budget_ms} ms (--compaction-budget-ms)")
    if shard_imbalance is not None and shards \
            and (shards.get("imbalance") or 0) > shard_imbalance:
        violations.append(
            f"index shard imbalance {shards['imbalance']:.3f} "
            f"(max/mean bytes across {shards['n_shards']} shards) > "
            f"budget {shard_imbalance} (--shard-imbalance)")
    for name, budget in (budgets or {}).items():
        got = report["phases"].get(name, {}).get("total_s", 0.0)
        if got > budget:
            violations.append(
                f"phase {name!r} spent {got:.3f}s > budget {budget}s")
    report["violations"] = violations
    report["ok"] = not violations
    return report


def render(report: dict) -> str:
    lines = [f"doctor: {report['trace']}"]
    lines.append(f"  lanes: {report['lanes']}   wall "
                 f"{report['wall_s'] * 1e3:.1f} ms")
    lines.append(f"  {'phase':<12}{'spans':>6}{'total ms':>10}"
                 f"{'% wall':>8}{'MB':>10}{'GB/s':>8}")
    wall = report["wall_s"] or 1e-12
    for name, rec in sorted(report["phases"].items(),
                            key=lambda kv: -kv[1]["total_s"]):
        mb = rec["bytes"] / 1e6 if rec["bytes"] else None
        lines.append(
            f"  {name:<12}{rec['spans']:>6}"
            f"{rec['total_s'] * 1e3:>10.1f}"
            f"{rec['total_s'] / wall * 100:>7.0f}%"
            + (f"{mb:>10.2f}" if mb is not None else f"{'-':>10}")
            + (f"{rec['gb_s']:>8.2f}" if "gb_s" in rec else f"{'-':>8}"))
    if "serialized_sum_s" in report:
        lines.append(
            f"  serialized sum {report['serialized_sum_s'] * 1e3:.1f} ms"
            f" -> overlap efficiency {report['overlap_efficiency']:.1%}")
    if "serve" in report:
        sv = report["serve"]
        lines.append(f"  serve: {sv['requests']} requests in "
                     f"{sv['batches']} batches, outcomes "
                     f"{sv['outcomes']}")
    if report.get("slowest_requests"):
        lines.append("  slowest requests (doctor --request RID for "
                     "the timeline):")
        for r in report["slowest_requests"]:
            lines.append(
                f"    {r['ms']:>9.1f} ms  {(r['rid'] or '-'):<20} "
                f"{r['outcome']} ({r['queries']} queries)")
    fl = report.get("flight")
    if fl:
        lines.append(f"  flight: {fl['events']} events, "
                     f"{fl['digests']} digests"
                     + (f", suppressed {fl['suppressed']}"
                        if fl["suppressed"] else ""))
        fa = fl.get("faults", {})
        if any(v for k, v in fa.items()
               if k not in ("breaker_open_at_exit",
                            "restarts_by_worker")):
            by_worker = fa.get("restarts_by_worker")
            lines.append(
                f"  faults: {fa['dispatch_retry']} retries, "
                f"{fa['worker_restart']} worker restarts"
                + (f" {by_worker}" if by_worker else "")
                + f", {fa['breaker_trip']} breaker trips "
                f"({'OPEN' if fa['breaker_open_at_exit'] else 'closed'}"
                f" at exit), {fa['query_quarantined']} quarantined, "
                f"{fa['fault_injected']} injected")
        sg = fl.get("segments", {})
        if sg.get("seals") or sg.get("compactions") \
                or sg.get("mutations"):
            lines.append(
                f"  segments: {sg['mutations']} mutation install(s), "
                f"{sg['seals']} seal(s), {sg['compactions']} "
                f"compaction(s) (total pause "
                f"{sg['total_pause_ms']:.1f} ms, max "
                f"{sg['max_pause_ms']:.1f} ms, "
                f"{sg['tombstones_dropped']} tombstones dropped)")
        sh = fl.get("shards")
        if sh:
            per = ", ".join(f"d{i} {b / 1e6:.2f} MB" for i, b in
                            enumerate(sh.get("shard_bytes") or []))
            lines.append(
                f"  shards: {sh['n_shards']} docs-shards ({per}), "
                f"imbalance {sh['imbalance']:.3f} "
                f"({sh['installs_seen']} install(s) seen)")
        rp = fl.get("replicas")
        if rp:
            per = ", ".join(
                f"r{rank} {info['state']}"
                f" boot={info['boot']}"
                f" share={info['routed_share']:.0%}"
                + (f" deaths={info['deaths']}" if info["deaths"]
                   else "")
                + (" BUDGET-EXHAUSTED" if info["budget_exhausted"]
                   else "")
                for rank, info in rp["ranks"].items())
            lines.append(
                f"  replicas: {per}; epochs: {rp['epoch_prepares']} "
                f"prepare(s), {rp['epoch_commits']} commit(s), "
                f"{rp['epoch_aborts']} abort(s)"
                + (f", {rp['partial_commits']} PARTIAL"
                   if rp["partial_commits"] else "")
                + (f", last epoch {rp['last_epoch']}"
                   if rp["last_epoch"] is not None else ""))
        if "hbm_owners" in fl:
            owners = ", ".join(
                f"{name} {info.get('bytes', 0) / 1e6:.1f} MB"
                for name, info in list(fl["hbm_owners"].items())[:5])
            lines.append(f"  hbm owners: {owners}")
        for w in fl["watermarks"]:
            lines.append(f"  HBM WATERMARK [{w['level']}]: pressure "
                         f"{w['pressure']} >= {w['watermark']}")
        for r in fl["recompiles"]:
            lines.append(f"  RECOMPILE after warm-up: {r}")
    if report["ledger_tail"]:
        last = report["ledger_tail"][-1]
        lines.append(f"  ledger: {len(report['ledger_tail'])} trailing "
                     f"record(s); newest {last.get('source')} "
                     f"[{last.get('kind')}]")
    for v in report["violations"]:
        lines.append(f"FAIL: {v}")
    lines.append("healthy" if report["ok"]
                 else "unhealthy: budget violation(s) above")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="exit 0 = healthy, 1 = budget violation, 2 = unreadable")
    ap.add_argument("trace", help="Chrome trace-event JSON "
                                  "(--trace / TFIDF_TPU_TRACE output)")
    ap.add_argument("--flight", metavar="DUMP.jsonl", default=None,
                    help="flight-recorder dump (default: "
                         "<trace>.flight.jsonl when it exists)")
    ap.add_argument("--ledger",
                    default=os.path.join(_common.REPO,
                                         "BENCH_LEDGER.jsonl"))
    ap.add_argument("--allow-recompiles", type=int, default=0,
                    help="XLA recompiles after warm-up tolerated "
                         "before exit 1 (default 0)")
    ap.add_argument("--allow-watermarks", type=int, default=0,
                    help="HBM watermark breaches tolerated (default 0)")
    ap.add_argument("--allow-breaker-open", action="store_true",
                    help="tolerate a run whose flight dump ends with "
                         "the dispatch circuit breaker open (default: "
                         "exit 1 — the server never recovered)")
    ap.add_argument("--budget", action="append", default=[],
                    metavar="PHASE=SECONDS",
                    help="per-phase wall budget, repeatable "
                         "(e.g. --budget pack=0.5)")
    ap.add_argument("--compaction-budget-ms", type=float, default=None,
                    help="total milliseconds the run may spend with "
                         "mutation paused for compaction (summed "
                         "pause_s over the flight dump's compaction "
                         "events); past it exit 1 (default: report "
                         "only)")
    ap.add_argument("--shard-imbalance", type=float, default=None,
                    help="max tolerated index shard imbalance "
                         "(max/mean per-shard bytes from the newest "
                         "shard_balance flight event); past it exit 1 "
                         "(default: report only)")
    ap.add_argument("--request", metavar="RID|TRACE_ID", default=None,
                    help="render ONE request's full causal timeline "
                         "(every span carrying this rid directly or "
                         "via its batch, plus matching flight events "
                         "and digests) instead of the aggregate "
                         "report — the rid comes from a JSONL "
                         "response, a slow_query event, or the "
                         "slowest-requests table. A front-minted "
                         "t<16hex> trace id (against a "
                         "tools/trace_merge.py output) joins FLEET-"
                         "wide: front route, replica request/queued/"
                         "device spans and txn phases across "
                         "processes, with per-hop wire/queue/device "
                         "attribution")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report")
    args = ap.parse_args()

    budgets = {}
    for spec in args.budget:
        name, _, val = spec.partition("=")
        try:
            budgets[name] = float(val)
        except ValueError:
            print(f"doctor: bad --budget {spec!r} (want PHASE=SECONDS)",
                  file=sys.stderr)
            return 2
    flight = args.flight
    if flight is None:
        candidate = f"{args.trace}.flight.jsonl"
        flight = candidate if os.path.exists(candidate) else None

    if args.request:
        fleet = _is_trace_id(args.request)
        try:
            rep = (fleet_timeline(args.trace, flight, args.request)
                   if fleet else
                   request_timeline(args.trace, flight, args.request))
        except (OSError, ValueError, KeyError) as e:
            print(f"doctor: cannot read inputs: {e}", file=sys.stderr)
            return 2
        if rep is None:
            kind = "trace id" if fleet else "rid"
            print(f"doctor: {kind} {args.request!r} appears in "
                  f"neither the trace nor the flight dump",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(rep, sort_keys=True))
        else:
            print(render_fleet(rep) if fleet else render_request(rep))
        return 0

    try:
        report = diagnose(args.trace, flight, args.ledger,
                          allow_recompiles=args.allow_recompiles,
                          allow_watermarks=args.allow_watermarks,
                          allow_breaker_open=args.allow_breaker_open,
                          budgets=budgets,
                          compaction_budget_ms=args.compaction_budget_ms,
                          shard_imbalance=args.shard_imbalance)
    except (OSError, ValueError, KeyError) as e:
        print(f"doctor: cannot read inputs: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
